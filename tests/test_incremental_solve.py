"""Incremental solve: checkpointed scan-prefix reuse and suffix-only
re-solve (solver/incremental.py, ops/ffd_jax.py solve_scan_suffix,
solver/tpu.py _try_suffix).

Three layers, one contract — a suffix-served tick is byte-identical to
the from-scratch solve:

- planning (no jax): suffix_plan / suffix_buckets / ckpt_eligible /
  live_bound edges, and the server-side frontier recovered purely from
  patched word sections (ops/hostpack.frontier_from_sections).
- delta semantics (no jax): SnapshotDelta.dirty_frontier is the min
  canonical group index whose row moved; any node/pool/existing-row
  dirtiness pins it to 0 (those feed the scan's initial carry).
- staleness edges (jax): structural epoch bump, bucket regrow, version
  lag > 1 (a host-served tick), and a mid-stream fleet rebind each
  force a checkpoint-rebuilding full solve — never a stale suffix —
  and every tick stays fingerprint-identical to the CPU oracle.

The slow matrix (``make fuzz-suffix`` / hack/fuzzsuffix.sh) sweeps 10
seeds of randomized churn, including frontier == 0 and last-group-only
ticks, plus the exhaustive kernel byte-parity sweep over every
(checkpoint row, suffix bucket) pair.
"""

import random

import numpy as np
import pytest

from karpenter_provider_aws_tpu.apis.resources import Resources
from karpenter_provider_aws_tpu.fake.environment import Environment, make_pods
from karpenter_provider_aws_tpu.models.delta import DeltaEncoder
from karpenter_provider_aws_tpu.ops.hostpack import (frontier_from_sections,
                                                     in_layout_i64,
                                                     layout_sizes)
from karpenter_provider_aws_tpu.solver import CPUSolver
from karpenter_provider_aws_tpu.solver.incremental import (CKPT_CHUNK,
                                                           ckpt_eligible,
                                                           live_bound,
                                                           suffix_buckets,
                                                           suffix_plan)
from karpenter_provider_aws_tpu.solver.tpu import TPUSolver
from karpenter_provider_aws_tpu.solver.types import ExistingNode
from karpenter_provider_aws_tpu.utils.metrics import Metrics

CK = CKPT_CHUNK

FUZZ_SEEDS_SLOW = (3, 7, 11, 17, 23, 31, 42, 57, 71, 97)

_ZONE_L = "topology.kubernetes.io/zone"
_CT_L = "karpenter.sh/capacity-type"


# ---------------------------------------------------------------------------
# planning (no jax)

class TestPlanning:
    def test_ckpt_eligible_gates(self):
        assert ckpt_eligible(4 * CK)
        assert not ckpt_eligible(4 * CK, ndev=2)          # mesh engine
        assert not ckpt_eligible(4 * CK, use_pruned=True)  # pruned engine
        assert not ckpt_eligible(4 * CK, Fu=2)            # fused scan
        assert not ckpt_eligible(CK)                      # too small
        assert not ckpt_eligible(4 * CK + 1)              # not CK-aligned
        assert not ckpt_eligible(1024)                    # past the cap

    def test_suffix_plan_invariants(self):
        for Gp in (4 * CK, 8 * CK, 16 * CK):
            NC = Gp // CK
            for frontier in range(Gp + 1):
                jr, SUF = suffix_plan(frontier, Gp)
                assert SUF >= 1
                assert jr + SUF == NC          # the scan reaches the end
                assert jr * CK <= min(frontier, Gp - 1)  # no dirty row skipped
                assert SUF in suffix_buckets(Gp)

    def test_suffix_plan_live_bound(self):
        Gp = 16 * CK
        GL = 7 * CK
        for frontier in range(GL):
            jr, SUF = suffix_plan(frontier, Gp, GL=GL)
            assert jr + SUF == GL // CK        # the scan stops at GL
            assert jr * CK <= frontier
        # frontier at/past GL still yields a valid (clamped) plan
        jr, SUF = suffix_plan(GL + 3, Gp, GL=GL)
        assert jr + SUF == GL // CK and SUF >= 1

    def test_suffix_buckets_ladder(self):
        for Gp in (4 * CK, 8 * CK, 32 * CK):
            buckets = suffix_buckets(Gp)
            NC = Gp // CK
            assert buckets == tuple(sorted(buckets))
            assert buckets[-1] == NC           # frontier 0 -> full depth
            assert all(1 <= b <= NC for b in buckets)
            # the pow-1.5 ladder is O(log G), the whole point of bucketing
            assert len(buckets) <= 2 * NC.bit_length() + 2

    def test_live_bound(self):
        T, D, G = 3, 4, 8
        off = T * D + G * D
        buf = np.zeros(off + G, dtype=np.int64)
        assert live_bound(buf, T=T, D=D, G=G) == 0     # all-empty arena
        buf[off + 4] = 2                               # last live group: 4
        gl = live_bound(buf, T=T, D=D, G=G)
        assert gl % CK == 0 and gl >= 5
        buf[off + G - 1] = 1
        assert live_bound(buf, T=T, D=D, G=G) == G     # fully live


class TestFrontierFromSections:
    KV = dict(T=5, D=8, Z=2, C=2, G=16, E=3, P=2)

    def _offsets(self):
        kv = self.KV
        lay = in_layout_i64(kv["T"], kv["D"], kv["Z"], kv["C"], kv["G"],
                            kv["E"], kv["P"], 0, 0, 1, 0)
        off, out = 0, {}
        for nm, shp in lay:
            sz = 1
            for s in shp:
                sz *= s
            out[nm] = (off, off + sz)
            off += sz
        return out

    def test_empty_sections_are_clean(self):
        assert frontier_from_sections([], **self.KV) == self.KV["G"]

    def test_group_major_words_map_to_their_group(self):
        kv, offs = self.KV, self._offsets()
        n0 = offs["n"][0]
        assert frontier_from_sections([(n0 + 5, n0 + 6)], **kv) == 5
        r0 = offs["R"][0]
        w = r0 + 3 * kv["D"]  # first word of R row 3
        assert frontier_from_sections([(w, w + kv["D"])], **kv) == 3
        # min across several sections wins
        assert frontier_from_sections(
            [(n0 + 9, n0 + 10), (w, w + 1)], **kv) == 3

    def test_non_group_fields_force_full(self):
        kv, offs = self.KV, self._offsets()
        a0 = offs["A"][0]
        assert frontier_from_sections([(a0 + 2, a0 + 3)], **kv) == 0
        p0 = offs["pool_limit"][0]
        assert frontier_from_sections([(p0, p0 + 1)], **kv) == 0
        e0 = offs["ex_used0"][0]
        assert frontier_from_sections([(e0, e0 + 1)], **kv) == 0
        # one clean-looking section + one carry-feeding section -> 0
        n0 = offs["n"][0]
        assert frontier_from_sections(
            [(n0 + 12, n0 + 13), (a0, a0 + 1)], **kv) == 0

    def test_bool_sections_round_conservatively(self):
        kv = self.KV
        lay = in_layout_i64(kv["T"], kv["D"], kv["Z"], kv["C"], kv["G"],
                            kv["E"], kv["P"], 0, 0, 1, 0)
        n_i64 = layout_sizes(lay)
        # the first bool word covers avail_zc (a non-group field):
        # touching it must force frontier 0
        assert frontier_from_sections([(n_i64, n_i64 + 1)], **kv) == 0


# ---------------------------------------------------------------------------
# delta semantics (no jax)

def _decreasing_cpu_cluster(n_groups=8, per_group=3, prefix="inc"):
    """Pod groups whose cpu strictly DECREASES with the build index, so
    the canonical order (-cpu major) makes canonical position == index:
    churning group k must yield dirty_frontier == k exactly."""
    env = Environment()
    pool = env.nodepool(prefix)
    sigs = [dict(cpu=f"{900 - 100 * i}m", memory=f"{512 + 64 * i}Mi",
                 group=f"{prefix}g{i}") for i in range(n_groups)]

    def mk(gi, n=1):
        return make_pods(n, cpu=sigs[gi]["cpu"], memory=sigs[gi]["memory"],
                         prefix=sigs[gi]["group"], group=sigs[gi]["group"])

    pods = {gi: mk(gi, per_group) for gi in range(n_groups)}

    def snap(existing=()):
        # iterate the dict's keys, not range(n_groups): tests add NEW
        # groups (structural transitions) by inserting fresh keys
        flat = [p for gi in sorted(pods) for p in pods[gi]]
        return env.snapshot(flat, [pool], existing_nodes=list(existing))

    return env, sigs, pods, mk, snap


def _node(name, cpu_used="500m"):
    return ExistingNode(
        name=name,
        labels={_ZONE_L: "us-east-1a", _CT_L: "on-demand"},
        allocatable=Resources.parse(
            {"cpu": "8", "memory": "32Gi", "pods": "110"}),
        used=Resources.parse({"cpu": cpu_used, "memory": "1Gi"}))


class TestDirtyFrontier:
    def test_churned_group_sets_frontier_to_its_index(self):
        _, _, pods, mk, snap = _decreasing_cpu_cluster()
        denc = DeltaEncoder()
        for k in (5, 2, 7):
            denc.encode(snap(), None, [])
            pods[k][0] = mk(k)[0]       # swap one pod: membership churn
            _, _, d = denc.encode(snap(), None, [])
            assert d.tier == "rows"
            assert d.dirty_frontier == k

    def test_quiet_tick_frontier_is_group_count(self):
        _, _, _, _, snap = _decreasing_cpu_cluster(n_groups=6)
        denc = DeltaEncoder()
        denc.encode(snap(), None, [])
        _, _, d = denc.encode(snap(), None, [])
        assert d.tier == "hit"
        assert d.dirty_frontier == 6

    def test_node_dirtiness_forces_frontier_zero(self):
        _, _, pods, mk, snap = _decreasing_cpu_cluster()
        denc = DeltaEncoder()
        denc.encode(snap(), None, [])
        # a launched node feeds ex rows -> initial carry: frontier 0
        # even though pod churn alone would have said 6
        pods[6][0] = mk(6)[0]
        n1 = _node("inc-n-1")
        _, _, d = denc.encode(snap([n1]), None, [n1])
        assert d.dirty_frontier == 0

    def test_rebind_used_bump_forces_frontier_zero(self):
        _, _, _, _, snap = _decreasing_cpu_cluster()
        denc = DeltaEncoder()
        n1 = _node("inc-n-1")
        denc.encode(snap([n1]), None, [n1])
        n2 = _node("inc-n-1", cpu_used="2")   # same node, bound pods
        _, _, d = denc.encode(snap([n2]), None, [n2])
        assert d.tier == "rows"
        assert d.dirty_frontier == 0


# ---------------------------------------------------------------------------
# staleness edges (jax; every tick fingerprint-checked vs the oracle)

def _oracle_print(snap):
    return CPUSolver().solve(snap).decision_fingerprint()


def _device_or_skip():
    from karpenter_provider_aws_tpu.solver import route
    if not route.device_alive():
        pytest.skip("no dev engine in this environment")


def _jax_solver():
    s = TPUSolver(backend="jax")
    # conftest forces 8 virtual CPU devices; the mesh route is
    # ckpt-ineligible, so pin the single-device packed path under test
    s._dev_devices = lambda: 1
    return s


def _solve_checked(solver, snap):
    """One solve, fingerprint-checked against the from-scratch CPU
    oracle; returns the dispatch-mode marker ('full' or
    'suffix@<bucket>')."""
    res = solver.solve(snap)
    assert res.decision_fingerprint() == _oracle_print(snap)
    return solver.last_phase_stats.get("solve", "full")


class TestCheckpointStaleness:
    def test_staleness_edges_force_full_then_suffix_resumes(self):
        _device_or_skip()
        from karpenter_provider_aws_tpu.solver import route
        env, sigs, pods, mk, snap = _decreasing_cpu_cluster(
            n_groups=8, per_group=4, prefix="stale")
        nodes = [_node("stale-n-1"), _node("stale-n-2")]
        solver = _jax_solver()
        solver.metrics = Metrics()
        oracle_nodes = list(nodes)

        def tick():
            return snap(oracle_nodes)

        # cold adopt: full solve records the bank
        assert _solve_checked(solver, tick()) == "full"

        # let the slot bucket settle (the 8-solve shrink window walks
        # 256 -> 16 on a cluster this small) BEFORE probing the edges:
        # each shrink step changes the kernel shape class and would
        # alias its own re-record full into an edge that expects a
        # suffix
        for _ in range(24):
            if solver._bucket == 16:
                break
            pods[7][0] = mk(7)[0]
            _solve_checked(solver, tick())
        assert solver._bucket == 16
        pods[7][0] = mk(7)[0]
        _solve_checked(solver, tick())  # re-record at the settled bucket

        # warm churn in a deep group -> suffix, correct resume depth
        pods[6][0] = mk(6)[0]
        mode = _solve_checked(solver, tick())
        assert mode.startswith("suffix@"), mode
        assert solver.last_dispatch_stats["resume_group"] <= 6

        # structural transition (a NEW signature joins) -> epoch bump:
        # the bank must NOT serve, and the next warm tick re-adopts
        sigs.append(dict(cpu="150m", memory="128Mi", group="stalegX"))
        pods[8] = make_pods(2, cpu="150m", memory="128Mi",
                            prefix="stalegX", group="stalegX")
        env2 = snap(oracle_nodes)
        assert _solve_checked(solver, env2) == "full"
        pods[8][0] = make_pods(1, cpu="150m", memory="128Mi",
                               prefix="stalegX", group="stalegX")[0]
        assert _solve_checked(solver, tick()).startswith("suffix@")

        # a tick served by the host twin (device probe forced dead)
        # does NOT strand the bank: routing happens before the
        # incremental encode, so the encoder never observes the
        # intermediate state and the next device delta SPANS both
        # ticks — the suffix stays exact (the fingerprint check is
        # the proof)
        orig = route.dev_engine_usable
        route.dev_engine_usable = lambda *a, **k: False
        try:
            pods[5][0] = mk(5)[0]
            _solve_checked(solver, tick())
        finally:
            route.dev_engine_usable = orig
        pods[5][1] = mk(5)[0]
        mode = _solve_checked(solver, tick())
        assert mode.startswith("suffix@"), mode
        assert solver.last_dispatch_stats["resume_group"] <= 5

        # version lag proper: a bank whose token trails the arena by
        # MORE than the current delta (a dropped/unobserved tick) must
        # not serve — rewind the token one version and the next rows
        # tick full-solves, then suffixes resume
        bk = solver._ckpt_bank
        bk["token"] = (bk["token"][0], bk["token"][1] - 1)
        pods[5][1] = mk(5)[0]
        assert _solve_checked(solver, tick()) == "full"
        pods[7][0] = mk(7)[0]
        assert _solve_checked(solver, tick()).startswith("suffix@")

        # mid-stream fleet rebind: a node's used bump dirties the
        # initial carry -> frontier 0 -> full re-record, then resume
        oracle_nodes[0] = _node("stale-n-1", cpu_used="3")
        assert _solve_checked(solver, tick()) == "full"
        pods[6][1] = mk(6)[0]
        assert _solve_checked(solver, tick()).startswith("suffix@")

        # the metric families carry the streak's evidence
        rendered = solver.metrics.render()
        assert "karpenter_solver_solve_suffix_total" in rendered
        assert "karpenter_solver_solve_full_total" in rendered
        assert "karpenter_solver_solve_suffix_groups" in rendered

    def test_bucket_shrink_and_regrow_rebuild_bank(self):
        _device_or_skip()
        _, _, pods, mk, snap = _decreasing_cpu_cluster(
            n_groups=8, per_group=2, prefix="grow")
        solver = _jax_solver()
        solver.metrics = Metrics()
        assert _solve_checked(solver, snap()) == "full"
        pods[7][0] = mk(7)[0]
        assert _solve_checked(solver, snap()).startswith("suffix@")

        # slot-bucket SHRINK (the 8-solve settle window stepping the
        # 256 cold bucket down the 16/64/256 ladder): each step changes
        # the kernel shape class, so the step tick must re-record — and
        # the streak resumes at the narrow bucket
        shrunk = False
        for t in range(24):
            if solver._bucket == 16:
                shrunk = True
                break
            pods[7][0] = mk(7)[0]
            mode = _solve_checked(solver, snap())
            assert mode == "full" or mode.startswith("suffix@")
        assert shrunk, f"bucket never settled: {solver._bucket}"
        # the first tick at the settled bucket re-records (the bank was
        # keyed to the wide shape class) — the one after serves suffix
        pods[7][0] = mk(7)[0]
        assert _solve_checked(solver, snap()) == "full"
        pods[7][0] = mk(7)[0]
        assert _solve_checked(solver, snap()).startswith("suffix@")

        # slot exhaustion: a burst in the CHEAPEST (last) group floods
        # past the narrow bucket — the suffix serves first, overflows,
        # and the grown retry lands as a bank-rebuilding full (reason
        # "exhausted"); the streak resumes at the wider bucket
        # sized past the narrow bucket's absolute capacity: 16 slots of
        # the biggest offering (192 cpu -> 960 of these 200m pods) hold
        # 15360 — 25k forces leftover at 16 slots, ~26 nodes at 64
        pods[7] = pods[7] + mk(7, 25000)
        assert _solve_checked(solver, snap()) == "full"
        assert solver._bucket > 16
        pods[7][0] = mk(7)[0]
        assert _solve_checked(solver, snap()).startswith("suffix@")
        rendered = solver.metrics.render()
        assert 'karpenter_solver_solve_full_total{reason="exhausted"}' \
            in rendered


# ---------------------------------------------------------------------------
# slow sweeps: hack/fuzzsuffix.sh (make fuzz-suffix)

def _fuzz_suffix(seed: int, ticks: int = 12):
    env, sigs, pods, mk, snap = _decreasing_cpu_cluster(
        n_groups=8, per_group=3, prefix=f"fz{seed}")
    nodes = [_node(f"fz{seed}-n-1")]
    solver = _jax_solver()
    rng = random.Random(seed)
    suffix_ticks = 0
    # first two mutations pinned: last-group-only churn then a random
    # churn — the frontier regimes the suffix exists for
    forced = ["last", "rand"]
    for t in range(ticks):
        op = forced.pop(0) if forced else rng.choices(
            ("rand", "last", "zero", "bind", "structural"),
            weights=(60, 15, 10, 10, 5))[0]
        if op == "rand":
            k = rng.randrange(len(pods))
            pods[k][rng.randrange(len(pods[k]))] = mk(k)[0]
        elif op == "last":
            k = max(pods)
            pods[k][0] = mk(k)[0]
        elif op == "zero":
            pods[0][0] = mk(0)[0]          # frontier == 0 group churn
        elif op == "bind":
            nodes[0] = _node(nodes[0].name,
                             cpu_used=f"{rng.randint(1, 4)}")
        elif op == "structural":
            gi = len(pods)
            grp = f"fz{seed}gX{t}"
            # register the sig so later "rand" churn can hit the new
            # group through mk() like any other
            sigs.append(dict(cpu=f"{80 + t}m", memory="128Mi", group=grp))
            pods[gi] = mk(gi)
        sn = snap(nodes)
        res = solver.solve(sn)
        assert res.decision_fingerprint() == _oracle_print(sn), \
            (seed, t, op)
        if str(solver.last_phase_stats.get("solve", "")).startswith(
                "suffix@"):
            suffix_ticks += 1
    assert suffix_ticks >= 1, seed


@pytest.mark.slow
@pytest.mark.parametrize("seed", FUZZ_SEEDS_SLOW)
def test_fuzz_suffix_byte_equality(seed):
    _device_or_skip()
    _fuzz_suffix(seed)


@pytest.mark.slow
def test_kernel_suffix_byte_parity_exhaustive():
    """Every (checkpoint row, suffix bucket, live bound) combination of
    a randomized packed arena reproduces the full solve byte-for-byte:
    takes/leftover rows over the scanned window, every carry-derived
    output field, and the spliced bank itself."""
    import jax
    from karpenter_provider_aws_tpu.ops.ffd_jax import (
        solve_scan_packed1, solve_scan_packed1_ckpt, solve_scan_suffix)
    from karpenter_provider_aws_tpu.ops.hostpack import (pack_inputs1,
                                                         unpack_outputs1)
    rng = np.random.default_rng(11)

    def instance(G, E, P, T=5, D=8, Z=2, C=2, n_max=8, live=None):
        ex_alloc = rng.integers(0, 25, size=(E, D))
        n = rng.integers(1, 9, size=(G,))
        if live is not None:
            n[live:] = 0
        arrays = dict(
            A=rng.integers(0, 20, size=(T, D)),
            R=rng.integers(0, 4, size=(G, D)), n=n,
            daemon=rng.integers(0, 2, size=(G, P, D)),
            pool_limit=np.where(
                rng.random((P, D)) < 0.5, -1,
                rng.integers(0, 60, size=(P, D))).astype(np.int64),
            pool_used0=rng.integers(0, 5, size=(P, D)),
            ex_alloc=ex_alloc,
            ex_used0=np.minimum(rng.integers(0, 25, size=(E, D)),
                                ex_alloc),
            avail_zc=(rng.random((T, Z, C)) < 0.7).reshape(T, Z * C),
            F=rng.random((G, T)) < 0.6,
            agz=rng.random((G, Z)) < 0.8,
            agc=rng.random((G, C)) < 0.8,
            admit=rng.random((G, P)) < 0.7,
            pool_types=rng.random((P, T)) < 0.6,
            pool_agz=rng.random((P, Z)) < 0.8,
            pool_agc=rng.random((P, C)) < 0.8,
            ex_compat=rng.random((G, E)) < 0.5)
        kv = dict(T=T, D=D, Z=Z, C=C, G=G, E=E, P=P, n_max=n_max)
        return kv, pack_inputs1(arrays, T, D, Z, C, G, E, P)

    for G, live in ((4 * CK, None), (8 * CK, 5 * CK + 1), (8 * CK, 3)):
        E, P = int(rng.integers(0, 5)), int(rng.integers(1, 4))
        kv, buf = instance(G, E, P, live=live)
        gl = live_bound(buf, T=kv["T"], D=kv["D"], G=G)
        ref = np.asarray(solve_scan_packed1(buf, **kv))
        rv = unpack_outputs1(ref.copy(), **kv)
        full, bank = solve_scan_packed1_ckpt(buf, CK=CK, **kv)
        assert np.array_equal(np.asarray(full), ref)
        for SUF in range(1, max(gl // CK, 1) + 1):
            sb, nb = solve_scan_suffix(buf, bank, CK=CK, SUF=SUF,
                                       GL=gl or None, **kv)
            sv = unpack_outputs1(np.asarray(sb), **{**kv, "G": SUF * CK})
            s0 = (gl or G) - SUF * CK
            assert np.array_equal(sv["takes"],
                                  rv["takes"][s0:s0 + SUF * CK])
            assert np.array_equal(sv["leftover"],
                                  rv["leftover"][s0:s0 + SUF * CK])
            for nm in ("used", "pool", "num_nodes", "pool_used",
                       "types", "zones", "ct", "alive"):
                assert np.array_equal(sv[nm], rv[nm]), (G, SUF, nm)
            for f, m in zip(jax.tree_util.tree_leaves(bank),
                            jax.tree_util.tree_leaves(nb)):
                assert np.array_equal(np.asarray(f), np.asarray(m)), \
                    (G, SUF, "bank drift on a clean arena")
