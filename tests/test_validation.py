"""Admission validation: CEL-rule analog enforcement at the fake API
server boundary, mirroring the reference's apis/v1 CRD suites
(karpenter.sh_nodepools.yaml / karpenter.k8s.aws_ec2nodeclasses.yaml
x-kubernetes-validations)."""

import pytest

from karpenter_provider_aws_tpu.apis import labels as L
from karpenter_provider_aws_tpu.apis.objects import (BlockDeviceMapping,
                                                     DisruptionBudget,
                                                     Disruption, EC2NodeClass,
                                                     KubeletConfiguration,
                                                     NodeClassRef, NodePool,
                                                     NodePoolTemplate,
                                                     SelectorTerm)
from karpenter_provider_aws_tpu.apis.requirements import Requirements
from karpenter_provider_aws_tpu.apis.validation import ValidationError
from karpenter_provider_aws_tpu.fake.kube import FakeKube


@pytest.fixture
def kube():
    return FakeKube()


def pool(name="p", requirements=(), labels=None, budgets=None,
         ref=None) -> NodePool:
    return NodePool(name, template=NodePoolTemplate(
        node_class_ref=ref or NodeClassRef("nc"),
        requirements=Requirements.from_terms(list(requirements)),
        labels=dict(labels or {})),
        disruption=Disruption(budgets=list(budgets))
        if budgets is not None else None)


class TestNodePoolRules:
    def test_valid_pool_accepted(self, kube):
        kube.create(pool(requirements=[
            {"key": L.INSTANCE_FAMILY, "operator": "In",
             "values": ["m5", "c5"]}]))

    def test_min_values_floor(self, kube):
        with pytest.raises(ValidationError, match="at least that many"):
            kube.create(pool(requirements=[
                {"key": L.INSTANCE_FAMILY, "operator": "In",
                 "values": ["m5", "c5"], "minValues": 3}]))

    def test_min_values_bounds(self, kube):
        with pytest.raises(ValidationError, match="minValues must be in"):
            kube.create(pool(requirements=[
                {"key": L.INSTANCE_FAMILY, "operator": "Exists",
                 "minValues": 51}]))

    def test_in_requires_values(self, kube):
        with pytest.raises(ValidationError, match="must have a value"):
            kube.create(pool(requirements=[
                {"key": L.INSTANCE_FAMILY, "operator": "In", "values": []}]))

    def test_restricted_domains(self, kube):
        for key, frag in (
                ("karpenter.sh/custom", 'domain "karpenter.sh"'),
                (L.NODEPOOL, '"karpenter.sh/nodepool" is restricted'),
                (L.HOSTNAME, '"kubernetes.io/hostname" is restricted'),
                ("kubernetes.io/foo", 'domain "kubernetes.io"'),
                ("kustomize.toolkit.fluxcd.k8s.io/x", 'domain "k8s.io"'),
                ("karpenter.k8s.aws/bogus", 'domain "karpenter.k8s.aws"')):
            with pytest.raises(ValidationError, match=frag):
                kube.create(pool(name=f"p-{key.replace('/', '-')}",
                                 requirements=[{"key": key,
                                                "operator": "Exists"}]))

    def test_allowed_special_labels(self, kube):
        kube.create(pool(name="ok", requirements=[
            {"key": L.CAPACITY_TYPE, "operator": "In", "values": ["spot"]},
            {"key": "kubernetes.io/arch", "operator": "In",
             "values": ["amd64"]},
            {"key": "node.kubernetes.io/instance-type", "operator": "Exists"},
            {"key": L.INSTANCE_CPU, "operator": "Gt", "values": ["4"]}]))

    def test_restricted_template_labels(self, kube):
        with pytest.raises(ValidationError, match="restricted"):
            kube.create(pool(labels={L.NODEPOOL: "x"}))

    def test_budget_schedule_needs_duration(self, kube):
        with pytest.raises(ValidationError,
                           match="'schedule' must be set with 'duration'"):
            kube.create(pool(budgets=[DisruptionBudget(
                nodes="10%", schedule="0 0 * * *")]))

    def test_nodeclass_ref_nonempty(self, kube):
        with pytest.raises(ValidationError, match="name may not be empty"):
            kube.create(pool(ref=NodeClassRef("")))

    def test_nodeclass_ref_immutable(self, kube):
        p = kube.create(pool())
        import copy
        p2 = copy.deepcopy(p)
        p2.template.node_class_ref.group = "other.group"
        with pytest.raises(ValidationError, match="group is immutable"):
            kube.update(p2)


class TestEC2NodeClassRules:
    def test_default_accepted(self, kube):
        kube.create(EC2NodeClass("ok"))

    def test_empty_subnet_terms_rejected(self, kube):
        with pytest.raises(ValidationError,
                           match="subnetSelectorTerms cannot be empty"):
            kube.create(EC2NodeClass("bad", subnet_selector_terms=()))

    def test_empty_sg_terms_rejected(self, kube):
        with pytest.raises(
                ValidationError,
                match="securityGroupSelectorTerms cannot be empty"):
            kube.create(EC2NodeClass("bad2",
                                     security_group_selector_terms=()))

    def test_term_needs_a_field(self, kube):
        with pytest.raises(ValidationError, match="expected at least one"):
            kube.create(EC2NodeClass(
                "bad3", subnet_selector_terms=(SelectorTerm(),)))

    def test_id_mutually_exclusive(self, kube):
        with pytest.raises(ValidationError, match="mutually exclusive"):
            kube.create(EC2NodeClass("bad4", subnet_selector_terms=(
                SelectorTerm.of({"a": "b"}, id="subnet-123"),)))

    def test_alias_mutually_exclusive_with_other_terms(self, kube):
        with pytest.raises(ValidationError, match="mutually exclusive"):
            kube.create(EC2NodeClass("bad5", ami_selector_terms=(
                SelectorTerm(alias="al2023@latest"),
                SelectorTerm.of({"a": "b"}))))

    def test_alias_format(self, kube):
        with pytest.raises(ValidationError, match="improperly formatted"):
            kube.create(EC2NodeClass("bad6", ami_selector_terms=(
                SelectorTerm(alias="al2023latest"),)))

    def test_alias_family_supported(self, kube):
        with pytest.raises(ValidationError, match="family is not supported"):
            kube.create(EC2NodeClass("bad7", ami_selector_terms=(
                SelectorTerm(alias="cos@latest"),)))

    def test_windows_version_latest_only(self, kube):
        with pytest.raises(ValidationError, match="only specify version"):
            kube.create(EC2NodeClass("bad8", ami_selector_terms=(
                SelectorTerm(alias="windows2022@v1.2"),)))

    def test_empty_tag_values(self, kube):
        with pytest.raises(ValidationError, match="empty tag keys"):
            kube.create(EC2NodeClass("bad9", subnet_selector_terms=(
                SelectorTerm.of({"key": ""}),)))

    def test_one_root_volume(self, kube):
        with pytest.raises(ValidationError, match="only one"):
            kube.create(EC2NodeClass("bad10", block_device_mappings=[
                BlockDeviceMapping(device_name="/dev/xvda", root_volume=True),
                BlockDeviceMapping(device_name="/dev/xvdb",
                                   root_volume=True)]))

    def test_restricted_tags(self, kube):
        with pytest.raises(ValidationError, match="restricted"):
            kube.create(EC2NodeClass(
                "bad11", tags={"karpenter.sh/nodepool": "x"}))

    def test_kubelet_eviction_keys(self, kube):
        with pytest.raises(ValidationError, match="valid keys for"):
            kube.create(EC2NodeClass("bad12", kubelet=KubeletConfiguration(
                eviction_hard={"bogus.signal": "5%"})))

    def test_kubelet_reserved_keys(self, kube):
        with pytest.raises(ValidationError, match="valid keys for"):
            kube.create(EC2NodeClass("bad13", kubelet=KubeletConfiguration(
                kube_reserved={"gpu": "1"})))

    def test_role_required(self, kube):
        with pytest.raises(ValidationError, match="role cannot be empty"):
            kube.create(EC2NodeClass("bad14", role=""))

    def test_role_immutable(self, kube):
        nc = kube.create(EC2NodeClass("mut"))
        import copy
        nc2 = copy.deepcopy(nc)
        nc2.role = "OtherRole"
        with pytest.raises(ValidationError, match="immutable field changed"):
            kube.update(nc2)
