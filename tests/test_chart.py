"""Chart rendering: deploy/chart + hack/render_chart.py must produce
valid manifests with every value overridable — the one-command-install
packaging analog of charts/karpenter (values.yaml:38)."""

import os
import subprocess
import sys

import yaml

ROOT = os.path.join(os.path.dirname(__file__), "..")
RENDER = os.path.join(ROOT, "hack", "render_chart.py")


def render(*sets):
    cmd = [sys.executable, RENDER]
    for s in sets:
        cmd += ["--set", s]
    out = subprocess.run(cmd, capture_output=True, text=True, check=True)
    return list(d for d in yaml.safe_load_all(out.stdout) if d is not None)


def by_kind(docs, kind):
    return [d for d in docs if d["kind"] == kind]


class TestChartRender:
    def test_default_render_is_valid(self):
        docs = render()
        kinds = {d["kind"] for d in docs}
        assert {"Namespace", "ServiceAccount", "ConfigMap", "Deployment",
                "Service"} <= kinds

    def test_values_flow_into_flags_and_replicas(self):
        docs = render("settings.clusterName=prod",
                      "settings.interruptionQueue=intr-q",
                      "settings.reservedENIs=2",
                      "replicas=3",
                      "image.tag=v9",
                      "controller.solver=cpu")
        dep = by_kind(docs, "Deployment")[0]
        spec = dep["spec"]["template"]["spec"]
        assert dep["spec"]["replicas"] == 3
        ctr = spec["containers"][0]
        assert ctr["image"].endswith(":v9")
        args = ctr["args"]
        assert "--cluster-name=prod" in args
        assert "--interruption-queue=intr-q" in args
        assert "--reserved-enis=2" in args
        assert "--solver=cpu" in args

    def test_conditional_flags_absent_by_default(self):
        docs = render()
        args = by_kind(docs, "Deployment")[0][
            "spec"]["template"]["spec"]["containers"][0]["args"]
        assert not any(a.startswith("--interruption-queue") for a in args)
        assert not any(a.startswith("--cluster-endpoint") for a in args)
        assert "--isolated-vpc" not in args
        assert "--eks-control-plane" in args  # default true

    def test_sidecar_toggle(self):
        assert len(render()[0] and by_kind(render(), "Deployment")[0][
            "spec"]["template"]["spec"]["containers"]) == 1
        docs = render("sidecar.enabled=true")
        names = [c["name"] for c in by_kind(docs, "Deployment")[0][
            "spec"]["template"]["spec"]["containers"]]
        assert names == ["controller", "solver-sidecar"]

    def test_resources_overridable(self):
        docs = render("controller.resources.requests.cpu=4")
        ctr = by_kind(docs, "Deployment")[0][
            "spec"]["template"]["spec"]["containers"][0]
        assert ctr["resources"]["requests"]["cpu"] == "4"

    def test_crds_ship_alongside(self):
        crds = os.listdir(os.path.join(ROOT, "deploy", "crds"))
        assert {"karpenter.sh_nodepools.yaml", "karpenter.sh_nodeclaims.yaml",
                "karpenter.k8s.aws_ec2nodeclasses.yaml"} <= set(crds)
