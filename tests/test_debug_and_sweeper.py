"""Debug transition watchers (test/pkg/debug analog) + leaked-resource
sweeper (test/hack/resource analog)."""

import sys


from karpenter_provider_aws_tpu.apis.objects import (EC2NodeClass,
                                                     NodeClassRef, NodePool,
                                                     NodePoolTemplate)
from karpenter_provider_aws_tpu.fake.environment import make_pods
from karpenter_provider_aws_tpu.operator import Operator
from karpenter_provider_aws_tpu.utils.debug import attach

sys.path.insert(0, ".")
from hack.sweeper import sweep  # noqa: E402


def mk(op):
    op.kube.create(EC2NodeClass("dbg-class"))
    op.kube.create(NodePool("default", template=NodePoolTemplate(
        node_class_ref=NodeClassRef("dbg-class"))))


class TestTransitionWatcher:
    def test_logs_full_lifecycle(self):
        op = Operator()
        mk(op)
        watcher = attach(op.kube)
        for p in make_pods(1, cpu="500m", memory="1Gi", prefix="dbg"):
            op.kube.create(p)
        op.run_until_settled()
        watcher.drain()
        joined = "\n".join(watcher.transitions)
        # the whole chain is visible: pod pending -> claim launched ->
        # registered -> initialized -> node ready -> pod running
        assert "Pod/default/dbg" in joined
        assert "launched:False->True" in joined
        assert "registered:False->True" in joined
        assert "initialized:False->True" in joined
        assert "phase:Pending->Running" in joined
        assert any(line.startswith("Node/") and "ready:None->True" in line
                   for line in watcher.transitions)

    def test_resync_noise_suppressed(self):
        op = Operator()
        mk(op)
        for p in make_pods(1, cpu="500m", memory="1Gi", prefix="quiet"):
            op.kube.create(p)
        op.run_until_settled()
        watcher = attach(op.kube)   # attaches AFTER steady state
        watcher.drain()             # initial-list replay -> baselines
        base = len(watcher.transitions)
        op.run_until_settled()      # no-op reconciles re-update objects
        watcher.drain()
        # steady-state updates that change nothing are not transitions
        assert len(watcher.transitions) == base

    def test_deletion_logged(self):
        op = Operator()
        mk(op)
        for p in make_pods(1, cpu="500m", memory="1Gi", prefix="del"):
            op.kube.create(p)
        op.run_until_settled()
        watcher = attach(op.kube)
        watcher.drain()
        claim = op.kube.list("NodeClaim")[0]
        op.kube.delete("NodeClaim", claim.name)
        op.run_until_settled()
        watcher.drain()
        assert any(ln == f"NodeClaim//{claim.name} DELETED"
                   for ln in watcher.transitions)


class TestSweeper:
    def test_orphan_instance_swept_after_grace(self):
        op = Operator()
        mk(op)
        for p in make_pods(2, cpu="500m", memory="1Gi", prefix="sw"):
            op.kube.create(p)
        op.run_until_settled()
        victim = op.kube.list("NodeClaim")[0]
        inst_id = victim.provider_id.split("/")[-1]
        op.kube.remove_finalizer(victim, "karpenter.sh/termination")
        op.kube.delete("NodeClaim", victim.name)
        # within grace: untouched
        assert sweep(op)["instances"] == []
        op.ec2.instances[inst_id].launch_time -= 120
        reaped = sweep(op)
        assert reaped["instances"] == [inst_id]
        assert op.ec2.instances[inst_id].state == "terminated"

    def test_launch_templates_of_deleted_nodeclass_swept(self):
        op = Operator()
        mk(op)
        for p in make_pods(1, cpu="500m", memory="1Gi", prefix="lt"):
            op.kube.create(p)
        op.run_until_settled()
        assert op.ec2.describe_launch_templates()
        # nodeclass vanishes without the deletion flow (leak scenario:
        # finalizer force-removed, e.g. a kubectl patch during an outage)
        nc = op.kube.get("EC2NodeClass", "dbg-class")
        op.kube.remove_finalizer(nc, "karpenter.k8s.aws/termination")
        if op.kube.try_get("EC2NodeClass", "dbg-class"):
            op.kube.delete("EC2NodeClass", "dbg-class")
        reaped = sweep(op)
        assert reaped["launch_templates"]
        assert not [lt for lt in op.ec2.describe_launch_templates()
                    if "/dbg-class/" in lt.name]

    def test_healthy_cluster_untouched(self):
        op = Operator()
        mk(op)
        for p in make_pods(2, cpu="500m", memory="1Gi", prefix="ok"):
            op.kube.create(p)
        op.run_until_settled()
        reaped = sweep(op)
        assert reaped == {"instances": [], "launch_templates": []}
