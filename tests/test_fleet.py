"""Horizontal solver fleet: shape-affine routing + chaos.

The fleet contract, from the top of docs/fleet.md:

- AFFINITY: a (tenant, shape-class) key routes to ONE replica via
  rendezvous hashing — deterministic fleet-wide, minimal movement on
  membership change — so warm ticks keep their hot kernels, bucketed
  shapes, and server-resident patch arena on one peer.
- FAILOVER: the ring gives a total preference order; a parked replica's
  keys move to the SAME next peer for every client.
- RE-PRIME: any binding move deliberately breaks the patch stream
  (endpoint-scoped state clears) so the next tick rides PR 10's
  no_resident path — ONE full Solve, never a stale delta —
  and karpenter_solver_fleet_reprimes_total counts exactly those.
- DEGRADATION: unchanged — a dead pick costs a wire attempt; the
  bit-identical host twin serves; decisions stay oracle-identical
  through every kill/flap/roll this file throws at the fleet.
"""

import random
import time

import numpy as np
import pytest

from karpenter_provider_aws_tpu.fake.environment import Environment, make_pods
from karpenter_provider_aws_tpu.fake.faultwire import (FleetChaosPlan,
                                                       downgrade_server)
from karpenter_provider_aws_tpu.fleet import (FleetMembership, FleetSolver,
                                              loopback_fleet, owner_order,
                                              shape_class)
from karpenter_provider_aws_tpu.sidecar import (RemoteSolver, SolverClient,
                                                SolverServer)
from karpenter_provider_aws_tpu.sidecar.resilience import (OPEN,
                                                           CircuitBreaker,
                                                           ResiliencePolicy,
                                                           RetryPolicy)
from karpenter_provider_aws_tpu.solver import CPUSolver
from karpenter_provider_aws_tpu.solver.route import DEV_FAILED_MS, Router
from karpenter_provider_aws_tpu.tenancy.admission import PatchArenaTable
from karpenter_provider_aws_tpu.utils.metrics import Metrics


@pytest.fixture(scope="module")
def env():
    return Environment()


_SIG_SEQ = [0]


def _churn_snaps(env, n_ticks, churn=2, seed=17, prefix=None, groups=8):
    """Warm-tick replay: stable pod-group population, `churn` swaps per
    tick — the delta-wire regime (same fixture family as
    tests/test_patch_wire.py)."""
    if prefix is None:
        _SIG_SEQ[0] += 1
        prefix = f"ft{_SIG_SEQ[0]}"
    pool = env.nodepool(prefix)
    sigs = [dict(cpu=f"{100 + (i * 7) % 400}m",
                 memory=f"{256 + (i * 13) % 700}Mi",
                 group=f"{prefix}g{i:03d}") for i in range(groups)]
    rng = random.Random(seed)

    def mk(gi):
        return make_pods(1, cpu=sigs[gi]["cpu"], memory=sigs[gi]["memory"],
                         prefix=sigs[gi]["group"], group=sigs[gi]["group"])

    cur = []
    for gi in range(len(sigs)):
        for _ in range(2):
            cur.extend(mk(gi))
    snaps = [env.snapshot(list(cur), [pool])]
    for _ in range(n_ticks - 1):
        for _ in range(churn):
            cur.pop(rng.randrange(len(cur)))
            cur.extend(mk(rng.randrange(len(sigs))))
        snaps.append(env.snapshot(list(cur), [pool]))
    return snaps


def _oracle_prints(snaps):
    oracle = CPUSolver()
    return [oracle.solve(s).decision_fingerprint() for s in snaps]


def _policy_factory(max_attempts=2, threshold=2, cooldown_s=60.0):
    def pf(address):
        return ResiliencePolicy(
            retry=RetryPolicy(max_attempts=max_attempts,
                              sleep=lambda s: None),
            breaker=CircuitBreaker(threshold=threshold,
                                   cooldown_s=cooldown_s))
    return pf


def _fleet(n, metrics=None, tenant="t1", seed_policy=True, **kw):
    servers = [SolverServer(metrics=metrics).start() for _ in range(n)]
    ms = FleetMembership(
        [s.address for s in servers],
        policy_factory=_policy_factory() if seed_policy else None)
    solver = FleetSolver(membership=ms, n_max=64, backend="jax",
                         tenant=tenant, metrics=metrics, **kw)
    for a in ms.addresses():
        ms.get(a).client.timeout = 5.0
    solver._router.alive.mark_ok()
    return servers, solver


def _stop_all(servers, solver):
    solver.close()
    for s in servers:
        try:
            s.stop()
        except Exception:
            pass


def _count(metrics, name, **labels):
    total = 0.0
    for (n, lbl), v in metrics.counters.items():
        if n == name and all(dict(lbl).get(k) == want
                             for k, want in labels.items()):
            total += v
    return total


# ---------------------------------------------------------------------------
# ring


class TestRing:
    def test_order_is_deterministic_and_total(self):
        eps = [f"replica-{i}:50151" for i in range(5)]
        for tenant in ("a", "b", None):
            for shape in ((1, 2, 3), (4,) * 10):
                o1 = owner_order(eps, tenant, shape)
                o2 = owner_order(list(reversed(eps)), tenant, shape)
                assert o1 == o2  # input order never matters
                assert sorted(o1) == sorted(eps)  # total order

    def test_minimal_disruption_on_leave(self):
        """Removing one replica re-homes ONLY the keys it owned; every
        other key keeps its owner AND its full failover order — the
        HRW property the patch arenas' survival depends on."""
        eps = [f"r{i}:1" for i in range(4)]
        keys = [("t%d" % (i % 5), (i, i * 7 % 13, 3)) for i in range(60)]
        gone = eps[2]
        for tenant, shape in keys:
            before = owner_order(eps, tenant, shape)
            after = owner_order([e for e in eps if e != gone],
                                tenant, shape)
            assert after == [e for e in before if e != gone]

    def test_spread_across_replicas(self):
        eps = [f"r{i}:1" for i in range(4)]
        owners = {owner_order(eps, f"tenant-{i}", (8, 16, 4))[0]
                  for i in range(40)}
        assert len(owners) >= 3  # 40 tenants land on >=3 of 4 replicas

    def test_shape_class_is_patch_layout(self):
        from karpenter_provider_aws_tpu.sidecar.server import \
            PATCH_LAYOUT_KEYS
        st = {k: i + 1 for i, k in enumerate(PATCH_LAYOUT_KEYS)}
        st["unrelated"] = 99
        assert shape_class(st) == tuple(
            st[k] for k in PATCH_LAYOUT_KEYS)


# ---------------------------------------------------------------------------
# per-endpoint router evidence (satellite: the shared-verdict poisoning fix)


class TestRouterPerEndpoint:
    def test_slow_replica_does_not_poison_peer_verdict(self):
        r = Router()
        b = ("shape",)
        r.endpoint = "fast:1"
        r.observe(b, "host", 50.0)
        r.observe(b, "dev", 1.0)
        r.endpoint = "slow:1"
        for _ in range(10):
            r.observe(b, "dev", 5000.0)
        assert r.choose(b)[0] == "host"  # slow replica routes host
        r.endpoint = "fast:1"
        assert r.snapshot()[b]["dev"] == 1.0  # untouched by the peer
        assert r.choose(b)[0] == "dev"

    def test_park_endpoint_leaves_peers_routed(self):
        r = Router()
        b = ("shape",)
        for ep in ("a:1", "b:1"):
            r.endpoint = ep
            r.observe(b, "dev", 2.0)
        r.park_dev(endpoint="a:1")
        r.endpoint = "a:1"
        assert r.snapshot()[b]["dev"] == DEV_FAILED_MS
        r.endpoint = "b:1"
        assert r.snapshot()[b]["dev"] == 2.0

    def test_fresh_endpoint_inherits_aggregate(self):
        """A scale-out replica with no history starts from the fleet's
        non-parked mean instead of re-calibrating (and a parked peer is
        excluded from that mean)."""
        r = Router()
        b = ("shape",)
        r.endpoint = "a:1"
        r.observe(b, "dev", 10.0)
        r.endpoint = "b:1"
        r.observe(b, "dev", 30.0)
        r.park_dev(endpoint="b:1")
        r.endpoint = "new:1"
        assert r.snapshot()[b]["dev"] == 10.0  # a's evidence only

    def test_forget_endpoint_drops_evidence(self):
        r = Router()
        b = ("shape",)
        r.endpoint = "a:1"
        r.observe(b, "dev", 10.0)
        r.forget_endpoint("a:1")
        r.endpoint = "new:1"
        assert r.snapshot()[b]["dev"] is None

    def test_legacy_single_endpoint_untouched(self):
        """endpoint=None keeps the exact pre-fleet semantics (pinned
        separately by tests/test_resilience.py park/unpark tests)."""
        r = Router()
        b = ("b",)
        r.observe(b, "dev", 10.0)
        r.observe(b, "host", 20.0)
        assert r.choose(b)[0] == "dev"
        r.park_dev()
        assert r.snapshot()[b]["dev"] == DEV_FAILED_MS


# ---------------------------------------------------------------------------
# membership


class TestMembership:
    def test_env_config(self, monkeypatch):
        from karpenter_provider_aws_tpu.fleet import endpoints_from_env
        monkeypatch.setenv("SOLVER_FLEET_ENDPOINTS",
                           "s-0.solver:50151, s-1.solver:50151")
        assert endpoints_from_env() == ["s-0.solver:50151",
                                        "s-1.solver:50151"]
        monkeypatch.setenv("SOLVER_FLEET_ENDPOINTS", "")
        monkeypatch.setenv("SOLVER_SIDECAR_ADDRESS", "one:50151")
        assert endpoints_from_env() == ["one:50151"]

    def test_breaker_open_parks_only_that_replica(self):
        ms = FleetMembership(["a:1", "b:1"],
                             policy_factory=_policy_factory(threshold=1))
        router = Router()
        ms.router = router
        b = ("shape",)
        for ep in ("a:1", "b:1"):
            router.endpoint = ep
            router.observe(b, "dev", 2.0)
        pol = ms.get("a:1").policy
        with pytest.raises(Exception):
            pol.call(lambda d: (_ for _ in ()).throw(
                _unavailable()), rpc="Solve")
        assert pol.breaker.state == OPEN
        assert not ms.routable("a:1")
        assert ms.routable("b:1")
        router.endpoint = "a:1"
        assert router.snapshot()[b]["dev"] == DEV_FAILED_MS
        router.endpoint = "b:1"
        assert router.snapshot()[b]["dev"] == 2.0
        ms.close()

    def test_probe_records_health_and_caps(self):
        srv = SolverServer().start()
        ms = FleetMembership([srv.address, "127.0.0.1:1"],
                             policy_factory=_policy_factory(threshold=50))
        try:
            assert ms.probe(srv.address) is True
            assert ms.get(srv.address).caps.get("patch") is True
            assert ms.probe("127.0.0.1:1", timeout=0.5) is False
            assert not ms.routable("127.0.0.1:1")
            assert ms.alive() == [srv.address]
        finally:
            ms.close()
            srv.stop()

    def test_replicas_gauge_follows_membership(self):
        m = Metrics()
        ms = FleetMembership(["a:1", "b:1"], metrics=m,
                             policy_factory=_policy_factory())
        assert m.gauge("karpenter_solver_fleet_replicas") == 2.0
        ms.remove("a:1")
        assert m.gauge("karpenter_solver_fleet_replicas") == 1.0
        ms.add("c:1")
        assert m.gauge("karpenter_solver_fleet_replicas") == 2.0
        ms.close()


def _unavailable():
    from karpenter_provider_aws_tpu.fake.faultwire import _injected_error
    import grpc
    return _injected_error(grpc.StatusCode.UNAVAILABLE, "test: down")


# ---------------------------------------------------------------------------
# endpoint-tied capabilities (satellite regression: no SolvePatch frame
# may ever ship to a legacy replica after failover)


class TestEndpointCaps:
    def test_bind_client_clears_endpoint_state(self):
        srv = SolverServer().start()
        try:
            remote = RemoteSolver(srv.address, n_max=64, backend="jax")
            remote._router.alive.mark_ok()
            assert remote._ping()
            assert remote.supports_batch_kernel
            remote._patch_srv = dict(shape=(1,), epoch=(0, 0), version=3)
            old_gen = remote._bind_gen
            assert remote.bind_client(SolverClient(srv.address))
            assert remote._bind_gen == old_gen + 1
            assert remote._patch_srv is None  # residency prediction died
            assert remote.supports_batch_kernel  # re-resolved by the ping
        finally:
            srv.stop()

    def test_stale_caps_never_apply_across_rebind(self):
        """Flags resolved under binding N must read False under binding
        N+1 until ITS ping lands — even if the attribute survives."""
        srv = SolverServer().start()
        try:
            remote = RemoteSolver(srv.address, n_max=64, backend="jax")
            remote._router.alive.mark_ok()
            assert remote._ping()
            assert remote.supports_subset_kernel
            # simulate a re-route that somehow skipped the flag clear:
            remote._bind_gen += 1
            assert not remote.supports_subset_kernel
            assert not remote.supports_batch_kernel
            assert remote._patch_plan(np.zeros(4, dtype=np.int64),
                                      {}) is None
        finally:
            srv.stop()

    def test_failover_to_legacy_ships_no_patch_frame(self, env):
        """THE regression: warm patch stream against a patch-capable
        replica, then failover to a legacy build — zero SolvePatch
        frames may reach the legacy peer, decisions stay oracle-
        identical, and the flags re-resolve to the legacy truth."""
        modern = SolverServer().start()
        legacy = SolverServer().start()
        restore = downgrade_server(legacy, drop=("patch",))
        arrivals = {"patch": 0}
        # downgrade_server already swapped solve_patch for the
        # UNIMPLEMENTED shim; count around THAT so any arrival at all
        # is visible even though it would be rejected
        shim = legacy._handler.solve_patch

        def counting_shim(request, context):
            arrivals["patch"] += 1
            return shim(request, context)
        legacy._handler.solve_patch = counting_shim
        try:
            m = Metrics()
            remote = RemoteSolver(modern.address, n_max=64,
                                  backend="jax")
            remote.metrics = m
            remote._router.alive.mark_ok()
            assert remote._ping()
            snaps = _churn_snaps(env, 8)
            oracle = _oracle_prints(snaps)
            got = [remote.solve(s).decision_fingerprint()
                   for s in snaps[:4]]
            assert _count(m, "karpenter_solver_wire_patch_total") > 0
            # failover: rebind onto the legacy replica
            assert remote.bind_client(SolverClient(legacy.address))
            assert not remote._patch_ok  # legacy Info has no flag
            got += [remote.solve(s).decision_fingerprint()
                    for s in snaps[4:]]
            assert got == oracle
            assert arrivals["patch"] == 0
        finally:
            restore()
            modern.stop()
            legacy.stop()


# ---------------------------------------------------------------------------
# FleetSolver behavior


class TestFleetSteady:
    def test_warm_ticks_stay_on_one_replica_and_ride_deltas(self, env):
        m = Metrics()
        servers, solver = _fleet(2, metrics=m)
        try:
            snaps = _churn_snaps(env, 8)
            got = [solver.solve(s).decision_fingerprint() for s in snaps]
            assert got == _oracle_prints(snaps)
            # warm ticks pinned: once bound, every dispatch is affinity
            # on ONE replica
            per_replica = {}
            for (n, lbl), v in m.counters.items():
                if n == "karpenter_solver_fleet_routed_total":
                    per_replica.setdefault(
                        dict(lbl)["replica"], 0)
                    per_replica[dict(lbl)["replica"]] += v
            assert per_replica.get(solver._bound, 0) >= len(snaps) - 1
            # and they ride the delta wire, not full frames
            assert _count(m, "karpenter_solver_wire_patch_total",
                          kind="delta") > 0
            assert _count(
                m, "karpenter_solver_fleet_reprimes_total") == 0
        finally:
            _stop_all(servers, solver)

    def test_two_tenants_can_land_on_distinct_replicas(self, env):
        """The load-spreading half of affinity: tenants hash
        independently, so SOME tenant pair splits across a 2-fleet.
        (Seeded fixture: these two do.)"""
        servers = [SolverServer().start() for _ in range(2)]
        addrs = [s.address for s in servers]
        shape = (1, 2, 3, 4, 5, 6, 7, 8, 9, 10)
        owners = {owner_order(addrs, f"tenant-{i}", shape)[0]
                  for i in range(16)}
        for s in servers:
            s.stop()
        assert owners == set(addrs)


class TestFleetKill:
    def test_kill_mid_patch_stream(self, env):
        """Kill the bound owner mid-stream: the killed tick degrades to
        the host twin (fingerprint-identical), the next binds the ring's
        next replica, exactly ONE re-prime is counted, and the delta
        stream resumes on the new owner."""
        m = Metrics()
        servers, solver = _fleet(2, metrics=m)
        try:
            snaps = _churn_snaps(env, 12)
            oracle = _oracle_prints(snaps)
            got = [solver.solve(s).decision_fingerprint()
                   for s in snaps[:6]]
            assert _count(m, "karpenter_solver_wire_patch_total",
                          kind="delta") > 0
            bound = solver._bound
            for s in servers:
                if s.address == bound:
                    s.stop()
            got += [solver.solve(s).decision_fingerprint()
                    for s in snaps[6:]]
            assert got == oracle
            assert solver._bound != bound
            assert _count(
                m, "karpenter_solver_fleet_reprimes_total") == 1.0
            assert _count(m, "karpenter_solver_fleet_routed_total",
                          reason="failover") > 0
            # the break cost exactly one full Solve: one transport
            # fallback on the dying patch, then the new owner was
            # re-primed and deltas resumed
            assert _count(m, "karpenter_solver_wire_fallback_total",
                          reason="transport") == 1.0
            hist = m.histograms.get(
                ("karpenter_solver_fleet_handoff_ms", ()))
            assert hist and len(hist) >= 1
        finally:
            _stop_all(servers, solver)


class TestFleetFlap:
    def test_membership_flap_rebalances_and_reprimes(self, env):
        """Flap the bound owner OUT of membership (config re-render) and
        back IN: both moves are planned rebalances, each breaking the
        stream costs one counted re-prime, decisions never diverge."""
        m = Metrics()
        servers, solver = _fleet(2, metrics=m)
        ms = solver._fleet
        try:
            snaps = _churn_snaps(env, 14)
            oracle = _oracle_prints(snaps)
            got = [solver.solve(s).decision_fingerprint()
                   for s in snaps[:5]]
            home = solver._bound
            rep = ms.get(home)
            ms.remove(home)
            got += [solver.solve(s).decision_fingerprint()
                    for s in snaps[5:10]]
            assert solver._bound != home
            assert _count(m, "karpenter_solver_fleet_routed_total",
                          reason="rebalance") > 0
            reprimes_mid = _count(
                m, "karpenter_solver_fleet_reprimes_total")
            assert reprimes_mid == 1.0
            ms.add(home, client=rep.client)  # flap back in
            got += [solver.solve(s).decision_fingerprint()
                    for s in snaps[10:]]
            assert solver._bound == home  # the ring owner reclaims
            assert got == oracle
            assert _count(
                m, "karpenter_solver_fleet_reprimes_total") == 2.0
        finally:
            _stop_all(servers, solver)


class TestFleetRoll:
    def test_roll_owner_to_legacy_build(self, env):
        """Roll the bound owner to a build without `patch` mid-stream:
        the first patch after the roll is answered UNIMPLEMENTED, the
        tick rides one full Solve, the flag clears, and NO further
        SolvePatch frame ships — while decisions stay oracle-identical."""
        m = Metrics()
        servers, solver = _fleet(2, metrics=m)
        try:
            snaps = _churn_snaps(env, 12)
            oracle = _oracle_prints(snaps)
            got = [solver.solve(s).decision_fingerprint()
                   for s in snaps[:6]]
            owner_srv = next(s for s in servers
                             if s.address == solver._bound)
            restore = downgrade_server(owner_srv, drop=("patch",))
            arrivals = {"n": 0}
            shim = owner_srv._handler.solve_patch

            def counting(request, context):
                arrivals["n"] += 1
                return shim(request, context)
            owner_srv._handler.solve_patch = counting
            got += [solver.solve(s).decision_fingerprint()
                    for s in snaps[6:]]
            assert got == oracle
            # exactly the one in-flight patch hit the rolled build;
            # after its UNIMPLEMENTED verdict the gate closed for good
            assert arrivals["n"] == 1
            assert not solver._patch_ok
            assert _count(m, "karpenter_solver_wire_fallback_total",
                          reason="unimplemented") == 1.0
            restore()
        finally:
            _stop_all(servers, solver)


# ---------------------------------------------------------------------------
# PatchArenaTable two-replica isolation (satellite: tenancy/admission.py)


class TestPatchArenaTwoReplica:
    KEY_A = ("tenant-a", (1, 2, 3), 7, (0, 0))
    KEY_B = ("tenant-b", (1, 2, 3), 9, (0, 0))

    def test_arenas_never_cross_replicas(self):
        """Each replica process owns its own table: residency primed on
        replica 1 is invisible to replica 2 (the client's re-prime on
        failover is CORRECT behavior, not an optimization gap)."""
        t1, t2 = PatchArenaTable(), PatchArenaTable()
        buf = np.arange(16, dtype=np.int64)
        assert t1.prime(self.KEY_A, buf, version=4, tenant="tenant-a")
        assert t1.version_of(self.KEY_A) == 4
        assert t2.version_of(self.KEY_A) is None  # never crossed
        assert len(t2) == 0

    def test_eviction_attribution_per_tenant_per_replica(self):
        """Evictions bill the admitting tenant ON THE REPLICA that
        evicted — replica 2's registry never sees replica 1's churn."""
        clock = [0.0]
        m1, m2 = Metrics(), Metrics()
        t1 = PatchArenaTable(capacity=2, min_idle_s=0.0, metrics=m1,
                             clock=lambda: clock[0])
        t2 = PatchArenaTable(capacity=2, min_idle_s=0.0, metrics=m2,
                             clock=lambda: clock[0])
        buf = np.arange(8, dtype=np.int64)
        assert t1.prime(self.KEY_A, buf, version=1, tenant="tenant-a")
        clock[0] += 1.0
        assert t1.prime(self.KEY_B, buf, version=1, tenant="tenant-b")
        clock[0] += 1.0
        # replica 1 overflows: the LRU entry (tenant-a's) is evicted
        # and billed to tenant-a on m1
        assert t1.prime(("tenant-c", (9,), 1, (0, 0)), buf, version=1,
                        tenant="tenant-c")
        assert _count(m1,
                      "karpenter_solver_wire_resident_evictions_total",
                      tenant="tenant-a", reason="lru") == 1.0
        assert _count(m2,
                      "karpenter_solver_wire_resident_evictions_total"
                      ) == 0.0
        # replica 2 still has capacity for the same tenants
        assert t2.prime(self.KEY_A, buf, version=1, tenant="tenant-a")
        assert t2.version_of(self.KEY_A) == 1
        assert t1.version_of(self.KEY_A) is None  # evicted there


# ---------------------------------------------------------------------------
# the seeded multi-replica chaos sweep (slow tier; hack/chaosfleet.sh)


CHAOS_SEEDS = (3, 7, 11, 17, 23)


@pytest.mark.slow
@pytest.mark.parametrize("seed", CHAOS_SEEDS)
def test_fleet_chaos_sweep(env, seed):
    """Seeded kill/flap/roll sweep over a 3-replica fleet: every tick's
    decision lands fingerprint-identical to the CPU oracle, per-tick
    wall time stays bounded, and every counted re-prime corresponds to
    a binding move that broke an active stream (never more than the
    disruptions the schedule applied)."""
    m = Metrics()
    servers, solver = _fleet(3, metrics=m)
    ms = solver._fleet
    plan = FleetChaosPlan(seed)
    killed, flapped, rolled = [], [], {}
    moves = revives = stream_moves = 0  # stream_moves: a kill/flap
    # that lands while a patch stream is live MUST cost one re-prime
    try:
        snaps = _churn_snaps(env, 24, seed=seed)
        oracle = _oracle_prints(snaps)
        tick_ms = []
        for i, snap in enumerate(snaps):
            action = plan.next(i)
            if action == "kill" and len(killed) < len(servers) - 1:
                srv = next((s for s in servers
                            if s.address == solver._bound
                            and s.address not in killed), None)
                if srv is not None:
                    if solver._stream_active \
                            or solver._patch_srv is not None:
                        stream_moves += 1
                    srv.stop()
                    killed.append(srv.address)
                    moves += 1
            elif action == "revive":
                if flapped:
                    addr, rep = flapped.pop()
                    ms.add(addr, client=rep.client)
                    revives += 1  # the ring owner may reclaim its keys
                elif rolled:
                    addr, restore = rolled.popitem()
                    restore()
            elif action == "flap" and len(ms.addresses()) > 1:
                addr = solver._bound
                if addr not in killed and addr in ms.addresses():
                    if solver._stream_active \
                            or solver._patch_srv is not None:
                        stream_moves += 1
                    rep = ms.get(addr)
                    ms.remove(addr)
                    flapped.append((addr, rep))
                    moves += 1
            elif action == "roll":
                # rolls degrade a replica's BUILD, not the binding: a
                # rolled owner costs one unimplemented fallback, never
                # a re-prime
                live = [s for s in servers
                        if s.address not in killed
                        and s.address not in rolled]
                if live:
                    srv = live[0]
                    rolled[srv.address] = downgrade_server(
                        srv, drop=("patch",))
            t0 = time.perf_counter()
            got = solver.solve(snap).decision_fingerprint()
            tick_ms.append((time.perf_counter() - t0) * 1e3)
            assert got == oracle[i], \
                f"seed {seed} tick {i} diverged after {action}"
        reprimes = _count(m, "karpenter_solver_fleet_reprimes_total")
        # every counted re-prime must correspond to a binding move:
        # a kill/flap moves the stream off the owner, a revive may move
        # it back; +1 slack for the initial ring placement
        assert reprimes <= moves + revives + 1
        if stream_moves and len(killed) < len(servers):
            assert reprimes >= 1
        tick_ms.sort()
        p99 = tick_ms[int(0.99 * (len(tick_ms) - 1))]
        # generous CI bound: the point is no unbounded stall (a hung
        # failover would sit on a 5s deadline * retries)
        assert p99 < 30_000, f"seed {seed} p99 {p99:.0f}ms unbounded"
    finally:
        _stop_all(servers, solver)
