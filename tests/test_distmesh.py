"""Cross-process distributed mesh (parallel/distmesh.py) and its
coordinator (fleet/meshgroup.py).

Fast tier: config/geometry/workload/wire units and the single-process
twins of the distributed paths (dispatch_dist on an in-process 2-D
mesh, MeshGroup local mode, the degradation taxonomy) — everything
that doesn't need a second OS process. The `slow` tier spawns REAL
worker subprocesses joined by jax.distributed and pins the
cross-process solve fingerprint-identical to the CPU oracle, including
a mid-stream worker kill (`make multihost` runs the larger driver
sweep on top: 1M-pod ceiling, batch routing, chaos)."""

import os
import socket

import numpy as np
import pytest

import jax

from karpenter_provider_aws_tpu.fleet.meshgroup import MeshGroup
from karpenter_provider_aws_tpu.parallel import distmesh
from karpenter_provider_aws_tpu.parallel.distmesh import (
    COORDINATOR_ENV, DIRTY_FIELDS, LOCAL_DEVICES_ENV, PROCESS_ID_ENV,
    PROCESSES_ENV, WORKERS_ENV, LocalSlab, collective_bill,
    commit_global, config_from_env, dist_dp, dist_mesh2, dispatch_dist,
    local_slot_rows, oracle_out, result_fingerprint, tick_arrays)
from karpenter_provider_aws_tpu.utils.metrics import Metrics

SHAPE = dict(G=6, T=11, n_max=64, E=24, P=2, Z=3, C=2, D=4,
             pods_per_group=17)


class TestConfigFromEnv:
    def test_unset_is_none(self, monkeypatch):
        monkeypatch.delenv(COORDINATOR_ENV, raising=False)
        assert config_from_env() is None

    def test_explicit_contract(self, monkeypatch):
        monkeypatch.setenv(COORDINATOR_ENV, "10.0.0.1:52021")
        monkeypatch.setenv(PROCESSES_ENV, "3")
        monkeypatch.setenv(PROCESS_ID_ENV, "2")
        monkeypatch.setenv(LOCAL_DEVICES_ENV, "4")
        cfg = config_from_env()
        assert cfg == ("10.0.0.1:52021", 3, 2, 4)

    def test_workers_env_derives_process_count(self, monkeypatch):
        """The chart never templates arithmetic: processes = workers+1
        is derived here, at runtime."""
        monkeypatch.setenv(COORDINATOR_ENV, "solver-0.solver:52021")
        monkeypatch.delenv(PROCESSES_ENV, raising=False)
        monkeypatch.setenv(WORKERS_ENV, "2")
        monkeypatch.delenv(PROCESS_ID_ENV, raising=False)
        monkeypatch.setenv("POD_NAME", "solver-mesh-1")
        monkeypatch.delenv(LOCAL_DEVICES_ENV, raising=False)
        cfg = config_from_env()
        assert cfg.num_processes == 3
        # StatefulSet ordinal 1 -> process 2 (the coordinator is 0)
        assert cfg.process_id == 2
        assert cfg.local_devices is None

    def test_non_ordinal_pod_name_is_process_zero(self, monkeypatch):
        monkeypatch.setenv(COORDINATOR_ENV, "c:1")
        monkeypatch.setenv(WORKERS_ENV, "1")
        monkeypatch.delenv(PROCESS_ID_ENV, raising=False)
        monkeypatch.setenv("POD_NAME", "controller-abc")
        assert config_from_env().process_id == 0


class TestMeshGeometry:
    def test_dist_dp_is_process_multiple(self, monkeypatch):
        monkeypatch.delenv("KARP_DIST_DP", raising=False)
        # nproc x _default_dp(per-process share): 8dev/1proc -> 2,
        # 16dev/2proc -> 2 x _default_dp(8) = 4
        assert dist_dp(8, 1) == 2
        assert dist_dp(16, 2) == 4
        assert dist_dp(16, 2) % 2 == 0

    def test_dist_dp_uneven_devices_raise(self, monkeypatch):
        monkeypatch.delenv("KARP_DIST_DP", raising=False)
        with pytest.raises(ValueError):
            dist_dp(9, 2)

    def test_dist_dp_env_override(self, monkeypatch):
        monkeypatch.setenv("KARP_DIST_DP", "8")
        assert dist_dp(16, 2) == 8
        # invalid overrides fall back: not a divisor / below nproc /
        # not a process multiple
        monkeypatch.setenv("KARP_DIST_DP", "6")
        assert dist_dp(16, 2) == 4
        monkeypatch.setenv("KARP_DIST_DP", "1")
        assert dist_dp(16, 2) == 4
        monkeypatch.setenv("KARP_DIST_DP", "3")
        assert dist_dp(16, 4) == 8

    def test_local_slot_rows_contiguous_partition(self):
        rows = [local_slot_rows(96, 3, pid) for pid in range(3)]
        assert rows == [(0, 32), (32, 64), (64, 96)]
        with pytest.raises(ValueError):
            local_slot_rows(97, 3, 0)

    def test_dist_mesh2_process_major(self, monkeypatch):
        monkeypatch.delenv("KARP_DIST_DP", raising=False)
        mesh = dist_mesh2()
        assert mesh.axis_names == ("dp", "tp")
        assert mesh.devices.size == len(jax.devices())

    def test_collective_bill_splits_at_process_boundary(self):
        one = collective_bill(P=2, dp=4, nproc=1, G=10)
        two = collective_bill(P=2, dp=4, nproc=2, G=10)
        # identical per-step program; only the process boundary moves
        assert one["per_step"] == two["per_step"]
        assert one["cross_process_per_step"] == 0
        assert two["cross_process_per_step"] == 2 + 3  # (P+1) + 2
        assert two["cross_process_total"] == 50
        assert two["bytes_per_dp_collective"] == 32


class TestTickArrays:
    def test_slab_parity_with_full_generation(self):
        """Generating rows [lo, hi) must equal slicing the full
        generation — the property that lets every host build only its
        slab while all hosts agree on the logical arena."""
        full, statics = tick_arrays(SHAPE, seed=5, tick=3)
        E, D = SHAPE["E"], SHAPE["D"]
        Np = 96
        for lo, hi in ((0, 48), (48, 96)):
            slabbed, st2 = tick_arrays(SHAPE, seed=5, tick=3,
                                       slab=(lo, hi, Np))
            assert st2 == statics
            a = slabbed["ex_alloc"]
            assert isinstance(a, LocalSlab)
            assert (a.lo, a.hi, a.axis, a.global_shape) == \
                (lo, hi, 0, (Np, D))
            top = min(hi, E)
            assert np.array_equal(a.array[:max(0, top - lo)],
                                  full["ex_alloc"][lo:top])
            assert (a.array[max(0, top - lo):] == 0).all()
            c = slabbed["ex_compat"]
            assert c.axis == 1 and c.global_shape == (SHAPE["G"], Np)
            assert np.array_equal(c.array[:, :max(0, top - lo)],
                                  full["ex_compat"][:, lo:top])
            # replicated fields are identical either mode
            assert np.array_equal(slabbed["n"], full["n"])

    def test_dirty_contract_across_ticks(self):
        """Only DIRTY_FIELDS may move between ticks: the resident-arena
        patch path re-places exactly those, so any other field drifting
        would silently desynchronize the on-device arena."""
        t0, _ = tick_arrays(SHAPE, seed=5, tick=0)
        t1, _ = tick_arrays(SHAPE, seed=5, tick=1)
        changed = {k for k in t0
                   if not np.array_equal(np.asarray(t0[k]),
                                         np.asarray(t1[k]))}
        assert changed == set(DIRTY_FIELDS)


class TestWire:
    def test_roundtrip_with_arrays(self):
        a, b = socket.socketpair()
        try:
            arrays = {"x": np.arange(6).reshape(2, 3),
                      "m": np.array([True, False])}
            distmesh._send_msg(a, {"cmd": "t", "k": 1}, arrays)
            msg, got = distmesh._recv_msg(b)
            assert msg == {"cmd": "t", "k": 1}
            assert set(got) == {"x", "m"}
            assert np.array_equal(got["x"], arrays["x"])
            assert got["m"].dtype == np.bool_
        finally:
            a.close()
            b.close()

    def test_headers_only_and_orderly_close(self):
        a, b = socket.socketpair()
        try:
            distmesh._send_msg(a, {"cmd": "halt"})
            msg, got = distmesh._recv_msg(b)
            assert msg == {"cmd": "halt"} and got == {}
            a.close()
            assert distmesh._recv_msg(b) == (None, {})
        finally:
            b.close()


class TestCommitGlobal:
    def test_slab_commit_equals_full_commit(self):
        from jax.sharding import PartitionSpec as PS
        mesh = dist_mesh2()
        ndp = mesh.shape["dp"]
        Np, D = 8 * ndp, 3
        full = np.arange(Np * D, dtype=np.int64).reshape(Np, D)
        spec = PS("dp", None)
        want = np.asarray(commit_global(full, mesh, spec))
        # single process owns every row, so the whole-range slab is the
        # degenerate (but geometry-exercising) case
        got = commit_global(LocalSlab(full, 0, Np, 0, (Np, D)),
                            mesh, spec)
        assert np.array_equal(np.asarray(got), want)

    def test_slab_outside_ownership_refuses(self):
        from jax.sharding import PartitionSpec as PS
        mesh = dist_mesh2()
        Np, D = 8 * mesh.shape["dp"], 3
        half = Np // 2
        slab = LocalSlab(np.zeros((half, D), np.int64), 0, half, 0,
                         (Np, D))
        with pytest.raises(ValueError, match="outside local slab"):
            commit_global(slab, mesh, PS("dp", None))


class TestDispatchDistSingleProcess:
    """dispatch_dist on an in-process 2-D mesh: the same code path the
    workers run, minus the cross-process collectives (process_count=1),
    so modes/fingerprints/rejections are all checkable in the fast
    tier."""

    def _arrays(self, tick):
        return tick_arrays(SHAPE, seed=9, tick=tick)

    def test_full_patch_reuse_and_oracle_parity(self):
        mesh = dist_mesh2()
        cache = {}
        metrics = Metrics()
        arrays, statics = self._arrays(0)
        out0 = dispatch_dist(arrays, mesh=mesh, cache=cache,
                             metrics=metrics, **statics)
        assert cache["last_placement"]["mode"] == "full"
        assert result_fingerprint(out0) == \
            result_fingerprint(oracle_out(self._arrays(0)[0],
                                          **statics))
        arrays1, _ = self._arrays(1)
        out1 = dispatch_dist(arrays1, mesh=mesh, cache=cache,
                             dirty=list(DIRTY_FIELDS), **statics)
        assert cache["last_placement"]["mode"] == "patch"
        assert sorted(cache["last_placement"]["fields"]) == \
            sorted(DIRTY_FIELDS)
        assert result_fingerprint(out1) == \
            result_fingerprint(oracle_out(self._arrays(1)[0],
                                          **statics))
        dispatch_dist(arrays1, mesh=mesh, cache=cache, dirty=[],
                      **statics)
        assert cache["last_placement"]["mode"] == "reuse"
        assert "commit_s" in cache["last_timing"]
        assert metrics.gauge(
            "karpenter_solver_distmesh_processes") == 1
        assert metrics.counter("karpenter_solver_distmesh_patch_total",
                               labels={"mode": "full"}) == 1

    def test_minvalues_floors_rejected(self):
        arrays, statics = self._arrays(0)
        arrays = dict(arrays, mv_floor=np.zeros(3, np.int64))
        with pytest.raises(ValueError, match="minValues"):
            dispatch_dist(arrays, mesh=dist_mesh2(), cache={},
                          **statics)


class TestMeshGroupLocalMode:
    def test_workers_zero_serves_locally(self):
        metrics = Metrics()
        mg = MeshGroup(workers=0, metrics=metrics).start()
        try:
            assert not mg.alive()  # no distributed mesh, by design
            r0 = mg.solve_seeded(SHAPE, seed=4, tick=0)
            assert r0["mode"] == "full" and not r0["distributed"]
            o = mg.solve_oracle(SHAPE, seed=4, tick=0)
            assert r0["fingerprint"] == o["fingerprint"]
            r1 = mg.solve_seeded(SHAPE, seed=4, tick=1,
                                 dirty=list(DIRTY_FIELDS))
            assert r1["mode"] == "patch"
            assert metrics.counter(
                "karpenter_solver_distmesh_dispatch_total",
                labels={"mode": "local"}) == 2
            assert metrics.gauge(
                "karpenter_solver_distmesh_processes") == 1
        finally:
            mg.stop()

    def test_degrade_taxonomy_exactly_one_full(self):
        """After a degrade the FIRST dispatch ignores the caller's
        dirty list (residency died with the workers) and every later
        one honors it — exactly one full Solve."""
        metrics = Metrics()
        mg = MeshGroup(workers=0, metrics=metrics).start()
        try:
            mg.solve_seeded(SHAPE, seed=4, tick=0)
            mg.degrade(reason="worker_lost")
            r = mg.solve_seeded(SHAPE, seed=4, tick=1,
                                dirty=list(DIRTY_FIELDS))
            assert r["mode"] == "full"
            r2 = mg.solve_seeded(SHAPE, seed=4, tick=2,
                                 dirty=list(DIRTY_FIELDS))
            assert r2["mode"] == "patch"
            for tick, rr in ((1, r), (2, r2)):
                o = mg.solve_oracle(SHAPE, seed=4, tick=tick)
                assert rr["fingerprint"] == o["fingerprint"]
            assert metrics.counter(
                "karpenter_solver_distmesh_degraded_total",
                labels={"reason": "worker_lost"}) == 1
            # degrading twice must not double-count or re-arm
            mg.degrade(reason="worker_lost")
            assert metrics.counter(
                "karpenter_solver_distmesh_degraded_total",
                labels={"reason": "worker_lost"}) == 1
            assert mg.solve_batch(np.zeros((1, 4), np.uint32),
                                  {}) is None
        finally:
            mg.stop()

    def test_spawn_failure_degrades_not_raises(self):
        metrics = Metrics()
        mg = MeshGroup(workers=1, metrics=metrics,
                       python="/nonexistent/python").start()
        try:
            assert not mg.alive()
            assert metrics.counter(
                "karpenter_solver_distmesh_degraded_total",
                labels={"reason": "spawn_failed"}) == 1
            # a solver that cannot form its group still serves
            r = mg.solve_seeded(SHAPE, seed=4, tick=0)
            o = mg.solve_oracle(SHAPE, seed=4, tick=0)
            assert r["fingerprint"] == o["fingerprint"]
        finally:
            mg.stop()


class TestFingerprintSplit:
    def test_split_degrades_once_and_local_serves(self):
        """Processes disagreeing on a replicated output is a
        correctness emergency: the collect path degrades
        (fingerprint_split), raises, and — exactly like any other
        degrade — the local twin serves with the one-full-Solve
        taxonomy while the supervisor schedules a regroup."""
        metrics = Metrics()
        mg = MeshGroup(workers=1, metrics=metrics)
        replies = [({"fingerprint": "aaaa", "mode": "full"}, None),
                   ({"fingerprint": "bbbb", "mode": "full"}, None)]
        with pytest.raises(RuntimeError, match="fingerprint mismatch"):
            mg._collect(replies, "seeded", False)
        assert mg._degraded
        assert metrics.counter(
            "karpenter_solver_distmesh_degraded_total",
            labels={"reason": "fingerprint_split"}) == 1
        assert mg._regroup_at is not None  # supervised regroup armed
        r = mg.solve_seeded(SHAPE, seed=4, tick=0,
                            dirty=list(DIRTY_FIELDS))
        assert r["mode"] == "full" and not r["distributed"]
        o = mg.solve_oracle(SHAPE, seed=4, tick=0)
        assert r["fingerprint"] == o["fingerprint"]
        # degrading again (e.g. the raise's caller falling back) must
        # not double-count or re-arm a fresh backoff
        mg.degrade(reason="fingerprint_split")
        assert metrics.counter(
            "karpenter_solver_distmesh_degraded_total",
            labels={"reason": "fingerprint_split"}) == 1
        mg.stop()

    def test_agreeing_fingerprints_do_not_degrade(self):
        metrics = Metrics()
        mg = MeshGroup(workers=1, metrics=metrics)
        replies = [({"fingerprint": "cccc", "mode": "patch"}, None),
                   ({"fingerprint": "cccc", "mode": "patch"}, None)]
        r = mg._collect(replies, "seeded", False)
        assert r["fingerprint"] == "cccc" and r["distributed"]
        assert not mg._degraded
        mg.stop()


def test_membership_advertises_mesh_group_capability():
    from karpenter_provider_aws_tpu.fleet.membership import _CAP_FLAGS
    assert "mesh_group" in _CAP_FLAGS


@pytest.mark.slow
class TestTwoProcessMesh:
    """REAL cross-process solving: worker subprocesses joined by
    jax.distributed over gloo, exercised through the coordinator."""

    @pytest.fixture()
    def group(self):
        mg = MeshGroup(workers=1, local_devices=4,
                       metrics=Metrics()).start()
        if not mg.alive():
            pytest.skip("2-process mesh failed to form on this host")
        yield mg
        mg.stop()

    def test_distributed_solve_matches_oracle(self, group):
        info = group.mesh_info
        assert info["ndev"] == 8 and info["dp"] % 2 == 0
        r0 = group.solve_seeded(SHAPE, seed=7, tick=0)
        assert r0["distributed"] and r0["mode"] == "full"
        o0 = group.solve_oracle(SHAPE, seed=7, tick=0)
        assert r0["fingerprint"] == o0["fingerprint"]
        r1 = group.solve_seeded(SHAPE, seed=7, tick=1,
                                dirty=list(DIRTY_FIELDS))
        assert r1["mode"] == "patch"
        o1 = group.solve_oracle(SHAPE, seed=7, tick=1)
        assert r1["fingerprint"] == o1["fingerprint"]
        assert set(r1["timing"]) == {"commit_s", "solve_s", "gather_s"}

    def test_worker_kill_degrades_with_one_full_solve(self, group):
        group.solve_seeded(SHAPE, seed=7, tick=0)
        group._procs[-1].kill()
        group._procs[-1].wait(timeout=10)
        r = group.solve_seeded(SHAPE, seed=7, tick=1,
                               dirty=list(DIRTY_FIELDS))
        assert not r["distributed"] and r["mode"] == "full"
        assert not group.alive()
        r2 = group.solve_seeded(SHAPE, seed=7, tick=2,
                                dirty=list(DIRTY_FIELDS))
        assert r2["mode"] == "patch"
        o2 = group.solve_oracle(SHAPE, seed=7, tick=2)
        assert r2["fingerprint"] == o2["fingerprint"]
        assert group.metrics.counter(
            "karpenter_solver_distmesh_degraded_total",
            labels={"reason": "worker_lost"}) == 1
