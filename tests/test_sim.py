"""The endurance simulator (karpenter_provider_aws_tpu/sim/).

Four layers, mirroring the package:

- the Clock seam itself — coercions, RealClock parity (the default
  stays byte-for-byte the pre-seam behavior), VirtualClock wake
  semantics (a waiter wakes AT its deadline, never past it);
- exact-boundary regressions for every timer behind the seam: breaker
  half-open at +cooldown, TTL eviction at +ttl, admission-bucket
  refill at +retry_after, meshgroup regroup at +backoff — not
  "+backoff plus whatever the polling loop added";
- trace/chaos determinism — the same seed yields a bytes-identical
  event stream and schedule, in THIS process and across independent
  processes (the subprocess test, the strongest replay guarantee);
- replay smoke — a 10-virtual-minute EnduranceSim must come back
  clean in tier-1; the full simulated day rides behind `-m slow`
  (`make sim` / the nightly soak).
"""

import json
import subprocess
import sys
import threading
import time

import pytest

from karpenter_provider_aws_tpu.sim import audit as audit_mod
from karpenter_provider_aws_tpu.sim import chaos as chaos_mod
from karpenter_provider_aws_tpu.sim import traces as traces_mod
from karpenter_provider_aws_tpu.sim.clock import (REAL_CLOCK,
                                                  CallableClock, Clock,
                                                  RealClock, VirtualClock,
                                                  as_clock, monotonic_of)
from karpenter_provider_aws_tpu.utils.metrics import Metrics

# ---------------------------------------------------------------------------
# the seam's coercions


class TestClockCoercions:
    def test_none_is_the_shared_real_clock(self):
        assert as_clock(None) is REAL_CLOCK
        assert monotonic_of(None) is time.monotonic

    def test_clock_instances_pass_through(self):
        v = VirtualClock()
        assert as_clock(v) is v
        assert as_clock(REAL_CLOCK) is REAL_CLOCK
        assert monotonic_of(v)() == 0.0

    def test_bare_callable_is_the_legacy_seam(self):
        t = [7.0]
        c = as_clock(lambda: t[0])
        assert isinstance(c, CallableClock)
        assert c.monotonic() == 7.0
        t[0] = 9.0
        assert c.time() == 9.0
        # monotonic_of never wraps a callable — the legacy seam is free
        fn = lambda: 3.0  # noqa: E731
        assert monotonic_of(fn) is fn

    def test_garbage_raises(self):
        with pytest.raises(TypeError):
            as_clock(42)
        with pytest.raises(TypeError):
            monotonic_of(42)

    def test_real_clock_is_the_clock_protocol(self):
        assert RealClock is Clock
        assert REAL_CLOCK.name == "real"


class TestRealClockParity:
    """clock=None keeps every component on the pre-seam defaults."""

    def test_token_bucket_default_reads_os_monotonic(self):
        from karpenter_provider_aws_tpu.tenancy.admission import \
            TokenBucket
        assert TokenBucket(rate=1.0, burst=1)._clock is time.monotonic

    def test_ttl_cache_default_reads_os_monotonic(self):
        from karpenter_provider_aws_tpu.cache.ttl import TTLCache
        assert TTLCache(ttl=1.0)._clock is time.monotonic

    def test_breaker_default_reads_os_monotonic(self):
        from karpenter_provider_aws_tpu.sidecar.resilience import \
            CircuitBreaker
        assert CircuitBreaker()._clock is time.monotonic

    def test_retry_default_sleeps_for_real(self):
        from karpenter_provider_aws_tpu.sidecar.resilience import \
            RetryPolicy
        p = RetryPolicy(max_attempts=2, backoff_base_s=0.001,
                        backoff_cap_s=0.002)
        t0 = time.monotonic()
        p.sleep(0.01)
        assert time.monotonic() - t0 >= 0.009

    def test_batcher_default_is_the_shared_real_clock(self):
        from karpenter_provider_aws_tpu.batcher.core import \
            DescribeInstancesBatcher
        b = DescribeInstancesBatcher(ec2=None)
        try:
            assert b._clockobj is REAL_CLOCK
        finally:
            b.stop()


# ---------------------------------------------------------------------------
# VirtualClock semantics


class TestVirtualClock:
    def test_reads_start_at_origin(self):
        v = VirtualClock(start=5.0, epoch=1000.0)
        assert v.monotonic() == 5.0
        assert v.time() == 1005.0

    def test_warp_wall_moves_only_wall_time(self):
        v = VirtualClock()
        v.warp_wall(3600.0)
        assert v.monotonic() == 0.0
        assert v.time() == 1_700_000_000.0 + 3600.0

    def test_sleeper_wakes_at_exact_deadline(self):
        """The whole point of the seam: a thread sleeping 30s reads
        EXACTLY 30.0 when it wakes, even when the driver advances far
        past it in one hop."""
        v = VirtualClock()
        woke_at = []

        def sleeper():
            v.sleep(30.0)
            woke_at.append(v.monotonic())

        th = threading.Thread(target=sleeper, daemon=True)
        th.start()
        assert v.wait_for_waiters(1)
        assert v.pending_deadline() == 30.0
        v.advance_to(10_000.0)
        th.join(timeout=5)
        assert not th.is_alive()
        assert woke_at == [30.0]

    def test_sleepers_wake_in_deadline_order(self):
        v = VirtualClock()
        order = []

        def sleeper(s):
            v.sleep(s)
            order.append((s, v.monotonic()))

        ths = [threading.Thread(target=sleeper, args=(s,), daemon=True)
               for s in (20.0, 5.0, 12.0)]
        for th in ths:
            th.start()
        assert v.wait_for_waiters(3)
        v.advance_to(100.0)
        for th in ths:
            th.join(timeout=5)
        # each sleeper observed ITS OWN deadline — never a later hop's
        # instant, no matter how the OS interleaved the wakes (append
        # order across threads is scheduling, so compare sorted)
        assert sorted(order) == [(5.0, 5.0), (12.0, 12.0), (20.0, 20.0)]

    def test_cond_wait_times_out_virtually(self):
        v = VirtualClock()
        cv = threading.Condition()
        out = []

        def waiter():
            with cv:
                out.append(v.cond_wait(cv, timeout=15.0))

        th = threading.Thread(target=waiter, daemon=True)
        th.start()
        assert v.wait_for_waiters(1)
        v.advance_to(15.0)
        th.join(timeout=5)
        assert out == [False]  # the Condition.wait timeout contract

    def test_cond_wait_true_when_notified_before_deadline(self):
        v = VirtualClock()
        cv = threading.Condition()
        out = []

        def waiter():
            with cv:
                out.append(v.cond_wait(cv, timeout=50.0))

        th = threading.Thread(target=waiter, daemon=True)
        th.start()
        assert v.wait_for_waiters(1)
        with cv:
            cv.notify_all()
        th.join(timeout=5)
        assert out == [True]

    def test_advance_is_relative(self):
        v = VirtualClock()
        v.advance(7.5)
        v.advance(2.5)
        assert v.monotonic() == 10.0


# ---------------------------------------------------------------------------
# exact timer boundaries through the seam


class TestSeamBoundaries:
    def test_breaker_half_opens_at_exact_cooldown(self):
        from karpenter_provider_aws_tpu.sidecar.resilience import (
            HALF_OPEN, OPEN, CircuitBreaker)
        v = VirtualClock()
        br = CircuitBreaker(threshold=1, cooldown_s=30.0, clock=v)
        br.record_failure()
        assert br.state == OPEN
        v.advance_to(29.999)
        assert not br.allow()  # one ulp early: still failing fast
        v.advance_to(30.0)
        assert br.allow()  # AT the boundary: this caller is the probe
        assert br.state == HALF_OPEN
        br.record_failure()  # probe fails: straight back to open,
        assert br.state == OPEN  # cooldown re-anchored at NOW
        v.advance_to(59.999)
        assert not br.allow()
        v.advance_to(60.0)
        assert br.allow()

    def test_ttl_evicts_at_exact_expiry(self):
        from karpenter_provider_aws_tpu.cache.ttl import TTLCache
        v = VirtualClock()
        c = TTLCache(ttl=180.0, clock=v)
        c.put("k", "v")
        v.advance_to(179.999)
        assert c.get("k") == "v"
        v.advance_to(180.0)
        assert c.get("k") is None

    def test_bucket_refills_at_exact_retry_after(self):
        from karpenter_provider_aws_tpu.tenancy.admission import \
            TokenBucket
        v = VirtualClock()
        # exact binary fractions throughout so the refill arithmetic is
        # fp-exact: rate 1/4 token/s => one token back in exactly 4s
        b = TokenBucket(rate=0.25, burst=1, clock=v)
        ok, _ = b.take()
        assert ok
        ok, retry_after = b.take()
        assert not ok and retry_after == 4.0
        v.advance(3.75)
        assert not b.take()[0]  # 0.9375 tokens: still shedding
        v.advance(0.25)  # ...and AT +4.0s the token is whole again
        ok, hint = b.take()
        assert ok and hint == 0.0

    def test_meshgroup_regroups_at_exact_backoff(self):
        import socket

        from karpenter_provider_aws_tpu.fleet.meshgroup import MeshGroup
        v = VirtualClock()
        m = Metrics()
        mg = MeshGroup(workers=1, metrics=m, regroup_backoff_s=30.0,
                       regroup_attempts=3, clock=v)
        stub_peer = []

        def fake_form():
            mg.epoch += 1
            a, b = socket.socketpair()
            mg._socks = {0: a}
            stub_peer.append(b)

        mg._form = fake_form
        mg._canary_group = lambda: True
        try:
            mg.degrade(reason="worker_lost")
            assert mg._regroup_at == 30.0  # anchored on the virtual axis
            v.advance_to(29.999)
            assert mg._maybe_regroup() is False  # not due: ONE ulp early
            assert mg._degraded
            v.advance_to(30.0)
            assert mg._maybe_regroup() is True  # due AT the boundary
            assert not mg._degraded and mg.alive()
        finally:
            for s in list(mg._socks.values()) + stub_peer:
                try:
                    s.close()
                except Exception:
                    pass
            mg._socks.clear()

    def test_arena_table_ages_out_at_exact_ttl(self):
        from karpenter_provider_aws_tpu.tenancy.admission import \
            PatchArenaTable
        v = VirtualClock()
        m = Metrics()
        t = PatchArenaTable(capacity=4, ttl_s=600.0, metrics=m, clock=v)
        assert t.prime("early", [1.0, 2.0], 1, tenant="a")
        v.advance(0.001)
        assert t.prime("late", [3.0, 4.0], 1, tenant="a")
        v.advance_to(600.0)
        # primed at 0: dead AT +ttl exactly; primed one tick later: alive
        buf, reason = t.apply("early", [], [], 1, 2)
        assert buf is None and reason == "no_resident"
        buf, reason = t.apply("late", [], [], 1, 2)
        assert buf is not None and reason is None

    def test_arena_wipe_evicts_everything_with_reason_wipe(self):
        from karpenter_provider_aws_tpu.tenancy.admission import \
            PatchArenaTable
        m = Metrics()
        t = PatchArenaTable(capacity=8, metrics=m)
        assert t.prime("k1", [1.0], 1, tenant="a")
        assert t.prime("k2", [2.0], 3, tenant="b")
        t.clear()
        assert len(t) == 0
        assert t.version_of("k1") is None
        wiped = sum(
            val for (name, labels), val in m.counters.items()
            if name == "karpenter_solver_wire_resident_evictions_total"
            and dict(labels).get("reason") == "wipe")
        assert wiped == 2


# ---------------------------------------------------------------------------
# trace + chaos determinism


class TestTraceDeterminism:
    def test_same_seed_is_bytes_identical(self):
        a = traces_mod.generate(11, 86400.0)
        b = traces_mod.generate(11, 86400.0)
        assert traces_mod.encode(a) == traces_mod.encode(b)
        assert traces_mod.stream_digest(a) == traces_mod.stream_digest(b)

    def test_different_seeds_differ(self):
        assert traces_mod.stream_digest(traces_mod.generate(1, 86400.0)) \
            != traces_mod.stream_digest(traces_mod.generate(2, 86400.0))

    def test_stream_is_totally_ordered(self):
        evts = traces_mod.generate(5, 43200.0)
        assert [e.seq for e in evts] == list(range(len(evts)))
        assert all(a.t <= b.t for a, b in zip(evts, evts[1:]))

    def test_every_regime_emits_and_subsets_restrict(self):
        evts = traces_mod.generate(3, 86400.0)
        assert {e.regime for e in evts} == set(traces_mod.REGIMES)
        only = traces_mod.generate(3, 86400.0, regimes=["diurnal"])
        assert {e.regime for e in only} == {"diurnal"}

    def test_unknown_regime_raises(self):
        with pytest.raises(ValueError):
            traces_mod.generate(3, 3600.0, regimes=["lunar"])


class TestChaosSchedule:
    def test_same_seed_is_identical(self):
        a = chaos_mod.schedule(9, 86400.0)
        b = chaos_mod.schedule(9, 86400.0)
        assert [w.encode() for w in a] == [w.encode() for w in b]

    def test_composition_has_forced_overlaps(self):
        ws = chaos_mod.schedule(9, 86400.0)
        assert any(w.overlaps for w in ws)
        assert {w.kind for w in ws} == set(chaos_mod.CHAOS_KINDS)

    def test_windows_stay_inside_the_day(self):
        for w in chaos_mod.schedule(4, 86400.0):
            assert 0.0 <= w.t0 <= w.t1 <= 86400.0

    def test_plans_are_convergence_bounded(self):
        for w in chaos_mod.schedule(2, 86400.0):
            if w.kind == "cloud":
                assert w.params["max_faults"] <= 30
            if w.kind in ("cloud", "wire"):
                assert w.params["max_consecutive"] <= 2

    def test_unknown_kind_raises(self):
        with pytest.raises(ValueError):
            chaos_mod.schedule(1, 3600.0, kinds=["gremlins"])


# ---------------------------------------------------------------------------
# the auditor


class TestAudit:
    def test_accounting_partition_holds_and_breaks(self):
        m = Metrics()
        m.inc("karpenter_solver_tenant_admitted_total", 3.0,
              labels={"tenant": "a", "rpc": "Solve"})
        m.inc("karpenter_solver_tenant_shed_total", 2.0,
              labels={"tenant": "a", "rpc": "Solve", "reason": "rate"})
        assert audit_mod.check_accounting(m, {"a": 5}) == []
        bad = audit_mod.check_accounting(m, {"a": 6})
        assert [v.check for v in bad] == ["admission-partition"]

    def test_recovery_never_outruns_degrades(self):
        m = Metrics()
        m.inc("karpenter_solver_distmesh_degraded_total", 1.0,
              labels={"reason": "worker_lost"})
        m.inc("karpenter_solver_distmesh_recovered_total", 2.0,
              labels={"reason": "worker_lost"})
        assert [v.check for v in audit_mod.check_accounting(m)] == \
            ["recovery-exceeds-degrades"]

    def test_fallback_taxonomy_is_closed(self):
        m = Metrics()
        m.inc("karpenter_solver_wire_fallback_total",
              labels={"reason": "gremlins"})
        assert [v.check for v in audit_mod.check_accounting(m)] == \
            ["unknown-fallback-reason"]

    def test_slo_flags_slow_regimes_only(self):
        lats = {"tenant_mix": [0.001] * 99 + [9.0],
                "diurnal": [0.001] * 100}
        out = audit_mod.check_slo(lats, slo_p99_ms={"default": 100.0})
        assert [v.check for v in out] == ["solve-slo"]
        assert "tenant_mix" in out[0].detail

    def test_cluster_check_flags_a_stranded_pod(self):
        from karpenter_provider_aws_tpu.apis.objects import Pod
        from karpenter_provider_aws_tpu.operator import Operator
        op = Operator()
        p = Pod(name="lost")
        p.node_name = "node-that-never-was"
        op.kube.create(p)
        assert "pod-missing-node" in \
            [v.check for v in audit_mod.check_cluster(op)]

    def test_leak_monitor_bounds_the_tables(self):
        class _T:
            capacity = 2

            def __len__(self):
                return 3

        class _H:
            _shapes_seen = _T()
            _patch_arenas = _T()

        out = audit_mod.LeakMonitor().check(handler=_H())
        assert {v.check for v in out} == \
            {"shape-table-overflow", "arena-table-overflow"}

    def test_violation_formats_with_its_check(self):
        v = audit_mod.Violation("thread-leak", "too many")
        assert str(v) == "[thread-leak] too many"


# ---------------------------------------------------------------------------
# replays

_SUBPROC = r"""
import json, sys
from karpenter_provider_aws_tpu.sim.driver import EnduranceSim
r = EnduranceSim(seed=int(sys.argv[1]), duration_s=300.0, wire=False,
                 audit_every=10).run()
print(json.dumps({"stream": r["stream_sha256"],
                  "fingerprint": r["terminal_fingerprint"],
                  "clean": r["clean"]}))
"""


@pytest.mark.sim
class TestReplay:
    def test_ten_virtual_minutes_comes_back_clean(self):
        from karpenter_provider_aws_tpu.sim.driver import EnduranceSim
        r = EnduranceSim(seed=7, duration_s=600.0, wire=False,
                         audit_every=10).run()
        assert r["clean"], r["violations"]
        assert r["events_total"] > 0
        assert r["chaos_windows"] > 0 and r["chaos_overlaps"] > 0

    @pytest.mark.slow
    def test_replay_is_deterministic_in_process(self):
        from karpenter_provider_aws_tpu.sim.driver import EnduranceSim
        a = EnduranceSim(seed=13, duration_s=600.0, wire=False,
                         chaos=False).run()
        b = EnduranceSim(seed=13, duration_s=600.0, wire=False,
                         chaos=False).run()
        assert a["stream_sha256"] == b["stream_sha256"]
        assert a["terminal_fingerprint"] == b["terminal_fingerprint"]

    @pytest.mark.slow
    def test_replay_is_deterministic_across_processes(self):
        """The strongest guarantee: two INDEPENDENT interpreters replay
        the same seed to a byte-identical event stream AND a byte-
        identical terminal cluster fingerprint."""
        outs = []
        for _ in range(2):
            proc = subprocess.run(
                [sys.executable, "-c", _SUBPROC, "23"],
                capture_output=True, text=True, timeout=300)
            assert proc.returncode == 0, proc.stderr[-2000:]
            outs.append(json.loads(proc.stdout.strip().splitlines()[-1]))
        assert outs[0] == outs[1]
        assert outs[0]["clean"]

    @pytest.mark.slow
    def test_wire_replay_audits_the_admission_ledger(self):
        pytest.importorskip("grpc")
        from karpenter_provider_aws_tpu.sim.driver import EnduranceSim
        sim = EnduranceSim(seed=5, duration_s=1800.0, audit_every=20)
        r = sim.run()
        assert r["wire"] and r["solves"] > 0
        assert r["clean"], r["violations"]


@pytest.mark.sim
@pytest.mark.slow
class TestFullDayReplay:
    def test_simulated_day_under_composed_chaos(self):
        """The headline: 24 virtual hours, all regimes, all chaos
        kinds, continuous audit — clean, in minutes of wall time
        (hack/sim.sh enforces the <=10min wall budget in CI)."""
        from karpenter_provider_aws_tpu.sim.driver import EnduranceSim
        r = EnduranceSim(seed=1, duration_s=86400.0,
                         audit_every=40).run()
        assert r["clean"], r["violations"]
        assert r["events_total"] > 200
        assert r["chaos_overlaps"] >= 2
