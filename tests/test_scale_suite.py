"""Simulated-cluster scale suite (SURVEY §2.8/§6 — the reference's E2E
scale envelopes, run against the fake cloud instead of EKS):

- node-dense: 500 nodes, one pod per node (hostname anti-affinity)
- pod-dense: 55,000 pods packed ~110/node
- minValues scale-up: launch candidates respect requirement minValues
- deprovisioning: consolidation / emptiness / expiration / drift, with
  all methods exercised in one cluster
- chaos moved to its own suite (tests/suites/test_suite_chaos.py), the
  reference's dedicated chaos suite analog

The TPU solver drives provisioning (the whole point of the rebuild); the
reference's wall-clock envelope is 30m on real EKS — here the cluster is
simulated so the suite asserts outcomes and keeps runtimes in CI range.
"""

import pytest

#: the scale tier: 500-node / 55k-pod envelopes (minutes of wall clock);
#: excluded from the fast path via `pytest -m "not scale"`
pytestmark = pytest.mark.scale

from karpenter_provider_aws_tpu.apis import labels as L
from karpenter_provider_aws_tpu.apis.objects import (Disruption, EC2NodeClass,
                                                     NodeClassRef, NodePool,
                                                     NodePoolTemplate,
                                                     PodAffinityTerm)
from karpenter_provider_aws_tpu.apis.requirements import Requirements
from karpenter_provider_aws_tpu.fake.environment import make_pods
from karpenter_provider_aws_tpu.operator import Operator
from karpenter_provider_aws_tpu.solver.tpu import TPUSolver


class FakeClock:
    def __init__(self, t=1_000_000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def mk_cluster(op, pool_name="default", requirements=(), disruption=None,
               limits=None, expire_after=None):
    nc = EC2NodeClass(pool_name + "-class")
    op.kube.create(nc)
    np = NodePool(pool_name, template=NodePoolTemplate(
        node_class_ref=NodeClassRef(nc.name),
        requirements=Requirements.from_terms(list(requirements)),
        expire_after=expire_after),
        disruption=disruption, limits=limits)
    op.kube.create(np)
    return np, nc


@pytest.fixture
def clock():
    return FakeClock()


@pytest.fixture
def op(clock):
    return Operator(clock=clock, solver=TPUSolver(backend="jax"))


class TestNodeDense:
    def test_500_nodes_one_pod_each(self, op, clock):
        """scale/provisioning_test.go:86-122 analog: 500 single-pod nodes
        via self anti-affinity on hostname."""
        mk_cluster(op)
        pods = make_pods(
            500, cpu="2", memory="4Gi", prefix="dense",
            pod_affinity=[PodAffinityTerm(topology_key=L.HOSTNAME,
                                          group="dense", anti=True)])
        for p in pods:
            op.kube.create(p)
        op.run_until_settled(max_steps=12, disrupt=False)
        nodes = op.kube.list("Node")
        assert len(nodes) == 500
        assert all(p.node_name for p in op.kube.list("Pod"))
        per_node = {}
        for p in op.kube.list("Pod"):
            per_node[p.node_name] = per_node.get(p.node_name, 0) + 1
        assert max(per_node.values()) == 1


class TestPodDense:
    def test_55k_pods_packed(self, op, clock):
        """scale/provisioning_test.go:179-214 analog: 55k pods packed
        ~110/node; every pod bound, nodes near the pod-limit envelope."""
        mk_cluster(op, requirements=[
            {"key": L.INSTANCE_SIZE, "operator": "In",
             "values": ["4xlarge", "8xlarge", "12xlarge"]}])
        pods = make_pods(55_000, cpu="25m", memory="64Mi", prefix="pd")
        for p in pods:
            op.kube.create(p)
        op.run_until_settled(max_steps=14, disrupt=False)
        pods = op.kube.list("Pod")
        unbound = [p for p in pods if not p.node_name]
        assert not unbound, f"{len(unbound)} pods unbound"
        nodes = op.kube.list("Node")
        # pods-per-node rides the ENI limit envelope (~110 for 4xlarge)
        assert len(nodes) <= 55_000 // 100
        assert all(c.launched and c.registered
                   for c in op.kube.list("NodeClaim"))

    def test_minvalues_scale_up(self, op, clock):
        """minValues CEL analog (karpenter.sh_nodepools.yaml:284): the
        launch candidate set must keep >= minValues distinct families."""
        mk_cluster(op, requirements=[
            {"key": L.INSTANCE_FAMILY, "operator": "Exists",
             "minValues": 5}])
        for p in make_pods(1000, cpu="500m", memory="1Gi", prefix="mv"):
            op.kube.create(p)
        op.run_until_settled(max_steps=12, disrupt=False)
        assert all(p.node_name for p in op.kube.list("Pod"))
        for claim in op.kube.list("NodeClaim"):
            fams = {t.split(".")[0] for t in claim.instance_type_names}
            assert len(fams) >= 5, (claim.name, sorted(fams))


class TestDeprovisioningScale:
    def test_emptiness_at_scale(self, op, clock):
        mk_cluster(op, disruption=Disruption(consolidation_policy="WhenEmpty"))
        pods = make_pods(200, cpu="2", memory="4Gi", prefix="dep",
                         pod_affinity=[PodAffinityTerm(
                             topology_key=L.HOSTNAME, group="dep",
                             anti=True)])
        for p in pods:
            op.kube.create(p)
        op.run_until_settled(max_steps=12, disrupt=False)
        assert len(op.kube.list("Node")) == 200
        # all pods finish; nodes empty out and are consolidated away
        for p in op.kube.list("Pod"):
            op.kube.delete("Pod", p.metadata.name, p.metadata.namespace)
        for _ in range(40):
            op.run_until_settled()
            clock.advance(30)
            if not op.kube.list("Node"):
                break
        assert not op.kube.list("Node")

    def test_expiration_rolls_fleet(self, op, clock):
        mk_cluster(op, expire_after=3600.0)
        for p in make_pods(60, cpu="1", memory="2Gi", prefix="exp"):
            op.kube.create(p)
        op.run_until_settled(disrupt=False)
        before = {c.name for c in op.kube.list("NodeClaim")}
        assert before
        clock.advance(7200)
        for _ in range(25):
            op.run_until_settled()
            clock.advance(30)
            after = {c.name for c in op.kube.list("NodeClaim")}
            if after and not (after & before):
                break
        after = {c.name for c in op.kube.list("NodeClaim")}
        assert after and not (after & before), "fleet did not roll"
        assert all(p.node_name for p in op.kube.list("Pod"))

    def test_drift_rolls_fleet(self, op, clock):
        np_, nc = mk_cluster(op)
        for p in make_pods(40, cpu="1", memory="2Gi", prefix="drift"):
            op.kube.create(p)
        op.run_until_settled(disrupt=False)
        before = {c.name for c in op.kube.list("NodeClaim")}
        # roll the AMI fleet-wide
        from karpenter_provider_aws_tpu.fake.ec2 import FakeImage, _new_id
        for img in list(op.ec2.images.values()):
            img.deprecated = True
        for arch in ("amd64", "arm64"):
            new = FakeImage(id=_new_id("ami"), name=f"al2023-{arch}-v9",
                            arch=arch, creation_date=2_000_000_000.0,
                            ssm_alias=f"al2023@latest/{arch}")
            op.ec2.images[new.id] = new
            op.ec2.ssm_parameters[
                f"/aws/service/al2023/{arch}/latest/image_id"] = new.id
        op.ssm_invalidation.reconcile(force=True)
        for _ in range(30):
            op.run_until_settled()
            clock.advance(30)
            after = {c.name for c in op.kube.list("NodeClaim")}
            if after and not (after & before):
                break
        after = {c.name for c in op.kube.list("NodeClaim")}
        assert after and not (after & before), "drifted fleet did not roll"


