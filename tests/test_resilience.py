"""Sidecar resilience layer: retry policy, circuit breaker, the guarded
call path, AliveCache probe dedupe, and router park/recovery."""

import random
import threading
import time

import numpy as np
import pytest

from karpenter_provider_aws_tpu.sidecar.resilience import (
    CLOSED, HALF_OPEN, OPEN, CircuitBreaker, ResiliencePolicy, RetryPolicy,
    SidecarUnavailable)
from karpenter_provider_aws_tpu.solver.route import (DEV_FAILED_MS,
                                                     AliveCache, Router)


def _unavailable():
    import grpc

    from karpenter_provider_aws_tpu.fake.faultwire import _injected_error
    return _injected_error(grpc.StatusCode.UNAVAILABLE, "test: down")


def _rejected(code=None):
    import grpc

    from karpenter_provider_aws_tpu.fake.faultwire import _injected_error
    return _injected_error(code or grpc.StatusCode.INVALID_ARGUMENT,
                           "test: rejected")


def _policy(max_attempts=3, threshold=5, cooldown_s=60.0, clock=None,
            metrics=None):
    return ResiliencePolicy(
        retry=RetryPolicy(max_attempts=max_attempts, backoff_base_s=0.0,
                          backoff_cap_s=0.0, rng=random.Random(0),
                          sleep=lambda s: None),
        breaker=CircuitBreaker(threshold=threshold, cooldown_s=cooldown_s,
                               clock=clock or time.monotonic),
        metrics=metrics)


class TestRetryPolicy:
    def test_full_jitter_is_seeded_and_bounded(self):
        a = RetryPolicy(backoff_base_s=0.1, backoff_cap_s=1.0,
                        rng=random.Random(42))
        b = RetryPolicy(backoff_base_s=0.1, backoff_cap_s=1.0,
                        rng=random.Random(42))
        seq_a = [a.backoff_s(i) for i in range(8)]
        seq_b = [b.backoff_s(i) for i in range(8)]
        assert seq_a == seq_b  # same seed, same schedule
        for i, s in enumerate(seq_a):
            assert 0.0 <= s <= min(1.0, 0.1 * 2 ** i)
        # the cap binds: late attempts never exceed it
        assert all(s <= 1.0 for s in seq_a)

    def test_deadline_scales_with_payload(self):
        pol = ResiliencePolicy(wire_bytes_per_s=1e6, max_deadline_s=50.0)
        assert pol.deadline_for(0, 10.0) == 10.0
        assert pol.deadline_for(2_000_000, 10.0) == pytest.approx(12.0)
        assert pol.deadline_for(10**9, 10.0) == 50.0  # capped


class TestCircuitBreaker:
    def test_state_machine_full_cycle(self):
        now = [0.0]
        br = CircuitBreaker(threshold=3, cooldown_s=10.0,
                            clock=lambda: now[0])
        seen = []
        br.on_transition.append(lambda o, n: seen.append((o, n)))
        assert br.state == CLOSED
        for _ in range(2):
            br.record_failure()
        assert br.state == CLOSED  # below threshold
        br.record_failure()
        assert br.state == OPEN
        assert br.allow() is False  # cooldown not elapsed: fail fast
        now[0] = 11.0
        assert br.allow() is True  # the half-open probe
        assert br.state == HALF_OPEN
        assert br.allow() is False  # ONE probe at a time
        br.record_success()
        assert br.state == CLOSED
        assert br.allow() is True
        assert seen == [(CLOSED, OPEN), (OPEN, HALF_OPEN),
                        (HALF_OPEN, CLOSED)]

    def test_half_open_failure_reopens(self):
        now = [0.0]
        br = CircuitBreaker(threshold=2, cooldown_s=5.0,
                            clock=lambda: now[0])
        br.record_failure()
        br.record_failure()
        now[0] = 6.0
        assert br.allow()
        assert br.state == HALF_OPEN
        br.record_failure()
        assert br.state == OPEN
        assert br.allow() is False  # cooldown restarted at reopen
        now[0] = 11.5
        assert br.allow()

    def test_success_resets_consecutive_count(self):
        br = CircuitBreaker(threshold=3)
        br.record_failure()
        br.record_failure()
        br.record_success()
        br.record_failure()
        br.record_failure()
        assert br.state == CLOSED  # never 3 CONSECUTIVE failures

    def test_transition_callback_errors_are_swallowed(self):
        br = CircuitBreaker(threshold=1)

        def boom(o, n):
            raise RuntimeError("observer bug")

        br.on_transition.append(boom)
        br.record_failure()  # must not raise
        assert br.state == OPEN


class TestPolicyCall:
    def test_retries_then_succeeds(self):
        pol = _policy(max_attempts=3)
        calls = {"n": 0}

        def attempt(deadline):
            calls["n"] += 1
            if calls["n"] < 3:
                raise _unavailable()
            return "served"

        assert pol.call(attempt, rpc="Solve") == "served"
        assert calls["n"] == 3
        assert pol.last_call["retries"] == 2
        assert pol.last_call["ok"] is True

    def test_exhausted_raises_sidecar_unavailable(self):
        import grpc
        pol = _policy(max_attempts=2)

        def attempt(deadline):
            raise _unavailable()

        with pytest.raises(SidecarUnavailable) as ei:
            pol.call(attempt, rpc="Solve")
        assert not isinstance(ei.value, grpc.RpcError)
        assert ei.value.attempts == 2

    def test_rejection_reraises_without_retry(self):
        import grpc
        pol = _policy(max_attempts=3)
        calls = {"n": 0}

        def attempt(deadline):
            calls["n"] += 1
            raise _rejected()

        with pytest.raises(grpc.RpcError) as ei:
            pol.call(attempt, rpc="Solve")
        assert ei.value.code() == grpc.StatusCode.INVALID_ARGUMENT
        assert calls["n"] == 1  # the peer answered; retrying is pointless
        assert pol.breaker.state == CLOSED

    def test_malformed_response_is_retried(self):
        pol = _policy(max_attempts=3)
        calls = {"n": 0}

        def attempt(deadline):
            calls["n"] += 1
            if calls["n"] == 1:
                raise ValueError("arena checksum mismatch")
            return "served"

        assert pol.call(attempt, rpc="SolveTopo") == "served"
        assert calls["n"] == 2

    def test_breaker_open_fails_fast_without_wire_attempt(self):
        pol = _policy(max_attempts=1, threshold=2)
        for _ in range(2):
            with pytest.raises(SidecarUnavailable):
                pol.call(lambda d: (_ for _ in ()).throw(_unavailable()),
                         rpc="Solve")
        assert pol.breaker.state == OPEN
        calls = {"n": 0}

        def attempt(deadline):
            calls["n"] += 1
            return "served"

        with pytest.raises(SidecarUnavailable) as ei:
            pol.call(attempt, rpc="Solve")
        assert ei.value.breaker_open is True
        assert calls["n"] == 0  # no wire attempt while open

    def test_open_mid_call_stops_the_retry_loop(self):
        pol = _policy(max_attempts=5, threshold=2)
        calls = {"n": 0}

        def attempt(deadline):
            calls["n"] += 1
            raise _unavailable()

        with pytest.raises(SidecarUnavailable):
            pol.call(attempt, rpc="Solve")
        # the 2nd failure opened the breaker; attempts 3..5 never ran
        assert calls["n"] == 2

    def test_half_open_probe_success_closes(self):
        now = [0.0]
        pol = _policy(max_attempts=1, threshold=1, cooldown_s=5.0,
                      clock=lambda: now[0])
        with pytest.raises(SidecarUnavailable):
            pol.call(lambda d: (_ for _ in ()).throw(_unavailable()),
                     rpc="Info")
        assert pol.breaker.state == OPEN
        now[0] = 6.0
        assert pol.call(lambda d: "pong", rpc="Info") == "pong"
        assert pol.breaker.state == CLOSED

    def test_metrics_series_emitted(self):
        from karpenter_provider_aws_tpu.utils.metrics import Metrics
        m = Metrics()
        now = [0.0]
        pol = _policy(max_attempts=2, threshold=2, clock=lambda: now[0],
                      metrics=m)
        pol.emit_state()
        assert m.gauge("karpenter_solver_sidecar_breaker_state") == 0
        with pytest.raises(SidecarUnavailable):
            pol.call(lambda d: (_ for _ in ()).throw(_unavailable()),
                     rpc="Solve")
        assert m.counter("karpenter_solver_sidecar_retries_total",
                         labels={"rpc": "Solve"}) == 1
        assert m.counter("karpenter_solver_sidecar_rpc_total",
                         labels={"rpc": "Solve",
                                 "outcome": "unavailable"}) == 1
        assert m.counter(
            "karpenter_solver_sidecar_breaker_transitions_total",
            labels={"from": CLOSED, "to": OPEN}) == 1
        assert m.gauge("karpenter_solver_sidecar_breaker_state") == 2


class TestAliveCacheDedupe:
    def test_concurrent_blocking_runs_one_probe(self):
        """Satellite: the thundering herd — N concurrent blocking()
        callers must share ONE probe run, not launch N."""
        probes = {"n": 0}
        gate = threading.Event()

        def probe():
            probes["n"] += 1
            gate.wait(5.0)
            return True

        cache = AliveCache(probe)
        verdicts = []

        def worker():
            verdicts.append(cache.blocking())

        threads = [threading.Thread(target=worker) for _ in range(5)]
        for t in threads:
            t.start()
        time.sleep(0.2)  # let every caller reach the wait
        gate.set()
        for t in threads:
            t.join(10.0)
        assert verdicts == [True] * 5
        assert probes["n"] == 1

    def test_false_verdict_expires_and_reprobes(self):
        verdicts = iter([False, True])
        cache = AliveCache(lambda: next(verdicts), recheck_s=0.05)
        assert cache.blocking() is False
        assert cache.blocking() is False  # cached within recheck window
        time.sleep(0.06)
        assert cache.blocking() is True

    def test_mark_failed_and_mark_ok(self):
        cache = AliveCache(lambda: True, recheck_s=30.0)
        cache.mark_failed()
        assert cache.nonblocking() is False  # no probe, external evidence
        cache.mark_ok()
        assert cache.nonblocking() is True
        assert cache.blocking() is True


class TestRouterParkRecovery:
    def test_observe_parks_and_unparks_absolutely(self):
        r = Router()
        b = ("bucket",)
        r.observe(b, "dev", 10.0)
        r.observe(b, "dev", DEV_FAILED_MS)
        assert r.snapshot()[b]["dev"] == DEV_FAILED_MS  # not blended
        r.observe(b, "dev", 12.0)
        assert r.snapshot()[b]["dev"] == 12.0  # recovery is immediate

    def test_park_dev_parks_every_bucket(self):
        r = Router()
        for i in range(3):
            r.observe((i,), "dev", 5.0)
            r.observe((i,), "host", 9.0)
        r.park_dev()
        snap = r.snapshot()
        for i in range(3):
            assert snap[(i,)]["dev"] == DEV_FAILED_MS
            assert snap[(i,)]["host"] == 9.0
        assert r.choose((0,))[0] == "host"

    def test_refresh_probe_restores_dev_within_one_cycle(self, monkeypatch):
        """Satellite: after DEV_FAILED_MS parking, a healthy dev engine
        must win routing back within one REFRESH_EVERY cycle via the
        background refresh probe (the recovery half of the routing
        story; the failure half is covered in test_solver_route)."""
        from karpenter_provider_aws_tpu.solver import route
        monkeypatch.setattr(route, "REFRESH_EVERY", 4)
        r = Router()
        r.alive = AliveCache(lambda: True)
        assert r.alive.blocking()
        b = ("shape",)
        served = {"dev": 0, "host": 0}

        def host_fn():
            served["host"] += 1
            time.sleep(0.005)  # the slow side: dev must win on merit
            return "host"

        def dev_fn():
            served["dev"] += 1
            return "dev"

        r.observe(b, "host", 5.0)
        r.observe(b, "dev", 1.0)
        r.park_dev()  # breaker opened: dev EWMA parked
        for _ in range(route.REFRESH_EVERY):
            assert route.routed(r, b, host_fn, dev_fn) == "host"
        # the REFRESH_EVERY-th solve kicked the background probe; it
        # re-measures dev_fn and the absolute un-park restores routing
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if r.snapshot()[b]["dev"] < DEV_FAILED_MS:
                break
            time.sleep(0.01)
        assert r.snapshot()[b]["dev"] < DEV_FAILED_MS, \
            "refresh probe never un-parked the dev EWMA"
        assert route.routed(r, b, host_fn, dev_fn) == "dev"


class TestRemoteSolverDegradation:
    def test_dispatch_converts_unavailable_to_device_dispatch_failed(self):
        """The tentpole crash gap: base Solve against a dead address must
        raise DeviceDispatchFailed (router/solve-core degrade), never a
        grpc.RpcError."""
        import grpc

        from karpenter_provider_aws_tpu.sidecar import RemoteSolver
        from karpenter_provider_aws_tpu.solver.tpu import \
            DeviceDispatchFailed
        remote = RemoteSolver("127.0.0.1:1", n_max=64,
                              policy=_policy(max_attempts=2))
        remote.client.timeout = 0.5
        with pytest.raises(DeviceDispatchFailed) as ei:
            remote._dispatch(np.zeros(4, dtype=np.int64),
                             T=1, D=8, Z=1, C=3, G=1, E=0, P=1, K=0,
                             V=0, M=0, n_max=4, F=1)
        assert not isinstance(ei.value, grpc.RpcError)
        assert remote.last_dispatch_stats["served_by"] == "host-twin"
        assert remote.last_dispatch_stats["retries"] == 1

    def test_breaker_open_parks_router_and_marks_not_alive(self):
        from karpenter_provider_aws_tpu.sidecar import RemoteSolver
        from karpenter_provider_aws_tpu.solver.tpu import \
            DeviceDispatchFailed
        remote = RemoteSolver("127.0.0.1:1", n_max=64,
                              policy=_policy(max_attempts=1, threshold=2))
        remote.client.timeout = 0.5
        remote._router.alive.mark_ok()
        remote._router.observe(("b",), "dev", 1.0)
        for _ in range(2):
            with pytest.raises(DeviceDispatchFailed):
                remote._dispatch(np.zeros(4, dtype=np.int64),
                                 T=1, D=8, Z=1, C=3, G=1, E=0, P=1,
                                 K=0, V=0, M=0, n_max=4, F=1)
        assert remote.client.policy.breaker.state == OPEN
        assert remote._router.snapshot()[("b",)]["dev"] == DEV_FAILED_MS
        assert remote._router.alive.nonblocking() is False

    def test_ping_survives_malformed_info(self):
        """Satellite: an Info response missing `devices` must be an
        explicit not-alive verdict, not a KeyError out of the probe."""
        from karpenter_provider_aws_tpu.sidecar import RemoteSolver

        class WeirdClient:
            def info(self, timeout=None):
                return {}  # truncated/hostile peer: no 'devices'

        remote = RemoteSolver.__new__(RemoteSolver)
        remote.client = WeirdClient()
        remote._pruned_ok = None
        assert RemoteSolver._ping(remote) is False
        assert remote._pruned_ok is False

        class DeadClient:
            def info(self, timeout=None):
                raise SidecarUnavailable("Info", 3)

        remote.client = DeadClient()
        assert RemoteSolver._ping(remote) is False
