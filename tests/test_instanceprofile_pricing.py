"""Instance-profile lifecycle (ref instanceprofile.go:43-46) and pricing
static-fallback semantics (ref pricing.go:108-157)."""

import pytest

from karpenter_provider_aws_tpu.apis.objects import EC2NodeClass
from karpenter_provider_aws_tpu.fake.iam import FakeIAM, ProfileNotFoundError
from karpenter_provider_aws_tpu.providers.instanceprofile import \
    InstanceProfileProvider
from karpenter_provider_aws_tpu.providers.pricing import PricingProvider


def _nodeclass(name="default", role="KarpenterNodeRole", profile=""):
    return EC2NodeClass(name=name, role=role, instance_profile=profile)


class FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


class TestInstanceProfileLifecycle:
    def test_create_get_delete(self):
        iam = FakeIAM()
        p = InstanceProfileProvider("cl", "us-west-2", iam=iam)
        nc = _nodeclass()
        name = p.create(nc)
        assert name == "cl_default_us-west-2_profile"
        assert p.get(name) == "KarpenterNodeRole"
        prof = iam.get_instance_profile(name)
        assert prof.tags["karpenter.k8s.aws/ec2nodeclass"] == "default"
        p.delete(nc)
        assert p.get(name) is None
        with pytest.raises(ProfileNotFoundError):
            iam.get_instance_profile(name)

    def test_create_is_idempotent_and_cached(self):
        iam = FakeIAM()
        p = InstanceProfileProvider("cl", "us-west-2", iam=iam)
        nc = _nodeclass()
        p.create(nc)
        p.create(nc)
        p.create(nc)
        # the UID cache short-circuits the IAM round trips
        assert iam.create_profile_calls.called_times == 1
        assert iam.add_role_calls.called_times == 1

    def test_role_drift_rebinds(self):
        iam = FakeIAM()
        clock = FakeClock()
        p = InstanceProfileProvider("cl", "us-west-2", iam=iam, clock=clock)
        nc = _nodeclass(role="RoleA")
        name = p.create(nc)
        assert p.get(name) == "RoleA"
        # the role changes on the nodeclass; after cache expiry create()
        # must remove the stale role and attach the new one
        # (instanceprofile.go:92-113)
        nc.role = "RoleB"
        clock.t += 16 * 60
        assert p.create(nc) == name
        assert p.get(name) == "RoleB"
        assert iam.remove_role_calls.called_times == 1
        assert iam.create_profile_calls.called_times == 1  # no recreate

    def test_role_path_is_stripped(self):
        iam = FakeIAM()
        p = InstanceProfileProvider("cl", "us-west-2", iam=iam)
        nc = _nodeclass(role="path/to/KarpenterNodeRole")
        name = p.create(nc)
        assert p.get(name) == "KarpenterNodeRole"

    def test_spec_pinned_profile_is_user_managed(self):
        iam = FakeIAM()
        p = InstanceProfileProvider("cl", "us-west-2", iam=iam)
        nc = _nodeclass(profile="my-own-profile")
        assert p.create(nc) == "my-own-profile"
        assert iam.create_profile_calls.called_times == 0
        p.delete(nc)  # never touches IAM for user-managed profiles
        assert iam.delete_profile_calls.called_times == 0

    def test_delete_ignores_absent_profile(self):
        p = InstanceProfileProvider("cl", "us-west-2", iam=FakeIAM())
        p.delete(_nodeclass())  # no raise

    def test_nodeclass_deletion_reaps_profile_via_controller(self):
        from karpenter_provider_aws_tpu.operator import Operator
        op = Operator()
        nc = _nodeclass(name="reap-me")
        op.kube.create(nc)
        op.nodeclass_status.reconcile()
        name = nc.status_instance_profile
        assert op.instance_profiles.get(name) == "KarpenterNodeRole"
        assert "karpenter.k8s.aws/termination" in nc.metadata.finalizers
        op.kube.delete('EC2NodeClass', nc.metadata.name)  # finalizer holds it
        op.nodeclass_status.reconcile()
        assert op.instance_profiles.get(name) is None
        import pytest as _pt
        from karpenter_provider_aws_tpu.fake.kube import NotFound
        with _pt.raises(NotFound):
            op.kube.get("EC2NodeClass", nc.metadata.name)


class TestPricingFallback:
    class DeadPricingAPI:
        def on_demand_prices(self):
            raise ConnectionError("pricing API unreachable")

        def describe_spot_price_history(self):
            raise ConnectionError("pricing API unreachable")

    class EmptyPricingAPI:
        def on_demand_prices(self):
            return {}

        def describe_spot_price_history(self):
            return []

    def test_boot_with_dead_api_prices_every_type(self):
        p = PricingProvider(self.DeadPricingAPI())
        assert p.update_on_demand_pricing() is False
        assert p.update_spot_pricing() is False
        types = p.instance_types()
        assert len(types) > 500  # the full static table
        for t in types[:50]:
            od = p.on_demand_price(t)
            sp = p.spot_price(t, "us-west-2a")
            assert od and od > 0
            assert sp and 0 < sp < od  # static default spot < od

    def test_empty_refresh_keeps_previous_prices(self):
        p = PricingProvider(self.EmptyPricingAPI())
        before = p.on_demand_prices()
        assert before
        assert p.update_on_demand_pricing() is False
        assert p.update_spot_pricing() is False
        assert p.on_demand_prices() == before

    def test_live_refresh_takes_over_spot_zoning(self):
        class LiveAPI:
            def on_demand_prices(self):
                return {"m5.large": 96_000}

            def describe_spot_price_history(self):
                return [("m5.large", "us-west-2a", 30_000)]

        p = PricingProvider(LiveAPI())
        # pre-refresh: static default regardless of zone
        assert p.spot_price("m5.large", "nonexistent-zone") is not None
        assert p.update_spot_pricing() is True
        assert p.spot_price("m5.large", "us-west-2a") == 30_000
        # post-refresh the per-zone map is authoritative: unknown zone
        # has no price (pricing.go SpotPrice second branch)
        assert p.spot_price("m5.large", "nonexistent-zone") is None
        assert p.update_on_demand_pricing() is True
        assert p.on_demand_price("m5.large") == 96_000
