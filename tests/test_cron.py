"""Budget schedule engine (utils/cron.py): upstream cronjob syntax,
naive UTC, the dom/dow either-matches quirk, and the active-window
semantics budgets consume (karpenter.sh_nodepools.yaml:126-133)."""

from datetime import datetime, timezone

import pytest

from karpenter_provider_aws_tpu.apis.objects import DisruptionBudget
from karpenter_provider_aws_tpu.utils.cron import (Cron, CronError,
                                                   parse_duration)


def ts(y, mo, d, h=0, m=0):
    return datetime(y, mo, d, h, m, tzinfo=timezone.utc).timestamp()


class TestParse:
    def test_shortcuts(self):
        assert Cron("@daily").most_recent_fire(ts(2026, 7, 31, 13, 5)) \
            == ts(2026, 7, 31)
        assert Cron("@hourly").most_recent_fire(ts(2026, 7, 31, 13, 5)) \
            == ts(2026, 7, 31, 13)
        assert Cron("@weekly").most_recent_fire(ts(2026, 7, 31, 13, 5)) \
            == ts(2026, 7, 26)  # Sunday
        assert Cron("@monthly").most_recent_fire(ts(2026, 7, 31)) \
            == ts(2026, 7, 1)
        assert Cron("@yearly").most_recent_fire(ts(2026, 7, 31)) \
            == ts(2026, 1, 1)

    def test_steps_ranges_lists(self):
        c = Cron("*/15 9-17 * * 1-5")
        # Friday 2026-07-31 13:05 -> 13:00 is within window
        assert c.most_recent_fire(ts(2026, 7, 31, 13, 5)) \
            == ts(2026, 7, 31, 13, 0)
        # Sunday morning -> falls back to Friday 17:45
        assert c.most_recent_fire(ts(2026, 8, 2, 7, 0)) \
            == ts(2026, 7, 31, 17, 45)
        c2 = Cron("0 0,12 * * *")
        assert c2.most_recent_fire(ts(2026, 7, 31, 11, 59)) \
            == ts(2026, 7, 31, 0, 0)

    def test_names_and_sunday_seven(self):
        assert Cron("0 9 * * sun").most_recent_fire(
            ts(2026, 7, 31)) == ts(2026, 7, 26, 9)
        assert Cron("0 9 * * 7").most_recent_fire(
            ts(2026, 7, 31)) == ts(2026, 7, 26, 9)
        assert Cron("0 0 1 jan *").most_recent_fire(
            ts(2026, 7, 31)) == ts(2026, 1, 1)

    def test_dom_dow_either_quirk(self):
        # both restricted: the 15th OR a Monday fires
        c = Cron("0 0 15 * 1")
        # 2026-07-31 is Friday; most recent = Mon Jul 27 (after the 15th)
        assert c.most_recent_fire(ts(2026, 7, 31)) == ts(2026, 7, 27)

    def test_rejects_garbage(self):
        for bad in ("* * * *", "61 * * * *", "* 25 * * *", "a b c d e",
                    "*/0 * * * *"):
            with pytest.raises(CronError):
                Cron(bad)

    def test_durations(self):
        assert parse_duration("8h") == 8 * 3600
        assert parse_duration("30m") == 1800
        assert parse_duration("1h30m") == 5400
        assert parse_duration(90.0) == 90.0
        with pytest.raises(CronError):
            parse_duration("ten minutes")


class TestBudgetWindow:
    def test_active_within_window_only(self):
        b = DisruptionBudget(nodes="0", schedule="0 9 * * *",
                             duration="8h")
        assert b.active(ts(2026, 7, 31, 9, 0))
        assert b.active(ts(2026, 7, 31, 16, 59))
        assert not b.active(ts(2026, 7, 31, 17, 0))  # window closed
        assert not b.active(ts(2026, 7, 31, 8, 59))  # not yet open

    def test_no_schedule_always_active(self):
        assert DisruptionBudget(nodes="1").active(ts(2026, 1, 1))

    def test_float_duration_seconds(self):
        b = DisruptionBudget(nodes="0", schedule="@hourly",
                             duration=600.0)
        assert b.active(ts(2026, 7, 31, 13, 9))
        assert not b.active(ts(2026, 7, 31, 13, 11))

    def test_validation_rejects_bad_schedule(self):
        from karpenter_provider_aws_tpu.apis.objects import (
            Disruption, NodeClassRef, NodePool, NodePoolTemplate)
        from karpenter_provider_aws_tpu.apis.requirements import \
            Requirements
        from karpenter_provider_aws_tpu.apis.validation import (
            ValidationError, validate_nodepool)
        np = NodePool("p", template=NodePoolTemplate(
            node_class_ref=NodeClassRef("c"),
            requirements=Requirements()),
            disruption=Disruption(budgets=[DisruptionBudget(
                nodes="0", schedule="not a cron", duration="1h")]))
        with pytest.raises(ValidationError):
            validate_nodepool(np)
