"""Controller manager, file lease, and the runnable daemon: cadence
scheduling, error isolation, leader election, HTTP endpoints, and an
end-to-end provision-through-the-daemon flow (cmd/controller/main.go:28-74
run continuously, not stepped)."""

import json
import threading
import time
import urllib.request

import pytest

from karpenter_provider_aws_tpu.daemon import Daemon
from karpenter_provider_aws_tpu.fake.environment import make_pods
from karpenter_provider_aws_tpu.manager import ControllerManager, FileLease, _Entry
from karpenter_provider_aws_tpu.utils.metrics import Metrics


class TestControllerManager:
    def test_cadence_and_error_isolation(self):
        m = Metrics()
        mgr = ControllerManager(metrics=m)
        counts = {"fast": 0, "slow": 0, "bad": 0}

        def fast():
            counts["fast"] += 1

        def slow():
            counts["slow"] += 1

        def bad():
            counts["bad"] += 1
            raise RuntimeError("boom")

        mgr.register("fast", fast, 0.02)
        mgr.register("slow", slow, 10.0)
        mgr.register("bad", bad, 0.05)
        mgr.start()
        time.sleep(0.5)
        mgr.stop()
        assert counts["fast"] >= 5          # many fires at 20ms cadence
        assert counts["slow"] == 1          # immediate fire, then 10s wait
        assert counts["bad"] >= 2           # errors don't unschedule it
        assert counts["fast"] >= counts["bad"]
        assert m.counter("karpenter_controller_reconcile_errors_total",
                         {"controller": "bad"}) == counts["bad"]

    def test_warmup_schedule(self):
        # GC's 10s x 20 then 2m (garbagecollection/controller.go:55-62)
        e = _Entry(due=0, seq=0, name="gc", reconcile=lambda: None,
                   interval=120.0, initial_interval=10.0, initial_count=20)
        delays = []
        for _ in range(22):
            delays.append(e.next_delay())
            e.fired += 1
        assert delays[:20] == [10.0] * 20
        assert delays[20:] == [120.0, 120.0]


class TestFileLease:
    def test_exclusive_acquire_and_release(self, tmp_path):
        path = str(tmp_path / "lease")
        a = FileLease(path, identity="a", ttl=5.0)
        b = FileLease(path, identity="b", ttl=5.0)
        assert a.try_acquire()
        assert not b.try_acquire()
        a.release()
        assert b.try_acquire()
        b.release()

    def test_steal_expired(self, tmp_path):
        path = str(tmp_path / "lease")
        with open(path, "w") as f:
            json.dump({"holder": "dead", "renewed": time.time() - 60}, f)
        c = FileLease(path, identity="c", ttl=5.0)
        assert c.try_acquire()
        c.release()

    def test_concurrent_steal_single_winner(self, tmp_path):
        """Split-brain guard: when two standbys race to steal an expired
        lease, the post-write re-read ensures at most one claims it."""
        path = str(tmp_path / "lease")
        with open(path, "w") as f:
            json.dump({"holder": "dead", "renewed": time.time() - 60}, f)
        a = FileLease(path, identity="a", ttl=5.0)
        b = FileLease(path, identity="b", ttl=5.0)
        got_a, got_b = a.try_acquire(), b.try_acquire()
        assert got_a + got_b == 1
        # the loser's later heartbeat must not re-steal: simulate by
        # checking the file still names the winner after both heartbeats
        time.sleep(0.1)
        cur = json.load(open(path))
        assert cur["holder"] == ("a" if got_a else "b")
        a.release(); b.release()

    def test_reacquire_own_stale(self, tmp_path):
        path = str(tmp_path / "lease")
        with open(path, "w") as f:
            json.dump({"holder": "me", "renewed": time.time() - 60}, f)
        me = FileLease(path, identity="me", ttl=5.0)
        assert me.try_acquire()
        me.release()


@pytest.fixture
def daemon():
    d = Daemon(metrics_port=0, simulate_kubelet=True)
    d.start()
    yield d
    d.shutdown()


def _get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=5) as r:
        return r.status, r.read().decode()


class TestDaemon:
    def test_endpoints(self, daemon):
        status, body = _get(daemon.metrics_port, "/healthz")
        assert status == 200 and body == "ok"
        status, body = _get(daemon.metrics_port, "/metrics")
        assert status == 200
        assert "karpenter_controller_reconcile_duration_seconds" in body \
            or body == "\n"  # first scrape may race the first reconcile

    def test_provisions_pending_pods_continuously(self, daemon):
        op = daemon.operator
        # create nodeclass/nodepool/pods through the kube API the daemon
        # watches — no step() calls anywhere
        from karpenter_provider_aws_tpu.apis.objects import (
            EC2NodeClass, NodeClassRef, NodePool, NodePoolTemplate)
        op.kube.create(EC2NodeClass("daemon-class"))
        op.kube.create(NodePool("daemon-pool", template=NodePoolTemplate(
            node_class_ref=NodeClassRef("daemon-class"))))
        for p in make_pods(40, cpu="500m", memory="1Gi", prefix="dmn"):
            op.kube.create(p)
        deadline = time.time() + 30
        while time.time() < deadline:
            pods = op.kube.list("Pod")
            nodes = op.kube.list("Node")
            if pods and all(p.node_name for p in pods) \
                    and nodes and all(n.ready for n in nodes):
                break
            time.sleep(0.25)
        pods = op.kube.list("Pod")
        assert pods and all(p.node_name for p in pods), \
            "daemon did not schedule pods"
        assert op.kube.list("Node")
        status, body = _get(daemon.metrics_port, "/metrics")
        assert "karpenter_controller_reconcile_duration_seconds" in body

    def test_graceful_shutdown(self):
        d = Daemon(metrics_port=0)
        d.start()
        assert d.healthy()
        d.shutdown()
        assert not d.manager.running

    def test_daemon_provisions_through_sidecar(self):
        """The chart's sidecar.enabled wiring end to end: a daemon built
        with --solver tpu --solver-sidecar-address provisions pending
        pods with its solve dispatches riding the gRPC companion."""
        from karpenter_provider_aws_tpu.apis.objects import (
            EC2NodeClass, NodeClassRef, NodePool, NodePoolTemplate)
        from karpenter_provider_aws_tpu.sidecar.client import RemoteSolver
        from karpenter_provider_aws_tpu.sidecar.server import SolverServer
        server = SolverServer().start()
        d = None
        try:
            d = Daemon(metrics_port=0, solver="tpu",
                       sidecar_address=server.address)
            assert isinstance(d.operator.solver, RemoteSolver)
            d.start()
            op = d.operator
            op.kube.create(EC2NodeClass("sc-class"))
            op.kube.create(NodePool("sc-pool", template=NodePoolTemplate(
                node_class_ref=NodeClassRef("sc-class"))))
            for p in make_pods(15, cpu="500m", memory="1Gi", prefix="sc"):
                op.kube.create(p)
            deadline = time.time() + 30
            while time.time() < deadline:
                pods = op.kube.list("Pod")
                if pods and all(p.node_name for p in pods):
                    break
                time.sleep(0.25)
            pods = op.kube.list("Pod")
            assert pods and all(p.node_name for p in pods), \
                "sidecar-backed daemon did not schedule pods"
        finally:
            if d is not None:
                d.shutdown()
            server.stop(0)

    def test_daemon_provisions_through_fleet(self):
        """--solver-fleet-endpoints (chart: sidecar.fleetEndpoints) builds
        a FleetSolver over the replica list — and takes precedence over
        --solver-sidecar-address when both are set."""
        from karpenter_provider_aws_tpu.apis.objects import (
            EC2NodeClass, NodeClassRef, NodePool, NodePoolTemplate)
        from karpenter_provider_aws_tpu.fleet import FleetSolver
        from karpenter_provider_aws_tpu.sidecar.server import SolverServer
        servers = [SolverServer().start() for _ in range(2)]
        d = None
        try:
            eps = ",".join(s.address for s in servers)
            d = Daemon(metrics_port=0, solver="tpu",
                       sidecar_address="127.0.0.1:1",   # must be ignored
                       fleet_endpoints=eps)
            assert isinstance(d.operator.solver, FleetSolver)
            assert sorted(d.operator.solver._fleet.addresses()) == \
                sorted(s.address for s in servers)
            d.start()
            op = d.operator
            op.kube.create(EC2NodeClass("fl-class"))
            op.kube.create(NodePool("fl-pool", template=NodePoolTemplate(
                node_class_ref=NodeClassRef("fl-class"))))
            for p in make_pods(15, cpu="500m", memory="1Gi", prefix="fl"):
                op.kube.create(p)
            deadline = time.time() + 30
            while time.time() < deadline:
                pods = op.kube.list("Pod")
                if pods and all(p.node_name for p in pods):
                    break
                time.sleep(0.25)
            pods = op.kube.list("Pod")
            assert pods and all(p.node_name for p in pods), \
                "fleet-backed daemon did not schedule pods"
        finally:
            if d is not None:
                d.shutdown()
            for s in servers:
                s.stop(0)

    def test_leader_election_gates_controllers(self, tmp_path):
        path = str(tmp_path / "lease")
        holder = FileLease(path, identity="other", ttl=30.0)
        assert holder.try_acquire()
        d = Daemon(metrics_port=0, lease_path=path)
        t = threading.Thread(target=d.start, daemon=True)
        t.start()
        time.sleep(0.6)
        assert not d.manager.running      # blocked on the lease
        holder.release()
        deadline = time.time() + 10
        while time.time() < deadline and not d.manager.running:
            time.sleep(0.2)
        assert d.manager.running          # took over after release
        d.shutdown()


class TestBootPreflight:
    """Fail-fast boot contract (operator.go:111-115,218-227 analogs): a
    dead or WEDGED cloud seam must abort boot with a clear error well
    inside 5s, never start controllers that spin against it."""

    def test_healthy_boot_discovers_region(self):
        from karpenter_provider_aws_tpu.operator import Operator
        op = Operator()
        assert op.region == "us-west-2"
        assert op.instance_profiles.region == "us-west-2"

    def test_dead_link_fails_fast(self):
        import time

        from karpenter_provider_aws_tpu.fake.ec2 import FakeEC2
        from karpenter_provider_aws_tpu.operator import (Operator,
                                                         PreflightError)
        ec2 = FakeEC2()
        ec2.link_down = True
        t0 = time.perf_counter()
        with pytest.raises(PreflightError, match="unreachable"):
            Operator(ec2=ec2)
        assert time.perf_counter() - t0 < 5.0

    def test_wedged_link_fails_within_deadline(self):
        import time

        from karpenter_provider_aws_tpu.fake.ec2 import FakeEC2
        from karpenter_provider_aws_tpu.operator import (Operator,
                                                         PreflightError)
        ec2 = FakeEC2()
        ec2.link_stall_s = 30.0  # blocks, does not error — the wedge
        t0 = time.perf_counter()
        with pytest.raises(PreflightError, match="wedged"):
            Operator(ec2=ec2, preflight_deadline=1.0)
        assert time.perf_counter() - t0 < 5.0

    def test_daemon_main_exits_nonzero_on_dead_cloud(self, monkeypatch,
                                                     tmp_path):
        from karpenter_provider_aws_tpu import daemon as daemon_mod
        from karpenter_provider_aws_tpu.operator import PreflightError

        def _boom(*a, **k):
            raise PreflightError("EC2 connectivity preflight failed")

        monkeypatch.setattr(daemon_mod, "Daemon", _boom)
        rc = daemon_mod.main(["--cluster-name", "demo"])
        assert rc == 1


class TestLeaseLossSplitBrain:
    """Satellite of the split-brain fix: losing the lease must PAUSE the
    controllers (not just flip a flag), flip /readyz to 503, and a later
    re-acquire must resume them without a process restart."""

    def test_lease_loss_pauses_then_reacquire_resumes(self, tmp_path):
        import urllib.error
        path = str(tmp_path / "lease")
        d = Daemon(metrics_port=0, lease_path=path)
        d.lease.ttl = 0.6  # fast heartbeat (ttl/3) so the test stays quick
        t = threading.Thread(target=d.start, daemon=True)
        t.start()
        deadline = time.time() + 10
        while time.time() < deadline and not d.manager.running:
            time.sleep(0.05)
        assert d.manager.running and d.healthy()
        status, _ = _get(d.metrics_port, "/readyz")
        assert status == 200
        # a usurper replaces the lease out from under the daemon; renewed
        # sits slightly in the future so the daemon cannot steal it back
        # before we observe the demoted state
        with open(path, "w") as f:
            json.dump({"holder": "usurper",
                       "renewed": time.time() + 1.0}, f)
        deadline = time.time() + 5
        while time.time() < deadline and d.manager.running:
            time.sleep(0.05)
        assert not d.manager.running   # controllers paused, not running
        assert not d.healthy()
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(d.metrics_port, "/readyz")
        assert ei.value.code == 503    # demoted replica sheds traffic
        # the usurper never renews: its lease expires and the daemon's
        # rejoin loop steals it back and restarts the controllers
        deadline = time.time() + 15
        while time.time() < deadline and not d.manager.running:
            time.sleep(0.1)
        assert d.manager.running and d.healthy()
        status, _ = _get(d.metrics_port, "/readyz")
        assert status == 200
        d.shutdown()


class TestLinkFlapRecovery:
    """Runtime companion to TestBootPreflight: a link that drops AFTER a
    healthy boot makes reconciles error (counted, retried) but must not
    require a restart — clearing the fault resumes provisioning."""

    def test_runtime_flap_recovers_without_restart(self):
        from karpenter_provider_aws_tpu.apis.objects import (
            EC2NodeClass, NodeClassRef, NodePool, NodePoolTemplate)
        from karpenter_provider_aws_tpu.operator import Operator
        op = Operator()
        op.kube.create(EC2NodeClass("flap-class"))
        op.kube.create(NodePool("flap-pool", template=NodePoolTemplate(
            node_class_ref=NodeClassRef("flap-class"))))
        for p in make_pods(3, cpu="500m", memory="1Gi", prefix="flap"):
            op.kube.create(p)
        op.run_until_settled()
        assert all(p.node_name for p in op.kube.list("Pod"))
        # the link drops mid-run: new work errors through the retry
        # policy (transient, bounded backoff) and surfaces ConnectionError
        op.ec2.link_down = True
        for p in make_pods(2, cpu="500m", memory="1Gi", prefix="flap2"):
            op.kube.create(p)
        with pytest.raises((ConnectionError, OSError)):
            for _ in range(8):
                op.step()
        # the link heals: the SAME operator converges, no restart
        op.ec2.link_down = False
        op.run_until_settled()
        pods = op.kube.list("Pod")
        assert all(p.node_name for p in pods
                   if p.phase not in ("Succeeded", "Failed"))
