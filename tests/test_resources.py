import pytest

from karpenter_provider_aws_tpu.apis.resources import (
    Resources, format_quantity, parse_quantity, sum_resources)


def test_parse_cpu():
    assert parse_quantity("1", "cpu") == 1000
    assert parse_quantity("100m", "cpu") == 100
    assert parse_quantity("2.5", "cpu") == 2500
    assert parse_quantity(2, "cpu") == 2000


def test_parse_memory():
    assert parse_quantity("1Gi", "memory") == 1024**3
    assert parse_quantity("512Mi", "memory") == 512 * 1024**2
    assert parse_quantity("1G", "memory") == 10**9
    assert parse_quantity("128", "memory") == 128


def test_parse_counts():
    assert parse_quantity("4", "nvidia.com/gpu") == 4
    assert parse_quantity(110, "pods") == 110


def test_parse_invalid():
    with pytest.raises(ValueError):
        parse_quantity("abc", "cpu")


def test_arithmetic():
    a = Resources.parse({"cpu": "1", "memory": "1Gi"})
    b = Resources.parse({"cpu": "500m", "memory": "512Mi", "pods": 3})
    s = a + b
    assert s["cpu"] == 1500 and s["memory"] == 1024**3 + 512 * 1024**2 and s["pods"] == 3
    d = a - b
    assert d["cpu"] == 500 and d["pods"] == -3
    assert d.clamp_nonnegative()["pods"] == 0


def test_fits():
    cap = Resources.parse({"cpu": "4", "memory": "8Gi", "pods": 110})
    req = Resources.parse({"cpu": "3500m", "memory": "6Gi"})
    assert req.fits(cap)
    too_big = Resources.parse({"cpu": "5"})
    assert not too_big.fits(cap)
    # extended resource not present in capacity
    gpu = Resources.parse({"nvidia.com/gpu": 1})
    assert not gpu.fits(cap)


def test_zero_canonicalization():
    assert Resources({"cpu": 0}) == Resources()
    assert len(Resources({"cpu": 0, "memory": 5})) == 1
    assert Resources({"cpu": 1}) - Resources({"cpu": 1}) == Resources()


def test_merge_max_and_sum():
    a = Resources({"cpu": 100, "memory": 10})
    b = Resources({"cpu": 50, "memory": 20})
    m = a.merge_max(b)
    assert m["cpu"] == 100 and m["memory"] == 20
    assert sum_resources([a, b])["cpu"] == 150


def test_format():
    assert format_quantity(1500, "cpu") == "1500m"
    assert format_quantity(2000, "cpu") == "2"
    assert format_quantity(1024**3, "memory") == "1Gi"
    assert format_quantity(7, "pods") == "7"


def test_hashable():
    assert hash(Resources({"cpu": 1})) == hash(Resources({"cpu": 1, "memory": 0}))


def test_parse_rejects_negative():
    with pytest.raises(ValueError):
        Resources.parse({"cpu": "-1"})
