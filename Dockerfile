# ko-build analog: the controller image runs the daemon
# (cmd/controller/main.go:28-74 equivalent entrypoint).
FROM python:3.12-slim
WORKDIR /app
COPY karpenter_provider_aws_tpu/ karpenter_provider_aws_tpu/
RUN pip install --no-cache-dir numpy jax grpcio
ENTRYPOINT ["python", "-m", "karpenter_provider_aws_tpu"]
