"""Controller manager: per-controller cadences on one reconcile loop.

The reference registers every controller on a single controller-runtime
manager (cmd/controller/main.go:42-73, pkg/controllers/controllers.go:63-101)
where each controller requeues at its own cadence — 12h catalog/pricing
refresh (providers/instancetype/controller.go:59), 30m SSM invalidation
(ssm/invalidation/controller.go:55), 10s x 20 then 2m garbage collection
(nodeclaim/garbagecollection/controller.go:55-90), continuous SQS long-poll
interruption (interruption/controller.go:94-134).

This manager is the Python analog: controllers register with an interval
(optionally a warm-up schedule like GC's), a binary heap orders due times,
and one worker thread runs reconciles sequentially — the same effective
concurrency as one manager whose controllers each have
MaxConcurrentReconciles=1. Parallelism *within* a reconcile (the
reference's workqueue.ParallelizeUntil fan-outs) belongs to the individual
controllers. Every reconcile is wrapped with duration/error metrics and
panic isolation, matching controller-runtime's recovery behavior.

Leader election (charts/karpenter/values.yaml:38 runs 2 replicas with
leader election) is a file lease: acquire-or-steal-on-expiry with a
heartbeat, so an active/passive replica pair can share a node.
"""

from __future__ import annotations

import heapq
import json
import logging
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional

log = logging.getLogger(__name__)


class ReconcileError(Exception):
    """A typed, retryable reconcile failure — the controller-runtime
    'return error' path (counted as an error + retry, not a panic)."""


class TerminalReconcileError(Exception):
    """A reconcile failure retrying cannot fix (bad object spec) —
    controller_runtime_terminal_reconcile_errors_total."""


@dataclass(order=True)
class _Entry:
    due: float
    seq: int
    name: str = field(compare=False)
    reconcile: Callable = field(compare=False)
    interval: float = field(compare=False)
    initial_interval: Optional[float] = field(compare=False, default=None)
    initial_count: int = field(compare=False, default=0)
    fired: int = field(compare=False, default=0)

    def next_delay(self) -> float:
        """Warm-up schedule: `initial_interval` for the first
        `initial_count` fires, then the steady `interval` (GC's 10s x 20
        then 2m — garbagecollection/controller.go:55-62)."""
        if self.initial_interval is not None \
                and self.fired < self.initial_count:
            return self.initial_interval
        return self.interval


class ControllerManager:
    def __init__(self, metrics=None, clock=time.monotonic):
        self._metrics = metrics
        self._clock = clock
        self._heap: List[_Entry] = []
        self._seq = 0
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._mu = threading.Lock()

    def register(self, name: str, reconcile: Callable[[], object],
                 interval: float, *, initial_interval: Optional[float] = None,
                 initial_count: int = 0, immediate: bool = True) -> None:
        """Register a controller. `immediate` fires the first reconcile at
        start (the reference hydrates catalog/pricing/version at boot —
        operator.go:152-155 — and every singleton reconciles on start)."""
        with self._mu:
            self._seq += 1
            due = self._clock() if immediate else \
                self._clock() + (initial_interval if initial_interval
                                 is not None else interval)
            heapq.heappush(self._heap, _Entry(
                due=due, seq=self._seq, name=name, reconcile=reconcile,
                interval=interval, initial_interval=initial_interval,
                initial_count=initial_count))
        if self._metrics is not None:
            self._metrics.inc("workqueue_adds_total",
                              labels={"controller": name})
        self._wake.set()

    # ------------------------------------------------------------------
    def start(self) -> "ControllerManager":
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="controller-manager")
        self._thread.start()
        return self

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    # ------------------------------------------------------------------
    def _run(self) -> None:
        while not self._stop.is_set():
            with self._mu:
                entry = self._heap[0] if self._heap else None
            if entry is None:
                self._wake.wait(0.2)
                self._wake.clear()
                continue
            delay = entry.due - self._clock()
            if delay > 0:
                self._wake.wait(min(delay, 1.0))
                self._wake.clear()
                continue
            with self._mu:
                entry = heapq.heappop(self._heap)
            self._reconcile_one(entry)
            entry.fired += 1
            entry.due = self._clock() + entry.next_delay()
            with self._mu:
                heapq.heappush(self._heap, entry)
            if self._metrics is not None:  # the cadence requeue
                self._metrics.inc("workqueue_adds_total",
                                  labels={"controller": entry.name})

    def _reconcile_one(self, entry: _Entry) -> None:
        t0 = self._clock()
        m = self._metrics
        lab = {"controller": entry.name}
        if m is not None:
            # workqueue group: how long the item sat due before running,
            # and the single-worker loop's live state
            m.observe("workqueue_queue_duration_seconds",
                      max(0.0, t0 - entry.due), labels=lab)
            m.set_gauge("workqueue_depth", float(len(self._heap)))
            m.set_gauge("controller_runtime_active_workers", 1.0,
                        labels=lab)
            m.set_gauge("controller_runtime_max_concurrent_reconciles",
                        1.0, labels=lab)
        try:
            entry.reconcile()
        except ReconcileError:
            # a typed, retryable reconcile error (the requeue-with-error
            # path); the cadence retries it
            log.exception("reconcile %s errored", entry.name)
            if m is not None:
                m.inc("karpenter_controller_reconcile_errors_total",
                      labels=lab)
                m.inc("controller_runtime_reconcile_errors_total",
                      labels=lab)
                m.inc("workqueue_retries_total", labels=lab)
        except TerminalReconcileError:
            log.exception("reconcile %s failed terminally", entry.name)
            if m is not None:
                m.inc("controller_runtime_terminal_reconcile_errors_total",
                      labels=lab)
        except Exception:  # noqa: BLE001 - reconcile panics must not kill
            # the manager; controller-runtime recovers and requeues
            log.exception("reconcile %s panicked", entry.name)
            if m is not None:
                m.inc("karpenter_controller_reconcile_errors_total",
                      labels=lab)
                m.inc("controller_runtime_reconcile_panics_total",
                      labels=lab)
                m.inc("workqueue_retries_total", labels=lab)
        finally:
            dt = self._clock() - t0
            if m is not None:
                m.observe(
                    "karpenter_controller_reconcile_duration_seconds",
                    dt, labels=lab)
                m.inc("controller_runtime_reconcile_total", labels=lab)
                m.observe("controller_runtime_reconcile_time_seconds",
                          dt, labels=lab)
                m.observe("workqueue_work_duration_seconds", dt,
                          labels=lab)
                m.set_gauge("workqueue_unfinished_work_seconds", 0.0,
                            labels=lab)
                m.set_gauge(
                    "workqueue_longest_running_processor_seconds",
                    max(dt, m.gauge(
                        "workqueue_longest_running_processor_seconds",
                        labels=lab)), labels=lab)
                m.set_gauge("controller_runtime_active_workers", 0.0,
                            labels=lab)


# ---------------------------------------------------------------------------
# leader election
# ---------------------------------------------------------------------------

class FileLease:
    """File-based lease lock: the HA analog of the chart's 2-replica
    leader election (charts/karpenter/values.yaml:38). Acquire by O_EXCL
    create; steal only when the holder's heartbeat is older than the TTL;
    renew on a heartbeat thread while held."""

    def __init__(self, path: str, identity: str = "",
                 ttl: float = 15.0, clock=time.time, metrics=None):
        self.path = path
        self.identity = identity or f"pid-{os.getpid()}"
        self.ttl = ttl
        self._clock = clock
        self._held = False
        self._hb: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.metrics = metrics
        #: callbacks fired (from the heartbeat thread) when a HELD lease
        #: is observed lost — losing leadership must pause the holder's
        #: controllers, not just flip a flag (split-brain guard)
        self.on_lost: List[Callable[[], None]] = []

    def _set_master(self, held: bool) -> None:
        self._held = held
        if self.metrics is not None:
            self.metrics.set_gauge("leader_election_master_status",
                                   1.0 if held else 0.0,
                                   labels={"name": self.identity})

    def _read(self) -> Optional[dict]:
        try:
            with open(self.path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _write(self) -> None:
        tmp = f"{self.path}.{self.identity}.tmp"
        with open(tmp, "w") as f:
            json.dump({"holder": self.identity,
                       "renewed": self._clock()}, f)
        os.replace(tmp, self.path)

    def try_acquire(self) -> bool:
        if self._held:
            return True
        try:
            fd = os.open(self.path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            os.close(fd)
            self._write()
            self._set_master(True)
        except FileExistsError:
            cur = self._read()
            if cur is not None and cur.get("holder") == self.identity:
                self._set_master(True)  # our own stale lease (restart)
                self._write()
            elif cur is None or \
                    self._clock() - cur.get("renewed", 0) > self.ttl:
                # expired: steal — but N standbys race here, and os.replace
                # makes last-writer-wins, so re-read to learn who actually
                # won before claiming leadership (split-brain guard)
                if self.metrics is not None:
                    self.metrics.inc("leader_election_slowpath_total",
                                     labels={"name": self.identity})
                self._write()
                winner = self._read()
                self._set_master(winner is not None
                                 and winner.get("holder") == self.identity)
        if self._held:
            self._stop.clear()
            self._hb = threading.Thread(target=self._heartbeat, daemon=True,
                                        name="lease-heartbeat")
            self._hb.start()
        return self._held

    def acquire(self, poll: float = 1.0,
                stop: Optional[threading.Event] = None) -> bool:
        """Block until the lease is held (or `stop` is set)."""
        while not (stop and stop.is_set()):
            if self.try_acquire():
                return True
            time.sleep(poll)
        return False

    def _heartbeat(self) -> None:
        while not self._stop.wait(self.ttl / 3):
            if not self._held:
                continue
            # renew only while the file still names us: a heartbeat that
            # blindly rewrites would re-steal a lease another replica won
            cur = self._read()
            if cur is not None and cur.get("holder") == self.identity:
                self._write()
            else:
                # lost the lease: demote FIRST (listeners observe
                # held=False), notify, and exit this heartbeat — a
                # re-acquire starts a fresh one, so a stale thread can
                # never renew a lease another replica now owns
                self._set_master(False)
                for cb in list(self.on_lost):
                    try:
                        cb()
                    except Exception:  # noqa: BLE001 - a listener crash
                        # must not kill the demotion path
                        log.exception("lease on_lost callback failed")
                return

    def release(self) -> None:
        self._stop.set()
        if self._hb is not None:
            self._hb.join(1.0)
            self._hb = None
        if self._held:
            cur = self._read()
            if cur is not None and cur.get("holder") == self.identity:
                try:
                    os.unlink(self.path)
                except OSError:
                    pass
            self._set_master(False)

    @property
    def held(self) -> bool:
        return self._held
