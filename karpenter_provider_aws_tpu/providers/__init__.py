from .instancetype import (DEFAULT_VM_MEMORY_OVERHEAD_PERCENT,
                           InstanceTypeProvider, OfferingsSnapshot)

__all__ = ["InstanceTypeProvider", "OfferingsSnapshot",
           "DEFAULT_VM_MEMORY_OVERHEAD_PERCENT"]
