from .amifamily import AMI, AMIProvider, BootstrapConfig, generate_user_data
from .instance import InstanceProvider, LaunchedInstance
from .instancetype import (DEFAULT_VM_MEMORY_OVERHEAD_PERCENT,
                           InstanceTypeProvider, OfferingsSnapshot)
from .launchtemplate import LaunchTemplateProvider, ResolvedLaunchTemplate
from .network import SecurityGroupProvider, SubnetInfo, SubnetProvider
from .instanceprofile import InstanceProfileProvider
from .pricing import PricingProvider
from .sqs import InterruptionMessage, SQSProvider
from .version import VersionProvider

__all__ = ["InstanceTypeProvider", "OfferingsSnapshot",
           "DEFAULT_VM_MEMORY_OVERHEAD_PERCENT", "InstanceProvider",
           "LaunchedInstance", "LaunchTemplateProvider",
           "ResolvedLaunchTemplate", "SubnetProvider", "SubnetInfo",
           "SecurityGroupProvider", "AMIProvider", "AMI", "BootstrapConfig",
           "generate_user_data", "PricingProvider", "SQSProvider",
           "InterruptionMessage", "InstanceProfileProvider", "VersionProvider"]
