"""IAM instance-profile lifecycle for the NodeClass role.

Mirrors pkg/providers/instanceprofile/instanceprofile.go:43-46 (the 264
LoC Create/Delete provider):

- ``create(nc)`` is get-or-create against IAM, validated for role drift:
  a profile that exists with a DIFFERENT role gets the old role removed
  and the desired one attached (instanceprofile.go:92-113) — IAM
  profiles hold at most one role. Role paths are stripped before
  AddRole (AddRoleToInstanceProfile takes bare names).
- a per-NodeClass-UID TTL cache (cache.go InstanceProfile 15m) skips the
  IAM round trips while the binding is known-good; role drift is
  revalidated after expiry.
- ``delete(nc)`` removes the role then the profile, ignoring NotFound
  (instanceprofile.go:117-140) — called by the NodeClass termination
  path, so deleting a NodeClass reaps the profile it created.
- A spec-pinned ``instanceProfile`` bypasses the provider entirely: the
  user owns that profile's lifecycle (cloudprovider semantics for
  spec.instanceProfile).
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from ..cache.ttl import INSTANCE_PROFILE_TTL, TTLCache
from ..fake.iam import FakeIAM, ProfileNotFoundError

REGION_TAG = "topology.kubernetes.io/region"


class InstanceProfileProvider:
    def __init__(self, cluster_name: str = "cluster",
                 region: str = "us-west-2",
                 iam: Optional[FakeIAM] = None, clock=None):
        self.cluster_name = cluster_name
        self.region = region
        self.iam = iam if iam is not None else FakeIAM()
        self._mu = threading.Lock()
        self._cache = TTLCache(ttl=INSTANCE_PROFILE_TTL,
                               clock=clock or time.monotonic)

    def profile_name(self, nodeclass) -> str:
        """Deterministic per-(cluster, nodeclass, region) profile name —
        reconstructable on restart, the state-in-cluster discipline."""
        return (f"{self.cluster_name}_{nodeclass.metadata.name}_"
                f"{self.region}_profile")

    @staticmethod
    def _role_name(role: str) -> str:
        # AddRoleToInstanceProfile takes the bare role name; strip any
        # IAM path prefix (instanceprofile.go:106-108)
        return role.rsplit("/", 1)[-1]

    def create(self, nodeclass) -> str:
        if nodeclass.instance_profile:
            return nodeclass.instance_profile  # user-managed profile
        name = self.profile_name(nodeclass)
        if self._cache.get(nodeclass.metadata.uid) is not None:
            return name
        role = self._role_name(nodeclass.role)
        # the get-or-create + role-rebind sequence is check-then-act;
        # serialize it (concurrent reconciles of one class race the IAM
        # create, and the rebind must never interleave)
        with self._mu:
            try:
                profile = self.iam.get_instance_profile(name)
            except ProfileNotFoundError:
                try:
                    self.iam.create_instance_profile(
                        name,
                        tags={REGION_TAG: self.region,
                              "karpenter.k8s.aws/cluster": self.cluster_name,
                              "karpenter.k8s.aws/ec2nodeclass":
                                  nodeclass.metadata.name})
                except ValueError:
                    pass  # EntityAlreadyExists: another actor won the race
                profile = self.iam.get_instance_profile(name)
            if profile.roles:
                if profile.roles[0] == role:
                    self._cache.put(nodeclass.metadata.uid, name)
                    return name
                # role drift: rebind (profiles hold at most one role)
                self.iam.remove_role_from_instance_profile(
                    name, profile.roles[0])
            self.iam.add_role_to_instance_profile(name, role)
            self._cache.put(nodeclass.metadata.uid, name)
            return name

    def delete(self, nodeclass) -> None:
        if nodeclass.instance_profile:
            return  # user-managed: never reap
        name = self.profile_name(nodeclass)
        # same serialization as create(): remove-roles-then-delete is
        # check-then-act, and a concurrent create() re-adding a role
        # between the two steps must not crash the reconcile
        with self._mu:
            try:
                profile = self.iam.get_instance_profile(name)
            except ProfileNotFoundError:
                return
            for role in list(profile.roles):
                self.iam.remove_role_from_instance_profile(name, role)
            try:
                self.iam.delete_instance_profile(name)
            except (ProfileNotFoundError, ValueError):
                # NotFound: someone else deleted it; ValueError ("still
                # has a role"): a create() raced us — it will be reaped
                # on the next termination reconcile
                pass
            self._cache.delete(nodeclass.metadata.uid)

    # compatibility with callers that look profiles up by name ------------
    def get(self, name: str) -> Optional[str]:
        try:
            profile = self.iam.get_instance_profile(name)
        except ProfileNotFoundError:
            return None
        return profile.roles[0] if profile.roles else ""
