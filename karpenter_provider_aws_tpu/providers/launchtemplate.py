"""Launch-template provider.

Mirrors pkg/providers/launchtemplate: resolve per-(AMI x arch) launch
templates — ``ensure_all`` (launchtemplate.go:112-135), name = hash of the
resolved options (:146), create with network interfaces / block device
mappings (:275-343), cache hydration on start (:345-371), eviction →
DeleteLaunchTemplates (:373-390).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, replace as replace_dataclass
from typing import Dict, List, Optional, Sequence

from ..apis.objects import EC2NodeClass, Taint, stable_hash
from ..cache.ttl import TTLCache
from ..fake.ec2 import FakeLaunchTemplate
from ..apis.resources import AWS_EFA
from .amifamily import (AMI, AMIProvider, BootstrapConfig,
                        generate_user_data, map_to_instance_types)
from .network import SecurityGroupProvider

LT_NAME_PREFIX = "karpenter.k8s.aws"


@dataclass(frozen=True)
class ResolvedLaunchTemplate:
    name: str
    image_id: str
    arch: str
    #: instance type names this template serves (same AMI mapping bucket,
    #: same EFA interface count)
    instance_type_names: tuple
    efa_count: int = 0


#: per-family default root volumes when the NodeClass specifies none
#: (amifamily resolvers' DefaultBlockDeviceMappings; bottlerocket splits
#: OS and data volumes)
_DEFAULT_BDMS = {
    "al2": [{"device_name": "/dev/xvda", "volume_size": "20Gi",
             "volume_type": "gp3", "encrypted": True, "root_volume": True}],
    "al2023": [{"device_name": "/dev/xvda", "volume_size": "20Gi",
                "volume_type": "gp3", "encrypted": True,
                "root_volume": True}],
    "bottlerocket": [
        {"device_name": "/dev/xvda", "volume_size": "4Gi",
         "volume_type": "gp3", "encrypted": True, "root_volume": True},
        {"device_name": "/dev/xvdb", "volume_size": "20Gi",
         "volume_type": "gp3", "encrypted": True, "root_volume": False}],
    "windows2019": [{"device_name": "/dev/sda1", "volume_size": "50Gi",
                     "volume_type": "gp3", "encrypted": True,
                     "root_volume": True}],
    "windows2022": [{"device_name": "/dev/sda1", "volume_size": "50Gi",
                     "volume_type": "gp3", "encrypted": True,
                     "root_volume": True}],
}


class LaunchTemplateProvider:
    def __init__(self, ec2, ami_provider: AMIProvider,
                 sg_provider: SecurityGroupProvider,
                 cluster_name: str = "cluster",
                 cluster_endpoint: str = "https://cluster.local",
                 ca_bundle: str = "", kube_dns_ip: str = "", clock=None):
        self.ec2 = ec2
        #: cluster service CIDR, resolved lazily from the cluster on first
        #: template build (launchtemplate.go:433+ resolveClusterCIDR)
        self._cluster_cidr: Optional[str] = None
        self.ami = ami_provider
        self.sg = sg_provider
        self.cluster_name = cluster_name
        self.cluster_endpoint = cluster_endpoint
        self.ca_bundle = ca_bundle
        self.kube_dns_ip = kube_dns_ip
        #: cluster IP family from the kube-dns address family
        #: (launchtemplate.go:98)
        self.cluster_ip_family = "ipv6" if ":" in kube_dns_ip else "ipv4"
        self._cache = TTLCache(ttl=600, clock=clock)
        self._mu = threading.Lock()
        self.hydrate()

    def hydrate(self) -> None:
        """Re-learn existing templates on restart (launchtemplate.go:345-371)."""
        for lt in self.ec2.describe_launch_templates():
            if lt.name.startswith(LT_NAME_PREFIX):
                self._cache.put(lt.name, lt)

    def _resolve_cluster_cidr(self) -> str:
        """Service CIDR from the cluster, resolved once and cached
        (launchtemplate.go:433+; nodeadm userdata needs it)."""
        if self._cluster_cidr is None:
            # IPv6 service CIDR wins when the cluster has one
            # (launchtemplate.go:448-450)
            self._cluster_cidr = (
                getattr(self.ec2, "eks_service_ipv6_cidr", None)
                or getattr(self.ec2, "eks_cluster_cidr", None)
                or "10.100.0.0/16")
        return self._cluster_cidr

    def _network_interfaces(self, efa_count: int,
                            nodeclass: EC2NodeClass) -> List[dict]:
        """EFA-capable buckets get one EFA interface per available slot
        (device 0 carries the primary IP); plain buckets get the single
        default interface with the NodeClass's public-IP choice. IPv6
        clusters ask for one IPv6 address on the primary interface
        (PrimaryIpv6/Ipv6AddressCount, launchtemplate.go:275-305)."""
        ipv6 = self.cluster_ip_family == "ipv6"
        if efa_count > 0:
            out = [{"device_index": 0 if i == 0 else 1,
                    "network_card_index": i,
                    "interface_type": "efa",
                    "groups": "nodeclass"} for i in range(efa_count)]
            if nodeclass.associate_public_ip is not None:
                # the public-IP choice rides the primary (device 0)
                # interface even when EFA is enabled (launchtemplate.go)
                out[0]["associate_public_ip_address"] = \
                    nodeclass.associate_public_ip
            if ipv6:
                out[0]["primary_ipv6"] = True
                out[0]["ipv6_address_count"] = 1
            return out
        out = []
        if nodeclass.associate_public_ip is not None:
            out = [{"device_index": 0,
                    "associate_public_ip_address":
                        nodeclass.associate_public_ip}]
        if ipv6:
            if not out:
                out = [{"device_index": 0}]
            out[0]["primary_ipv6"] = True
            out[0]["ipv6_address_count"] = 1
        return out

    def _block_device_mappings(self, nodeclass: EC2NodeClass) -> List[dict]:
        if nodeclass.block_device_mappings:
            return [vars(b) for b in nodeclass.block_device_mappings]
        return [dict(b) for b in
                _DEFAULT_BDMS.get(nodeclass.ami_family, ())]

    def ensure_all(self, nodeclass: EC2NodeClass, instance_types,
                   labels: Optional[Dict[str, str]] = None,
                   taints: Sequence[Taint] = (),
                   ) -> List[ResolvedLaunchTemplate]:
        """One launch template per (AMI bucket x EFA interface count)
        covering the given types (launchtemplate.go:112-135; EFA types
        need their own template because the interface config differs)."""
        amis = self.ami.list(nodeclass)
        buckets = map_to_instance_types(amis, instance_types)
        sgs = self.sg.list(nodeclass)
        out: List[ResolvedLaunchTemplate] = []
        with self._mu:
            for ami in amis:
                types = buckets.get(ami.id, [])
                if not types:
                    continue
                by_efa: Dict[int, list] = {}
                for t in types:
                    # EFA slots ride the capacity vector
                    # (vpc.amazonaws.com/efa, labels.go:91-98)
                    efa = int(t.capacity.get(AWS_EFA, 0))                         if hasattr(t, "capacity") else 0
                    by_efa.setdefault(efa, []).append(t)
                for efa_count, efa_types in sorted(by_efa.items()):
                    out.append(self._ensure_one(
                        nodeclass, ami, efa_types, efa_count, sgs,
                        labels, taints))
        return out

    def _effective_kubelet(self, nodeclass: EC2NodeClass):
        """Default ClusterDNS to the discovered kube-dns IP when the
        NodeClass doesn't set one (resolver.go:188-200)."""
        kl = nodeclass.kubelet
        if self.kube_dns_ip and not kl.cluster_dns:
            kl = replace_dataclass(kl, cluster_dns=[self.kube_dns_ip])
        return kl

    def _effective_metadata_options(self, nodeclass: EC2NodeClass) -> dict:
        """Spec metadata options, with HttpProtocolIpv6 defaulting to
        enabled on IPv6 clusters when the NodeClass leaves the options
        untouched (resolver.go:178-184 DefaultMetadataOptions)."""
        md = vars(nodeclass.metadata_options).copy()
        from ..apis.objects import MetadataOptions
        if (self.cluster_ip_family == "ipv6"
                and nodeclass.metadata_options == MetadataOptions()):
            md["http_protocol_ipv6"] = "enabled"
        return md

    def _ensure_one(self, nodeclass: EC2NodeClass, ami: AMI, types,
                    efa_count: int, sgs, labels, taints
                    ) -> ResolvedLaunchTemplate:
        user_data = generate_user_data(
            nodeclass.ami_family, BootstrapConfig(
                cluster_name=self.cluster_name,
                cluster_endpoint=self.cluster_endpoint,
                ca_bundle=self.ca_bundle,
                cluster_cidr=self._resolve_cluster_cidr(),
                ip_family=self.cluster_ip_family,
                instance_store_policy=nodeclass.instance_store_policy,
                labels=dict(labels or {}), taints=tuple(taints),
                kubelet=self._effective_kubelet(nodeclass),
                custom_user_data=nodeclass.user_data))
        name = self._lt_name(nodeclass, ami, sgs, user_data,
                             efa_count=efa_count)
        if self._cache.get(name) is None:
            nis = self._network_interfaces(efa_count, nodeclass)
            for ni in nis:
                if ni.get("groups") == "nodeclass":
                    ni["groups"] = list(sgs)
            lt = FakeLaunchTemplate(
                id="", name=name, image_id=ami.id,
                security_group_ids=list(sgs), user_data=user_data,
                tags=dict(nodeclass.tags),
                metadata_options=self._effective_metadata_options(nodeclass),
                block_device_mappings=self._block_device_mappings(nodeclass),
                network_interfaces=nis,
                instance_profile=nodeclass.status_instance_profile
                or nodeclass.instance_profile)
            self.ec2.create_launch_template(lt)
            self._cache.put(name, lt)
        return ResolvedLaunchTemplate(
            name=name, image_id=ami.id, arch=ami.arch,
            instance_type_names=tuple(t.name for t in types),
            efa_count=efa_count)

    def _lt_name(self, nodeclass: EC2NodeClass, ami: AMI,
                 sgs: Sequence[str], user_data: str,
                 efa_count: int = 0) -> str:
        """Deterministic name from the resolved options (launchtemplate.go:146)."""
        h = stable_hash({
            "ami": ami.id, "sgs": list(sgs), "userData": user_data,
            "nodeClassHash": nodeclass.hash(),
            "instanceProfile": nodeclass.status_instance_profile,
            "efaCount": efa_count,
        })
        return f"{LT_NAME_PREFIX}/{nodeclass.metadata.name}/{h}"

    def invalidate(self, names) -> None:
        """Drop cached templates so the next ensure_all recreates them
        (the launcher's LT-not-found retry path, instance.go:111-115)."""
        with self._mu:
            for n in names:
                self._cache.delete(n)

    def delete_for_nodeclass(self, nodeclass: EC2NodeClass) -> int:
        """NodeClass deletion -> drop its templates (launchtemplate.go:373-390)."""
        prefix = f"{LT_NAME_PREFIX}/{nodeclass.metadata.name}/"
        doomed = [lt.name for lt in self.ec2.describe_launch_templates()
                  if lt.name.startswith(prefix)]
        self.ec2.delete_launch_templates(doomed)
        for n in doomed:
            self._cache.delete(n)
        return len(doomed)
