"""Launch-template provider.

Mirrors pkg/providers/launchtemplate: resolve per-(AMI x arch) launch
templates — ``ensure_all`` (launchtemplate.go:112-135), name = hash of the
resolved options (:146), create with network interfaces / block device
mappings (:275-343), cache hydration on start (:345-371), eviction →
DeleteLaunchTemplates (:373-390).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

from ..apis.objects import EC2NodeClass, Taint, stable_hash
from ..cache.ttl import TTLCache
from ..fake.ec2 import FakeLaunchTemplate
from .amifamily import AMI, AMIProvider, BootstrapConfig, generate_user_data, map_to_instance_types
from .network import SecurityGroupProvider

LT_NAME_PREFIX = "karpenter.k8s.aws"


@dataclass(frozen=True)
class ResolvedLaunchTemplate:
    name: str
    image_id: str
    arch: str
    #: instance type names this template serves (same AMI mapping bucket)
    instance_type_names: tuple


class LaunchTemplateProvider:
    def __init__(self, ec2, ami_provider: AMIProvider,
                 sg_provider: SecurityGroupProvider,
                 cluster_name: str = "cluster",
                 cluster_endpoint: str = "https://cluster.local",
                 ca_bundle: str = "", clock=None):
        self.ec2 = ec2
        self.ami = ami_provider
        self.sg = sg_provider
        self.cluster_name = cluster_name
        self.cluster_endpoint = cluster_endpoint
        self.ca_bundle = ca_bundle
        self._cache = TTLCache(ttl=600, clock=clock)
        self._mu = threading.Lock()
        self.hydrate()

    def hydrate(self) -> None:
        """Re-learn existing templates on restart (launchtemplate.go:345-371)."""
        for lt in self.ec2.describe_launch_templates():
            if lt.name.startswith(LT_NAME_PREFIX):
                self._cache.put(lt.name, lt)

    def ensure_all(self, nodeclass: EC2NodeClass, instance_types,
                   labels: Optional[Dict[str, str]] = None,
                   taints: Sequence[Taint] = (),
                   ) -> List[ResolvedLaunchTemplate]:
        """One launch template per (AMI bucket) covering the given types
        (launchtemplate.go:112-135)."""
        amis = self.ami.list(nodeclass)
        buckets = map_to_instance_types(amis, instance_types)
        sgs = self.sg.list(nodeclass)
        out: List[ResolvedLaunchTemplate] = []
        with self._mu:
            for ami in amis:
                types = buckets.get(ami.id, [])
                if not types:
                    continue
                user_data = generate_user_data(
                    nodeclass.ami_family, BootstrapConfig(
                        cluster_name=self.cluster_name,
                        cluster_endpoint=self.cluster_endpoint,
                        ca_bundle=self.ca_bundle,
                        labels=dict(labels or {}), taints=tuple(taints),
                        kubelet=nodeclass.kubelet,
                        custom_user_data=nodeclass.user_data))
                name = self._lt_name(nodeclass, ami, sgs, user_data)
                if self._cache.get(name) is None:
                    lt = FakeLaunchTemplate(
                        id="", name=name, image_id=ami.id,
                        security_group_ids=list(sgs), user_data=user_data,
                        tags=dict(nodeclass.tags),
                        metadata_options=vars(nodeclass.metadata_options),
                        block_device_mappings=[vars(b) for b in
                                               nodeclass.block_device_mappings],
                        instance_profile=nodeclass.status_instance_profile
                        or nodeclass.instance_profile)
                    self.ec2.create_launch_template(lt)
                    self._cache.put(name, lt)
                out.append(ResolvedLaunchTemplate(
                    name=name, image_id=ami.id, arch=ami.arch,
                    instance_type_names=tuple(t.name for t in types)))
        return out

    def _lt_name(self, nodeclass: EC2NodeClass, ami: AMI,
                 sgs: Sequence[str], user_data: str) -> str:
        """Deterministic name from the resolved options (launchtemplate.go:146)."""
        h = stable_hash({
            "ami": ami.id, "sgs": list(sgs), "userData": user_data,
            "nodeClassHash": nodeclass.hash(),
            "instanceProfile": nodeclass.status_instance_profile,
        })
        return f"{LT_NAME_PREFIX}/{nodeclass.metadata.name}/{h}"

    def invalidate(self, names) -> None:
        """Drop cached templates so the next ensure_all recreates them
        (the launcher's LT-not-found retry path, instance.go:111-115)."""
        with self._mu:
            for n in names:
                self._cache.delete(n)

    def delete_for_nodeclass(self, nodeclass: EC2NodeClass) -> int:
        """NodeClass deletion -> drop its templates (launchtemplate.go:373-390)."""
        prefix = f"{LT_NAME_PREFIX}/{nodeclass.metadata.name}/"
        doomed = [lt.name for lt in self.ec2.describe_launch_templates()
                  if lt.name.startswith(prefix)]
        self.ec2.delete_launch_templates(doomed)
        for n in doomed:
            self._cache.delete(n)
        return len(doomed)
