"""Interruption queue (pkg/providers/sqs, sqs.go:31-36): receive/delete
plus send for tests, and the normalized interruption-message model
(interruption/messages/types.go:21-57)."""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, List


@dataclass
class InterruptionMessage:
    """Parsed SQS interruption message (interruption/messages/types.go:21-57).
    kinds: spot_interruption | rebalance_recommendation | scheduled_change |
    state_change | noop"""
    kind: str
    instance_id: str
    detail: str = ""
    receipt: str = ""


class SQSProvider:
    """Receive/delete with send for tests; messages are insertion-ordered
    with O(1) delete (a 15k-message drain must not be O(n^2))."""

    def __init__(self, queue_name: str = "karpenter-interruption"):
        self.queue_name = queue_name
        self._mu = threading.Lock()
        self._messages: Dict[str, InterruptionMessage] = {}
        self._receipt = 0

    def send(self, message: InterruptionMessage) -> None:
        with self._mu:
            self._receipt += 1
            message.receipt = str(self._receipt)
            self._messages[message.receipt] = message

    def send_raw(self, raw: str) -> None:
        """Enqueue a raw EventBridge JSON body — what real SQS delivers.
        Parsed through the messages parsers (one envelope may fan out to
        several normalized messages, e.g. a multi-instance AWS Health
        scheduled change)."""
        from .interruption_messages import parse_message
        for m in parse_message(raw):
            self.send(m)

    def receive(self, max_messages: int = 10) -> List[InterruptionMessage]:
        with self._mu:
            out = []
            for m in self._messages.values():
                out.append(m)
                if len(out) >= max_messages:
                    break
            return out

    def delete(self, message: InterruptionMessage) -> None:
        with self._mu:
            self._messages.pop(message.receipt, None)

    def __len__(self) -> int:
        with self._mu:
            return len(self._messages)
