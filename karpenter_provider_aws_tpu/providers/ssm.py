"""SSM parameter provider (pkg/providers/ssm, provider.go:29-31).

Cached GetParameter with *mutable* vs *immutable* entries: a parameter
whose path pins an exact version (e.g. ``...@v20240807``) can never change
and caches forever; a floating path (``@latest``/``@recommended``) is
mutable and subject to the 24h TTL *and* to deprecation-driven eviction by
the SSM invalidation controller (ssm/invalidation/controller.go:55-88) —
when the AMI a cached parameter resolves to is deprecated, the entry is
evicted so the next resolve re-reads the source of truth.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Callable, Dict, Iterable, Optional

from ..cache.ttl import SSM_TTL, TTLCache

#: floating selectors that make a parameter mutable
_MUTABLE_MARKERS = ("latest", "recommended")


@dataclass
class Parameter:
    """A cached SSM parameter (value + mutability)."""
    path: str
    value: str
    mutable: bool


def is_mutable(path: str) -> bool:
    return any(m in path for m in _MUTABLE_MARKERS)


class SSMProvider:
    def __init__(self, ec2, clock: Optional[Callable[[], float]] = None):
        self.ec2 = ec2
        self._mu = threading.Lock()
        self._cache: TTLCache = TTLCache(ttl=SSM_TTL, clock=clock)

    def get(self, path: str) -> str:
        """Cached GetParameter; immutable entries never expire logically
        (their value cannot change at the source), mutable entries are
        TTL'd and deprecation-evicted."""
        with self._mu:
            ent: Optional[Parameter] = self._cache.get(path)
            if ent is not None:
                return ent.value
        value = self.ec2.ssm_get_parameter(path)
        mutable = is_mutable(path)
        with self._mu:
            # version-pinned parameters can never change at the source:
            # cache them forever; floating ones get the standard TTL
            self._cache.put(path, Parameter(path, value, mutable),
                            ttl=None if mutable else float("inf"))
        return value

    def cached(self) -> Dict[str, Parameter]:
        with self._mu:
            return {k: self._cache.get(k) for k in self._cache.keys()
                    if self._cache.get(k) is not None}

    def invalidate_deprecated(self, deprecated_values: Iterable[str]) -> int:
        """Evict mutable entries whose resolved value became deprecated;
        returns the eviction count (the invalidation controller's work)."""
        bad = set(deprecated_values)
        evicted = 0
        with self._mu:
            for path in list(self._cache.keys()):
                ent: Optional[Parameter] = self._cache.get(path)
                if ent is not None and ent.mutable and ent.value in bad:
                    self._cache.delete(path)
                    evicted += 1
        return evicted
