"""Subnet + security-group discovery providers.

Subnet provider mirrors pkg/providers/subnet: discovery by selector terms
(subnet.go:81-126), zonal subnet choice for launch = most available IPs
(subnet.go:128-175), and in-flight IP accounting after CreateFleet
(subnet.go:177-233). Security-group provider mirrors
pkg/providers/securitygroup (securitygroup.go:36-38).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, List

from ..apis.objects import EC2NodeClass, SelectorTerm
from ..cache.ttl import DEFAULT_TTL, TTLCache


@dataclass(frozen=True)
class SubnetInfo:
    id: str
    zone: str
    zone_id: str
    available_ips: int
    #: availability-zone | local-zone (DescribeAvailabilityZones ZoneType;
    #: the localzone E2E suite filters on it)
    zone_type: str = "availability-zone"


class SubnetProvider:
    def __init__(self, ec2, clock=None):
        self.ec2 = ec2
        self._cache = TTLCache(ttl=DEFAULT_TTL, clock=clock)
        self._mu = threading.Lock()
        #: in-flight IPs not yet visible in DescribeSubnets (subnet.go:177)
        self._inflight: Dict[str, int] = {}

    def list(self, nodeclass: EC2NodeClass) -> List[SubnetInfo]:
        key = tuple(nodeclass.subnet_selector_terms)
        cached = self._cache.get(key)
        if cached is not None:
            return cached
        found: Dict[str, SubnetInfo] = {}
        terms = nodeclass.subnet_selector_terms or [SelectorTerm()]
        for term in terms:
            for s in self.ec2.describe_subnets(
                    tag_filters=dict(term.tags),
                    ids=[term.id] if term.id else ()):
                found[s.id] = SubnetInfo(s.id, s.zone, s.zone_id,
                                         s.available_ips, s.zone_type)
        out = sorted(found.values(), key=lambda s: s.id)
        self._cache.put(key, out)
        return out

    def zonal_subnets_for_launch(self, nodeclass: EC2NodeClass
                                 ) -> Dict[str, SubnetInfo]:
        """zone -> best subnet (most available IPs, accounting in-flight);
        ties break on subnet id (deterministic) — subnet.go:128-175."""
        with self._mu:
            best: Dict[str, SubnetInfo] = {}
            for s in self.list(nodeclass):
                avail = s.available_ips - self._inflight.get(s.id, 0)
                cur = best.get(s.zone)
                if cur is None or (avail, s.id) > (cur.available_ips, cur.id):
                    best[s.zone] = SubnetInfo(s.id, s.zone, s.zone_id,
                                              avail, s.zone_type)
            return best

    def update_inflight_ips(self, subnet_id: str, count: int = 1) -> None:
        """Called post-CreateFleet for each launched instance
        (subnet.go:177-233)."""
        with self._mu:
            self._inflight[subnet_id] = self._inflight.get(subnet_id, 0) + count

    def clear_inflight(self) -> None:
        with self._mu:
            self._inflight.clear()
            self._cache.clear()


class SecurityGroupProvider:
    def __init__(self, ec2, clock=None):
        self.ec2 = ec2
        self._cache = TTLCache(ttl=DEFAULT_TTL, clock=clock)

    def invalidate(self) -> None:
        """Drop cached discovery (tests / forced refresh)."""
        self._cache.clear()

    def list(self, nodeclass: EC2NodeClass) -> List[str]:
        key = tuple(nodeclass.security_group_selector_terms)
        cached = self._cache.get(key)
        if cached is not None:
            return cached
        found = set()
        terms = nodeclass.security_group_selector_terms or [SelectorTerm()]
        for term in terms:
            for g in self.ec2.describe_security_groups(
                    tag_filters=dict(term.tags),
                    ids=[term.id] if term.id else (),
                    names=[term.name] if term.name else ()):
                found.add(g.id)
        out = sorted(found)
        self._cache.put(key, out)
        return out
