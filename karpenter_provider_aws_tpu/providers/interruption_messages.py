"""EventBridge interruption-message parsing.

Mirrors pkg/controllers/interruption/messages: five parsers keyed on the
envelope's (source, detail-type) — spot interruption, rebalance
recommendation, scheduled change (AWS Health), instance state change,
and the noop fallback for everything else (messages/types.go:21-57,
messages/{spotinterruption,rebalancerecommendation,scheduledchange,
statechange,noop}/parser.go). ``parse_message`` takes the raw SQS body
(JSON string) and yields normalized ``InterruptionMessage``s.
"""

from __future__ import annotations

import json
from typing import List

from .sqs import InterruptionMessage

#: instance states worth reacting to (statechange/parser.go:27)
_ACCEPTED_STATES = {"stopping", "stopped", "shutting-down", "terminated"}


def _instance_id_from_arn(arn: str) -> str:
    """arn:aws:ec2:region:acct:instance/i-... -> i-...
    (scheduledchange/model.go EC2InstanceIDs)."""
    return arn.rsplit("/", 1)[-1] if "/" in arn else ""


def parse_message(raw: str) -> List[InterruptionMessage]:
    """One raw EventBridge envelope -> normalized messages (scheduled
    changes may name several instances in `resources`; everything
    unrecognized degrades to a single noop, never an error —
    interruption/controller.go parseMessage)."""
    try:
        env = json.loads(raw)
    except (json.JSONDecodeError, TypeError):
        env = None
    if not isinstance(env, dict):
        return [InterruptionMessage(kind="noop", instance_id="",
                                    detail=str(raw)[:200])]
    source = env.get("source", "")
    detail_type = env.get("detail-type", "")
    detail = env.get("detail")
    if not isinstance(detail, dict):
        detail = {}

    if source == "aws.ec2" and \
            detail_type == "EC2 Spot Instance Interruption Warning":
        return [InterruptionMessage(kind="spot_interruption",
                                    instance_id=detail.get("instance-id", ""))]
    if source == "aws.ec2" and \
            detail_type == "EC2 Instance Rebalance Recommendation":
        return [InterruptionMessage(kind="rebalance_recommendation",
                                    instance_id=detail.get("instance-id", ""))]
    if source == "aws.health" and detail_type == "AWS Health Event":
        # only EC2 scheduled changes are actionable
        # (scheduledchange/parser.go:25-40)
        if detail.get("service") != "EC2" or \
                detail.get("eventTypeCategory") != "scheduledChange":
            return [InterruptionMessage(kind="noop", instance_id="")]
        resources = env.get("resources")
        if not isinstance(resources, (list, tuple)):
            resources = ()
        ids = [_instance_id_from_arn(r) for r in resources
               if isinstance(r, str)]
        return [InterruptionMessage(kind="scheduled_change", instance_id=i)
                for i in ids if i] or \
            [InterruptionMessage(kind="noop", instance_id="")]
    if source == "aws.ec2" and \
            detail_type == "EC2 Instance State-change Notification":
        if str(detail.get("state", "")).lower() not in _ACCEPTED_STATES:
            return [InterruptionMessage(kind="noop", instance_id="")]
        return [InterruptionMessage(kind="state_change",
                                    instance_id=detail.get("instance-id", ""))]
    return [InterruptionMessage(kind="noop", instance_id="", detail=detail_type)]
