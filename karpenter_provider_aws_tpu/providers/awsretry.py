"""AWS-style retry classification + adaptive client-side rate control
for the cloud seam — the provider-half sibling of sidecar/resilience.py.

The reference guards every SDK call with aws-sdk-go-v2's retryer
(``retry.NewStandard`` wrapped by the operator's config, operator.go:110)
and classifies provider errors through ``awserrors``: throttling
(``RequestLimitExceeded`` et al) and transient transport/5xx failures
are retried with exponential backoff + jitter under a client-side token
bucket; ICE (``InsufficientInstanceCapacity``) is NEVER retried — it is
a capacity signal that feeds ``UnavailableOfferings``; NotFound is an
eventual-consistency signal the *controllers* interpret (a NodeClaim's
instance invisible right after CreateFleet is "not yet converged", not
gone); validation/auth rejections are terminal.

Three composable pieces:

- :func:`classify` — the error taxonomy. Works on :class:`AWSError`
  (coded), on the fake cloud's native errors (``ConnectionError`` from a
  DOWN link, ``KeyError("InvalidInstanceID.NotFound: ...")``), and on
  anything carrying an AWS-shaped code string.
- :class:`RetryQuota` + :class:`AdaptiveRateLimiter` — the two AWS
  client-side token buckets. The quota is the standard retryer's retry
  bucket (retries cost tokens, successes slowly refund them — sustained
  failure sheds *retries*, first attempts always pass). The limiter is
  the adaptive mode's send-rate bucket (multiplicative-decrease on
  throttle, additive recovery — sustained throttling sheds *request
  rate*).
- :class:`CloudRetryPolicy` — bounded exponential backoff with FULL
  jitter over retryable classes only, consulting both buckets, with
  injectable ``rng`` / ``sleep`` / ``clock`` so chaos tests are seeded
  and fast. :class:`ResilientCloud` wraps a cloud handle so every
  EC2/SSM/EKS/pricing call site in providers/ and batcher/ rides the
  policy without per-site plumbing.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Optional

#: classification classes (the label values of the
#: karpenter_cloud_retry_* series)
THROTTLE = "throttle"
TRANSIENT = "transient"
ICE = "ice"
NOT_FOUND = "not-found"
TERMINAL = "terminal"

#: the awserrors.IsThrottle / aws-sdk-go-v2 retry.ThrottleErrorCodes set
THROTTLE_CODES = frozenset({
    "RequestLimitExceeded", "Throttling", "ThrottlingException",
    "ThrottledException", "RequestThrottled", "RequestThrottledException",
    "TooManyRequestsException", "ProvisionedThroughputExceededException",
    "TransactionInProgressException", "EC2ThrottledException", "SlowDown",
    "PriorRequestNotComplete", "BandwidthLimitExceeded", "LimitExceededException",
})

#: transient service-side codes (retry.DefaultRetryableErrorCodes)
TRANSIENT_CODES = frozenset({
    "RequestTimeout", "RequestTimeoutException", "InternalError",
    "InternalFailure", "ServiceUnavailable", "TransientError",
})

#: ICE-class codes (awserrors.go isUnfulfillableCapacity): capacity
#: signals, never retried — they feed UnavailableOfferings
ICE_CODES = frozenset({
    "InsufficientInstanceCapacity", "MaxSpotInstanceCountExceeded",
    "VcpuLimitExceeded", "UnfulfillableCapacity", "Unsupported",
    "InsufficientFreeAddressesInSubnet",
})


class AWSError(Exception):
    """A coded AWS API error (the smithy APIError shape: code + message
    + HTTP status). The fault-injection harness raises these; real
    adapters would translate botocore ClientErrors into them."""

    def __init__(self, code: str, message: str = "", status: int = 0):
        self.code = code
        self.status = status
        super().__init__(f"{code}: {message}" if message else code)


def error_code(exc: BaseException) -> str:
    """Best-effort AWS error code of ``exc``. Coded errors carry it;
    the fake cloud's native errors embed it as the leading
    ``Code: detail`` token (``KeyError("ParameterNotFound: /aws/...")``,
    ``KeyError("InvalidInstanceID.NotFound: i-...")``)."""
    code = getattr(exc, "code", "")
    if isinstance(code, str) and code:
        return code
    msg = str(exc)
    if isinstance(exc, KeyError):
        msg = msg.strip("'\"")
    head = msg.split(":", 1)[0].strip()
    if head and " " not in head and head[:1].isalpha():
        return head
    return ""


def classify(exc: BaseException) -> str:
    """The AWS error taxonomy: throttle | transient | ice | not-found |
    terminal. Only throttle and transient are retryable; ICE feeds
    UnavailableOfferings (never retried); not-found is an
    eventual-consistency signal for the controllers; everything else
    (validation, auth) is terminal — the service answered, retrying
    cannot change its mind."""
    code = error_code(exc)
    status = getattr(exc, "status", 0) or 0
    if code in THROTTLE_CODES or status == 429:
        return THROTTLE
    if code in ICE_CODES:
        return ICE
    if code.endswith(".NotFound") or code.endswith(".NotFoundException") \
            or code in ("ParameterNotFound", "ResourceNotFoundException"):
        return NOT_FOUND
    if code in TRANSIENT_CODES or 500 <= status < 600:
        return TRANSIENT
    if isinstance(exc, (ConnectionError, TimeoutError)):
        return TRANSIENT
    return TERMINAL


def is_retryable(cls: str) -> bool:
    return cls in (THROTTLE, TRANSIENT)


class RetryQuota:
    """The standard retryer's client-side retry token bucket
    (aws-sdk-go-v2 retry/standard.go): a retry costs ``retry_cost``
    tokens (``timeout_retry_cost`` for timeout-ish failures), a
    successful call refunds ``refund``. When the bucket runs dry no
    retries are attempted (first attempts always pass) — sustained
    failure degrades to fail-fast instead of amplifying the storm."""

    def __init__(self, capacity: float = 500.0, retry_cost: float = 5.0,
                 timeout_retry_cost: float = 10.0, refund: float = 1.0):
        self.capacity = capacity
        self.retry_cost = retry_cost
        self.timeout_retry_cost = timeout_retry_cost
        self.refund = refund
        self._mu = threading.Lock()
        self._tokens = capacity

    @property
    def tokens(self) -> float:
        with self._mu:
            return self._tokens

    def try_spend(self, timeout: bool = False) -> bool:
        """Take the cost of one retry; False = bucket dry, do not retry."""
        cost = self.timeout_retry_cost if timeout else self.retry_cost
        with self._mu:
            if self._tokens < cost:
                return False
            self._tokens -= cost
            return True

    def on_success(self) -> None:
        with self._mu:
            self._tokens = min(self.capacity, self._tokens + self.refund)


class AdaptiveRateLimiter:
    """The adaptive retry mode's send-rate token bucket: a throttled
    response multiplicatively cuts the client's send rate; successes
    recover it additively (AIMD). ``acquire`` returns the delay the
    caller should sleep before sending — bounded by ``max_delay_s`` so
    shedding never wedges a reconcile.

    Like the SDK's adaptive mode, the limiter is DORMANT until the
    first throttle response arms it — an API that has never throttled
    us is never slowed down (a 2000-message interruption drain must run
    at full tilt). Additive recovery back to ``max_rate`` disarms it
    again, so a past storm stops taxing a healed seam."""

    def __init__(self, rate: float = 50.0, burst: float = 20.0,
                 min_rate: float = 1.0, max_rate: float = 200.0,
                 increase: float = 1.0, decrease: float = 0.5,
                 max_delay_s: float = 1.0,
                 clock: Callable[[], float] = time.monotonic):
        self.min_rate = min_rate
        self.max_rate = max_rate
        self.increase = increase
        self.decrease = decrease
        self.burst = burst
        self.max_delay_s = max_delay_s
        self._clock = clock
        self._mu = threading.Lock()
        self._rate = rate
        self._tokens = burst
        self._last = clock()
        self._engaged = False

    @property
    def rate(self) -> float:
        with self._mu:
            return self._rate

    @property
    def engaged(self) -> bool:
        with self._mu:
            return self._engaged

    def _refill_locked(self, now: float) -> None:
        self._tokens = min(self.burst,
                           self._tokens + (now - self._last) * self._rate)
        self._last = now

    def acquire(self) -> float:
        """Take one send token; returns seconds to sleep (0 when the
        limiter is dormant or the bucket has headroom)."""
        with self._mu:
            if not self._engaged:
                return 0.0
            now = self._clock()
            self._refill_locked(now)
            self._tokens -= 1.0
            if self._tokens >= 0.0:
                return 0.0
            return min(self.max_delay_s, -self._tokens / self._rate)

    def on_throttle(self) -> None:
        with self._mu:
            if not self._engaged:
                # arm with a full burst so the very next sends are not
                # charged for time that passed while dormant
                self._engaged = True
                self._tokens = self.burst
                self._last = self._clock()
            self._rate = max(self.min_rate, self._rate * self.decrease)

    def on_success(self) -> None:
        with self._mu:
            if not self._engaged:
                return
            self._rate = min(self.max_rate, self._rate + self.increase)
            if self._rate >= self.max_rate:
                self._engaged = False  # fully recovered: stop limiting


class CloudRetryPolicy:
    """Bounded exponential backoff with full jitter over the retryable
    classes, under both client-side buckets. One policy instance guards
    a whole cloud handle (see :class:`ResilientCloud`) and is safe to
    share across batcher/GC/interruption worker threads."""

    def __init__(self, max_attempts: int = 4,
                 backoff_base_s: float = 0.02,
                 backoff_cap_s: float = 1.0,
                 throttle_backoff_base_s: float = 0.1,
                 rng: Optional[random.Random] = None,
                 sleep: Callable[[float], None] = time.sleep,
                 quota: Optional[RetryQuota] = None,
                 limiter: Optional[AdaptiveRateLimiter] = None,
                 service: str = "EC2", metrics=None):
        assert max_attempts >= 1
        self.max_attempts = max_attempts
        self.backoff_base_s = backoff_base_s
        self.backoff_cap_s = backoff_cap_s
        self.throttle_backoff_base_s = throttle_backoff_base_s
        self.rng = rng or random.Random()
        self._rng_mu = threading.Lock()
        self.sleep = sleep
        self.quota = quota or RetryQuota()
        self.limiter = limiter or AdaptiveRateLimiter()
        self.service = service
        self.metrics = metrics

    # -- observability --------------------------------------------------
    def emit_state(self) -> None:
        """Seed/refresh the bucket gauges so a scrape before the first
        fault still sees the series."""
        m = self.metrics
        if m is not None:
            lab = {"service": self.service}
            m.set_gauge("karpenter_cloud_retry_token_bucket_tokens",
                        self.quota.tokens, labels=lab)
            m.set_gauge("karpenter_cloud_retry_send_rate",
                        self.limiter.rate, labels=lab)

    def backoff_s(self, attempt: int, cls: str) -> float:
        """Full jitter: uniform in [0, min(cap, base * 2^attempt)];
        throttling uses a larger base (the SDK's throttle backoff)."""
        base = self.throttle_backoff_base_s if cls == THROTTLE \
            else self.backoff_base_s
        cap = min(self.backoff_cap_s, base * (2.0 ** attempt))
        with self._rng_mu:
            return self.rng.uniform(0.0, cap)

    # -- the guarded call ----------------------------------------------
    def call(self, fn: Callable, *args, operation: str = "", **kwargs):
        """Run ``fn(*args, **kwargs)`` under the policy. Retries only
        throttle/transient; ICE, not-found, and terminal errors re-raise
        immediately (their meaning belongs to the caller)."""
        m = self.metrics
        lab = {"service": self.service, "operation": operation}
        delay = self.limiter.acquire()
        if delay > 0.0:
            self.sleep(delay)
        last: Optional[BaseException] = None
        for attempt in range(self.max_attempts):
            try:
                out = fn(*args, **kwargs)
            except Exception as e:  # noqa: BLE001 - classified below
                cls = classify(e)
                if m is not None:
                    m.inc("karpenter_cloud_retry_errors_total",
                          labels={**lab, "class": cls})
                if cls == THROTTLE:
                    self.limiter.on_throttle()
                    if m is not None:
                        m.inc("karpenter_cloud_retry_throttle_events_total",
                              labels={"service": self.service})
                if not is_retryable(cls):
                    raise
                last = e
                if attempt + 1 >= self.max_attempts:
                    break
                if not self.quota.try_spend(
                        timeout=isinstance(e, TimeoutError)):
                    # retry bucket dry: shed the retry, fail fast — the
                    # adaptive degradation under sustained failure
                    break
                if m is not None:
                    m.inc("karpenter_cloud_retry_attempts_total",
                          labels={**lab, "class": cls})
                    m.inc("aws_sdk_go_request_retry_count", labels=lab)
                self.sleep(self.backoff_s(attempt, cls))
            else:
                self.quota.on_success()
                self.limiter.on_success()
                if m is not None:
                    self.emit_state()
                return out
        if m is not None:
            m.inc("karpenter_cloud_retry_exhausted_total", labels=lab)
            self.emit_state()
        raise last


#: cloud-handle methods the proxy guards — every EC2/SSM/EKS/pricing
#: operation a provider or batcher calls (the boot-preflight seams
#: imds_region / dry_run_describe_instance_types stay raw on purpose:
#: preflight owns its own deadline semantics and must fail FAST)
GUARDED_OPS = (
    "describe_instance_types", "describe_instance_type_offerings",
    "describe_spot_price_history", "on_demand_prices",
    "describe_subnets", "describe_security_groups", "describe_images",
    "create_launch_template", "describe_launch_templates",
    "delete_launch_templates", "create_fleet", "describe_instances",
    "terminate_instances", "create_tags", "ssm_get_parameter",
    "eks_describe_cluster_version",
)


class ResilientCloud:
    """Proxy over a cloud handle: every :data:`GUARDED_OPS` call runs
    through the :class:`CloudRetryPolicy`; everything else (stores,
    call logs, behavior-injection knobs) passes straight through, so
    tests keep poking the raw fake while the control plane's call sites
    all ride the policy. Method lookup happens per call — wrappers
    installed later on the inner handle (telemetry instrumentation,
    fault injectors) stay in the path."""

    def __init__(self, inner, policy: Optional[CloudRetryPolicy] = None):
        object.__setattr__(self, "inner", inner)
        object.__setattr__(self, "policy", policy or CloudRetryPolicy())

    def __getattr__(self, name):
        if name in GUARDED_OPS:
            policy = self.policy
            inner = self.inner

            def guarded(*args, _name=name, **kwargs):
                return policy.call(getattr(inner, _name), *args,
                                   operation=_name, **kwargs)
            return guarded
        return getattr(self.inner, name)

    def __setattr__(self, name, value):
        setattr(self.inner, name, value)
