"""Kubernetes version discovery, hydrated synchronously at boot
(pkg/providers/version, version.go:46-50; operator.go:155)."""

from __future__ import annotations


class VersionProvider:
    SUPPORTED = ("1.28", "1.29", "1.30", "1.31", "1.32")

    def __init__(self, version: str = "1.31"):
        self._version = version

    def get(self) -> str:
        return self._version

    def update(self, version: str) -> bool:
        major_minor = ".".join(version.split(".")[:2])
        if major_minor not in self.SUPPORTED:
            raise ValueError(f"unsupported kubernetes version {version}")
        changed = self._version != major_minor
        self._version = major_minor
        return changed
