"""Instance provider — the launcher.

Mirrors pkg/providers/instance: filter exotic/expensive-spot types
(instance.go:385-452), truncate to 60 types (:55,106), spot-vs-OD capacity
type selection (:365-381), CreateFleet request construction (instant fleet,
price-capacity-optimized spot / lowest-price OD :227-245), overrides =
instance-type x zonal-subnet cross product (:317-355), ICE errors →
UnavailableOfferings (:357-363), OD-fallback flexibility warning at <5
types (:270-288), and instance → NodeClaim reconstruction (:147-163).
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from typing import Dict, List, Optional

from ..apis import labels as L
from ..apis.objects import EC2NodeClass, NodeClaim
from ..apis.requirements import IN, Requirement, Requirements
from ..cache.ttl import UnavailableOfferings
from ..cloudprovider.types import (
    InstanceTypes,
    InsufficientCapacityError,
    NodeClaimNotFoundError)
from ..batcher.core import (CreateFleetBatcher, CreateFleetRequest,
                            DescribeInstancesBatcher,
                            TerminateInstancesBatcher, to_hashable)
from .launchtemplate import LaunchTemplateProvider
from .network import SubnetProvider

log = logging.getLogger(__name__)

MAX_INSTANCE_TYPES = 60   # instance.go:55
MIN_FLEXIBLE_TYPES = 5    # instance.go:270-288 (OD fallback warning)


@dataclass
class LaunchedInstance:
    id: str
    instance_type: str
    zone: str
    zone_id: str
    capacity_type: str
    image_id: str
    provider_id: str
    subnet_id: str
    tags: Dict[str, str]
    state: str = "running"
    launch_time: float = 0.0
    security_group_ids: List[str] = None


class InstanceProvider:
    def __init__(self, ec2, subnet_provider: SubnetProvider,
                 launch_template_provider: LaunchTemplateProvider,
                 unavailable_offerings: UnavailableOfferings,
                 cluster_name: str = "cluster", clock=None, metrics=None):
        self.ec2 = ec2
        self.subnets = subnet_provider
        self.launch_templates = launch_template_provider
        self.unavailable = unavailable_offerings
        self.cluster_name = cluster_name
        self.metrics = metrics
        clock = clock or time.monotonic
        self.create_fleet = CreateFleetBatcher(ec2, clock=clock,
                                               metrics=metrics)
        self.describe = DescribeInstancesBatcher(ec2, clock=clock,
                                                 metrics=metrics)
        self.terminate_batcher = TerminateInstancesBatcher(ec2, clock=clock,
                                                           metrics=metrics)

    # -- create --------------------------------------------------------
    def create(self, nodeclass: EC2NodeClass, nodeclaim: NodeClaim,
               instance_types: InstanceTypes,
               tags: Optional[Dict[str, str]] = None) -> LaunchedInstance:
        """Launch one instance for the NodeClaim (instance.go:100-128)."""
        reqs = nodeclaim.requirements
        types = self._filter_instance_types(
            instance_types, reqs, nodeclaim.resources_requested)
        types = InstanceTypes(types).truncate(reqs, MAX_INSTANCE_TYPES)
        if not types:
            raise InsufficientCapacityError(
                f"no viable instance types for {nodeclaim.name}")
        capacity_type = self._capacity_type(reqs, types)
        if capacity_type == L.CAPACITY_TYPE_ON_DEMAND and len(types) < MIN_FLEXIBLE_TYPES:
            log.warning("launching with only %d instance type options (<%d): "
                        "flexibility is degraded", len(types), MIN_FLEXIBLE_TYPES)
        zonal_subnets = self.subnets.zonal_subnets_for_launch(nodeclass)
        # launch-template-not-found retries ONCE: the template can be
        # deleted between EnsureAll and CreateFleet (cache eviction or an
        # external cleanup); invalidate and re-ensure (instance.go:111-115)
        for attempt in range(2):
            lts = self.launch_templates.ensure_all(
                nodeclass, types,
                labels=dict(nodeclaim.metadata.labels),
                taints=nodeclaim.taints)
            overrides = self._overrides(types, reqs, capacity_type,
                                        zonal_subnets, lts)
            if not overrides:
                raise InsufficientCapacityError(
                    f"no (type x zone x subnet) overrides for {nodeclaim.name}")
            configs = _group_overrides(overrides)
            fut = self.create_fleet.add(CreateFleetRequest(
                launch_template_configs=to_hashable(configs),
                capacity_type=capacity_type,
                tags=to_hashable(tags or {})))
            instance, errors = fut.result(timeout=30)
            lt_gone = [e for e in errors if is_launch_template_not_found(
                e["code"])]
            for err in errors:
                if is_launch_template_not_found(err["code"]):
                    continue  # not a capacity signal
                # ICE -> blacklist the offering for 3m; feeds the next Solve
                self.unavailable.mark_unavailable(
                    err["capacity_type"], err["instance_type"], err["zone"],
                    reason=err["code"])
            if instance is None and lt_gone and attempt == 0:
                log.info("launch templates disappeared mid-launch for %s; "
                         "re-ensuring and retrying once", nodeclaim.name)
                if self.metrics is not None:
                    self.metrics.inc(
                        "aws_sdk_go_request_retry_count",
                        labels={"service": "EC2",
                                "operation": "create_fleet"})
                self.launch_templates.invalidate(
                    {cfg["launch_template_name"] for cfg in configs})
                continue
            break
        if instance is None:
            raise InsufficientCapacityError(
                "CreateFleet returned no instance: "
                + "; ".join(e["code"] for e in errors))
        self.subnets.update_inflight_ips(instance.subnet_id)
        return _to_launched(instance)

    # -- read/delete ---------------------------------------------------
    def get(self, instance_id: str) -> LaunchedInstance:
        inst = self.describe.add_sync(instance_id)
        if inst is None or inst.state in ("terminated", "shutting-down"):
            raise NodeClaimNotFoundError(instance_id)
        return _to_launched(inst)

    def list(self) -> List[LaunchedInstance]:
        """All karpenter-owned instances (tag-scoped; instance.go List)."""
        out = []
        for inst in self.ec2.describe_instances(
                tag_filters={"karpenter.sh/nodepool": "*"}):
            if f"kubernetes.io/cluster/{self.cluster_name}" in inst.tags \
                    or inst.tags.get("eks:eks-cluster-name") == self.cluster_name:
                out.append(_to_launched(inst))
        return out

    def delete(self, instance_id: str) -> None:
        ok = self.terminate_batcher.add_sync(instance_id)
        if not ok:
            raise NodeClaimNotFoundError(instance_id)

    def create_tags(self, instance_id: str, tags: Dict[str, str]) -> None:
        try:
            self.ec2.create_tags([instance_id], tags)
        except KeyError as e:
            raise NodeClaimNotFoundError(str(e)) from e

    # -- internals -----------------------------------------------------
    def _filter_instance_types(self, types: InstanceTypes,
                               reqs: Requirements,
                               requested) -> InstanceTypes:
        """filterInstanceTypes (instance.go:385-392): drop exotic types when
        generic alternatives exist; for mixed spot/OD launches, drop spot
        types priced above the cheapest on-demand. Each heuristic stage is
        reverted if it would break an explicit minValues floor the
        candidate set satisfies (the same shape as the filter's own
        fall-back-when-empty rule — heuristics never override user
        constraints)."""
        filtered = _keep_min_values(_filter_exotic(types), types, reqs)
        if self._is_mixed_capacity(reqs, filtered):
            filtered = _keep_min_values(
                _filter_unwanted_spot(filtered), filtered, reqs)
        return filtered

    @staticmethod
    def _is_mixed_capacity(reqs: Requirements, types: InstanceTypes) -> bool:
        """instance.go:397-421: both capacity types allowed AND both kinds of
        offerings available among compatible types."""
        ct = reqs.get(L.CAPACITY_TYPE)
        if ct is not None and not (ct.has(L.CAPACITY_TYPE_SPOT)
                                   and ct.has(L.CAPACITY_TYPE_ON_DEMAND)):
            return False
        has_spot = has_od = False
        for t in types:
            for o in t.offerings.available():
                if not o.compatible_with(reqs):
                    continue
                if o.capacity_type == L.CAPACITY_TYPE_SPOT:
                    has_spot = True
                else:
                    has_od = True
        return has_spot and has_od

    @staticmethod
    def _capacity_type(reqs: Requirements, types: InstanceTypes) -> str:
        """Spot if allowed and any spot offering remains available, else
        on-demand (instance.go:365-381)."""
        ct = reqs.get(L.CAPACITY_TYPE)
        if ct is None or ct.has(L.CAPACITY_TYPE_SPOT):
            # the spot probe must honor ALL the claim's requirements
            # (zone included): a zone-constrained claim whose zone offers
            # no spot must fall to on-demand (instance.go:365-381 checks
            # offering compatibility against the full requirement set)
            spot_req = reqs.union(Requirements([Requirement.new(
                L.CAPACITY_TYPE, IN, [L.CAPACITY_TYPE_SPOT])]))
            for t in types:
                if t.offerings.available().compatible(spot_req):
                    return L.CAPACITY_TYPE_SPOT
        return L.CAPACITY_TYPE_ON_DEMAND

    def _overrides(self, types: InstanceTypes, reqs: Requirements,
                   capacity_type: str, zonal_subnets, lts) -> List[dict]:
        """type x zone cross product with price priorities
        (instance.go:317-355)."""
        lt_by_type: Dict[str, str] = {}
        image_by_type: Dict[str, str] = {}
        for lt in lts:
            for tn in lt.instance_type_names:
                lt_by_type.setdefault(tn, lt.name)
                image_by_type.setdefault(tn, lt.image_id)
        ct_req = Requirements([Requirement.new(L.CAPACITY_TYPE, IN, [capacity_type])])
        overrides = []
        for t in types:
            lt_name = lt_by_type.get(t.name)
            if lt_name is None:
                continue
            for o in t.offerings.available().compatible(reqs.union(ct_req)):
                sn = zonal_subnets.get(o.zone)
                if sn is None:
                    continue
                overrides.append({
                    "instance_type": t.name, "zone": o.zone,
                    "subnet_id": sn.id, "image_id": image_by_type[t.name],
                    "launch_template_name": lt_name,
                    "priority": o.price,  # price-capacity-optimized proxy
                })
        return overrides


def _keep_min_values(filtered: InstanceTypes, original: InstanceTypes,
                     reqs: Requirements) -> InstanceTypes:
    """Revert a filtering heuristic that would break minValues floors the
    unfiltered set satisfies (floors are explicit user constraints)."""
    if any(r.min_values is not None for r in reqs) \
            and InstanceTypes._min_values_violations(filtered, reqs) \
            and not InstanceTypes._min_values_violations(original, reqs):
        return original
    return filtered


def _filter_exotic(types: InstanceTypes) -> InstanceTypes:
    """filterExoticInstanceTypes (instance.go:452-474): prefer non-metal,
    non-accelerator types; fall back to the ORIGINAL list when nothing
    generic remains (a GPU-requiring claim has only GPU candidates)."""
    from ..apis.resources import (AMD_GPU, AWS_NEURON, AWS_NEURON_CORE,
                                  HABANA_GAUDI, NVIDIA_GPU)
    generic = InstanceTypes()
    for it in types:
        size = it.requirements.get(L.INSTANCE_SIZE)
        if size is not None and any("metal" in v for v in size.values):
            continue
        if any(it.capacity[r] > 0 for r in
               (NVIDIA_GPU, AMD_GPU, AWS_NEURON, AWS_NEURON_CORE, HABANA_GAUDI)):
            continue
        generic.append(it)
    return generic if generic else types


def _filter_unwanted_spot(types: InstanceTypes) -> InstanceTypes:
    """filterUnwantedSpot (instance.go:425-449): drop types whose cheapest
    available offering exceeds the cheapest on-demand price."""
    cheapest_od = None
    for it in types:
        for o in it.offerings.available():
            if o.capacity_type == L.CAPACITY_TYPE_ON_DEMAND:
                if cheapest_od is None or o.price < cheapest_od:
                    cheapest_od = o.price
    if cheapest_od is None:
        return types
    out = InstanceTypes()
    for it in types:
        avail = it.offerings.available()
        if not avail:
            continue
        if avail.cheapest().price <= cheapest_od:
            out.append(it)
    return out


def _group_overrides(overrides: List[dict]) -> List[dict]:
    by_lt: Dict[str, List[dict]] = {}
    for o in overrides:
        by_lt.setdefault(o["launch_template_name"], []).append(
            {k: v for k, v in o.items() if k != "launch_template_name"})
    return [{"launch_template_name": name, "overrides": ovs}
            for name, ovs in sorted(by_lt.items())]


def is_launch_template_not_found(code: str) -> bool:
    """errors.go IsLaunchTemplateNotFound classification."""
    return code in ("InvalidLaunchTemplateName.NotFoundException",
                    "InvalidLaunchTemplateId.NotFound")


def _to_launched(inst) -> LaunchedInstance:
    return LaunchedInstance(
        id=inst.id, instance_type=inst.instance_type, zone=inst.zone,
        zone_id=inst.zone_id, capacity_type=inst.capacity_type,
        image_id=inst.image_id, provider_id=inst.provider_id,
        subnet_id=inst.subnet_id, tags=dict(inst.tags), state=inst.state,
        launch_time=inst.launch_time,
        security_group_ids=list(getattr(inst, "security_group_ids", []) or []))
