"""Pricing provider (pkg/providers/pricing).

On-demand prices via the pricing API pages (pricing.go:228-354), spot via
DescribeSpotPriceHistory into a per-zone map (:281-309,356-399), 12h
refresh driven by the pricing controller. All prices fixed-point
micro-USD.

Static-fallback semantics mirror the reference exactly
(pricing.go:108-157 NewDefaultProvider->Reset + the empty-result guards
in UpdateOnDemandPricing/UpdateSpotPricing):

- construction seeds BOTH maps from the static tables (the
  zz_generated.pricing analog — here derived from the deterministic
  catalog), so a cold control plane prices every offering before the
  first refresh, and a boot with a DEAD pricing API still prices
  everything;
- a refresh that errors or returns an empty page KEEPS the previous
  data (last-known-good, falling back to static at boot) instead of
  wiping the maps — the reference returns "no on-demand pricing found"
  and leaves its maps untouched;
- spot lookups before the first live spot refresh serve the per-type
  static default price regardless of zone (pricing.go SpotPrice's
  !spotPricingUpdated branch); after a live refresh, the per-zone map
  is authoritative.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..fake.catalog import build_catalog, spot_price

log = logging.getLogger(__name__)

#: static fallback tables (the zz_generated.pricing_aws*.go analog):
#: derived from the deterministic catalog at import, one OD price per
#: type and one zone-independent default spot price per type.
_STATIC_OD: Dict[str, int] = {}
_STATIC_SPOT_DEFAULT: Dict[str, int] = {}
for _i in build_catalog():
    _STATIC_OD[_i.name] = _i.od_price
    _STATIC_SPOT_DEFAULT[_i.name] = spot_price(_i, "")
del _i


class PricingProvider:
    def __init__(self, ec2, clock=None):
        self.ec2 = ec2
        self._mu = threading.RLock()
        self._od: Dict[str, int] = dict(_STATIC_OD)
        self._spot: Dict[Tuple[str, str], int] = {}
        #: False until the first successful live spot refresh: spot
        #: lookups serve the static per-type default until then
        self._spot_updated = False
        self._clock = clock or time.monotonic
        self.od_updated: float = 0.0
        self.spot_updated: float = 0.0

    def instance_types(self) -> List[str]:
        """Types with either an OD or spot price known
        (pricing.go InstanceTypes: the union of both maps)."""
        with self._mu:
            names = set(self._od)
            names.update(t for t, _z in self._spot)
            if not self._spot_updated:
                names.update(_STATIC_SPOT_DEFAULT)
            return sorted(names)

    def on_demand_price(self, instance_type: str) -> Optional[int]:
        with self._mu:
            return self._od.get(instance_type)

    def spot_price(self, instance_type: str, zone: str) -> Optional[int]:
        with self._mu:
            if not self._spot_updated:
                return _STATIC_SPOT_DEFAULT.get(instance_type)
            return self._spot.get((instance_type, zone))

    def spot_prices(self) -> Dict[Tuple[str, str], int]:
        with self._mu:
            return dict(self._spot)

    def on_demand_prices(self) -> Dict[str, int]:
        with self._mu:
            return dict(self._od)

    # controller-driven refreshes (providers/pricing/controller.go:43-60)
    def update_on_demand_pricing(self) -> bool:
        try:
            fresh = self.ec2.on_demand_prices()
        except Exception as e:  # dead pricing API: keep last known good
            log.warning("on-demand pricing refresh failed (%s); keeping "
                        "previous prices", e)
            return False
        if not fresh:
            # reference: "no on-demand pricing found" — maps untouched
            log.warning("on-demand pricing refresh returned no prices; "
                        "keeping previous prices")
            return False
        with self._mu:
            changed = fresh != self._od
            self._od = dict(fresh)
            self.od_updated = self._clock()
            return changed

    def update_spot_pricing(self) -> bool:
        try:
            rows = self.ec2.describe_spot_price_history()
        except Exception as e:
            log.warning("spot pricing refresh failed (%s); keeping "
                        "previous prices", e)
            return False
        fresh = {(t, z): p for t, z, p in rows}
        if not fresh:
            log.warning("spot pricing refresh returned no prices; "
                        "keeping previous prices")
            return False
        with self._mu:
            changed = (fresh != self._spot) or not self._spot_updated
            self._spot = fresh
            self._spot_updated = True
            self.spot_updated = self._clock()
            return changed
