"""Pricing + small providers (instance profile, version, SQS interruption
queue).

Pricing mirrors pkg/providers/pricing: on-demand prices via the pricing API
pages (pricing.go:228-354), spot via DescribeSpotPriceHistory into a
per-zone map (:281-309,356-399), a static fallback snapshot per partition
(zz_generated.pricing_aws*.go), 12h refresh cadence driven by the pricing
controller. All prices fixed-point micro-USD.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..fake.catalog import build_catalog, spot_price

#: static fallback (the zz_generated.pricing table analog): derived from the
#: deterministic catalog so a cold control plane prices sanely before the
#: first refresh.
_STATIC_OD: Dict[str, int] = {i.name: i.od_price for i in build_catalog()}


class PricingProvider:
    def __init__(self, ec2, clock=None):
        self.ec2 = ec2
        self._mu = threading.RLock()
        self._od: Dict[str, int] = dict(_STATIC_OD)
        self._spot: Dict[Tuple[str, str], int] = {}
        self._clock = clock or time.monotonic
        self.od_updated: float = 0.0
        self.spot_updated: float = 0.0

    def instance_types(self) -> List[str]:
        with self._mu:
            return sorted(self._od)

    def on_demand_price(self, instance_type: str) -> Optional[int]:
        with self._mu:
            return self._od.get(instance_type)

    def spot_price(self, instance_type: str, zone: str) -> Optional[int]:
        with self._mu:
            return self._spot.get((instance_type, zone))

    def spot_prices(self) -> Dict[Tuple[str, str], int]:
        with self._mu:
            return dict(self._spot)

    def on_demand_prices(self) -> Dict[str, int]:
        with self._mu:
            return dict(self._od)

    # controller-driven refreshes (providers/pricing/controller.go:43-60)
    def update_on_demand_pricing(self) -> bool:
        fresh = self.ec2.on_demand_prices()
        with self._mu:
            changed = fresh != self._od
            self._od = dict(fresh)
            self.od_updated = self._clock()
            return changed

    def update_spot_pricing(self) -> bool:
        fresh = {(t, z): p for t, z, p in self.ec2.describe_spot_price_history()}
        with self._mu:
            changed = fresh != self._spot
            self._spot = fresh
            self.spot_updated = self._clock()
            return changed


class InstanceProfileProvider:
    """IAM instance-profile CRUD for the NodeClass role
    (pkg/providers/instanceprofile, instanceprofile.go:43-46)."""

    def __init__(self, cluster_name: str = "cluster", region: str = "us-west-2"):
        self.cluster_name = cluster_name
        self.region = region
        self._mu = threading.Lock()
        self._profiles: Dict[str, str] = {}   # profile name -> role

    def create(self, nodeclass) -> str:
        if nodeclass.instance_profile:
            return nodeclass.instance_profile
        name = (f"{self.cluster_name}_{nodeclass.metadata.name}_"
                f"{self.region}_profile")
        with self._mu:
            self._profiles[name] = nodeclass.role
        return name

    def get(self, name: str) -> Optional[str]:
        with self._mu:
            return self._profiles.get(name)

    def delete(self, name: str) -> None:
        with self._mu:
            self._profiles.pop(name, None)


class VersionProvider:
    """Kubernetes version discovery, hydrated synchronously at boot
    (pkg/providers/version, version.go:46-50; operator.go:155)."""

    SUPPORTED = ("1.28", "1.29", "1.30", "1.31", "1.32")

    def __init__(self, version: str = "1.31"):
        self._version = version

    def get(self) -> str:
        return self._version

    def update(self, version: str) -> bool:
        major_minor = ".".join(version.split(".")[:2])
        if major_minor not in self.SUPPORTED:
            raise ValueError(f"unsupported kubernetes version {version}")
        changed = self._version != major_minor
        self._version = major_minor
        return changed


@dataclass
class InterruptionMessage:
    """Parsed SQS interruption message (interruption/messages/types.go:21-57).
    kinds: spot_interruption | rebalance_recommendation | scheduled_change |
    state_change | noop"""
    kind: str
    instance_id: str
    detail: str = ""
    receipt: str = ""


class SQSProvider:
    """Interruption queue (pkg/providers/sqs, sqs.go:31-36): receive/delete
    plus send for tests."""

    def __init__(self, queue_name: str = "karpenter-interruption"):
        self.queue_name = queue_name
        self._mu = threading.Lock()
        #: receipt -> message, insertion-ordered (O(1) delete — the list
        #: rebuild the naive version did made a 15k-message drain O(n^2))
        self._messages: Dict[str, InterruptionMessage] = {}
        self._receipt = 0

    def send(self, message: InterruptionMessage) -> None:
        with self._mu:
            self._receipt += 1
            message.receipt = str(self._receipt)
            self._messages[message.receipt] = message

    def send_raw(self, raw: str) -> None:
        """Enqueue a raw EventBridge JSON body — what real SQS delivers.
        Parsed through the messages parsers (one envelope may fan out to
        several normalized messages, e.g. a multi-instance AWS Health
        scheduled change)."""
        from .interruption_messages import parse_message
        for m in parse_message(raw):
            self.send(m)

    def receive(self, max_messages: int = 10) -> List[InterruptionMessage]:
        with self._mu:
            out = []
            for m in self._messages.values():
                out.append(m)
                if len(out) >= max_messages:
                    break
            return out

    def delete(self, message: InterruptionMessage) -> None:
        with self._mu:
            self._messages.pop(message.receipt, None)

    def __len__(self) -> int:
        with self._mu:
            return len(self._messages)
