"""Instance-type catalog provider: the solver's warm input.

Mirrors pkg/providers/instancetype: holds raw catalog rows + offerings
refreshed by a controller, and ``list()`` assembles ``InstanceType`` objects
per NodeClass under a seqnum-keyed cache (instancetype.go:119-130). Resolve
builds requirements (~20 labels, types.go:183-287), offerings with live
spot/OD prices x zones x capacity types (types.go:120-157), capacity with the
VM-memory-overhead haircut (types.go:307-478), and kubelet overhead
(kubeReserved / systemReserved / evictionThreshold, types.go:480-565).
A discovered-capacity cache corrects memory from real nodes
(instancetype.go:169-171,273-297).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, List, Mapping, Optional, Sequence, Set, Tuple

from ..apis import labels as L
from ..apis.objects import EC2NodeClass, KubeletConfiguration
from ..apis.requirements import DOES_NOT_EXIST, IN, Requirement, Requirements
from ..apis.resources import (ATTACHABLE_VOLUMES, AWS_EFA, AWS_NEURON,
                              AWS_POD_ENI, NVIDIA_GPU,
                              Resources, parse_quantity)
from ..cache.ttl import TTLCache
from ..cloudprovider.types import (InstanceType, InstanceTypes, Offering,
                                   Offerings, Overhead)
from ..fake.catalog import (BANDWIDTH_MBPS, GIB, InstanceTypeInfo, ZoneInfo,
                            ebs_attachment_limit as _ebs_attachment_limit)

#: default VM memory overhead (options.go: vm-memory-overhead-percent=0.075)
DEFAULT_VM_MEMORY_OVERHEAD_PERCENT = 0.075
MIB = 1024**2


@dataclass
class OfferingsSnapshot:
    """(type -> zones available) + prices, maintained by the catalog
    controller (instancetype.go:190-271)."""
    zones: Mapping[str, ZoneInfo]                  # zone name -> info
    type_zones: Mapping[str, Set[str]]             # type -> {zone}
    od_prices: Mapping[str, int]                   # type -> micro-USD
    spot_prices: Mapping[Tuple[str, str], int]     # (type, zone) -> micro-USD


class InstanceTypeProvider:
    """Thread-safe catalog with seqnum-invalidated resolution cache."""

    def __init__(self, vm_memory_overhead_percent: float = DEFAULT_VM_MEMORY_OVERHEAD_PERCENT,
                 unavailable_offerings=None, clock=None,
                 reserved_enis: int = 0):
        #: interfaces withheld from the ENI max-pods formula
        #: (--reserved-enis, options.go:36-85)
        self.reserved_enis = reserved_enis
        self._mu = threading.RLock()
        self._raw: List[InstanceTypeInfo] = []
        self._offerings: Optional[OfferingsSnapshot] = None
        self.instance_types_seqnum = 0
        self.offerings_seqnum = 0
        self._overhead_pct = vm_memory_overhead_percent
        self._cache = TTLCache(ttl=5 * 60, clock=clock)  # InstanceTypesAndZones TTL (cache.go)
        self._discovered_memory: Dict[Tuple[str, str], int] = {}  # (type, ami) -> bytes
        self.unavailable_offerings = unavailable_offerings

    # -- controller-facing updates (instancetype controller, 12h) ---------
    def update_instance_types(self, raw: Sequence[InstanceTypeInfo]) -> bool:
        with self._mu:
            new = sorted(raw, key=lambda r: r.name)
            if new != self._raw:
                self._raw = new
                self.instance_types_seqnum += 1
                return True
            return False

    def update_offerings(self, snapshot: OfferingsSnapshot) -> bool:
        with self._mu:
            changed = (self._offerings is None
                       or snapshot.type_zones != self._offerings.type_zones
                       or snapshot.od_prices != self._offerings.od_prices
                       or snapshot.spot_prices != self._offerings.spot_prices)
            self._offerings = snapshot
            if changed:
                self.offerings_seqnum += 1
            return changed

    def update_discovered_capacity(self, instance_type: str, ami_id: str,
                                   memory_bytes: int) -> None:
        """Real-node memory correction (capacity/controller.go:54-73)."""
        with self._mu:
            self._discovered_memory[(instance_type, ami_id)] = memory_bytes
            self._cache.clear()

    # -- the hot read ------------------------------------------------------
    def list(self, nodeclass: EC2NodeClass) -> InstanceTypes:
        """Assemble per-NodeClass InstanceTypes, cache-keyed on
        (both seqnums, AMI hash, subnet-zone hash, kubelet/blockdev config)
        — instancetype.go:119-130's 5-ary key."""
        with self._mu:
            if self._offerings is None:
                return InstanceTypes()
            subnet_zones = frozenset(
                (s["zone"], s.get("zoneID", "")) for s in nodeclass.status_subnets)
            amis = tuple(sorted(a["id"] for a in nodeclass.status_amis))
            key = (self.instance_types_seqnum, self.offerings_seqnum,
                   getattr(self.unavailable_offerings, "seqnum", 0),
                   amis, subnet_zones, _kubelet_key(nodeclass.kubelet),
                   _storage_key(nodeclass),
                   # resolution depends on the AMI family (OS/windows-build
                   # requirements, windows amd64-only filtering) — two
                   # same-shaped nodeclasses of different families must
                   # never share an entry
                   nodeclass.ami_family)
            cached = self._cache.get(key)
            if cached is not None:
                return cached
            out = self._resolve_all(nodeclass, subnet_zones)
            self._cache.put(key, out)
            return out

    def _resolve_all(self, nodeclass: EC2NodeClass,
                     subnet_zones: frozenset) -> InstanceTypes:
        assert self._offerings is not None
        ami_archs = {a.get("arch", "amd64") for a in nodeclass.status_amis} or {"amd64", "arm64"}
        zone_filter = {z for z, _ in subnet_zones} if subnet_zones else None
        primary_ami = {a.get("arch", "amd64"): a["id"] for a in nodeclass.status_amis}
        out = InstanceTypes()
        # windows runs on amd64 only (getOS, types.go:288-296): under a
        # windows family, non-amd64 types must be unsatisfiable — dropping
        # them entirely matches the reference's empty OS requirement
        if nodeclass.ami_family in L.WINDOWS_BUILDS:
            ami_archs &= {L.ARCH_AMD64}
        for info in self._raw:
            if info.arch not in ami_archs:
                continue
            offerings = self._build_offerings(info, zone_filter)
            if not offerings:
                continue
            out.append(self._resolve(info, nodeclass, offerings,
                                     primary_ami.get(info.arch, "")))
        return out

    def _build_offerings(self, info: InstanceTypeInfo,
                         zone_filter: Optional[Set[str]]) -> Offerings:
        snap = self._offerings
        assert snap is not None
        offs = Offerings()
        for zone in sorted(snap.type_zones.get(info.name, ())):
            if zone_filter is not None and zone not in zone_filter:
                continue
            zinfo = snap.zones.get(zone)
            zone_id = zinfo.zone_id if zinfo else ""
            od = snap.od_prices.get(info.name)
            if od is not None:
                offs.append(Offering(
                    L.CAPACITY_TYPE_ON_DEMAND, zone, zone_id, od,
                    available=self._available(L.CAPACITY_TYPE_ON_DEMAND, info.name, zone)))
            sp = snap.spot_prices.get((info.name, zone))
            if sp is not None:
                offs.append(Offering(
                    L.CAPACITY_TYPE_SPOT, zone, zone_id, sp,
                    available=self._available(L.CAPACITY_TYPE_SPOT, info.name, zone)))
        return offs

    def _available(self, capacity_type: str, name: str, zone: str) -> bool:
        uo = self.unavailable_offerings
        return uo is None or not uo.is_unavailable(capacity_type, name, zone)

    # -- resolution (types.go:98-118) -------------------------------------
    def _resolve(self, info: InstanceTypeInfo, nodeclass: EC2NodeClass,
                 offerings: Offerings, ami_id: str) -> InstanceType:
        capacity = self._capacity(info, nodeclass, ami_id)
        overhead = self._overhead(info, nodeclass, capacity)
        return InstanceType(
            name=info.name,
            requirements=self._requirements(info, offerings,
                                            nodeclass.ami_family),
            capacity=capacity,
            overhead=overhead,
            offerings=offerings,
        )

    def _requirements(self, info: InstanceTypeInfo, offerings: Offerings,
                      ami_family: str = "") -> Requirements:
        """The ~20-label requirement set (types.go:183-287)."""
        zones = sorted({o.zone for o in offerings})
        zone_ids = sorted({o.zone_id for o in offerings if o.zone_id})
        cts = sorted({o.capacity_type for o in offerings})
        # OS follows the resolved AMI family: windows families produce
        # windows nodes (getOS, types.go:288-296; non-amd64 types are
        # dropped in _resolve_all since windows has no arm64 AMIs); the
        # windows-build label pins the family's build (types.go:268-270)
        windows = ami_family in L.WINDOWS_BUILDS
        reqs = [
            Requirement.new(L.INSTANCE_TYPE, IN, [info.name]),
            Requirement.new(L.ARCH, IN, [info.arch]),
            Requirement.new(L.OS, IN,
                            [L.OS_WINDOWS if windows else L.OS_LINUX]),
            Requirement.new(L.WINDOWS_BUILD, IN,
                            [L.WINDOWS_BUILDS[ami_family]]) if windows
            else Requirement.new(L.WINDOWS_BUILD, DOES_NOT_EXIST),
            Requirement.new(L.ZONE, IN, zones),
            Requirement.new(L.ZONE_ID, IN, zone_ids),
            Requirement.new(L.CAPACITY_TYPE, IN, cts),
            Requirement.new(L.INSTANCE_CATEGORY, IN, [info.category]),
            Requirement.new(L.INSTANCE_FAMILY, IN, [info.family]),
            Requirement.new(L.INSTANCE_GENERATION, IN, [str(info.generation)]),
            Requirement.new(L.INSTANCE_SIZE, IN, [info.size]),
            Requirement.new(L.INSTANCE_CPU, IN, [str(info.vcpus)]),
            Requirement.new(L.INSTANCE_CPU_MANUFACTURER, IN, [info.cpu_manufacturer]),
            Requirement.new(L.INSTANCE_MEMORY, IN, [str(info.memory_bytes // MIB)]),
            Requirement.new(L.INSTANCE_NETWORK_BANDWIDTH, IN,
                            [str(BANDWIDTH_MBPS.get(
                                info.name, info.network_bandwidth_mbps))]),
            Requirement.new(L.INSTANCE_EBS_BANDWIDTH, IN,
                            [str(info.ebs_bandwidth_mbps)]),
            Requirement.new(L.INSTANCE_ENCRYPTION_IN_TRANSIT, IN,
                            [str(info.encryption_in_transit).lower()]),
        ]
        # Optional labels get explicit DoesNotExist when absent (the reference
        # seeds these so a pod requiring e.g. instance-gpu-name can never land
        # on a non-GPU type, types.go:183-287).
        if info.hypervisor:
            reqs.append(Requirement.new(L.INSTANCE_HYPERVISOR, IN, [info.hypervisor]))
        else:
            reqs.append(Requirement.new(L.INSTANCE_HYPERVISOR, DOES_NOT_EXIST))
        if info.local_nvme_bytes:
            reqs.append(Requirement.new(L.INSTANCE_LOCAL_NVME, IN,
                                        [str(info.local_nvme_bytes // GIB)]))
        else:
            reqs.append(Requirement.new(L.INSTANCE_LOCAL_NVME, DOES_NOT_EXIST))
        if info.gpu_count:
            reqs += [
                Requirement.new(L.INSTANCE_GPU_NAME, IN, [info.gpu_name]),
                Requirement.new(L.INSTANCE_GPU_MANUFACTURER, IN, [info.gpu_manufacturer]),
                Requirement.new(L.INSTANCE_GPU_COUNT, IN, [str(info.gpu_count)]),
                Requirement.new(L.INSTANCE_GPU_MEMORY, IN,
                                [str(info.gpu_memory_bytes // MIB)]),
            ]
        else:
            reqs += [Requirement.new(k, DOES_NOT_EXIST) for k in
                     (L.INSTANCE_GPU_NAME, L.INSTANCE_GPU_MANUFACTURER,
                      L.INSTANCE_GPU_COUNT, L.INSTANCE_GPU_MEMORY)]
        if info.accelerator_count:
            reqs += [
                Requirement.new(L.INSTANCE_ACCELERATOR_NAME, IN, [info.accelerator_name]),
                Requirement.new(L.INSTANCE_ACCELERATOR_MANUFACTURER, IN,
                                [info.accelerator_manufacturer]),
                Requirement.new(L.INSTANCE_ACCELERATOR_COUNT, IN,
                                [str(info.accelerator_count)]),
            ]
        else:
            reqs += [Requirement.new(k, DOES_NOT_EXIST) for k in
                     (L.INSTANCE_ACCELERATOR_NAME,
                      L.INSTANCE_ACCELERATOR_MANUFACTURER,
                      L.INSTANCE_ACCELERATOR_COUNT)]
        return Requirements(reqs)

    def _capacity(self, info: InstanceTypeInfo, nodeclass: EC2NodeClass,
                  ami_id: str) -> Resources:
        """types.go:307-478: memory gets the VM-overhead haircut unless a
        real node taught us the true value (discovered-capacity cache)."""
        discovered = self._discovered_memory.get((info.name, ami_id))
        if discovered is not None:
            memory = discovered
        else:
            memory = int(info.memory_bytes * (1 - self._overhead_pct))
        pods = self._max_pods(info, nodeclass.kubelet)
        cap = {
            "cpu": info.vcpus * 1000,
            "memory": memory,
            "pods": pods,
            "ephemeral-storage": _ephemeral_storage(info, nodeclass),
            # EBS CSI attachment limit (CSINode allocatable)
            ATTACHABLE_VOLUMES: _ebs_attachment_limit(info),
        }
        if info.gpu_count:
            cap[NVIDIA_GPU if info.gpu_manufacturer == "nvidia" else "amd.com/gpu"] = info.gpu_count
        if info.accelerator_count:
            cap[AWS_NEURON] = info.accelerator_count
        if info.efa_count:
            cap[AWS_EFA] = info.efa_count
        # pod-ENI trunking capacity on nitro (types.go: pod-eni)
        if info.hypervisor == "nitro":
            cap[AWS_POD_ENI] = min(info.enis * 9, 107)
        return Resources(cap)

    def _max_pods(self, info: InstanceTypeInfo,
                  kubelet: KubeletConfiguration) -> int:
        if kubelet.max_pods is not None:
            return kubelet.max_pods
        from ..fake.catalog import table_pod_limit
        pods = table_pod_limit(info, self.reserved_enis)
        if kubelet.pods_per_core is not None:
            pods = min(pods, kubelet.pods_per_core * info.vcpus)
        return pods

    def _overhead(self, info: InstanceTypeInfo, nodeclass: EC2NodeClass,
                  capacity: Resources) -> Overhead:
        """EKS kubelet-overhead formulas (types.go:480-565)."""
        kubelet = nodeclass.kubelet
        pods = capacity["pods"]
        if kubelet.kube_reserved:
            kube = Resources.parse(kubelet.kube_reserved)
        else:
            kube = Resources({
                "cpu": _reserved_cpu_millis(info.vcpus),
                "memory": 255 * MIB + 11 * MIB * pods,
            })
        system = Resources.parse(kubelet.system_reserved) if kubelet.system_reserved else Resources({
            "cpu": 100, "memory": 100 * MIB})
        if kubelet.eviction_hard or kubelet.eviction_soft:
            ev_mem = 0
            for spec in (kubelet.eviction_hard, kubelet.eviction_soft):
                v = spec.get("memory.available")
                if v:
                    if v.endswith("%"):
                        # kubelet accepts fractional percentages (e.g. "7.5%")
                        ev_mem = max(ev_mem, int(capacity["memory"] * float(v[:-1]) / 100))
                    else:
                        ev_mem = max(ev_mem, parse_quantity(v, "memory"))
            eviction = Resources({"memory": ev_mem})
        else:
            eviction = Resources({"memory": 100 * MIB})
        return Overhead(kube_reserved=kube, system_reserved=system,
                        eviction_threshold=eviction)


def _reserved_cpu_millis(vcpus: int) -> int:
    """The kubelet CPU-reservation staircase: 6% of the first core, 1% of the
    next, 0.5% of the next two, 0.25% of the rest."""
    millis = 0
    for core in range(vcpus):
        if core == 0:
            millis += 60
        elif core == 1:
            millis += 10
        elif core < 4:
            millis += 5
        else:
            millis += 2  # 0.25% of 1000, floor'd to stay integral
    return millis


def _ephemeral_storage(info: InstanceTypeInfo, nodeclass: EC2NodeClass) -> int:
    if nodeclass.instance_store_policy == "RAID0" and info.local_nvme_bytes:
        return info.local_nvme_bytes
    for bdm in nodeclass.block_device_mappings:
        if bdm.root_volume or len(nodeclass.block_device_mappings) == 1:
            return parse_quantity(bdm.volume_size, "ephemeral-storage")
    return 20 * GIB  # default root volume


def _kubelet_key(k: KubeletConfiguration) -> tuple:
    return (k.max_pods, k.pods_per_core,
            tuple(sorted(k.kube_reserved.items())),
            tuple(sorted(k.system_reserved.items())),
            tuple(sorted(k.eviction_hard.items())),
            tuple(sorted(k.eviction_soft.items())))


def _storage_key(nc: EC2NodeClass) -> tuple:
    return (nc.instance_store_policy,
            tuple((b.device_name, b.volume_size, b.root_volume)
                  for b in nc.block_device_mappings))
