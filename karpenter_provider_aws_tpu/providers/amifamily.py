"""AMI family resolution + userdata bootstrap generation.

Mirrors pkg/providers/amifamily: AMI discovery per family via SSM public
parameters + DescribeImages (ami.go:89-198), deprecation handling,
newest-first sort (types.go:46), ``map_to_instance_types`` by arch /
requirements (ami.go:200-222). Families: AL2 (al2.go), AL2023/nodeadm
(al2023.go), Bottlerocket TOML (bottlerocket.go), Windows (windows.go),
Custom (custom.go). Userdata generation mirrors amifamily/bootstrap: the
eksbootstrap.sh arg line, nodeadm NodeConfig YAML, Bottlerocket settings
TOML, and MIME-multipart merge of custom userdata (bootstrap/mime/).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from ..apis import labels as L
from ..apis.objects import EC2NodeClass, KubeletConfiguration, Taint

FAMILIES = ("al2", "al2023", "bottlerocket", "windows2019", "windows2022",
            "custom")


@dataclass(frozen=True)
class AMI:
    id: str
    name: str
    arch: str           # amd64 | arm64
    creation_date: float
    deprecated: bool = False

    @property
    def requirements(self):
        from ..apis.requirements import IN, Requirement, Requirements
        return Requirements([Requirement.new(L.ARCH, IN, [self.arch])])


class AMIProvider:
    def __init__(self, ec2, clock=None, ssm=None):
        self.ec2 = ec2
        if ssm is None:
            from .ssm import SSMProvider
            ssm = SSMProvider(ec2, clock=clock)
        self.ssm = ssm

    def list(self, nodeclass: EC2NodeClass) -> List[AMI]:
        """Resolve the nodeclass's AMI selector terms to concrete AMIs,
        newest-first then name (deterministic; types.go:46)."""
        amis: Dict[str, AMI] = {}
        for term in nodeclass.ami_selector_terms:
            if term.alias:
                family, _ = (term.alias.split("@", 1) + ["latest"])[:2]
                for arch in ("amd64", "arm64"):
                    ami = self._resolve_ssm(family, arch)
                    if ami is not None:
                        amis[ami.id] = ami
            else:
                # owner scoping (ami.go:106-122): explicit owner wins;
                # name-based discovery defaults to self+amazon so
                # cross-account AMIs need an explicit opt-in; tag/id
                # terms carry no implicit owner restriction
                owners = [term.owner] if term.owner else (
                    ["self", "amazon"] if term.name else [])
                for img in self.ec2.describe_images(
                        tag_filters=dict(term.tags),
                        ids=[term.id] if term.id else (),
                        names=[term.name] if term.name else (),
                        owners=owners):
                    # deprecated AMIs stay launchable when explicitly
                    # selected; they are deprioritized below
                    # (ami.go:173-182,216-222)
                    amis[img.id] = AMI(img.id, img.name, img.arch,
                                       img.creation_date, img.deprecated)
        # non-deprecated first, then newest, then id (types.go:44-55 +
        # the deprecation ordering of ami.go:216-222)
        return sorted(amis.values(),
                      key=lambda a: (a.deprecated, -a.creation_date, a.id))

    def _resolve_ssm(self, family: str, arch: str) -> Optional[AMI]:
        path = f"/aws/service/{family}/{arch}/latest/image_id"
        try:
            ami_id = self.ssm.get(path)
        except KeyError:
            return None
        imgs = self.ec2.describe_images(ids=[ami_id])
        if not imgs:
            return None
        img = imgs[0]
        return AMI(img.id, img.name, img.arch, img.creation_date, img.deprecated)

    def invalidate_deprecated(self) -> int:
        """SSM cache invalidation for params resolving to deprecated AMIs
        (ssm/invalidation/controller.go:55-88): evict the shared SSM
        provider's mutable entries whose AMI is deprecated or gone."""
        bad = set()
        for param in self.ssm.cached().values():
            imgs = self.ec2.describe_images(ids=[param.value])
            if not imgs or imgs[0].deprecated:
                bad.add(param.value)
        return self.ssm.invalidate_deprecated(bad)


def map_to_instance_types(amis: Sequence[AMI], instance_types) -> Dict[str, List]:
    """ami id -> instance types whose requirements the AMI satisfies
    (ami.go:200-222). First (newest) AMI compatible with a type wins."""
    out: Dict[str, List] = {a.id: [] for a in amis}
    for it in instance_types:
        for ami in amis:
            if not it.requirements.conflicts(ami.requirements):
                out[ami.id].append(it)
                break
    return out


# ---------------------------------------------------------------------------
# Bootstrap userdata (amifamily/bootstrap)
# ---------------------------------------------------------------------------

@dataclass
class BootstrapConfig:
    cluster_name: str
    cluster_endpoint: str
    ca_bundle: str = ""
    cluster_cidr: str = "10.100.0.0/16"
    #: "ipv4" | "ipv6" — derived from the kube-dns IP family
    #: (launchtemplate.go:98); AL2 adds --ip-family, nodeadm carries the
    #: IPv6 service CIDR in `cidr`
    ip_family: str = "ipv4"
    #: "" | "RAID0" — local NVMe pooling (ec2nodeclass instanceStorePolicy;
    #: AL2 renders --local-disks raid0, eksbootstrap.go:79-81; nodeadm
    #: renders instance.localStorage.strategy)
    instance_store_policy: str = ""
    labels: Dict[str, str] = field(default_factory=dict)
    taints: Sequence[Taint] = ()
    kubelet: KubeletConfiguration = field(default_factory=KubeletConfiguration)
    custom_user_data: str = ""


def generate_user_data(family: str, cfg: BootstrapConfig) -> str:
    """Family-specific node bootstrap userdata."""
    if family == "al2":
        return _al2(cfg)
    if family == "al2023":
        return _al2023(cfg)
    if family == "bottlerocket":
        return _bottlerocket(cfg)
    if family.startswith("windows"):
        return _windows(cfg)
    return cfg.custom_user_data  # custom family: verbatim (custom.go)


def _kubelet_args(cfg: BootstrapConfig, skip: Sequence[str] = ()) -> str:
    """The --kubelet-extra-args line (bootstrap/eksbootstrap.go kubelet
    flag assembly; deterministic ordering). ``skip`` drops flags a family
    renders elsewhere (AL2's --dns-cluster-ip bootstrap arg)."""
    kl = cfg.kubelet
    args = []
    if cfg.labels:
        args.append("--node-labels=" + ",".join(
            f"{k}={v}" for k, v in sorted(cfg.labels.items())))
    if cfg.taints:
        args.append("--register-with-taints=" + ",".join(
            f"{t.key}={t.value}:{t.effect}" for t in cfg.taints))
    if kl.max_pods is not None:
        args.append(f"--max-pods={kl.max_pods}")
    if kl.pods_per_core is not None:
        args.append(f"--pods-per-core={kl.pods_per_core}")
    if kl.kube_reserved:
        args.append("--kube-reserved=" + ",".join(
            f"{k}={v}" for k, v in sorted(kl.kube_reserved.items())))
    if kl.system_reserved:
        args.append("--system-reserved=" + ",".join(
            f"{k}={v}" for k, v in sorted(kl.system_reserved.items())))
    if kl.eviction_hard:
        args.append("--eviction-hard=" + ",".join(
            f"{k}<{v}" for k, v in sorted(kl.eviction_hard.items())))
    if kl.eviction_soft or kl.eviction_soft_grace_period:
        # kubelet refuses a soft threshold without a grace period (and a
        # grace period without a threshold is a typo'd signal name); the
        # reference rejects both at NodeClass validation, so surface the
        # misconfiguration instead of silently dropping entries
        missing = sorted(set(kl.eviction_soft) -
                         set(kl.eviction_soft_grace_period))
        extra = sorted(set(kl.eviction_soft_grace_period) -
                       set(kl.eviction_soft))
        if missing or extra:
            raise ValueError(
                "evictionSoft/evictionSoftGracePeriod signals must match: "
                f"missing grace period for {missing}, "
                f"grace period without threshold for {extra}")
        args.append("--eviction-soft=" + ",".join(
            f"{k}<{v}" for k, v in sorted(kl.eviction_soft.items())))
        args.append("--eviction-soft-grace-period=" + ",".join(
            f"{k}={v}" for k, v in
            sorted(kl.eviction_soft_grace_period.items())))
    if kl.cluster_dns:
        args.append("--cluster-dns=" + ",".join(kl.cluster_dns))
    if kl.image_gc_high_threshold_percent is not None:
        args.append(f"--image-gc-high-threshold={kl.image_gc_high_threshold_percent}")
    if kl.image_gc_low_threshold_percent is not None:
        args.append(f"--image-gc-low-threshold={kl.image_gc_low_threshold_percent}")
    if kl.cpu_cfs_quota is not None:
        args.append(f"--cpu-cfs-quota={str(kl.cpu_cfs_quota).lower()}")
    if skip:
        args = [a for a in args if not a.startswith(tuple(skip))]
    return " ".join(args)


def _al2(cfg: BootstrapConfig) -> str:
    """eksbootstrap.sh line (al2.go; bootstrap/eksbootstrap.go)."""
    script = (
        "#!/bin/bash -xe\n"
        f"/etc/eks/bootstrap.sh '{cfg.cluster_name}'"
        f" --apiserver-endpoint '{cfg.cluster_endpoint}'"
    )
    if cfg.ca_bundle:
        script += f" --b64-cluster-ca '{cfg.ca_bundle}'"
    if cfg.ip_family == "ipv6":
        script += " --ip-family ipv6"
    if cfg.kubelet.cluster_dns:
        # AL2 takes the DNS IP as a bootstrap.sh arg, not a kubelet flag
        # (eksbootstrap.go:70-72)
        script += f" --dns-cluster-ip '{cfg.kubelet.cluster_dns[0]}'"
    kargs = _kubelet_args(cfg, skip=("--cluster-dns=",))
    if kargs:
        script += f" --kubelet-extra-args '{kargs}'"
    if cfg.instance_store_policy == "RAID0":
        script += " --local-disks raid0"
    script += "\n"
    if cfg.custom_user_data:
        return _mime_merge([cfg.custom_user_data, script])
    return script


def _al2023(cfg: BootstrapConfig) -> str:
    """nodeadm NodeConfig YAML (al2023.go; bootstrap/nodeadm.go)."""
    lines = [
        "apiVersion: node.eks.aws/v1alpha1",
        "kind: NodeConfig",
        "spec:",
        "  cluster:",
        f"    name: {cfg.cluster_name}",
        f"    apiServerEndpoint: {cfg.cluster_endpoint}",
        f"    certificateAuthority: {cfg.ca_bundle}",
        f"    cidr: {cfg.cluster_cidr}",
    ]
    if cfg.instance_store_policy == "RAID0":
        lines += ["  instance:",
                  "    localStorage:",
                  "      strategy: RAID0"]
    lines += ["  kubelet:",
              "    config:"]
    if cfg.kubelet.max_pods is not None:
        lines.append(f"      maxPods: {cfg.kubelet.max_pods}")
    if cfg.kubelet.cluster_dns:
        lines.append(f"      clusterDNS: [{', '.join(cfg.kubelet.cluster_dns)}]")
    lines.append("    flags:")
    # settings already rendered into the config section above must not be
    # repeated as flags (nodeadm maps them into config only)
    _in_config = ("--max-pods=", "--cluster-dns=")
    for flag in _kubelet_args(cfg).split():
        if not flag.startswith(_in_config):
            lines.append(f"      - {flag}")
    body = "\n".join(lines) + "\n"
    parts = [body] + ([cfg.custom_user_data] if cfg.custom_user_data else [])
    return _mime_merge(parts, content_type="application/node.eks.aws")


def _bottlerocket(cfg: BootstrapConfig) -> str:
    """settings TOML (bottlerocket.go)."""
    lines = [
        "[settings.kubernetes]",
        f'cluster-name = "{cfg.cluster_name}"',
        f'api-server = "{cfg.cluster_endpoint}"',
    ]
    if cfg.ca_bundle:
        lines.append(f'cluster-certificate = "{cfg.ca_bundle}"')
    if cfg.kubelet.cluster_dns:
        # bottlerocket.go:54-55
        lines.append(f'cluster-dns-ip = "{cfg.kubelet.cluster_dns[0]}"')
    if cfg.kubelet.max_pods is not None:
        lines.append(f"max-pods = {cfg.kubelet.max_pods}")
    if cfg.labels:
        lines.append("[settings.kubernetes.node-labels]")
        for k, v in sorted(cfg.labels.items()):
            lines.append(f'"{k}" = "{v}"')
    if cfg.taints:
        lines.append("[settings.kubernetes.node-taints]")
        for t in cfg.taints:
            lines.append(f'"{t.key}" = "{t.value}:{t.effect}"')
    body = "\n".join(lines) + "\n"
    if cfg.custom_user_data:
        # bottlerocket: custom settings TOML merges after ours (bottlerocket.go)
        body += cfg.custom_user_data.rstrip() + "\n"
    return body


def _windows(cfg: BootstrapConfig) -> str:
    """PowerShell EKS bootstrap (windows.go)."""
    kargs = _kubelet_args(cfg)
    return (
        "<powershell>\n"
        "[string]$EKSBinDir = \"$env:ProgramFiles\\Amazon\\EKS\"\n"
        f"& $EKSBinDir\\Start-EKSBootstrap.ps1 -EKSClusterName '{cfg.cluster_name}'"
        f" -APIServerEndpoint '{cfg.cluster_endpoint}'"
        + (f" -KubeletExtraArgs '{kargs}'" if kargs else "")
        + "\n</powershell>\n"
    )


def _mime_merge(parts: Sequence[str],
                content_type: str = "text/x-shellscript; charset=\"us-ascii\"") -> str:
    """MIME multipart merge (bootstrap/mime/mime.go)."""
    boundary = "//"
    out = [f'MIME-Version: 1.0\nContent-Type: multipart/mixed; boundary="{boundary}"\n']
    for p in parts:
        ct = content_type
        if p.lstrip().startswith("MIME-Version"):
            p = p.split("\n\n", 1)[-1]
        elif p.lstrip().startswith("apiVersion: node.eks.aws"):
            ct = "application/node.eks.aws"
        elif p.lstrip().startswith("#!"):
            ct = 'text/x-shellscript; charset="us-ascii"'
        out.append(f"--{boundary}\nContent-Type: {ct}\n\n{p}")
    out.append(f"--{boundary}--\n")
    return "\n".join(out)
