"""Deficit-round-robin fair queueing for the coalescer's dispatch path.

The coalescer's per-shape-class queue was a plain FIFO: one chatty
tenant enqueueing back-to-back keeps every other tenant's requests
behind its own, and the leader's batch fills with the chatty tenant's
riders first. FairQueue replaces the deque: each tenant gets its own
FIFO lane, and pops cycle lanes deficit-round-robin — every visit
grants the lane ``quantum`` credits, a pop spends ``cost`` (1 per
request; all requests in a shape class cost the same kernel), so over
any window each active tenant drains at an equal share regardless of
arrival pattern. With one tenant the queue degenerates to the old FIFO
exactly.

The coalescer needs four operations, all O(active tenants) or better:
push, head (peek next in fair order — leader election compares
identity), pop (commit), and iteration over every queued request (the
deadline-share scan). head() must be stable between mutations so every
parked thread observes the same leader.
"""

from __future__ import annotations

import collections

#: credits granted per lane visit; unit request cost makes DRR behave
#: as strict round-robin between active lanes, which is the fairness
#: contract the two-tenant chaos tests pin
DRR_QUANTUM = 1.0


class FairQueue:
    """Multi-lane queue with deficit-round-robin pop order. Not
    thread-safe by itself — the coalescer serializes access under its
    own condition lock, matching the deque it replaces."""

    __slots__ = ("_lanes", "_order", "_deficit", "_rr", "quantum")

    def __init__(self, quantum: float = DRR_QUANTUM):
        self._lanes: dict = {}         # tenant -> deque of pendings
        self._order: list = []         # lane scan order (arrival of lane)
        self._deficit: dict = {}       # tenant -> accumulated credits
        self._rr = 0                   # next lane index to visit
        self.quantum = quantum

    def __len__(self) -> int:
        return sum(len(q) for q in self._lanes.values())

    def __bool__(self) -> bool:
        return any(self._lanes.values())

    def __iter__(self):
        for t in self._order:
            yield from self._lanes[t]

    def push(self, item, tenant: str) -> None:
        lane = self._lanes.get(tenant)
        if lane is None:
            lane = self._lanes[tenant] = collections.deque()
            self._order.append(tenant)
            self._deficit[tenant] = 0.0
        lane.append(item)

    def _scan(self, commit: bool):
        """One DRR sweep: find the next lane with work and enough
        deficit. commit=False peeks (head); commit=True pops and
        advances the round-robin state. Both walk identically, so
        head() IS the item the next pop returns."""
        if not self:
            return None
        order, rr = self._order, self._rr
        deficit = self._deficit if commit else dict(self._deficit)
        n = len(order)
        # two passes bound the walk: every nonempty lane gains quantum
        # >= cost (1) per visit, so a lane with work pops within two
        # laps of the ring
        for step in range(2 * n):
            t = order[(rr + step) % n]
            lane = self._lanes[t]
            if not lane:
                deficit[t] = 0.0   # idle lanes bank no credit
                continue
            deficit[t] += self.quantum
            if deficit[t] >= 1.0:
                if commit:
                    deficit[t] -= 1.0
                    # advance PAST the served lane: with unit quantum a
                    # lane that kept the pointer would win every pop
                    self._rr = (rr + step + 1) % n
                    item = lane.popleft()
                    if not lane:
                        # drop drained lanes so a one-shot tenant does
                        # not grow the ring forever
                        self._retire(t)
                    return item
                return lane[0]
        return None

    def head(self):
        """The item the next pop() will return (None when empty)."""
        return self._scan(commit=False)

    def pop(self):
        return self._scan(commit=True)

    def _retire(self, tenant: str) -> None:
        idx = self._order.index(tenant)
        self._order.pop(idx)
        self._lanes.pop(tenant)
        self._deficit.pop(tenant)
        if idx < self._rr:
            self._rr -= 1
        if self._order:
            self._rr %= len(self._order)
        else:
            self._rr = 0
