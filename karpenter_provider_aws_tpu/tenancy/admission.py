"""Tenant identity, admission control and shape-class accounting for
the multi-tenant sidecar.

Tenants self-identify with ``x-solver-tenant`` request metadata (the
shared-secret ``x-solver-token`` still gates the door; the tenant label
only partitions capacity). A request passes three gates before it may
queue for dispatch:

1. a per-tenant token-bucket RATE quota (sustained rps + burst),
2. a per-tenant concurrent-INFLIGHT cap,
3. the shape-class table (one compiled-kernel slot per bucket, LRU).

Shedding is explicit and cheap: the controller answers with a
retry-after hint sized from the bucket's refill rate, the server maps
it to RESOURCE_EXHAUSTED + ``x-retry-after-ms`` trailing metadata, and
the client's resilience layer (sidecar/resilience.py) classifies the
shed distinctly from a failure — it never trips the circuit breaker.

Defaults are permissive (no quotas configured -> every tenant admits,
exactly the pre-tenancy behavior); operators opt in per deployment
(docs/multi-tenant.md).
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Callable, Optional

import numpy as np

from ..sim.clock import monotonic_of

#: tenant label used when a client sends no x-solver-tenant metadata —
#: anonymous callers share one bucket, so a fleet of label-less clients
#: is ONE tenant to the fairness and quota machinery
DEFAULT_TENANT = "default"

#: metadata key carrying the tenant label (client sets, server reads)
TENANT_METADATA_KEY = "x-solver-tenant"

#: trailing-metadata key carrying the shed retry-after hint, in ms
RETRY_AFTER_METADATA_KEY = "x-retry-after-ms"


class TenantQuota:
    """Per-tenant limits. ``rate`` is sustained requests/second (None =
    unlimited), ``burst`` the token-bucket depth, ``max_inflight`` the
    concurrent-request cap (None = unlimited)."""

    __slots__ = ("rate", "burst", "max_inflight")

    def __init__(self, rate: Optional[float] = None,
                 burst: Optional[int] = None,
                 max_inflight: Optional[int] = None):
        if rate is not None and rate <= 0:
            raise ValueError(f"quota rate must be > 0, got {rate}")
        if burst is not None and burst < 1:
            raise ValueError(f"quota burst must be >= 1, got {burst}")
        if max_inflight is not None and max_inflight < 1:
            raise ValueError(
                f"quota max_inflight must be >= 1, got {max_inflight}")
        self.rate = rate
        self.burst = burst if burst is not None else (
            max(1, int(rate)) if rate is not None else None)
        self.max_inflight = max_inflight


class TokenBucket:
    """Classic token bucket with an injectable clock (tests drive time
    by hand). ``take`` returns (admitted, retry_after_s) — the hint is
    how long until one token refills, 0.0 when admitted."""

    def __init__(self, rate: float, burst: int, clock=None):
        self.rate = float(rate)
        self.burst = float(burst)
        self._clock = monotonic_of(clock)
        self._tokens = float(burst)
        self._last = self._clock()

    def take(self, n: float = 1.0):
        now = self._clock()
        self._tokens = min(self.burst,
                           self._tokens + (now - self._last) * self.rate)
        self._last = now
        if self._tokens >= n:
            self._tokens -= n
            return True, 0.0
        return False, (n - self._tokens) / self.rate


class AdmissionController:
    """The per-tenant gate in front of the dispatch path.

    ``enter(tenant)`` -> (admitted, reason, retry_after_s); on admit the
    caller MUST pair it with ``release(tenant)`` (try/finally in the
    server handler). ``quotas`` maps tenant -> TenantQuota; tenants
    without an entry fall back to ``default_quota`` (None = permissive:
    admit everything, the pre-tenancy posture)."""

    def __init__(self, quotas: Optional[dict] = None,
                 default_quota: Optional[TenantQuota] = None,
                 metrics=None, clock=None):
        self._quotas = dict(quotas or {})
        self._default = default_quota
        self._buckets: dict = {}
        self._inflight: dict = collections.defaultdict(int)
        self._mu = threading.Lock()
        self._clock = monotonic_of(clock)
        self.metrics = metrics

    def _quota(self, tenant: str) -> Optional[TenantQuota]:
        return self._quotas.get(tenant, self._default)

    def enter(self, tenant: str, rpc: str = ""):
        """One admission decision. Shed reasons: "rate" (token bucket
        empty) or "inflight" (concurrency cap reached)."""
        q = self._quota(tenant)
        with self._mu:
            if q is not None and q.max_inflight is not None \
                    and self._inflight[tenant] >= q.max_inflight:
                self._count("shed", tenant, rpc, reason="inflight")
                return False, "inflight", 0.0
            if q is not None and q.rate is not None:
                b = self._buckets.get(tenant)
                if b is None or b.rate != q.rate or b.burst != q.burst:
                    b = self._buckets[tenant] = TokenBucket(
                        q.rate, q.burst, clock=self._clock)
                ok, after = b.take()
                if not ok:
                    self._count("shed", tenant, rpc, reason="rate")
                    return False, "rate", after
            self._inflight[tenant] += 1
            n = self._inflight[tenant]
        self._count("admitted", tenant, rpc)
        if self.metrics is not None:
            self.metrics.set_gauge("karpenter_solver_tenant_inflight", n,
                                   labels={"tenant": tenant})
        return True, "", 0.0

    def release(self, tenant: str) -> None:
        with self._mu:
            n = self._inflight[tenant] = max(
                0, self._inflight[tenant] - 1)
            if n == 0:
                self._inflight.pop(tenant, None)
        if self.metrics is not None:
            self.metrics.set_gauge("karpenter_solver_tenant_inflight", n,
                                   labels={"tenant": tenant})

    def inflight(self, tenant: str) -> int:
        with self._mu:
            return self._inflight.get(tenant, 0)

    def _count(self, what: str, tenant: str, rpc: str, reason=None):
        if self.metrics is None:
            return
        labels = {"tenant": tenant, "rpc": rpc}
        if reason is not None:
            labels["reason"] = reason
        self.metrics.inc(f"karpenter_solver_tenant_{what}_total",
                         labels=labels)


class ShapeClassTable:
    """The compile-cache budget, multi-tenant edition.

    Replaces the server's first-come-forever shape-class set: every
    admitted bucket holds a slot keyed by last use, attributed to the
    tenant that first admitted it. When the table is full, a NEW bucket
    may evict the least-recently-used slot — but only one idle for at
    least ``min_idle_s`` (an actively-hot kernel is never evicted under
    churn; a table full of hot kernels still sheds, which is the budget
    doing its job). Looks like a set to existing callers (len/in).
    """

    def __init__(self, capacity: int = 64, min_idle_s: float = 30.0,
                 metrics=None, clock=None):
        self.capacity = capacity
        self.min_idle_s = min_idle_s
        self.metrics = metrics
        self._clock = monotonic_of(clock)
        self._mu = threading.Lock()
        #: key -> [tenant, last_use]; insertion order is maintained by
        #: re-inserting on touch, so iteration order IS the LRU order
        self._entries: "collections.OrderedDict" = collections.OrderedDict()

    def __len__(self) -> int:
        with self._mu:
            return len(self._entries)

    def __contains__(self, key) -> bool:
        with self._mu:
            return key in self._entries

    def admit(self, key, tenant: str = DEFAULT_TENANT) -> bool:
        """Touch-or-admit ``key``; False means the table is full of
        recently-used shape classes and the request must shed."""
        now = self._clock()
        with self._mu:
            ent = self._entries.get(key)
            if ent is not None:
                ent[1] = now
                self._entries.move_to_end(key)
                return True
            if len(self._entries) >= self.capacity:
                lru_key = next(iter(self._entries))
                lru = self._entries[lru_key]
                if now - lru[1] < self.min_idle_s:
                    return False
                self._entries.pop(lru_key)
                if self.metrics is not None:
                    self.metrics.inc(
                        "karpenter_solver_shape_class_evictions_total",
                        labels={"tenant": lru[0]})
            self._entries[key] = [tenant, now]
            return True

    def per_tenant(self) -> dict:
        """tenant -> slots currently attributed to it (the slot
        accounting the metrics surface)."""
        with self._mu:
            out: dict = collections.defaultdict(int)
            for tenant, _ in self._entries.values():
                out[tenant] += 1
            return dict(out)


class PatchArenaTable:
    """Server-resident arenas for the delta wire (``SolvePatch``).

    Each entry is a full packed input arena plus the delta version it
    reflects, keyed by (tenant, shape-class, client token, arena epoch).
    Same budget shape as :class:`ShapeClassTable`: bounded capacity,
    LRU eviction attributed to the admitting tenant, and an actively-hot
    arena is never evicted (``min_idle_s``) — but arenas additionally
    age out after ``ttl_s`` so a departed client's buffers don't pin
    memory forever. Misses/evictions are not errors: the client's next
    patch gets FAILED_PRECONDITION and degrades to one full Solve.
    """

    def __init__(self, capacity: int = 32, min_idle_s: float = 5.0,
                 ttl_s: float = 600.0, metrics=None, clock=None):
        self.capacity = capacity
        self.min_idle_s = min_idle_s
        self.ttl_s = ttl_s
        self.metrics = metrics
        self._clock = monotonic_of(clock)
        self._mu = threading.Lock()
        #: key -> [tenant, last_use, buf, version]; iteration order is
        #: the LRU order (re-inserted on touch, like ShapeClassTable)
        self._entries: "collections.OrderedDict" = collections.OrderedDict()

    def __len__(self) -> int:
        with self._mu:
            return len(self._entries)

    def _evict_locked(self, now: float) -> bool:
        """Drop expired entries; then, if still full, the LRU entry —
        unless it is hot. True if a slot is free afterwards."""
        for k in [k for k, e in self._entries.items()
                  if now - e[1] >= self.ttl_s]:
            self._drop_locked(k, "ttl")
        if len(self._entries) < self.capacity:
            return True
        lru_key = next(iter(self._entries))
        if now - self._entries[lru_key][1] < self.min_idle_s:
            return False
        self._drop_locked(lru_key, "lru")
        return True

    def _drop_locked(self, key, reason: str):
        tenant = self._entries.pop(key)[0]
        if self.metrics is not None:
            self.metrics.inc(
                "karpenter_solver_wire_resident_evictions_total",
                labels={"tenant": tenant, "reason": reason})

    def prime(self, key, buf, version: int,
              tenant: str = DEFAULT_TENANT) -> bool:
        """Install (or replace) the resident arena for ``key``. False
        means the table is full of hot arenas and the client should keep
        using the full-frame path."""
        now = self._clock()
        with self._mu:
            if key not in self._entries and not self._evict_locked(now):
                return False
            self._entries[key] = [tenant, now, np.array(buf, copy=True),
                                  int(version)]
            self._entries.move_to_end(key)
            return True

    def apply(self, key, sections, payloads, base_version: int,
              new_version: int):
        """Patch the resident arena in place and return a COPY of the
        patched buffer (the caller dispatches the copy, so a concurrent
        patch can never mutate an in-flight solve's input).

        Returns (buf, reason): buf is None when the patch cannot be
        applied — reason is "no_resident" (miss/evicted) or
        "stale_version" (the resident arena is not at base_version).
        An empty section list is a clean resend: the resident buffer is
        re-solved as-is (header-only wire cost).
        """
        now = self._clock()
        with self._mu:
            ent = self._entries.get(key)
            if ent is None:
                return None, "no_resident"
            if now - ent[1] >= self.ttl_s:
                # aged out: same verdict as an eviction between ticks
                self._drop_locked(key, "ttl")
                return None, "no_resident"
            if base_version >= 0 and ent[3] != base_version:
                self._drop_locked(key, "stale")
                return None, "stale_version"
            buf = ent[2]
            for (s0, s1), pl in zip(sections, payloads):
                if s1 > buf.size:
                    self._drop_locked(key, "stale")
                    return None, "stale_version"
                buf[s0:s1] = pl
            ent[1] = now
            ent[3] = int(new_version)
            self._entries.move_to_end(key)
            return np.array(buf, copy=True), None

    def clear(self) -> None:
        """Drop every resident arena (chaos: a server restart /
        compile-cache wipe mid-stream). Each tenant's next patch gets
        FAILED_PRECONDITION and degrades to one full Solve — the
        documented ``no_resident`` path, now forced at will."""
        with self._mu:
            for k in list(self._entries):
                self._drop_locked(k, "wipe")

    def version_of(self, key):
        with self._mu:
            ent = self._entries.get(key)
            return None if ent is None else ent[3]

    def per_tenant(self) -> dict:
        with self._mu:
            out: dict = collections.defaultdict(int)
            for tenant, _, _, _ in self._entries.values():
                out[tenant] += 1
            return dict(out)


def tenant_from_metadata(metadata) -> str:
    """The tenant label an RPC carried (invocation metadata key/value
    pairs), or DEFAULT_TENANT. Labels are clamped to 64 chars so a
    hostile peer cannot mint unbounded metric label values."""
    for k, v in metadata or ():
        if k == TENANT_METADATA_KEY and v:
            return str(v)[:64]
    return DEFAULT_TENANT
