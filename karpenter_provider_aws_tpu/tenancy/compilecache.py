"""Persistent AOT compile cache for the sidecar server.

A fresh server process (rolling restart, horizontal scale-out) pays a
full XLA compile for every bucket its tenants touch — seconds per shape
class on the serving path. JAX's persistent compilation cache persists
compiled executables keyed by HLO hash; pointing every server replica
at one directory means a known bucket's first solve on a NEW process is
a disk read, not a compile.

This module owns the wiring and the observability:

- ``configure_compile_cache`` points JAX at a cache dir versioned by
  jax/jaxlib (an executable compiled by one jaxlib is garbage to
  another — versioned subdirs make rollbacks safe) and drops the
  min-compile-time floor so EVERY kernel persists, not just slow ones.
- ``CompileCacheMonitor`` counts cache hits/misses via jax.monitoring
  events, surfaces them through utils.metrics counters and the Info
  RPC (clients and the warm-start acceptance test read them there).

Everything degrades to a no-op when jax is absent or predates the
monitoring events — the sidecar must keep serving without the cache.
"""

from __future__ import annotations

import logging
import os
import threading

log = logging.getLogger(__name__)

#: jax.monitoring event names fired by jax's compilation-cache lookup
_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_MISS_EVENT = "/jax/compilation_cache/cache_misses"

#: process-wide counts; jax.monitoring listeners cannot be unregistered,
#: so ONE module-level listener feeds however many monitors exist
_counts = {"hits": 0, "misses": 0}
_counts_mu = threading.Lock()
_monitors: list = []
_listener_installed = False


def _on_event(name, **kw):
    if name == _HIT_EVENT:
        kind = "hits"
    elif name == _MISS_EVENT:
        kind = "misses"
    else:
        return
    with _counts_mu:
        _counts[kind] += 1
        monitors = list(_monitors)
    for m in monitors:
        m._record(kind)


def _install_listener() -> bool:
    global _listener_installed
    if _listener_installed:
        return True
    try:
        from jax import monitoring
        monitoring.register_event_listener(_on_event)
        _listener_installed = True
        return True
    except Exception as e:  # jax absent / api moved: serve without it
        log.debug("compile-cache monitoring unavailable: %s", e)
        return False


def configure_compile_cache(cache_dir=None, min_compile_time_s=0.0) -> str:
    """Point JAX's persistent compilation cache at a jax/jaxlib-
    versioned subdir of ``cache_dir`` (default: $KARPENTER_JAX_CACHE or
    .jax_cache next to the package) and return the resolved path ("" if
    jax is unavailable). Idempotent; safe to call before or after
    ops/ffd_jax.py's import-time setup — the last call wins as long as
    nothing compiled yet, which is why the server calls this at
    startup, before the first solve."""
    try:
        import jax
        import jaxlib
    except Exception:
        return ""
    if cache_dir is None:
        cache_dir = os.environ.get("KARPENTER_JAX_CACHE") or os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            ".jax_cache")
    path = os.path.join(
        str(cache_dir), f"jax-{jax.__version__}-jaxlib-{jaxlib.__version__}")
    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          float(min_compile_time_s))
    except Exception as e:  # older jax without the knobs: still serve
        log.debug("persistent compile cache not configured: %s", e)
        return ""
    return path


class CompileCacheMonitor:
    """Hit/miss counts scoped to one consumer (the server handler):
    deltas against the process-wide counters from the moment the
    monitor was created, plus metric emission per event."""

    def __init__(self, metrics=None):
        self.metrics = metrics
        self.enabled = _install_listener()
        with _counts_mu:
            self._base = dict(_counts)
            _monitors.append(self)

    def _record(self, kind: str) -> None:
        if self.metrics is not None:
            self.metrics.inc(
                f"karpenter_solver_compile_cache_{kind}_total")

    def counts(self) -> dict:
        """{"hits": n, "misses": n} seen since this monitor started."""
        with _counts_mu:
            return {k: _counts[k] - self._base[k] for k in _counts}
