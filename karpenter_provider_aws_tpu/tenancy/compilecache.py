"""Persistent AOT compile cache for the sidecar server.

A fresh server process (rolling restart, horizontal scale-out) pays a
full XLA compile for every bucket its tenants touch — seconds per shape
class on the serving path. JAX's persistent compilation cache persists
compiled executables keyed by HLO hash; pointing every server replica
at one directory means a known bucket's first solve on a NEW process is
a disk read, not a compile.

This module owns the wiring and the observability:

- ``configure_compile_cache`` points JAX at a cache dir versioned by
  jax/jaxlib AND the host ISA fingerprint (an executable compiled by
  one jaxlib is garbage to another, and one compiled for a different
  CPU feature set is a SIGILL waiting to fire — the MULTICHIP r05 log
  caught exactly that as a ``cpu_aot_loader`` "+prefer-no-gather is not
  supported on the host machine" warning from a cross-machine cache
  entry) and drops the min-compile-time floor so EVERY kernel persists,
  not just slow ones.
- ``pin_host_isa`` pins XLA:CPU code generation to the executing
  host's ISA tier via ``--xla_cpu_max_isa`` so cache entries never
  carry feature requirements the host can't verify. Call it BEFORE the
  first jax backend touch (the flag is read at backend init).
- ``AOTStore`` + ``aot_kernel`` go one step further than the HLO-keyed
  persistent cache: serialized COMPILED executables keyed by (kernel,
  statics, arg shape), primed offline by ``hack/aotprime.py`` /
  ``make aot-prime``. A cold process that finds its shape class in the
  store serves its first solve with zero tracing and zero XLA compile —
  ``deserialize_and_load`` relinks the executable without ever entering
  the compilation path.
- ``CompileCacheMonitor`` counts cache hits/misses via jax.monitoring
  events, surfaces them through utils.metrics counters and the Info
  RPC (clients and the warm-start acceptance test read them there).

Everything degrades to a no-op when jax is absent or predates the
monitoring events — the sidecar must keep serving without the cache.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import platform
import threading

log = logging.getLogger(__name__)

#: jax.monitoring event names fired by jax's compilation-cache lookup
_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_MISS_EVENT = "/jax/compilation_cache/cache_misses"

#: process-wide counts; jax.monitoring listeners cannot be unregistered,
#: so ONE module-level listener feeds however many monitors exist
_counts = {"hits": 0, "misses": 0}
_counts_mu = threading.Lock()
_monitors: list = []
_listener_installed = False


def _on_event(name, **kw):
    if name == _HIT_EVENT:
        kind = "hits"
    elif name == _MISS_EVENT:
        kind = "misses"
    else:
        return
    with _counts_mu:
        _counts[kind] += 1
        monitors = list(_monitors)
    for m in monitors:
        m._record(kind)


def _install_listener() -> bool:
    global _listener_installed
    if _listener_installed:
        return True
    try:
        from jax import monitoring
        monitoring.register_event_listener(_on_event)
        _listener_installed = True
        return True
    except Exception as e:  # jax absent / api moved: serve without it
        log.debug("compile-cache monitoring unavailable: %s", e)
        return False


def _cpu_flags() -> set:
    """The host CPU's feature-flag set (/proc/cpuinfo; empty elsewhere —
    the fingerprint then keys on machine + versions alone)."""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    return set(line.split(":", 1)[1].split())
    except Exception:
        pass
    return set()


def host_isa_fingerprint() -> str:
    """Short stable hash of everything that makes a compiled CPU
    executable host-specific: machine arch, jax/jaxlib versions, and
    the CPU feature-flag set. Two hosts sharing a fingerprint can share
    compiled artifacts; two hosts differing in ANY feature flag get
    separate cache dirs — which is the whole fix for the cpu_aot_loader
    feature-mismatch warning (a cache entry never crosses an ISA
    boundary again)."""
    try:
        import jax
        import jaxlib
        vers = f"{jax.__version__}|{jaxlib.__version__}"
    except Exception:
        vers = "nojax"
    blob = "|".join([platform.machine(), vers,
                     ",".join(sorted(_cpu_flags()))])
    return hashlib.sha256(blob.encode()).hexdigest()[:12]


#: CPUID flag -> --xla_cpu_max_isa tier, best first. The pin says "emit
#: nothing ABOVE what the host verifiably has": XLA then never tags the
#: executable with pseudo-features a later host (or this one, after a
#: cache copy) can't check against CPUID.
_ISA_TIERS = (("avx512f", "AVX512"), ("avx2", "AVX2"),
              ("sse4_2", "SSE4_2"))


def pin_host_isa() -> str:
    """Pin XLA:CPU codegen to the executing host's ISA tier via
    XLA_FLAGS (--xla_cpu_max_isa). Returns the tier pinned ("" when the
    host reports none of the known tiers, or a pin is already present —
    an operator's explicit flag wins). MUST run before the first jax
    backend touch to take effect; calling late is harmless (the flag
    just isn't re-read)."""
    cur = os.environ.get("XLA_FLAGS", "")
    if "--xla_cpu_max_isa" in cur:
        return ""
    flags = _cpu_flags()
    for flag, isa in _ISA_TIERS:
        if flag in flags:
            os.environ["XLA_FLAGS"] = \
                (cur + " " if cur else "") + f"--xla_cpu_max_isa={isa}"
            return isa
    return ""


def pin_cpu_singlethread() -> bool:
    """Pin the XLA:CPU intra-op pool to ONE thread via XLA_FLAGS.

    The warm-tick serving kernels (suffix re-solves, small full solves)
    are dispatch-bound: their per-op tensors are a few KB, so Eigen's
    multi-thread fan-out buys nothing at the median and contributes the
    entire latency tail — a straggling worker wakeup turns a 1.2ms
    suffix into a 4ms one (measured at the 50k warm-tick shape; single-
    thread cut the p99 tail ~2.5x with an unchanged p50). Serving
    deployments that only dispatch small per-tick kernels should pin;
    batch/mesh deployments crunching big arenas should not. Returns
    False without touching anything when an operator already configured
    threading (their flag wins). MUST run before the first jax backend
    touch to take effect."""
    cur = os.environ.get("XLA_FLAGS", "")
    if ("multi_thread_eigen" in cur
            or "intra_op_parallelism_threads" in cur):
        return False
    os.environ["XLA_FLAGS"] = \
        (cur + " " if cur else "") + \
        "--xla_cpu_multi_thread_eigen=false intra_op_parallelism_threads=1"
    return True


def _cache_root(cache_dir=None) -> str:
    if cache_dir is None:
        cache_dir = os.environ.get("KARPENTER_JAX_CACHE") or os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            ".jax_cache")
    return str(cache_dir)


def configure_compile_cache(cache_dir=None, min_compile_time_s=0.0) -> str:
    """Point JAX's persistent compilation cache at a jax/jaxlib/ISA-
    fingerprinted subdir of ``cache_dir`` (default: $KARPENTER_JAX_CACHE
    or .jax_cache next to the package) and return the resolved path (""
    if jax is unavailable). Idempotent; safe to call before or after
    ops/ffd_jax.py's import-time setup — the last call wins as long as
    nothing compiled yet, which is why the server calls this at
    startup, before the first solve."""
    try:
        import jax
        import jaxlib
    except Exception:
        return ""
    path = os.path.join(
        _cache_root(cache_dir),
        f"jax-{jax.__version__}-jaxlib-{jaxlib.__version__}"
        f"-{platform.machine()}-{host_isa_fingerprint()}")
    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          float(min_compile_time_s))
    except Exception as e:  # older jax without the knobs: still serve
        log.debug("persistent compile cache not configured: %s", e)
        return ""
    return path


class CompileCacheMonitor:
    """Hit/miss counts scoped to one consumer (the server handler):
    deltas against the process-wide counters from the moment the
    monitor was created, plus metric emission per event."""

    def __init__(self, metrics=None):
        self.metrics = metrics
        self.enabled = _install_listener()
        with _counts_mu:
            self._base = dict(_counts)
            _monitors.append(self)

    def _record(self, kind: str) -> None:
        if self.metrics is not None:
            self.metrics.inc(
                f"karpenter_solver_compile_cache_{kind}_total")

    def counts(self) -> dict:
        """{"hits": n, "misses": n} seen since this monitor started."""
        with _counts_mu:
            return {k: _counts[k] - self._base[k] for k in _counts}


# ---------------------------------------------------------------------------
# deliberate AOT executable store
# ---------------------------------------------------------------------------

class AOTStore:
    """Serialized COMPILED executables on disk, keyed by (kernel name,
    statics, arg shape/dtype) inside a directory keyed by the host ISA
    fingerprint. Loading is ``deserialize_and_load`` — a relink, never
    a compile — so a primed store turns a cold process's first solve
    into a dict hit. The directory is only ever read by a host with the
    SAME fingerprint; priming and serving on different machines land in
    different dirs and simply miss (cold, correct) instead of warning
    about unverifiable machine features."""

    def __init__(self, root=None, metrics=None):
        self.metrics = metrics
        self.path = os.path.join(_cache_root(root),
                                 f"aot-{host_isa_fingerprint()}")
        os.makedirs(self.path, exist_ok=True)
        self._mem: dict = {}
        self._mu = threading.Lock()

    @staticmethod
    def entry_key(name: str, statics: dict, shape, dtype) -> str:
        blob = json.dumps([name, sorted((k, int(v))
                                        for k, v in statics.items()),
                           [int(s) for s in shape], str(dtype)])
        return hashlib.sha256(blob.encode()).hexdigest()[:16]

    def _file(self, name: str, key: str) -> str:
        return os.path.join(self.path, f"{name}-{key}.aot")

    def load(self, name: str, statics: dict, shape, dtype):
        """The ready executable for this call, or None (cold)."""
        key = self.entry_key(name, statics, shape, dtype)
        with self._mu:
            exe = self._mem.get(key)
        if exe is not None:
            return exe
        fp = self._file(name, key)
        if not os.path.exists(fp):
            return None
        exe = self._relink(fp)
        if exe is not None:
            with self._mu:
                self._mem[key] = exe
        return exe

    def save(self, name: str, statics: dict, shape, dtype,
             compiled) -> bool:
        """Persist a compiled executable (atomic: temp + rename, so a
        concurrent reader never sees a torn entry)."""
        try:
            import pickle

            from jax.experimental.serialize_executable import serialize
            payload = pickle.dumps(serialize(compiled))
        except Exception as e:
            log.debug("aot serialize failed for %s: %s", name, e)
            return False
        key = self.entry_key(name, statics, shape, dtype)
        fp = self._file(name, key)
        tmp = f"{fp}.{os.getpid()}.tmp"
        with open(tmp, "wb") as f:
            f.write(payload)
        os.replace(tmp, fp)
        with self._mu:
            self._mem[key] = compiled
        return True

    def _relink(self, fp: str):
        try:
            import pickle

            from jax.experimental.serialize_executable import \
                deserialize_and_load
            with open(fp, "rb") as f:
                blob = f.read()
            return deserialize_and_load(*pickle.loads(blob))
        except Exception as e:
            # a stale/corrupt entry degrades to a compile, never an
            # error on the serving path
            log.warning("aot entry %s unusable (%s); will recompile",
                        os.path.basename(fp), e)
            return None

    def preload(self) -> int:
        """Relink every entry into memory NOW (startup), so the first
        solve pays a dict lookup instead of a disk read + relink.
        Returns the number of executables resident."""
        n = 0
        try:
            names = sorted(os.listdir(self.path))
        except OSError:
            return 0
        for fn in names:
            if not fn.endswith(".aot"):
                continue
            key = fn[:-4].rsplit("-", 1)[-1]
            with self._mu:
                if key in self._mem:
                    n += 1
                    continue
            exe = self._relink(os.path.join(self.path, fn))
            if exe is not None:
                with self._mu:
                    self._mem[key] = exe
                n += 1
        return n


#: process-wide active store + record flag (the dispatch hook must cost
#: one attribute read when AOT is off — it sits on the solve hot path)
_aot_store: "AOTStore | None" = None
_aot_record = False
_aot_counts = {"served": 0, "cold": 0, "recorded": 0}


def activate_aot(store: "AOTStore | None" = None, record: bool = False,
                 root=None, metrics=None) -> AOTStore:
    """Install the process-wide AOT store consulted by the solver's
    dispatch sites (solver/tpu.py). ``record=True`` additionally
    compiles-and-persists every shape class the process dispatches —
    the mode hack/aotprime.py runs in; serving replicas run with it
    off so an unexpected shape degrades to a normal jit compile."""
    global _aot_store, _aot_record
    _aot_store = store if store is not None else AOTStore(
        root=root, metrics=metrics)
    _aot_record = bool(record)
    return _aot_store


def deactivate_aot() -> None:
    global _aot_store, _aot_record
    _aot_store, _aot_record = None, False


def aot_recording() -> bool:
    """True while ``activate_aot(record=True)`` is in effect — prime
    runs that should eagerly compile whole shape-class ladders
    (solver/tpu.py _prime_suffix) key off this."""
    return _aot_record


def aot_counts() -> dict:
    """{"served", "cold", "recorded"} since process start (served =
    dispatches answered by a stored executable, cold = store active but
    shape class absent, recorded = executables persisted in record
    mode)."""
    with _counts_mu:
        return dict(_aot_counts)


def aot_kernel(name: str, fn, arg, statics: dict):
    """Dispatch-site hook: the ready executable for ``fn(arg,
    **statics)`` from the active store, or None (take the jit path).
    In record mode a cold shape class is lowered, compiled, persisted
    and then served — so one representative solve primes the store for
    every future process on this fingerprint."""
    store = _aot_store
    if store is None:
        return None
    shape, dtype = tuple(arg.shape), str(arg.dtype)
    exe = store.load(name, statics, shape, dtype)
    kind = "served"
    if exe is None and _aot_record:
        try:
            exe = fn.lower(arg, **statics).compile()
        except Exception as e:
            log.debug("aot record compile failed for %s: %s", name, e)
            exe = None
        if exe is not None:
            store.save(name, statics, shape, dtype, exe)
            kind = "recorded"
    if exe is None:
        kind = "cold"
    with _counts_mu:
        _aot_counts[kind] += 1
    if store.metrics is not None:
        store.metrics.inc("karpenter_solver_aot_dispatch_total",
                          labels={"outcome": kind, "kernel": name})
    return exe


def aot_kernel_n(name: str, fn, args, statics: dict):
    """``aot_kernel`` for kernels taking operands beyond the packed
    buffer (the suffix kernel's checkpoint carry pytree). The store key
    stays (name, statics, first-operand shape/dtype): every extra
    operand's shape is a pure function of the statics (carry fields are
    sized by T/D/Z/C/E/P/n_max), so the key is still complete. Record
    mode lowers with ALL operands; the returned executable is called
    with the same full operand tuple."""
    store = _aot_store
    if store is None:
        return None
    arg0 = args[0]
    shape, dtype = tuple(arg0.shape), str(arg0.dtype)
    exe = store.load(name, statics, shape, dtype)
    kind = "served"
    if exe is None and _aot_record:
        try:
            exe = fn.lower(*args, **statics).compile()
        except Exception as e:
            log.debug("aot record compile failed for %s: %s", name, e)
            exe = None
        if exe is not None:
            store.save(name, statics, shape, dtype, exe)
            kind = "recorded"
    if exe is None:
        kind = "cold"
    with _counts_mu:
        _aot_counts[kind] += 1
    if store.metrics is not None:
        store.metrics.inc("karpenter_solver_aot_dispatch_total",
                          labels={"outcome": kind, "kernel": name})
    return exe
