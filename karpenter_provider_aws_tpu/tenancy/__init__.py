"""Multi-tenant serving layer for the solver sidecar.

One sidecar pool serving many clusters needs what any multi-tenant
service needs: identity (admission.py — x-solver-tenant metadata,
token-bucket rate quotas, inflight caps, LRU shape-class slots), fair
scheduling (fairness.py — deficit-round-robin lanes in front of the
coalescer), shape amortization (bucketing.py — pad near-miss shapes up
to a shared bucket so they ride one compiled kernel, byte-identically),
and warm starts (compilecache.py — JAX's persistent compilation cache
wired into server startup). sidecar/server.py composes all four; each
piece is independently testable and jax-free except compilecache.
"""

from .admission import (DEFAULT_TENANT, RETRY_AFTER_METADATA_KEY,
                        TENANT_METADATA_KEY, AdmissionController,
                        ShapeClassTable, TenantQuota, TokenBucket,
                        tenant_from_metadata)
from .bucketing import (BUCKET_DIMS, bucket_dim, bucket_statics,
                        pad_arena, unpad_outputs)
from .fairness import FairQueue

__all__ = [
    "AdmissionController", "BUCKET_DIMS", "DEFAULT_TENANT", "FairQueue",
    "RETRY_AFTER_METADATA_KEY", "ShapeClassTable", "TENANT_METADATA_KEY",
    "TenantQuota", "TokenBucket", "bucket_dim", "bucket_statics",
    "pad_arena", "tenant_from_metadata", "unpad_outputs",
]
