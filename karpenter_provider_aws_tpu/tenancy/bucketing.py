"""Bucketed-padding batching for the multi-tenant sidecar.

The coalescer (sidecar/server.py) batches only requests whose statics
hash to the SAME shape class — across a tenant population the near-miss
shapes (one tenant has 37 types, another 41) never share a vmapped
dispatch and each mints its own compiled kernel. This module generalizes
the shape class to a BUCKET: every bucketable dimension rounds up to the
next bucket boundary, the request arena is padded up to the bucket shape
with provably inert rows, and the bucket's output buffer is sliced back
to the caller's exact shape. Nearby tenants then ride one compiled
kernel and one dispatch.

The inertness contract (why padding cannot change a decision — see the
"inert padding" note in ops/ffd_jax.py for the kernel-side view):

- padded GROUPS have n=0 and all-False masks: their scan steps place
  nothing and open nothing (the client already pads G this way);
- padded TYPES have A=0, avail_zc=False and F=False for every group:
  no candidate mask ever admits them;
- padded ZONES / CAPACITY TYPES appear only as all-False columns of
  agz/agc/pool_agz/pool_agc/avail_zc: every kernel read ANDs them away;
- padded EXISTING rows have zero allocatable and ex_compat=False, so
  their headroom is pinned to 0 (dead rows, same as the client's E pad);
- padded POOLS admit nothing, offer no types and have all-zero limits;
- padded RESOURCE dims have R=0 everywhere, which every headroom/budget
  read guards on; live pools get limit=-1 (unlimited) in the new
  columns exactly as the client's own D-padding does.

Outputs demux byte-identically: the bucket solve's output arrays are
sliced back to the request dims (dropping the dead existing rows
[E, E_bucket) from the slot axis) and re-packed — fuzzed against solo
solves in tests/test_tenancy.py across bucket boundaries.
"""

from __future__ import annotations

import numpy as np

from ..ops.hostpack import (pack_inputs1, pack_outputs1, pad_to,
                            unpack_inputs1, unpack_outputs1)

#: dims that may round up to a bucket boundary; everything else in the
#: statics vector (n_max, K, V, M, F and the pruned S) stays exact and
#: keys the bucket verbatim
BUCKET_DIMS = ("T", "D", "Z", "C", "G", "E", "P")

_DIM_KEYS = ("T", "D", "Z", "C", "G", "E", "P", "K", "M", "F", "Q")


def _pow2(v: int) -> int:
    return 1 << (v - 1).bit_length() if v > 0 else 0


def _pow15(v: int) -> int:
    """Next boundary in the {2^k, 1.5*2^k} ladder (1,2,3,4,6,8,12,...):
    finer than plain pow2 so the padded waste on the widest axis (the
    type catalog) stays under 50%."""
    if v <= 2:
        return max(v, 0)
    p = _pow2(v)
    mid = (p >> 1) + (p >> 2)
    return mid if v <= mid else p


def bucket_dim(name: str, v: int) -> int:
    """Bucket boundary for one statics dim. G/E/P mirror the client's
    own pow2 padding (idempotent for modern clients); T gets the finer
    1.5-ladder because it is the widest axis; D keeps the client's
    max(8, .) floor."""
    if name == "T":
        return _pow15(v)
    if name == "D":
        return max(8, _pow2(v))
    if name == "E":
        return _pow2(v)
    if name in ("Z", "C", "G", "P"):
        return max(1, _pow2(v)) if v else v
    return v


def bucket_statics(kv: dict) -> dict:
    """The bucket a statics dict lands in: bucketable dims round up,
    exact dims pass through. Returns a NEW dict in the same key order
    (bucket keys feed the coalescer's shape-class hash)."""
    return {k: bucket_dim(k, v) if k in BUCKET_DIMS else v
            for k, v in kv.items()}


def _dims(kv: dict) -> dict:
    # Q is absent from pre-priority statics dicts (old clients, padded
    # wire vectors); default 0 = priority section absent
    return {k: kv.get(k, 0) if k == "Q" else kv[k] for k in _DIM_KEYS}


def pad_arena(buf: np.ndarray, kv: dict, kvB: dict) -> np.ndarray:
    """Pad a validated request arena from its exact statics ``kv`` up to
    the bucket statics ``kvB`` with inert rows (module docstring). The
    input buffer is not modified; when the shape already sits on its
    bucket boundary the original buffer is returned as-is."""
    if all(kv[k] == kvB[k] for k in BUCKET_DIMS):
        return np.asarray(buf)
    v = unpack_inputs1(np.asarray(buf), **_dims(kv))
    T, D, Z, C = kv["T"], kv["D"], kv["Z"], kv["C"]
    G, E, P = kv["G"], kv["E"], kv["P"]
    Tb, Db, Zb, Cb = kvB["T"], kvB["D"], kvB["Z"], kvB["C"]
    Gb, Eb, Pb = kvB["G"], kvB["E"], kvB["P"]
    K, M, F = kv["K"], kv["M"], kv["F"]
    Q = kv.get("Q", 0)
    out = {
        "A": pad_to(v["A"], (Tb, Db)),
        "R": pad_to(v["R"], (Gb, Db)),
        "n": pad_to(v["n"], (Gb,)),
        "daemon": pad_to(v["daemon"], (Gb, Pb, Db)),
        "pool_used0": pad_to(v["pool_used0"], (Pb, Db)),
        "ex_alloc": pad_to(v["ex_alloc"], (Eb, Db)),
        "ex_used0": pad_to(v["ex_used0"], (Eb, Db)),
        "F": pad_to(v["F"], (Gb, Tb)),
        "agz": pad_to(v["agz"], (Gb, Zb)),
        "agc": pad_to(v["agc"], (Gb, Cb)),
        "admit": pad_to(v["admit"], (Gb, Pb)),
        "pool_types": pad_to(v["pool_types"], (Pb, Tb)),
        "pool_agz": pad_to(v["pool_agz"], (Pb, Zb)),
        "pool_agc": pad_to(v["pool_agc"], (Pb, Cb)),
        "ex_compat": pad_to(v["ex_compat"], (Gb, Eb)),
    }
    # offerings ride flattened [T, Z*C]: pad in the unflattened view so
    # the new zone/capacity-type columns land where the bucket's
    # flattening expects them
    av = pad_to(v["avail_zc"].reshape(T, Z, C), (Tb, Zb, Cb))
    out["avail_zc"] = av.reshape(Tb, Zb * Cb)
    # live pools get -1 (unlimited) in the new resource columns — the
    # client's own D padding discipline; an appended 0 would flip the
    # has-limit gate for limitless pools. Dead rows (client's P pad)
    # stay all-zero; their limits are unreadable (admit=False).
    pl = np.full((Pb, Db), -1, dtype=np.int64)
    pl[:P, :D] = v["pool_limit"]
    pl[P:, :] = 0
    out["pool_limit"] = pl
    if K:
        out["mv_floor"] = pad_to(v["mv_floor"], (Pb, K))
        out["mv_pairs_t"] = v["mv_pairs_t"]
        out["mv_pairs_v"] = v["mv_pairs_v"]
    if F > 1:
        # padded groups are provable no-op steps, fusable with anything
        # (same convention as the client's G pad)
        out["fuse"] = pad_to(v["fuse"], (Gb,), fill=True)
    if Q:
        # padded groups are inert (n=0): priority 0 is fine for them
        out["prio"] = pad_to(v["prio"], (Gb,))
    return pack_inputs1(out, Tb, Db, Zb, Cb, Gb, Eb, Pb, K, M, F, Q)


def unpad_outputs(obuf: np.ndarray, kv: dict, kvB: dict) -> np.ndarray:
    """Slice a bucket-shaped output buffer back to the request's exact
    statics and re-pack — the inverse leg of pad_arena. Byte-identical
    to what a solo solve at ``kv`` would have produced (the inertness
    contract; fuzzed in tests/test_tenancy.py)."""
    if all(kv[k] == kvB[k] for k in BUCKET_DIMS):
        return np.asarray(obuf)
    o = unpack_outputs1(np.asarray(obuf), kvB["T"], kvB["D"], kvB["Z"],
                        kvB["C"], kvB["G"], kvB["E"], kvB["P"],
                        kv["n_max"])
    T, D, Z, C = kv["T"], kv["D"], kv["Z"], kv["C"]
    G, E, P = kv["G"], kv["E"], kv["P"]
    Eb, n_max = kvB["E"], kv["n_max"]
    # slot axis: keep the caller's existing rows, drop the dead padded
    # existing rows [E, Eb), keep the new-node section
    keep = np.r_[0:E, Eb:Eb + n_max]
    out = {
        "leftover": o["leftover"][:G],
        "used": o["used"][keep][:, :D],
        "pool": o["pool"][keep],
        "num_nodes": o["num_nodes"],
        "pool_used": o["pool_used"][:P, :D],
        "takes": o["takes"][:G][:, keep],
        "types": o["types"][keep][:, :T],
        "zones": o["zones"][keep][:, :Z],
        "ct": o["ct"][keep][:, :C],
        "alive": o["alive"][keep],
    }
    return pack_outputs1(out, T, D, Z, C, G, E, P, n_max)
