"""Device lowering of the topology pour (ops/topo.py) — a jitted group
scan whose topology groups run the pour's per-event decision loop inside
``lax.while_loop``, with the same event compression the host engine uses:

- run batching: each event places ``room`` pods (zone-run-room / host
  caps / budget bounded), not one;
- the periodic-cycle jump: a ring buffer of the last ``2*PMAX`` events
  detects the staggered-ladder steady state and commits ``k`` whole
  periods in one event (ops/topo.py:_try_jump, same bounds);
- the cap-1 hostname-anti ladder bulk commit (one event opens the whole
  one-pod-per-node run, ops/topo.py:_bulk_anti_clones).

Non-topology groups in the same scan run the shared closed-form step
(ops/ffd_jax.plain_group_step) plus membership-counter recording, so the
carry state any group sees is bit-identical to the host engine's.

Outputs: per-group ``takes`` plus a compact EVENT LOG (slot/zone/len/
kind/aux per event) that the solver decodes into the same placement-run
structure the host pour emits (including ("cyc", pattern, k) entries) —
pod-to-node identity assignment is therefore identical, which
tests/test_topology_equivalence.py enforces against the CPU oracle.

Scope (the host pour remains the engine outside it, chosen by
solver/tpu.py's lowerability predicate): no existing nodes, no minValues
floors, no duplicate counter references within one group's constraint
lists, and at most EVCAP events per group / periods up to PMAX (a bail
flag falls back to the host pour — never a wrong answer).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from .ffd_jax import (BIG, Carry, KernelInputs, _headroom_matrix,
                      _headroom_vec, _pool_budget_jax, plain_group_step)

#: event log kinds (host decode expands them into placement runs)
K_PLACE = 0   # run on a zone-decided (or zone-free) slot
K_FIX = 1     # run that also fixed an undecided slot's zone
K_OPEN = 2    # run on a freshly opened node
K_CYC = 3     # periodic jump: len=period p, aux=k whole periods
K_ANTIRUN = 4  # cap-1 anti ladder: len=m fresh one-pod nodes from slot

#: ring sentinel that can never equal a real event (see antirun poisoning)
_RB_INVALID = -9


class TopoGroupRows(NamedTuple):
    """Per-group dense topology structure (scanned alongside the plain
    group rows). GZ/GH are the interned zone/hostname counter spaces of
    ops/topo.py's TopoEncoding."""
    has_topo: jax.Array     # [G] bool
    zone_needed: jax.Array  # [G] bool
    min_mask: jax.Array     # [G, Z] bool  eligible zones for min-count
    zs_any: jax.Array       # [G, GZ] bool  spread records into counter
    zs_skew: jax.Array      # [G, GZ] i64   min enforced skew (BIG = none)
    hs_any: jax.Array       # [G, GH] bool
    hs_skew: jax.Array      # [G, GH] i64
    za_any: jax.Array       # [G, GZ] bool  required zone affinity
    za_anti: jax.Array      # [G, GZ] bool
    za_own: jax.Array       # [G, GZ] bool
    ha_any: jax.Array       # [G, GH] bool
    ha_anti: jax.Array      # [G, GH] bool
    ha_own: jax.Array       # [G, GH] bool
    member_z: jax.Array     # [G] i32  counter to record membership into,
    member_h: jax.Array     # [G] i32  -1 or already covered by zs/hs rows


class _EvState(NamedTuple):
    """Carry of the per-group event while_loop."""
    # node state (Carry fields, mutated by commits)
    used: jax.Array
    types: jax.Array
    zones: jax.Array
    ct: jax.Array
    pool: jax.Array
    alive: jax.Array
    num_nodes: jax.Array
    pool_used: jax.Array
    # topology counters
    cz: jax.Array           # [GZ, Z]
    ch: jax.Array           # [GH, N]
    zfix: jax.Array         # [N] i32
    # group-fill state
    take: jax.Array         # [N]
    rem: jax.Array          # [N]
    cand: jax.Array         # [N, T]
    ok: jax.Array           # [N] live admissibility (cleared on skips)
    n_rem: jax.Array
    # event log
    ev_slot: jax.Array      # [EVCAP] i64
    ev_zone: jax.Array
    ev_len: jax.Array
    ev_kind: jax.Array
    ev_aux: jax.Array
    ev_n: jax.Array
    # jump ring buffer: last RB events as (slot, zone, len, kind)
    rb: jax.Array           # [RB, 4] i64
    L: jax.Array            # total host-equivalent event count
    stuck: jax.Array        # bool: no placement possible this event
    bail: jax.Array         # bool: EVCAP exhausted -> host fallback


def _zone_ok(cz, min_mask, zs_skew, za_any, za_anti, za_own):
    """[Z] zones admissible under enforced spread + zone affinity
    (ops/topo.py:_zone_ok)."""
    GZ, Z = cz.shape
    elig_any = min_mask.any()
    mn = jnp.where(elig_any,
                   jnp.where(min_mask[None, :], cz, BIG).min(axis=1), 0)
    ok = ((cz + 1 - mn[:, None]) <= zs_skew[:, None]).all(axis=0)
    occ = cz > 0
    occ_any = occ.any(axis=1)
    aff_ok = jnp.where(
        za_anti[:, None], ~occ,
        jnp.where(occ_any[:, None], occ,
                  jnp.broadcast_to(za_own[:, None], (GZ, Z))))
    ok &= jnp.where(za_any[:, None], aff_ok, True).all(axis=0)
    return ok


def _zone_score(cz, zs_skew):
    """[Z] zone-choice score: sum of enforced-spread counts
    (ops/topo.py:_choose_zone). Zones are name-sorted in the encoding, so
    index order IS the lexicographic tie-break."""
    return jnp.where((zs_skew < BIG)[:, None], cz, 0).sum(axis=0)


def _choose_zone(zcand, zok, cz, zs_skew):
    """Min-(score, index) zone among zcand & zok; -1 if none."""
    ok = zcand & zok
    score = _zone_score(cz, zs_skew)
    Z = score.shape[0]
    key = jnp.where(ok, score * Z + jnp.arange(Z), BIG)
    zi = jnp.argmin(key)
    return jnp.where(ok.any(), zi, -1).astype(jnp.int64)


def _zone_run_room(zi, cz, min_mask, zs_skew, za_any, za_anti, za_own):
    """Consecutive-pour room in zone ``zi`` (ops/topo.py:_zone_run_room).
    Callers guarantee zi >= 0."""
    elig_any = min_mask.any()
    mn = jnp.where(elig_any,
                   jnp.where(min_mask[None, :], cz, BIG).min(axis=1), 0)
    c = cz[:, zi]
    at_min = elig_any & (c == mn)
    per = jnp.where(zs_skew < BIG,
                    jnp.where(at_min, 1, mn + zs_skew - c), BIG)
    room = per.min()
    occ_any = (cz > 0).any(axis=1)
    za_room = jnp.where(
        za_any & (za_anti | (za_own & ~occ_any)), 1, BIG)
    return jnp.maximum(jnp.minimum(room, za_room.min()), 1)


def _host_cap_slots(ch, hs_skew, ha_any, ha_anti, ha_own):
    """[N] max further pods per slot under hostname spread/affinity
    (ops/topo.py:_host_cap, vectorized over slots)."""
    cap = jnp.where((hs_skew < BIG)[:, None], hs_skew[:, None] - ch,
                    BIG).min(axis=0)
    occ_here = ch > 0
    occ_any = occ_here.any(axis=1)
    anti_cap = jnp.where(occ_here, 0, jnp.where(ha_own[:, None], 1, BIG))
    pos_cap = jnp.where(occ_any[:, None],
                        jnp.where(occ_here, BIG, 0),
                        jnp.where(ha_own[:, None], BIG, 0))
    ha_cap = jnp.where(ha_anti[:, None], anti_cap, pos_cap)
    cap = jnp.minimum(cap, jnp.where(ha_any[:, None], ha_cap, BIG).min(axis=0))
    return jnp.clip(cap, 0, BIG)


def _host_cap_new(ch, hs_skew, ha_any, ha_anti, ha_own):
    """Cap for a brand-new node (ops/topo.py:_host_cap_new)."""
    cap = jnp.where(hs_skew < BIG, hs_skew, BIG).min()
    occ_any = (ch > 0).any(axis=1)
    per = jnp.where(
        ha_anti, jnp.where(ha_own, 1, BIG),
        jnp.where(occ_any | ~ha_own, 0, BIG))
    cap = jnp.minimum(cap, jnp.where(ha_any, per, BIG).min())
    return jnp.clip(cap, 0, BIG)


def _record_scatter(st: _EvState, g, slot, zi, count):
    """Counter updates for one commit (ops/topo.py:_record): spread
    counters (zone ones only when a zone is decided), then membership
    counters not already covered."""
    zs_any, hs_any = g.zs_any, g.hs_any
    mz, mh = g.member_z, g.member_h
    has_z = zi >= 0
    zic = jnp.clip(zi, 0, st.cz.shape[1] - 1)
    dz = jnp.where(zs_any & has_z, count, 0)
    cz = st.cz.at[:, zic].add(dz)
    mz_ok = (mz >= 0) & has_z
    cz = cz.at[jnp.clip(mz, 0), zic].add(jnp.where(mz_ok, count, 0))
    dh = jnp.where(hs_any, count, 0)
    ch = st.ch.at[:, slot].add(dh)
    ch = ch.at[jnp.clip(mh, 0), slot].add(jnp.where(mh >= 0, count, 0))
    return st._replace(cz=cz, ch=ch)


def _log_event(st: _EvState, slot, zi, ln, kind, aux=0, ring=True):
    """Append to the event log (+ ring buffer unless the caller manages
    it). EVCAP overflow sets bail — the host engine takes over."""
    i = st.ev_n
    over = i >= st.ev_slot.shape[0]
    ic = jnp.clip(i, 0, st.ev_slot.shape[0] - 1)
    st = st._replace(
        ev_slot=st.ev_slot.at[ic].set(jnp.where(over, st.ev_slot[ic], slot)),
        ev_zone=st.ev_zone.at[ic].set(jnp.where(over, st.ev_zone[ic], zi)),
        ev_len=st.ev_len.at[ic].set(jnp.where(over, st.ev_len[ic], ln)),
        ev_kind=st.ev_kind.at[ic].set(jnp.where(over, st.ev_kind[ic], kind)),
        ev_aux=st.ev_aux.at[ic].set(jnp.where(over, st.ev_aux[ic], aux)),
        ev_n=i + 1,
        bail=st.bail | over,
    )
    if ring:
        ev = jnp.array([0, 0, 0, 0], jnp.int64)
        ev = ev.at[0].set(slot).at[1].set(zi).at[2].set(ln).at[3].set(kind)
        st = st._replace(rb=jnp.concatenate([st.rb[1:], ev[None, :]]),
                         L=st.L + 1)
    return st


def _commit(st: _EvState, g, R, slot, zi, count, kind):
    """Place ``count`` pods on ``slot`` (ops/topo.py:_commit)."""
    pi = st.pool[slot]
    st = st._replace(
        take=st.take.at[slot].add(count),
        rem=st.rem.at[slot].add(-count),
        used=st.used.at[slot].add(count * R),
        pool_used=st.pool_used.at[jnp.clip(pi, 0)].add(
            jnp.where(pi >= 0, count * R, 0)),
        n_rem=st.n_rem - count)
    st = _record_scatter(st, g, slot, zi, count)
    return _log_event(st, slot, zi, count, kind)


@partial(jax.jit, static_argnames=("n_max", "P", "V", "EVCAP", "PMAX"))
def solve_scan_topo(inp: KernelInputs, topo: TopoGroupRows, cz0, ch0,
                    n_max: int, P: int, V: int = 0,
                    EVCAP: int = 128, PMAX: int = 8):
    """The topology-aware group scan (existing-node-free: E=0 is enforced
    by the caller's lowerability predicate). Returns (takes[G, N],
    leftover[G], events dict, zfix[N], bail[G], final Carry)."""
    E = 0
    T, D = inp.A.shape
    Z = inp.agz.shape[1]
    C = inp.agc.shape[1]
    N = n_max
    GZ = cz0.shape[0]
    GH = ch0.shape[0]
    RB = 2 * PMAX
    slot_idx = jnp.arange(N)

    carry0 = Carry(
        used=jnp.zeros((N, D), jnp.int64),
        types=jnp.zeros((N, T), bool),
        zones=jnp.zeros((N, Z), bool),
        ct=jnp.zeros((N, C), bool),
        pool=jnp.full((N,), -1, jnp.int32),
        alive=jnp.zeros((N,), bool),
        num_nodes=jnp.int32(0),
        pool_used=inp.pool_used0,
    )
    tcarry0 = (carry0, cz0, ch0, jnp.full((N,), -1, jnp.int32))

    # static per-solve tensors
    avail_tzc = inp.avail_zc.reshape(T, Z, C)
    availz_anyct = avail_tzc.any(axis=2)                      # [T, Z]

    def topo_group(carry, czv, chv, zfixv, xs, gx: TopoGroupRows):
        R, n, F, agz, agc, admit, daemon, _ex = xs

        # ---- group-start eager state (the host computes these lazily;
        # values are identical because nothing mutates between events of
        # other groups) ------------------------------------------------
        zc = ((carry.zones & agz[None, :])[:, :, None]
              & (carry.ct & agc[None, :])[:, None, :]).reshape(N, Z * C)
        off_ok = (zc.astype(jnp.int32)
                  @ inp.avail_zc.T.astype(jnp.int32)) > 0
        pool_clipped = jnp.clip(carry.pool, 0, P - 1)
        adm_open = jnp.where(carry.pool >= 0, admit[pool_clipped], False)
        cand = carry.types & F[None, :] & off_ok & adm_open[:, None]
        hr_nt = _headroom_matrix(inp.A, carry.used, R)
        rem0 = jnp.where(cand, hr_nt, 0).max(axis=1)

        # per-pool open-a-node statics (ops/topo.py:_open_pool_static)
        agz_p = agz[None, :] & inp.pool_agz                   # [P, Z]
        agc_p = agc[None, :] & inp.pool_agc                   # [P, C]
        off_p = (avail_tzc[None] & agz_p[:, None, :, None]
                 & agc_p[:, None, None, :]).any(axis=(2, 3))  # [P, T]
        cand_new = F[None, :] & inp.pool_types & off_p        # [P, T]
        hr_new = jax.vmap(
            lambda d: _headroom_vec(inp.A, d[None, :], R))(daemon)  # [P, T]
        hrc_new = jnp.where(cand_new, hr_new, 0)
        open_ok0 = (admit & agz_p.any(axis=1) & agc_p.any(axis=1)
                    & (hrc_new.max(axis=1) >= 1))             # [P]
        availz_p = (avail_tzc[None] & agc_p[:, None, None, :]
                    ).any(axis=3)                             # [P, T, Z]
        cap_pz = jnp.where(availz_p & cand_new[:, :, None],
                           hr_new[:, :, None], 0).max(axis=1)  # [P, Z]
        cap_any = hrc_new.max(axis=1)                         # [P]
        zcand_pz = ((cand_new & (hr_new >= 1))[:, :, None]
                    & availz_anyct[None]).any(axis=1) & agz_p  # [P, Z]
        hcap_new0 = _host_cap_new(chv, gx.hs_skew, gx.ha_any,
                                  gx.ha_anti, gx.ha_own)
        anti_bulk_grp = (~gx.zs_any.any()) & (~gx.za_any.any()) \
            & (~gx.hs_any.any()) \
            & jnp.where(gx.ha_any, gx.ha_anti & gx.ha_own, True).all() \
            & gx.ha_any.any()
        need_zone = gx.zone_needed

        st0 = _EvState(
            used=carry.used, types=carry.types, zones=carry.zones,
            ct=carry.ct, pool=carry.pool, alive=carry.alive,
            num_nodes=carry.num_nodes, pool_used=carry.pool_used,
            cz=czv, ch=chv, zfix=zfixv,
            take=jnp.zeros(N, jnp.int64), rem=rem0, cand=cand,
            ok=jnp.ones(N, bool), n_rem=n,
            ev_slot=jnp.zeros(EVCAP, jnp.int64),
            ev_zone=jnp.full(EVCAP, -1, jnp.int64),
            ev_len=jnp.zeros(EVCAP, jnp.int64),
            ev_kind=jnp.full(EVCAP, -1, jnp.int64),
            ev_aux=jnp.zeros(EVCAP, jnp.int64),
            ev_n=jnp.int64(0),
            rb=jnp.full((RB, 4), _RB_INVALID, jnp.int64),
            L=jnp.int64(0),
            stuck=jnp.array(False), bail=jnp.array(False),
        )

        def budgets_of(st):
            return jax.vmap(
                lambda lim, us: _pool_budget_jax(lim, us, R)
            )(inp.pool_limit, st.pool_used)                   # [P]

        # ---- the periodic-cycle jump (ops/topo.py:_try_jump) ----------
        def try_jump(st: _EvState):
            halves_eq = []
            for p in range(1, PMAX + 1):
                a = st.rb[RB - 2 * p:RB - p]
                b = st.rb[RB - p:]
                halves_eq.append((st.L >= 2 * p) & (a == b).all())
            eq = jnp.array(halves_eq)
            p_star = jnp.argmax(eq) + 1          # smallest matching p
            found = eq.any()
            # host picks the FIRST matching p then requires all-place
            tail_kind = st.rb[:, 3]
            idx = jnp.arange(RB)
            in_pat = idx >= (RB - p_star)
            all_place = jnp.where(in_pat, tail_kind == K_PLACE, True).all()

            pat_slot = st.rb[:, 0]
            pat_zone = st.rb[:, 1]
            pat_len = jnp.where(in_pat, st.rb[:, 2], 0)
            d_n = pat_len.sum()
            d_take = jnp.zeros(N, jnp.int64).at[
                jnp.clip(pat_slot, 0, N - 1)].add(pat_len)
            zsafe = jnp.clip(pat_zone, 0, Z - 1)
            d_zone = jnp.zeros(Z, jnp.int64).at[zsafe].add(
                jnp.where(pat_zone >= 0, pat_len, 0))
            touched_z = d_zone > 0
            deltas = jnp.where(touched_z, d_zone, -1)
            delta = deltas.max()
            uniform = jnp.where(touched_z, d_zone == delta, True).all() \
                & (delta > 0)
            enforced_z = (gx.zs_skew < BIG).any()
            untouched_elig = (gx.min_mask & ~touched_z).any()
            viable = found & all_place & (d_n > 0) & uniform \
                & ~(enforced_z & gx.min_mask.any() & untouched_elig) \
                & ~jnp.where(gx.ha_any, gx.ha_anti & gx.ha_own,
                             False).any()

            k = st.n_rem // jnp.maximum(d_n, 1)
            # re-admission horizon of untouched zones with usable slots
            elig_any = gx.min_mask.any()
            mn = jnp.where(
                elig_any,
                jnp.where(gx.min_mask[None, :], st.cz, BIG).min(axis=1), 0)
            usable_z = jnp.zeros(Z, bool).at[
                jnp.clip(st.zfix, 0, Z - 1)].max(
                (st.rem > 0) & (st.zfix >= 0))
            horizon = jnp.where(
                (gx.zs_skew < BIG)[:, None]
                & (~touched_z & usable_z)[None, :],
                jnp.clip((st.cz - gx.zs_skew[:, None] - mn[:, None])
                         // jnp.maximum(delta, 1), 0, BIG), BIG)
            k = jnp.minimum(k, horizon.min())
            viable &= jnp.where((gx.zs_skew < BIG), elig_any, True).all()
            # per-slot capacity + hostname-spread bounds
            dt_safe = jnp.maximum(d_take, 1)
            k = jnp.minimum(k, jnp.where(d_take > 0,
                                         st.rem // dt_safe, BIG).min())
            hs_room = jnp.where(
                (gx.hs_skew < BIG)[:, None] & (d_take > 0)[None, :],
                (gx.hs_skew[:, None] - st.ch) // dt_safe[None, :], BIG)
            k = jnp.minimum(k, hs_room.min())
            # pool budgets
            d_pool = jnp.zeros(P + 1, jnp.int64).at[
                jnp.where(st.pool >= 0, st.pool, P)].add(d_take)[:P]
            k = jnp.minimum(k, jnp.where(
                d_pool > 0, budgets_of(st) // jnp.maximum(d_pool, 1),
                BIG).min())
            viable &= k >= 1

            def commit(st: _EvState):
                total_slot = d_take * k
                total_zone = d_zone * k
                st = st._replace(
                    take=st.take + total_slot,
                    rem=st.rem - total_slot,
                    used=st.used + total_slot[:, None] * R[None, :],
                    pool_used=st.pool_used + jnp.where(
                        (d_pool > 0)[:, None], (d_pool * k)[:, None] * R,
                        0),
                    n_rem=st.n_rem - d_n * k,
                    cz=st.cz + jnp.where(gx.zs_any[:, None],
                                         total_zone[None, :], 0)
                    + jnp.where(
                        (jnp.arange(GZ) == gx.member_z)[:, None]
                        & (gx.member_z >= 0),
                        total_zone[None, :], 0),
                    ch=st.ch + jnp.where(gx.hs_any[:, None],
                                         total_slot[None, :], 0)
                    + jnp.where(
                        (jnp.arange(GH) == gx.member_h)[:, None]
                        & (gx.member_h >= 0),
                        total_slot[None, :], 0),
                    # host appends the pattern k (k<3) or 2 more times;
                    # the ring tail is the pattern either way, so only
                    # the event count moves
                    L=st.L + p_star * jnp.minimum(k, 2),
                )
                return _log_event(st, 0, -1, p_star, K_CYC, aux=k,
                                  ring=False)

            return jax.lax.cond(viable, commit, lambda s: s, st), \
                jnp.where(viable, d_n * k, 0)

        # ---- slot selection + placement (ops/topo.py:_place_run) ------
        def place_event(st: _EvState):
            st, jumped = try_jump(st)

            def after_jump(st: _EvState):
                zok = _zone_ok(st.cz, gx.min_mask, gx.zs_skew,
                               gx.za_any, gx.za_anti, gx.za_own)
                # vectorized admissibility (ops/topo.py:_slot_admissible)
                ok = st.rem > 0
                hs_ok = (st.ch < gx.hs_skew[:, None]).all(axis=0)
                occ_here = st.ch > 0
                occ_any = occ_here.any(axis=1)
                ha_ok = jnp.where(
                    gx.ha_anti[:, None], ~occ_here,
                    jnp.where(occ_any[:, None], occ_here,
                              jnp.broadcast_to(gx.ha_own[:, None],
                                               occ_here.shape)))
                ok &= hs_ok & jnp.where(gx.ha_any[:, None], ha_ok,
                                        True).all(axis=0)
                bud = budgets_of(st)
                ok &= jnp.where(st.pool >= 0,
                                bud[jnp.clip(st.pool, 0)] > 0, False)
                enforced_z = (gx.zs_skew < BIG).any()
                needz = enforced_z | gx.za_any.any()
                dec = st.zfix >= 0
                zmask = jnp.where(dec, zok[jnp.clip(st.zfix, 0)], True)
                ok &= jnp.where(needz, zmask, True)

                hcaps = _host_cap_slots(st.ch, gx.hs_skew, gx.ha_any,
                                        gx.ha_anti, gx.ha_own)

                # first-admissible with skip-and-retry for undecided
                # slots whose zone choice fails (pure until commit)
                def sel_cond(c):
                    ok_v, done, *_ = c
                    return (~done) & ok_v.any()

                def sel_body(c):
                    ok_v, done, slot_o, zi_o, run_o, fix_o, keep_o, \
                        remnew_o = c
                    slot = jnp.argmax(ok_v)
                    decided = st.zfix[slot] >= 0
                    zi_d = st.zfix[slot].astype(jnp.int64)
                    hcap = hcaps[slot]
                    budget = bud[jnp.clip(st.pool[slot], 0)]
                    roomz_d = jnp.where(
                        needz & (zi_d >= 0),
                        _zone_run_room(jnp.clip(zi_d, 0), st.cz,
                                       gx.min_mask, gx.zs_skew,
                                       gx.za_any, gx.za_anti, gx.za_own),
                        BIG)
                    run_d = jnp.minimum(
                        jnp.minimum(st.rem[slot], hcap),
                        jnp.minimum(budget,
                                    jnp.minimum(st.n_rem, roomz_d)))
                    # undecided path: choose a zone from the slot's
                    # one-more-pod fit types (ops/topo.py:_choose_slot_zone)
                    new_used = st.used[slot] + R
                    fit1 = (new_used[None, :] <= inp.A).all(axis=1)
                    fit_types = st.cand[slot] & fit1
                    zcand = (availz_anyct & fit_types[:, None]).any(axis=0) \
                        & st.zones[slot] & agz
                    zi_u = _choose_zone(zcand, zok, st.cz, gx.zs_skew)
                    zuc = jnp.clip(zi_u, 0)
                    keep = st.cand[slot] & (
                        avail_tzc[:, zuc, :]
                        & (st.ct[slot] & agc)[None, :]).any(axis=1)
                    hr_slot = _headroom_vec(
                        inp.A, st.used[slot][None, :], R)
                    remnew = jnp.clip(
                        jnp.where(keep, hr_slot, 0).max()
                        - st.take[slot], 0, BIG)
                    roomz_u = _zone_run_room(zuc, st.cz, gx.min_mask,
                                             gx.zs_skew, gx.za_any,
                                             gx.za_anti, gx.za_own)
                    run_u = jnp.minimum(
                        jnp.minimum(remnew, hcap),
                        jnp.minimum(budget,
                                    jnp.minimum(st.n_rem, roomz_u)))
                    use_undecided = (~decided) & needz
                    run = jnp.where(use_undecided, run_u, run_d)
                    zi = jnp.where(use_undecided, zi_u,
                                   jnp.where(decided, zi_d, -1))
                    viable = jnp.where(use_undecided,
                                       (zi_u >= 0) & (run_u >= 1),
                                       run_d >= 1)
                    ok_v = ok_v.at[slot].set(jnp.where(viable,
                                                       ok_v[slot], False))
                    return (ok_v, viable, jnp.where(viable, slot, slot_o),
                            jnp.where(viable, zi, zi_o),
                            jnp.where(viable, run, run_o),
                            jnp.where(viable, use_undecided, fix_o),
                            jnp.where(viable, keep, keep_o),
                            jnp.where(viable, remnew, remnew_o))

                init = (ok, jnp.array(False), jnp.int64(0),
                        jnp.int64(-1), jnp.int64(0), jnp.array(False),
                        jnp.zeros(T, bool), jnp.int64(0))
                _okv, found, slot, zi, run, fix, keep, remnew = \
                    jax.lax.while_loop(sel_cond, sel_body, init)

                def commit_slot(st: _EvState):
                    def apply_fix(st: _EvState):
                        onehot = jnp.arange(Z) == zi
                        return st._replace(
                            zfix=st.zfix.at[slot].set(
                                zi.astype(jnp.int32)),
                            zones=st.zones.at[slot].set(onehot),
                            cand=st.cand.at[slot].set(keep),
                            rem=st.rem.at[slot].set(remnew))
                    st = jax.lax.cond(fix, apply_fix, lambda s: s, st)
                    return _commit(st, gx, R, slot, zi, run,
                                   jnp.where(fix, K_FIX, K_PLACE))

                # ---- open a new node (ops/topo.py:_open_new) ----------
                def open_new(st: _EvState):
                    hcap_new = _host_cap_new(st.ch, gx.hs_skew, gx.ha_any,
                                             gx.ha_anti, gx.ha_own)
                    bud2 = budgets_of(st)
                    free = N - st.num_nodes
                    candz = zcand_pz & zok[None, :]
                    score = _zone_score(st.cz, gx.zs_skew)
                    key = jnp.where(candz, score[None, :] * Z
                                    + jnp.arange(Z)[None, :], BIG)
                    zi_p = jnp.argmin(key, axis=1)               # [P]
                    zvalid = candz.any(axis=1)
                    capz = cap_pz[jnp.arange(P), zi_p]
                    cap = jnp.where(need_zone, capz, cap_any)
                    valid_p = open_ok0 & (bud2 >= 1) & (free > 0) \
                        & (cap >= 1) & (hcap_new >= 1) \
                        & jnp.where(need_zone, zvalid, True)
                    pi = jnp.argmax(valid_p)
                    any_p = valid_p.any()

                    def do_open(st: _EvState):
                        zi = jnp.where(need_zone,
                                       zi_p[pi].astype(jnp.int64), -1)
                        zc_ = jnp.clip(zi, 0)
                        slot = st.num_nodes.astype(jnp.int64)
                        keep = jnp.where(
                            need_zone,
                            cand_new[pi] & availz_p[pi, :, zc_],
                            cand_new[pi])
                        capn = jnp.where(need_zone, cap_pz[pi, zc_],
                                         cap_any[pi])
                        zmask = jnp.where(need_zone,
                                          jnp.arange(Z) == zi, agz_p[pi])
                        roomz = jnp.where(
                            need_zone & ((gx.zs_skew < BIG).any()
                                         | gx.za_any.any()),
                            _zone_run_room(zc_, st.cz, gx.min_mask,
                                           gx.zs_skew, gx.za_any,
                                           gx.za_anti, gx.za_own), BIG)
                        run = jnp.maximum(jnp.minimum(
                            jnp.minimum(capn, hcap_new),
                            jnp.minimum(bud2[pi],
                                        jnp.minimum(st.n_rem, roomz))), 1)
                        st = st._replace(
                            num_nodes=st.num_nodes + 1,
                            alive=st.alive.at[slot].set(True),
                            pool=st.pool.at[slot].set(
                                pi.astype(jnp.int32)),
                            zones=st.zones.at[slot].set(zmask),
                            ct=st.ct.at[slot].set(agc_p[pi]),
                            used=st.used.at[slot].set(daemon[pi]),
                            zfix=st.zfix.at[slot].set(jnp.where(
                                need_zone, zi.astype(jnp.int32), -1)),
                            cand=st.cand.at[slot].set(keep),
                            rem=st.rem.at[slot].set(capn))
                        st = _commit(st, gx, R, slot, zi, run, K_OPEN)

                        # cap-1 anti ladder bulk commit
                        bulk_ok = (run == 1) & (hcap_new == 1) \
                            & (zi < 0) & anti_bulk_grp & (st.n_rem > 0)

                        def do_bulk(st: _EvState):
                            m = jnp.minimum(
                                jnp.minimum(st.n_rem, budgets_of(st)[pi]),
                                (N - st.num_nodes).astype(jnp.int64))
                            s0 = st.num_nodes.astype(jnp.int64)
                            isn = (slot_idx >= s0) & (slot_idx < s0 + m)
                            st = st._replace(
                                num_nodes=st.num_nodes
                                + m.astype(jnp.int32),
                                alive=st.alive | isn,
                                pool=jnp.where(
                                    isn, pi.astype(jnp.int32), st.pool),
                                zones=jnp.where(isn[:, None],
                                                zmask[None, :], st.zones),
                                ct=jnp.where(isn[:, None],
                                             agc_p[pi][None, :], st.ct),
                                used=jnp.where(
                                    isn[:, None],
                                    (daemon[pi] + R)[None, :], st.used),
                                cand=jnp.where(isn[:, None],
                                               keep[None, :], st.cand),
                                rem=jnp.where(isn, 0, st.rem),
                                take=jnp.where(isn, 1, st.take),
                                pool_used=st.pool_used.at[pi].add(m * R),
                                n_rem=st.n_rem - m,
                                ch=st.ch + jnp.where(
                                    ((jnp.arange(GH) == gx.member_h)
                                     & (gx.member_h >= 0))[:, None]
                                    & isn[None, :], 1, 0),
                                # distinct fresh slots can never form a
                                # periodic pattern: poison the ring
                                rb=jnp.full((RB, 4), _RB_INVALID,
                                            jnp.int64),
                                L=st.L + m)
                            return _log_event(st, s0, -1, m, K_ANTIRUN,
                                              ring=False)

                        return jax.lax.cond(bulk_ok, do_bulk,
                                            lambda s: s, st)

                    return jax.lax.cond(
                        any_p, do_open,
                        lambda s: s._replace(stuck=True), st)

                return jax.lax.cond(found, commit_slot, open_new, st)

            return jax.lax.cond(jumped > 0, lambda s: s, after_jump, st)

        def ev_cond(st: _EvState):
            return (st.n_rem > 0) & ~st.stuck & ~st.bail

        st = jax.lax.while_loop(ev_cond, place_event, st0)

        # group-end narrowing (ops/topo.py:_commit_narrowing)
        touched = (st.take > 0) & (st.pool >= 0)
        fit = (st.used[:, None, :] <= inp.A[None, :, :]).all(axis=2)
        types = jnp.where(touched[:, None], st.cand & fit, st.types)
        zones = jnp.where((touched & (st.zfix < 0))[:, None],
                          st.zones & agz[None, :], st.zones)
        ct = jnp.where(touched[:, None], st.ct & agc[None, :], st.ct)
        new_carry = Carry(used=st.used, types=types, zones=zones, ct=ct,
                          pool=st.pool, alive=st.alive,
                          num_nodes=st.num_nodes, pool_used=st.pool_used)
        ys = (st.take, st.n_rem, st.ev_slot, st.ev_zone, st.ev_len,
              st.ev_kind, st.ev_aux, jnp.minimum(st.ev_n, EVCAP), st.bail)
        return (new_carry, st.cz, st.ch, st.zfix), ys

    def plain_group(carry, czv, chv, zfixv, xs, gx: TopoGroupRows):
        new_carry, (take, leftover) = plain_group_step(
            inp, carry, xs, axis=None, P=P, E=E, N=N, V=V,
            slot_idx=slot_idx)
        # membership recording (ops/topo.py:record_plain_fill)
        mz, mh = gx.member_z, gx.member_h
        chv = chv.at[jnp.clip(mh, 0)].add(
            jnp.where(mh >= 0, take, 0))
        zi = jnp.clip(zfixv, 0, Z - 1)
        dz = jnp.zeros((Z,), jnp.int64).at[zi].add(
            jnp.where((zfixv >= 0) & (take > 0), take, 0))
        czv = czv.at[jnp.clip(mz, 0)].add(jnp.where(mz >= 0, dz, 0))
        ys = (take, leftover,
              jnp.zeros(EVCAP, jnp.int64), jnp.full(EVCAP, -1, jnp.int64),
              jnp.zeros(EVCAP, jnp.int64), jnp.full(EVCAP, -1, jnp.int64),
              jnp.zeros(EVCAP, jnp.int64), jnp.int64(0),
              jnp.array(False))
        return (new_carry, czv, chv, zfixv), ys

    def step(tc, xs_all):
        carry, czv, chv, zfixv = tc
        xs = xs_all[:8]
        gx = TopoGroupRows(*xs_all[8:])
        return jax.lax.cond(
            gx.has_topo,
            lambda args: topo_group(*args),
            lambda args: plain_group(*args),
            (carry, czv, chv, zfixv, xs, gx))

    xs_all = (inp.R, inp.n, inp.F, inp.agz, inp.agc, inp.admit,
              inp.daemon, inp.ex_compat)
    topo_fields = (topo.has_topo, topo.zone_needed, topo.min_mask,
                   topo.zs_any, topo.zs_skew, topo.hs_any, topo.hs_skew,
                   topo.za_any, topo.za_anti, topo.za_own,
                   topo.ha_any, topo.ha_anti, topo.ha_own,
                   topo.member_z, topo.member_h)
    xs_all = xs_all + topo_fields
    (final, cz, ch, zfix), ys = jax.lax.scan(step, tcarry0, xs_all)
    takes, leftover, ev_slot, ev_zone, ev_len, ev_kind, ev_aux, ev_n, \
        bail = ys
    events = dict(slot=ev_slot, zone=ev_zone, len=ev_len, kind=ev_kind,
                  aux=ev_aux, n=ev_n)
    return takes, leftover, events, zfix, bail, final


def dispatch_topo(arrays: dict, rows: dict, statics: dict,
                  cache: "dict | None" = None) -> dict:
    """The one topology-kernel dispatch shared by the local solver
    (TPUSolver._dispatch_topo) and the sidecar server's SolveTopo RPC —
    dict in, dict out, so the two paths can never drift (same
    discipline as parallel/mesh.dispatch_mesh).

    ``arrays``: KernelInputs fields (bool masks may arrive as uint8 off
    the wire); ``rows``: TopoGroupRows fields; ``statics``: Z/P/GZ/GH/
    n_max/EVCAP/PMAX. ``cache`` reuses the device-placed inputs — across
    n_max escalations within one solve (a retry pays only the kernel,
    not a re-upload), and, when the caller keeps the dict resident
    (TPUSolver._topo_cache), across ticks. The ``inp`` and ``rows``
    entries are independent: the solver patches ``inp`` fields in place
    on rows-tier deltas and evicts only ``rows`` when the tenc-derived
    block may have moved. Output values may be jax arrays — callers
    np.asarray exactly what they consume (bail/leftover checks on retry
    iterations must not force the full event-log transfer)."""
    import numpy as np

    def conv(v):
        a = np.asarray(v)
        if a.dtype == np.uint8:  # wire bools
            a = a.view(bool)
        return jnp.asarray(a)

    if cache is not None and "inp" in cache:
        inp = cache["inp"]
    else:
        inp = KernelInputs(**{k: conv(v) for k, v in arrays.items()})
        if cache is not None:
            cache["inp"] = inp
    if cache is not None and "rows" in cache:
        trows = cache["rows"]
    else:
        trows = TopoGroupRows(**{k: conv(v) for k, v in rows.items()})
        if cache is not None:
            cache["rows"] = trows
    cz0 = jnp.zeros((statics["GZ"], statics["Z"]), jnp.int64)
    ch0 = jnp.zeros((statics["GH"], statics["n_max"]), jnp.int64)
    takes, leftover, events, zfix, bail, carry = solve_scan_topo(
        inp, trows, cz0, ch0, n_max=statics["n_max"], P=statics["P"],
        EVCAP=statics["EVCAP"], PMAX=statics["PMAX"])
    out = dict(takes=takes, leftover=leftover, zfix=zfix, bail=bail,
               used=carry.used, types=carry.types, zones=carry.zones,
               ct=carry.ct, pool=carry.pool, alive=carry.alive,
               num_nodes=jnp.reshape(carry.num_nodes, (1,)))
    for k, v in events.items():
        out[f"ev_{k}"] = v
    return out
