"""Topology-aware FFD: exact oracle semantics for topology-spread and pod
(anti-)affinity on the tensor path.

The CPU oracle (solver/cpu.py) enforces, per placement:

- zone topology-spread: ``count(group, zone) + 1 - min_eligible <= maxSkew``
  with min over the pod's *own* zone-requirement-filtered zone universe
  (``_eligible_domains``), and min-count/lexicographic zone choice for
  nodes whose zone is still undecided (``_choose_zone``);
- hostname topology-spread: a fresh node is always a hypothetical domain,
  so ``min_count == 0`` and the constraint degrades to a per-node cap of
  ``maxSkew`` pods per counter group;
- pod (anti-)affinity over zone/hostname occupancy sets (required terms
  only), with the self-affinity seeding rule (an unoccupied required
  affinity to the pod's own scheduling group admits anywhere);
- membership recording for pods with a ``scheduling_group`` (zone domain
  recorded only when the node's zone is *fixed* — an existing node's label
  or a domain decided by ``_choose_zone`` — mirroring ``node.domains``).

This module lowers those semantics onto the slot/tensor state of
:mod:`ops.ffd`: counters become dense arrays (``cz[GZ, Z]`` zone counts per
counter group, ``ch[GH, N]`` per-slot counts per counter group), and the
per-pod loop is an exact *pour* over slots in oracle order (existing by
name, then open by creation, then new nodes pool-by-pool).

Unsupported shapes (spread/affinity over keys other than zone/hostname,
zone-id requirements mixed with topology) are detected at build time —
``TopoEncoding.supported`` is False and the solver falls back to the CPU
oracle for the snapshot.

Reference behavior being mirrored: upstream core's topology handling as
consumed by the provider (SURVEY §3.2); the reference's scheduling universe
of well-known topology labels is pkg/apis/v1/labels.go:31-54.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from ..apis import labels as L
from ..models.encoding import SnapshotEncoding
from . import ffd

BIG = np.int64(1) << 60


# ---------------------------------------------------------------------------
# encoding
# ---------------------------------------------------------------------------

@dataclass
class TopoEncoding:
    """Topology constraint structure per pod group, with counter groups
    interned to dense indices (zone counters and hostname counters are
    separate index spaces)."""
    GZ: int
    GH: int
    #: per pod-group constraint lists, aligned with enc.groups
    zspread: List[List[Tuple[int, int, bool]]]   # (gz, skew, enforce)
    hspread: List[List[Tuple[int, int, bool]]]   # (gh, skew, enforce)
    zaff: List[List[Tuple[int, bool, bool]]]     # (gz, anti, own)
    haff: List[List[Tuple[int, bool, bool]]]     # (gh, anti, own)
    member_z: List[int]                          # gz or -1
    member_h: List[int]                          # gh or -1
    zone_needed: List[bool]
    has_topo: List[bool]                         # any constraint (not just sg)
    #: [G, Z] eligible-zone mask for min-count (own ZONE requirement over
    #: the oracle's zone universe)
    min_mask: Optional[np.ndarray]
    #: [E] zone index of each existing slot (-1 = no zone label)
    ex_zone: np.ndarray
    supported: bool = True
    reason: str = ""
    #: counter-group name -> index tables (for state seeding from existing
    #: nodes' pod_groups)
    gz_names: Dict[str, int] = field(default_factory=dict)
    gh_names: Dict[str, int] = field(default_factory=dict)


def _intern(table: Dict[str, int], name: str) -> int:
    i = table.get(name)
    if i is None:
        i = table[name] = len(table)
    return i


def build_topo_encoding(enc: SnapshotEncoding, snapshot,
                        existing: Sequence) -> TopoEncoding:
    """Compile per-group topology constraints to dense counter indices.

    ``existing`` must be the name-sorted ExistingNode list the solver uses
    for slots [0, E) — counter seeding is positional."""
    G = len(enc.groups)
    Z = len(enc.zones)
    zpos = {z: i for i, z in enumerate(enc.zones)}
    gz_of: Dict[str, int] = {}
    gh_of: Dict[str, int] = {}

    zspread: List[List[Tuple[int, int, bool]]] = [[] for _ in range(G)]
    hspread: List[List[Tuple[int, int, bool]]] = [[] for _ in range(G)]
    zaff: List[List[Tuple[int, bool, bool]]] = [[] for _ in range(G)]
    haff: List[List[Tuple[int, bool, bool]]] = [[] for _ in range(G)]
    member_z = [-1] * G
    member_h = [-1] * G
    zone_needed = [False] * G
    has_topo = [False] * G
    supported, reason = True, ""

    # the oracle's zone universe: snapshot.zones if non-empty else offering
    # zones (solver/cpu.py::solve) — both are subsets of enc.zones
    if snapshot.zones:
        universe = np.array([z in dict(snapshot.zones) for z in enc.zones], dtype=bool)
    else:
        universe = np.ones(Z, dtype=bool)
    min_mask = np.zeros((G, Z), dtype=bool)

    for g in enc.groups:
        pod = g.pods[0]
        sg = pod.scheduling_group
        constrained = bool(pod.topology_spread) or any(
            a.required for a in pod.pod_affinity)
        has_topo[g.index] = constrained
        if not (constrained or sg):
            continue
        # eligible zones for min-count: the pod's OWN zone requirement
        # (not merged with pool/node), over the oracle universe
        zr = pod.scheduling_requirements().get(L.ZONE)
        min_mask[g.index] = universe & np.array(
            [zr is None or zr.has(z) for z in enc.zones], dtype=bool)
        if pod.scheduling_requirements().get(L.ZONE_ID) is not None \
                and constrained:
            supported, reason = False, "zone-id requirement with topology"
        for c in pod.topology_spread:
            grp = c.group or sg
            if not grp:
                continue  # unreadable counters: oracle no-op for skew>=1
            enforce = c.when_unsatisfiable == "DoNotSchedule"
            if c.topology_key == L.ZONE:
                zspread[g.index].append((_intern(gz_of, grp), c.max_skew,
                                         enforce))
                zone_needed[g.index] = True
            elif c.topology_key == L.HOSTNAME:
                hspread[g.index].append((_intern(gh_of, grp), c.max_skew,
                                         enforce))
            else:
                supported, reason = False, \
                    f"spread key {c.topology_key} unsupported"
        for a in pod.pod_affinity:
            if not a.required:
                continue
            own = a.group == sg
            if a.topology_key == L.ZONE:
                zaff[g.index].append((_intern(gz_of, a.group), a.anti, own))
                zone_needed[g.index] = True
            elif a.topology_key == L.HOSTNAME:
                haff[g.index].append((_intern(gh_of, a.group), a.anti, own))
            else:
                supported, reason = False, \
                    f"affinity key {a.topology_key} unsupported"
        if sg:
            member_z[g.index] = _intern(gz_of, sg)
            member_h[g.index] = _intern(gh_of, sg)

    ex_zone = np.full(len(existing), -1, dtype=np.int32)
    for ei, node in enumerate(existing):
        zi = zpos.get(node.labels.get(L.ZONE, ""))
        if zi is not None:
            ex_zone[ei] = zi

    return TopoEncoding(
        GZ=len(gz_of), GH=len(gh_of),
        zspread=zspread, hspread=hspread, zaff=zaff, haff=haff,
        member_z=member_z, member_h=member_h,
        zone_needed=zone_needed, has_topo=has_topo,
        min_mask=min_mask, ex_zone=ex_zone,
        supported=supported, reason=reason,
        gz_names=gz_of, gh_names=gh_of,
    )


@dataclass
class TopoState:
    """Dense counter state; mutated by the pour."""
    cz: np.ndarray     # [GZ, Z] int64 zone counts per counter group
    ch: np.ndarray     # [GH, N] int64 per-slot counts per counter group
    zfix: np.ndarray   # [N] int32 fixed zone per slot (-1 undecided)

    @staticmethod
    def create(tenc: TopoEncoding, Z: int, N: int, E: int,
               existing: Sequence) -> "TopoState":
        ts = TopoState(
            cz=np.zeros((tenc.GZ, Z), dtype=np.int64),
            ch=np.zeros((tenc.GH, N), dtype=np.int64),
            zfix=np.full(N, -1, dtype=np.int32),
        )
        ts.zfix[:E] = tenc.ex_zone
        # seed counters from pods already on existing nodes — the oracle
        # records (group, ZONE, label) and (group, HOSTNAME, name) per
        # pod_groups entry (solver/cpu.py::solve)
        for ei, node in enumerate(existing):
            for grp in node.pod_groups:
                zi = ts.zfix[ei]
                gzi = tenc.gz_names.get(grp)
                if gzi is not None and zi >= 0:
                    ts.cz[gzi, zi] += 1
                ghi = tenc.gh_names.get(grp)
                if ghi is not None:
                    ts.ch[ghi, ei] += 1
        return ts


# ---------------------------------------------------------------------------
# the pour (host engine)
# ---------------------------------------------------------------------------

class _Pour:
    """Per-group pour: places the group's pods one decision at a time in
    exact oracle order, with closed-form *runs* batching consecutive
    identical placements."""

    def __init__(self, st: ffd.NodeState, enc: SnapshotEncoding,
                 tenc: TopoEncoding, ts: TopoState, g: int):
        self.st, self.enc, self.tenc, self.ts, self.g = st, enc, tenc, ts, g
        self.R = enc.R[g]
        self.agz = enc.agz[g]
        self.agc = enc.agc[g]
        self.zsp = tenc.zspread[g]
        self.hsp = tenc.hspread[g]
        self.zaf = tenc.zaff[g]
        self.haf = tenc.haff[g]
        self.member_z = tenc.member_z[g]
        self.member_h = tenc.member_h[g]
        self.zone_needed = tenc.zone_needed[g]
        self.min_mask = tenc.min_mask[g]
        #: zones with any available offering per type (_choose_zone scans
        #: zones of available offerings regardless of capacity type);
        #: computed once per encoding, not once per pour
        self.avail_anyct = getattr(enc, "_avail_anyct", None)
        if self.avail_anyct is None:
            self.avail_anyct = enc.avail.any(axis=2)           # [T, Z]
            enc._avail_anyct = self.avail_anyct

        # Slot admission is eager (cheap); candidate types and headroom per
        # slot are LAZY — first-fit only ever inspects a handful of slots
        # per event, and an eager [N, T] pass per group dominated pour time
        adm = ffd.admission(st, enc, g)
        #: cross-group full-slot mask for identical request vectors: a
        #: slot proven at zero headroom for this R stays full (usage only
        #: grows), so later same-R groups skip the exact recompute
        self._full_shared = st.full_for.get(self.R.tobytes())
        if self._full_shared is None:
            self._full_shared = st.full_for[self.R.tobytes()] = \
                np.zeros(st.N, dtype=bool)
        adm = adm & ~self._full_shared
        self.adm = adm
        self.cand = np.zeros((st.N, enc.A.shape[0]), dtype=bool)
        self._slot_ready = np.zeros(st.N, dtype=bool)
        #: BIG = "not yet evaluated" sentinel (admissibility treats it >0)
        self.rem = np.where(adm, BIG, 0).astype(np.int64)
        self.take = np.zeros(st.N, dtype=np.int64)
        self.touched: Set[int] = set()
        #: placement order: (slot, count) runs — pods of the group are
        #: assigned to slots in THIS order (the oracle stripes pods across
        #: zones, so slot-order chunking would mis-assign identities).
        #: A committed periodic jump is compressed to one
        #: ("cyc", pattern, k) entry = `pattern` repeated k times (decode
        #: expands it with strided slices instead of k*len(pattern) runs).
        self.runs: List[Tuple] = []
        self._enforced_z = any(e for _, _, e in self.zsp)
        #: per-pool static open-a-node arrays (see _open_new)
        self._open_cache: Dict[int, object] = {}
        #: (zones-mask, ct-mask) -> [T] any-available-offering mask; slots
        #: opened by the same pool share few distinct patterns, so the
        #: [T, Z, C] reduction in _ensure_slot runs once per pattern
        self._off_cache: Dict[bytes, np.ndarray] = {}
        #: headroom fast path: R's nonzero dims and A restricted to them,
        #: computed once per group (ffd._headroom re-slices per call)
        self._sel = self.R > 0
        self._Rsel = self.R[self._sel]
        self._Asel = enc.A[:, self._sel] if self._sel.any() else None
        #: (slot, zone, len, kind) event log for periodic-cycle detection
        self.event_log: List[Tuple[int, Optional[int], int, str]] = []
        #: generation replay (see _maybe_replay): tracks the nodes opened
        #: since the last time every generation slot filled. Disabled for
        #: affinity groups (the anti ladder has its own fast path and
        #: occupancy semantics the replay proof doesn't cover).
        self._gen_track = not self.zaf and not self.haf
        self._gen_slots: List[int] = []
        self._gen_opens: List[Tuple[int, Optional[int]]] = []
        self._gen_runs_start = 0
        self._gen_ztot: Dict[int, int] = {}
        #: validated previous generation: (opens, normalized runs, slots)
        self._gen_template: Optional[Tuple] = None
        #: slot-index vector reused by _slot_admissible (two fresh aranges
        #: per event added up at 50k-pod scale)
        self._idx = np.arange(st.N)

    def _hr_new(self, used: np.ndarray) -> np.ndarray:
        """[T] headroom of a slot with per-dim usage `used` (== ffd._headroom
        for the A=[T,D] case, minus the per-call slicing of A)."""
        if self._Asel is None:
            return np.full(self.enc.A.shape[0], BIG, dtype=np.int64)
        q = (self._Asel - used[self._sel]) // self._Rsel
        return np.clip(q.min(axis=1), 0, BIG)

    def _mv_cap(self, pi: int, cand: np.ndarray, hr: np.ndarray) -> int:
        """minValues floor cap for taking pods on a pool-`pi` node whose
        candidate types are `cand` with per-type headroom `hr` — the pour's
        analog of the closed form's min_values_cap application
        (ffd.fill_group_closed_form; core nodeclaim.Add SatisfiesMinValues).
        Existing nodes (pi < 0) are exempt, as in the oracle."""
        if pi < 0 or self.enc.mv_floor is None \
                or not self.enc.mv_floor[pi].any():
            return int(BIG)
        return int(ffd.min_values_cap(self.enc, pi, cand, hr))

    def _ensure_slot(self, slot: int) -> None:
        """Materialize candidate types + headroom for one slot."""
        if self._slot_ready[slot]:
            return
        self._slot_ready[slot] = True
        st, enc, g = self.st, self.enc, self.g
        if not self.adm[slot]:
            self.rem[slot] = 0
            return
        if slot < st.E:
            hr = int(ffd._headroom(st.ex_alloc[slot], st.used[slot], self.R))
            self.rem[slot] = max(hr - int(self.take[slot]), 0)
            if hr <= 0:
                # group-independent resource fullness: transfers to every
                # later same-R group (usage only grows within a solve)
                self._full_shared[slot] = True
            return
        cand = st.types[slot] & enc.F[g]
        zmask = st.zones[slot] & self.agz
        cmask = st.ct[slot] & self.agc
        ck = zmask.tobytes() + cmask.tobytes()
        off = self._off_cache.get(ck)
        if off is None:
            off = self._off_cache[ck] = (
                enc.avail & zmask[None, :, None]
                & cmask[None, None, :]).any(axis=(1, 2))
        cand &= off
        self.cand[slot] = cand
        if not cand.any():
            self.rem[slot] = 0
            return
        hr = self._hr_new(st.used[slot])
        hrc = np.where(cand, hr, 0)
        rem = max(int(hrc.max()) - int(self.take[slot]), 0)
        if rem > 0:
            rem = min(rem, self._mv_cap(int(st.pool[slot]), cand, hrc))
        elif self.take[slot] == 0 \
                and int(np.where(st.types[slot], hr, 0).max()) <= 0:
            # zero headroom over the slot's OWN type set (not the
            # group-masked subset): group-independent, safe to share
            self._full_shared[slot] = True
        self.rem[slot] = rem

    # -- dynamic topology predicates ------------------------------------
    def _zone_ok(self) -> np.ndarray:
        """[Z] zones admissible under enforced zone spread + zone affinity."""
        ts, enc = self.ts, self.enc
        ok = np.ones(len(enc.zones), dtype=bool)
        for gz, s, enforce in self.zsp:
            if not enforce:
                continue
            elig = self.min_mask
            mn = int(ts.cz[gz][elig].min()) if elig.any() else 0
            ok &= (ts.cz[gz] + 1 - mn) <= s
        for gz, anti, own in self.zaf:
            occ = ts.cz[gz] > 0
            if anti:
                ok &= ~occ
            else:
                if occ.any():
                    ok &= occ
                elif not own:
                    ok &= False
        return ok

    def _host_cap(self, slot: int) -> int:
        """Max further pods this pod group may put on `slot` under hostname
        spread (min_count==0 rule) and hostname affinity."""
        ts = self.ts
        cap = int(BIG)
        for gh, s, enforce in self.hsp:
            if enforce:
                cap = min(cap, s - int(ts.ch[gh, slot]))
        for gh, anti, own in self.haf:
            occ_here = ts.ch[gh, slot] > 0
            if anti:
                if occ_here:
                    return 0
                if own:
                    cap = min(cap, 1)  # own placement occupies the domain
            else:
                occ_any = (ts.ch[gh] > 0).any()
                if occ_any:
                    if not occ_here:
                        return 0
                elif not own:
                    return 0
        return max(cap, 0)

    def _host_cap_new(self) -> int:
        """Cap for a brand-new node (fresh hostname domain)."""
        cap = int(BIG)
        for gh, s, enforce in self.hsp:
            if enforce:
                cap = min(cap, s)
        for gh, anti, own in self.haf:
            if not anti:
                occ_any = (self.ts.ch[gh] > 0).any()
                if occ_any or not own:
                    return 0  # required affinity to an occupied/foreign set
            elif own:
                cap = min(cap, 1)
        return max(cap, 0)

    # -- zone choice (oracle _choose_zone) ------------------------------
    def _choose_zone(self, zcand: np.ndarray) -> Optional[int]:
        """Min-score (sum of enforced spread counts), lexicographic
        tie-break, among candidate zones that pass skew + affinity."""
        ts = self.ts
        zok = self._zone_ok()
        best = None
        best_key = None
        for zi in np.nonzero(zcand)[0]:
            if not zok[zi]:
                continue
            score = 0
            for gz, s, enforce in self.zsp:
                if enforce:
                    score += int(ts.cz[gz, zi])
            key = (score, self.enc.zones[zi])
            if best_key is None or key < best_key:
                best, best_key = int(zi), key
        return best

    # -- records (oracle _topology_ok_fixed tail + _record_membership) --
    def _record(self, slot: int, zi: Optional[int], count: int) -> None:
        if self._gen_track and zi is not None:
            self._gen_ztot[zi] = self._gen_ztot.get(zi, 0) + count
        ts = self.ts
        seen_z: Set[int] = set()
        seen_h: Set[int] = set()
        for gz, s, enforce in self.zsp:
            if zi is not None:
                ts.cz[gz, zi] += count
                seen_z.add(gz)
        for gh, s, enforce in self.hsp:
            ts.ch[gh, slot] += count
            seen_h.add(gh)
        if self.member_z >= 0 and self.member_z not in seen_z \
                and zi is not None:
            ts.cz[self.member_z, zi] += count
        if self.member_h >= 0 and self.member_h not in seen_h:
            ts.ch[self.member_h, slot] += count

    # -- slot zone status -----------------------------------------------
    def _slot_zone(self, slot: int) -> Tuple[Optional[int], bool]:
        """(zone index or None, decided). Existing slots use their label;
        open slots use zfix; undecided open slots return (None, False)."""
        if slot < self.st.E:
            zi = int(self.ts.zfix[slot])
            return (zi if zi >= 0 else None), True
        zi = int(self.ts.zfix[slot])
        if zi >= 0:
            return zi, True
        return None, False

    # -- run length under zone dynamics ---------------------------------
    def _zone_run_room(self, zi: int) -> int:
        """How many pods may pour consecutively into zone `zi` before an
        enforced-skew or occupancy-driven admissibility flip could change
        any slot's eligibility. Always >= 1 when the zone is admissible."""
        ts = self.ts
        room = int(BIG)
        for gz, s, enforce in self.zsp:
            if not enforce:
                continue
            elig = self.min_mask
            mn = int(ts.cz[gz][elig].min()) if elig.any() else 0
            c = int(ts.cz[gz, zi])
            if elig.any() and c == mn:
                # pouring may raise the global min -> earlier slots flip
                room = min(room, 1)
            else:
                room = min(room, mn + s - c)
        for gz, anti, own in self.zaf:
            if anti:
                room = min(room, 1)  # occupancy flips after one placement
            elif own and not (ts.cz[gz] > 0).any():
                room = min(room, 1)  # seeding flips occupancy
        # recording flips occupancy of the membership counter too, which
        # other constraints of THIS group never read twice wrongly (reads
        # happen per event), but conservative is fine:
        return max(room, 1)

    # -- the pour -------------------------------------------------------
    def run(self) -> Tuple[np.ndarray, int, List[Tuple[int, int]]]:
        st, enc, g = self.st, self.enc, self.g
        n_rem = int(enc.n[g])
        guard = 0
        max_events = n_rem * 4 + st.N + 16
        while n_rem > 0:
            guard += 1
            if guard > max_events:  # pragma: no cover - safety net
                break
            placed = self._place_run(n_rem)
            if placed == 0:
                break
            n_rem -= placed
        self._commit_narrowing()
        return self.take, n_rem, self.runs

    # -- periodic-cycle jump --------------------------------------------
    # The steady state of a spread pour is a staggered ladder: the event
    # sequence (slot, zone, run-length) becomes periodic (e.g. one pod per
    # zone's first slot, in slot order, per min-increment). Rather than
    # predict the cycle shape (it depends on slot arrangement and skew),
    # detect it: when the last 2p events form two identical halves of pure
    # placements AND the per-period counter deltas are uniform across every
    # eligible zone (so all (count - min) staggers are exactly restored),
    # the next k periods are provably identical — commit them in one shot,
    # bounded by slot headroom, hostname caps, pool budgets, pod count,
    # and the re-admission horizon of untouched zones.
    _MAX_PERIOD = 64

    def _try_jump(self, n_rem: int) -> int:
        log = self.event_log
        L_ = len(log)
        period = 0
        for p in range(1, min(self._MAX_PERIOD, L_ // 2) + 1):
            if log[L_ - 2 * p:L_ - p] == log[L_ - p:]:
                period = p
                break
        if not period:
            return 0
        ev = log[L_ - period:]
        if any(kind != "place" for _, _, _, kind in ev):
            return 0
        # per-period aggregates
        d_take: Dict[int, int] = {}
        d_zone: Dict[int, int] = {}
        d_n = 0
        for slot, zi, ln, _ in ev:
            d_take[slot] = d_take.get(slot, 0) + ln
            if zi is not None:
                d_zone[zi] = d_zone.get(zi, 0) + ln
            d_n += ln
        if d_n == 0:
            return 0
        ts, st, enc = self.ts, self.st, self.enc
        # uniform zone delta over the eligible universe (staggers periodic)
        deltas = set(d_zone.values())
        if len(deltas) != 1:
            return 0
        delta = deltas.pop()
        touched_z = set(d_zone)
        k = n_rem // d_n
        for zi in range(st.Z):
            if self.min_mask.any() and self.min_mask[zi] \
                    and zi not in touched_z:
                # an untouched eligible zone: its count must not pin the
                # min (delta>0 requires every eligible zone to advance)
                if self._enforced_z:
                    return 0
        if k < 1:
            return 0
        # re-admission horizon of untouched zones with usable slots: their
        # (count - min) shrinks by delta per period
        for gz, s, enforce in self.zsp:
            if not enforce:
                continue
            elig = self.min_mask
            if not elig.any():
                return 0
            mn = int(ts.cz[gz][elig].min())
            for zi in range(st.Z):
                if zi in touched_z:
                    continue
                c = int(ts.cz[gz, zi])
                has_usable = bool(((self.rem > 0)
                                   & (ts.zfix == zi)).any())
                if has_usable:
                    k = min(k, max(0, (c - s - mn) // delta))
        # occupancy-driven masks stay stable only for already-occupied
        # zones/slots; the repeated period proves transitions are done for
        # touched entries, but a zero-count untouched reader could flip —
        # zaff/haff read counts>0 which never DECREASE, so untouched masks
        # are static. Safe.
        # slot-capacity bounds
        for slot, dt in d_take.items():
            k = min(k, int(self.rem[slot]) // dt)
            for gh, s, enforce in self.hsp:
                if enforce:
                    room = s - int(ts.ch[gh, slot])
                    k = min(k, room // dt)
            for gh, anti, own in self.haf:
                if anti and own:
                    return 0  # cap-1 slots cannot repeat in a period anyway
        if enc.pools:
            d_pool: Dict[int, int] = {}
            for slot, dt in d_take.items():
                pi = int(st.pool[slot])
                if pi >= 0:
                    d_pool[pi] = d_pool.get(pi, 0) + dt
            for pi, dp in d_pool.items():
                budget = ffd._pool_budget(enc, st.pool_used, pi, self.R)
                k = min(k, int(budget) // dp)
        if k < 1:
            return 0
        # ---- commit k whole periods -----------------------------------
        pattern = [(slot, ln) for slot, _, ln, _ in ev]
        self.runs.append(("cyc", pattern, k))
        for slot, zi, ln, _ in ev:
            total = ln * k
            self.take[slot] += total
            self.rem[slot] -= total
            st.used[slot] += total * self.R
            pi = int(st.pool[slot])
            if pi >= 0:
                st.pool_used[pi] += total * self.R
            self.touched.add(slot)
            self._record(slot, zi, total)
        self.event_log.extend(ev * (k if k < 3 else 2))  # keep periodicity
        return d_n * k

    def _slot_admissible(self, zok: np.ndarray) -> np.ndarray:
        """[n_act] bool — vectorized slot admissibility (rem, hostname
        caps, pool budget, zone admissibility for decided slots; undecided
        open slots pass here and get their zone chosen on selection)."""
        st = self.st
        n_act = st.E + st.num_nodes
        ts = self.ts
        ok = self.rem[:n_act] > 0
        # hostname caps
        for gh, s, enforce in self.hsp:
            if enforce:
                ok &= ts.ch[gh, :n_act] < s
        for gh, anti, own in self.haf:
            occ_here = ts.ch[gh, :n_act] > 0
            if anti:
                ok &= ~occ_here
            else:
                if (ts.ch[gh] > 0).any():
                    ok &= occ_here
                elif not own:
                    ok &= False
        # pool budgets (>= 1 pod)
        if self.enc.pools:
            budgets = np.array(
                [ffd._pool_budget(self.enc, st.pool_used, pi, self.R)
                 for pi in range(len(self.enc.pools))], dtype=np.int64)
            open_sel = st.pool[:n_act] >= 0
            ok[open_sel] &= budgets[st.pool[:n_act][open_sel]] > 0
        # zone admissibility
        zfix = ts.zfix[:n_act]
        dec = zfix >= 0
        enforced_z = self._enforced_z
        need_zone = enforced_z or bool(self.zaf)
        if need_zone:
            zmask = np.zeros(n_act, dtype=bool)
            zmask[dec] = zok[zfix[dec]]
            # zone-label-less existing slots: enforced spread rejects;
            # affinity evaluates the empty domain (anti passes, positive
            # fails when occupied elsewhere or foreign)
            nolab = ~dec & (self._idx[:n_act] < st.E)
            if nolab.any() and not enforced_z:
                empty_ok = True
                for gz, anti, own in self.zaf:
                    occ_any = (self.ts.cz[gz] > 0).any()
                    if not anti and (occ_any or not own):
                        empty_ok = False
                zmask[nolab] = empty_ok
            und = ~dec & (self._idx[:n_act] >= st.E)
            zmask[und] = True  # zone chosen on selection; may still fail
            ok &= zmask
        return ok

    def _place_run(self, n_rem: int) -> int:
        """Place one run (>=1 pods on one target); 0 = unschedulable."""
        st, enc = self.st, self.enc
        placed = self._try_jump(n_rem)
        if placed:
            return placed
        zok = self._zone_ok()
        # one admissibility scan per event; disqualified slots are cleared
        # in place (nothing else about the state changes on a skip)
        ok = self._slot_admissible(zok)
        while True:
            idx = np.nonzero(ok)[0]
            if len(idx) == 0:
                break
            slot = int(idx[0])
            self._ensure_slot(slot)
            if self.rem[slot] <= 0:
                ok[slot] = False
                continue  # lazy evaluation found no real headroom
            pi = int(st.pool[slot])
            budget = ffd._pool_budget(enc, st.pool_used, pi, self.R) \
                if pi >= 0 else int(BIG)
            hcap = self._host_cap(slot)
            zi, decided = self._slot_zone(slot)
            enforced_z = self._enforced_z
            need_zone = enforced_z or bool(self.zaf)
            if decided:
                room_z = self._zone_run_room(zi) \
                    if (need_zone and zi is not None) else int(BIG)
                run = min(self.rem[slot], hcap, budget, n_rem, room_z)
                if run < 1:
                    ok[slot] = False
                    continue
                self._commit(slot, zi, int(run))
                return int(run)
            # undecided open slot — the zone decision must only stick if a
            # pod actually lands (the oracle discards the plan, and the
            # node's domains, on any failure)
            if self.zone_needed:
                zi = self._choose_slot_zone(slot)
                if zi is None:
                    ok[slot] = False
                    continue
                keep, rem_new = self._narrow_for_zone(slot, zi)
                room_z = self._zone_run_room(zi)
                run = min(rem_new, hcap, budget, n_rem, room_z)
                if run < 1:
                    ok[slot] = False
                    continue
                self._fix_slot_zone(slot, zi, keep, rem_new)
                self._commit(slot, zi, int(run), kind="fix")
                return int(run)
            run = min(self.rem[slot], hcap, budget, n_rem)
            if run < 1:
                ok[slot] = False
                continue
            self._commit(slot, None, int(run))
            return int(run)
        # ---- new node pool-by-pool ------------------------------------
        return self._open_new(n_rem)

    def _choose_slot_zone(self, slot: int) -> Optional[int]:
        """_choose_zone over the slot's fit types' available offerings."""
        st, enc = self.st, self.enc
        # fit for ONE more pod group member
        new_used = st.used[slot] + self.R
        hr_fit = (new_used[None, :] <= enc.A).all(axis=1)
        fit_types = self.cand[slot] & hr_fit
        if not fit_types.any():
            return None
        zcand = (self.avail_anyct[fit_types].any(axis=0)
                 & st.zones[slot] & self.agz)
        return self._choose_zone(zcand)

    def _narrow_for_zone(self, slot: int, zi: int) -> Tuple[np.ndarray, int]:
        """Candidate narrowing + headroom if `zi` were fixed. Pure — no
        state mutation (the decision may still fail)."""
        ct_mask = self.st.ct[slot] & self.agc
        keep = self.cand[slot] & (self.enc.avail[:, zi, :]
                                  & ct_mask[None, :]).any(axis=1)
        if not keep.any():
            return keep, 0
        hr = self._hr_new(self.st.used[slot])
        hr = np.where(keep, hr, 0)
        rem_new = max(int(hr.max()) - int(self.take[slot]), 0)
        if rem_new > 0:
            rem_new = min(rem_new, self._mv_cap(int(self.st.pool[slot]),
                                                keep, hr))
        return keep, rem_new

    def _fix_slot_zone(self, slot: int, zi: int, keep: np.ndarray,
                       rem_new: int) -> None:
        st = self.st
        self.ts.zfix[slot] = zi
        onehot = np.zeros(st.Z, dtype=bool)
        onehot[zi] = True
        st.zones[slot] &= onehot
        self.cand[slot] = keep
        self.rem[slot] = rem_new

    def _open_pool_static(self, pi: int):
        """Static (within one group's pour) open-a-node arrays for pool
        `pi`: admission, zone/ct masks, candidate types, per-type headroom.
        False = the pool can never open a node for this group."""
        ent = self._open_cache.get(pi)
        if ent is not None:
            return ent
        enc, g = self.enc, self.g
        pe = enc.pools[pi]
        ent = False
        if enc.admit[g, pi]:
            daemon = enc.daemon[g, pi]
            agz_p = self.agz & pe.agz
            agc_p = self.agc & pe.agc
            if agz_p.any() and agc_p.any():
                off_p = (enc.avail & agz_p[None, :, None]
                         & agc_p[None, None, :]).any(axis=(1, 2))
                cand_new = enc.F[g] & pe.type_rows & off_p
                if cand_new.any():
                    hr = self._hr_new(daemon)
                    hr = np.where(cand_new, hr, 0)
                    if int(hr.max()) >= 1:
                        ent = (daemon, agz_p, agc_p, cand_new, hr)
        self._open_cache[pi] = ent
        return ent

    # -- generation replay ----------------------------------------------
    # A spread ladder advances in *generations*: a set of fresh nodes
    # (typically one per eligible zone) opens, stripes full under the
    # cycle jump, and the next set opens. Event costs concentrate in the
    # ~9 open/redetect events per generation. Once two consecutive
    # generations are IDENTICAL up to slot renaming — same pool/zone open
    # sequence, same run pattern, no foreign-slot or existing-node
    # placements — and (for enforced spread) every zone in the group's
    # eligible/allowed universe advanced by the same per-generation delta
    # (so every count-vs-min and score relation is restored exactly), the
    # sequential pour provably repeats the generation verbatim: replay k
    # of them in one commit, bounded by pod count, pool budgets and slot
    # space. Decisions are bit-identical to the event loop
    # (tests/test_topology_equivalence.py fuzzes this path).

    def _gen_close(self) -> Optional[Tuple]:
        """Validate + normalize the just-finished generation; None if it
        can't serve as a replay template."""
        slots = self._gen_slots
        spos = {s: i for i, s in enumerate(slots)}
        runs = self.runs[self._gen_runs_start:]
        norm: List[Tuple] = []
        for entry in runs:
            if entry[0] == "cyc":
                _, pattern, kk = entry
                pat = []
                for s, ln in pattern:
                    if s not in spos:
                        return None  # foreign slot -> not periodic
                    pat.append((spos[s], ln))
                norm.append(("cyc", tuple(pat), kk))
            else:
                s, ln = entry
                if s not in spos:
                    return None
                norm.append((spos[s], ln))
        if self._enforced_z:
            # every zone the group could place into or that gates its
            # min-count must advance uniformly, or staggers shift and a
            # later generation could diverge from the template
            elig = self.min_mask | self.agz
            deltas = {self._gen_ztot.get(zi, 0)
                      for zi in np.nonzero(elig)[0]}
            if len(deltas) != 1 or deltas == {0}:
                return None
        return (tuple(self._gen_opens), tuple(norm), tuple(slots))

    def _maybe_replay(self, n_rem: int) -> int:
        """At a generation boundary (every current-gen slot full and the
        pour wants a new node): close the generation; if it matches the
        previous one, commit as many whole copies as fit."""
        if not self._gen_track or not self._gen_slots:
            return 0
        if any(self.rem[s] > 0 for s in self._gen_slots):
            return 0  # mid-generation open (zone set growing): no boundary
        closed = self._gen_close()
        template, self._gen_template = self._gen_template, closed
        self._gen_slots = []
        self._gen_opens = []
        self._gen_ztot = {}
        if closed is None or template is None \
                or closed[:2] != template[:2]:
            return 0
        st, enc = self.st, self.enc
        opens, norm, slots = closed
        takes = [int(self.take[s]) for s in slots]
        total = sum(takes)
        if total <= 0:
            return 0
        k = n_rem // total
        k = min(k, (st.N - st.E - st.num_nodes) // len(slots))
        if enc.pools:
            pool_pods: Dict[int, int] = {}
            for (pi, _zi), t in zip(opens, takes):
                pool_pods[pi] = pool_pods.get(pi, 0) + t
            for pi, dp in pool_pods.items():
                budget = ffd._pool_budget(enc, st.pool_used, pi, self.R)
                k = min(k, int(budget) // dp)
        if k < 1:
            return 0
        for _ in range(k):
            new_slots = [self._clone_slot(tsl, pi, zi, take)
                         for (pi, zi), tsl, take in
                         zip(opens, slots, takes)]
            for entry in norm:
                if entry[0] == "cyc":
                    _, pat, kk = entry
                    self.runs.append((
                        "cyc", [(new_slots[j], ln) for j, ln in pat], kk))
                else:
                    j, ln = entry
                    self.runs.append((new_slots[j], ln))
        # the template stays armed: the NEXT boundary compares against it
        return total * k

    def _open_new(self, n_rem: int) -> int:
        st, enc, g = self.st, self.enc, self.g
        placed = self._maybe_replay(n_rem)
        if placed:
            return placed
        hcap = self._host_cap_new()
        if hcap < 1:
            return 0
        for pe in enc.pools:
            pi = pe.index
            ent = self._open_pool_static(pi)
            if ent is False:
                continue
            budget = ffd._pool_budget(enc, st.pool_used, pi, self.R)
            if budget < 1:
                continue
            if st.num_nodes >= st.N - st.E:
                continue
            daemon, agz_p, agc_p, cand_new, hr = ent
            zi = None
            if self.zone_needed:
                fit_types = cand_new & (hr >= 1)
                zcand = self.avail_anyct[fit_types].any(axis=0) & agz_p
                zi = self._choose_zone(zcand)
                if zi is None:
                    continue  # topology unsatisfiable in this pool
            slot = st.E + st.num_nodes
            st.num_nodes += 1
            st.alive[slot] = True
            st.pool[slot] = pi
            if zi is not None:
                onehot = np.zeros(st.Z, dtype=bool)
                onehot[zi] = True
                st.zones[slot] = onehot
                self.ts.zfix[slot] = zi
                keep = cand_new & (enc.avail[:, zi, :]
                                   & agc_p[None, :]).any(axis=1)
            else:
                st.zones[slot] = agz_p
                keep = cand_new
            st.ct[slot] = agc_p
            st.used[slot] = daemon.copy()
            hr2 = np.where(keep, hr, 0)
            cap = int(hr2.max())
            if cap >= 1:
                # minValues floors bound the take exactly as in the closed
                # form (a node whose surviving candidates can't keep the
                # floors is unsatisfiable in this pool — core nodeclaim.Add)
                cap = min(cap, self._mv_cap(pi, keep, hr2))
            if cap < 1:
                # chosen zone has no capacity: the oracle would have failed
                # fit first; treat as unsatisfiable in this pool
                st.num_nodes -= 1
                st.alive[slot] = False
                st.pool[slot] = -1
                st.used[slot] = 0
                self.ts.zfix[slot] = -1
                continue
            self.cand[slot] = keep
            self.adm[slot] = True
            self.rem[slot] = cap
            self._slot_ready[slot] = True
            run_z = self._zone_run_room(zi) if (zi is not None and (
                self._enforced_z or self.zaf)) else int(BIG)
            run = min(cap, hcap, budget, n_rem, run_z)
            run = max(run, 1)
            if self._gen_track:
                if not self._gen_slots:
                    self._gen_runs_start = len(self.runs)
                    self._gen_ztot = {}
                self._gen_slots.append(slot)
                self._gen_opens.append((pi, zi))
            self._commit(slot, zi, int(run), kind="new")
            if (run == 1 and hcap == 1 and zi is None
                    and not self.zsp and not self.zaf and not self.hsp
                    and all(anti and own for _gh, anti, own in self.haf)
                    and n_rem > 1):
                # cap-1 hostname-anti ladder (the one-pod-per-node
                # deployment pattern): every subsequent pod provably
                # repeats this exact decision — no slot readmits (anti
                # occupancy and full slots are monotone, zone state is
                # untouched), earlier pools keep failing for their static
                # reasons, this pool's budget only decreases — so clone
                # the fresh-node state instead of re-running the event
                # loop once per pod
                return int(run) + self._bulk_anti_clones(slot, pi,
                                                         n_rem - 1)
            return int(run)
        return 0

    def _clone_slot(self, template: int, pi: int, zi: Optional[int],
                    take: int) -> int:
        """Open a new node whose state copies `template` (a same-pour slot
        whose open parameters are proven identical), committing `take`
        pods on it. Shared by the cap-1 anti ladder and the generation
        replay so open-slot bookkeeping lives in one place."""
        st = self.st
        slot = st.E + st.num_nodes
        st.num_nodes += 1
        st.alive[slot] = True
        st.pool[slot] = pi
        st.zones[slot] = st.zones[template].copy()
        st.ct[slot] = st.ct[template].copy()
        st.used[slot] = st.used[template].copy()
        self.cand[slot] = self.cand[template]
        self.adm[slot] = True
        self.rem[slot] = self.rem[template]
        self._slot_ready[slot] = True
        if zi is not None:
            self.ts.zfix[slot] = zi
        self.take[slot] = take
        st.pool_used[pi] += take * self.R
        self.touched.add(slot)
        self._record(slot, zi, take)
        return slot

    def _bulk_anti_clones(self, template: int, pi: int, want: int) -> int:
        """Open `want` more one-pod nodes identical to `template`
        (post-commit state copied), bounded by slot space and pool
        budget. Exactly the sequential pour's decisions, minus the
        per-event admissibility scans."""
        st, enc = self.st, self.enc
        placed = 0
        while placed < want:
            if st.num_nodes >= st.N - st.E:
                break
            if ffd._pool_budget(enc, st.pool_used, pi, self.R) < 1:
                break
            slot = self._clone_slot(template, pi, None, 1)
            self.runs.append((slot, 1))
            self.event_log.append((slot, None, 1, "new"))
            placed += 1
        return placed

    def _commit(self, slot: int, zi: Optional[int], count: int,
                kind: str = "place") -> None:
        st = self.st
        self.take[slot] += count
        if self.runs and self.runs[-1][0] == slot:
            self.runs[-1] = (slot, self.runs[-1][1] + count)
        else:
            self.runs.append((slot, count))
        self.event_log.append((slot, zi, count, kind))
        self.rem[slot] -= count
        st.used[slot] += count * self.R
        pi = int(st.pool[slot])
        if pi >= 0:
            st.pool_used[pi] += count * self.R
        self.touched.add(slot)
        self._record(slot, zi, count)

    def _commit_narrowing(self) -> None:
        """Mirror the closed-form commit: candidate-intersection + refit
        against final aggregate usage, zone/ct mask narrowing."""
        st, enc = self.st, self.enc
        open_slots = np.array(
            [s for s in sorted(self.touched) if st.pool[s] >= 0],
            dtype=np.int64)
        if not len(open_slots):
            return
        # one [S, T, D] comparison instead of S separate [T, D] passes
        fit = (st.used[open_slots][:, None, :]
               <= enc.A[None, :, :]).all(axis=2)
        st.types[open_slots] = self.cand[open_slots] & fit
        for slot in open_slots:
            if self.ts.zfix[slot] < 0:
                st.zones[slot] &= self.agz
            st.ct[slot] &= self.agc


def record_plain_fill(tenc: TopoEncoding, ts: TopoState, st: ffd.NodeState,
                      g: int, take: np.ndarray) -> None:
    """Membership recording for a scheduling_group'd pod group placed via
    the topology-free closed form (the oracle records membership for every
    pod with a scheduling_group even when it has no constraints)."""
    mz, mh = tenc.member_z[g], tenc.member_h[g]
    if mz < 0 and mh < 0:
        return
    for slot in np.nonzero(take > 0)[0]:
        cnt = int(take[slot])
        if mh >= 0:
            ts.ch[mh, slot] += cnt
        if mz >= 0:
            zi = int(ts.zfix[slot])
            if zi >= 0:
                ts.cz[mz, zi] += cnt


def fill_group_topo(st: ffd.NodeState, enc: SnapshotEncoding,
                    tenc: TopoEncoding, ts: TopoState,
                    g: int) -> Tuple[np.ndarray, int, List[Tuple[int, int]]]:
    """Pour group ``g``'s pods with full topology semantics. Mutates
    ``st`` and ``ts``; returns (take[N], leftover, placement runs)."""
    return _Pour(st, enc, tenc, ts, g).run()
