"""Host-side (numpy-only, jax-free) packing for the single-buffer solve.

The layout lists here are the single source of truth for BOTH sides of
the device boundary: the host packs kernel inputs into ONE int64 buffer
(bools bitpacked little-endian via the native codec) and unpacks the ONE
int64 output buffer; ops/ffd_jax.py walks the same layouts on device.
Living apart from ffd_jax keeps the control-plane side of the sidecar
(sidecar/client.py) free of any jax import — dispatch rides the wire.
"""

from __future__ import annotations

import numpy as np

from ..native import pack_bits, unpack_bits


#: statics order on the sidecar wire — shared by client and server. The
#: minValues keys append AFTER n_max so a version-skewed old server still
#: reads its 8 keys correctly (its buffer-size check then rejects K>0
#: requests loudly instead of misparsing n_max)
STATIC_KEYS = ("T", "D", "Z", "C", "G", "E", "P", "n_max", "K", "V", "M")

#: default exact-slot budget per pruned-kernel step — the ONE source for
#: the kernel signature default (ops/ffd_jax.py), the local solver knob
#: (solver/tpu.py dev_pruned_slots) and the sidecar client's wire
#: fallback (sidecar/client.py). The compat-aware bound pass counts only
#: slots the exact kernel could fill, and BASELINE config 7 (50k pods,
#: ~10k signatures, ~5 pods/signature) clears its deepest fill at S=48;
#: 64 leaves margin without moving the O(S*T*D) step-cost class.
DEV_PRUNED_SLOTS = 64


def in_layout_i64(T, D, Z, C, G, E, P, K=0, M=0):
    """(name, shape) of every int64 input, in buffer order. K/M are the
    minValues key/pair counts (0 = feature absent, zero extra bytes)."""
    return [("A", (T, D)), ("R", (G, D)), ("n", (G,)),
            ("daemon", (G, P, D)), ("pool_limit", (P, D)),
            ("pool_used0", (P, D)), ("ex_alloc", (E, D)),
            ("ex_used0", (E, D)), ("mv_floor", (P, K)),
            ("mv_pairs_t", (K, M)), ("mv_pairs_v", (K, M))]


def in_layout_bool(T, D, Z, C, G, E, P, K=0, M=0):
    return [("avail_zc", (T, Z * C)), ("F", (G, T)), ("agz", (G, Z)),
            ("agc", (G, C)), ("admit", (G, P)),
            ("pool_types", (P, T)), ("pool_agz", (P, Z)),
            ("pool_agc", (P, C)), ("ex_compat", (G, E))]


def out_layout(T, D, Z, C, G, E, P, n_max):
    """((i64 name, shape)…), ((bool name, shape)…) of the packed outputs."""
    N = E + n_max
    i64 = [("takes", (G, N)), ("leftover", (G,)), ("used", (N, D)),
           ("pool", (N,)), ("num_nodes", (1,)), ("pool_used", (P, D))]
    bl = [("types", (N, T)), ("zones", (N, Z)), ("ct", (N, C)),
          ("alive", (N,))]
    return i64, bl


def split(buf, layout) -> dict:
    """Walk a flat buffer by a (name, shape) layout list. Works on both
    numpy and jax arrays; the ONLY buffer walker — host pack and device
    unpack share it so the layouts can never drift apart."""
    vals = {}
    off = 0
    for nm, shp in layout:
        sz = 1
        for s in shp:
            sz *= s
        vals[nm] = buf[off:off + sz].reshape(shp)
        off += sz
    return vals


def layout_sizes(layout) -> int:
    total = 0
    for _, shp in layout:
        sz = 1
        for s in shp:
            sz *= s
        total += sz
    return total


def nwords(nbits: int) -> int:
    return (nbits + 63) // 64


def pack_inputs1(arrays: dict, T, D, Z, C, G, E, P, K=0, M=0) -> np.ndarray:
    """Host: all inputs -> ONE int64 buffer [i64 fields | bitpacked bools]."""
    empty = np.zeros(0, dtype=np.int64)
    i64 = np.concatenate([
        np.asarray(arrays.get(nm, empty)).reshape(-1).astype(np.int64)
        for nm, _ in in_layout_i64(T, D, Z, C, G, E, P, K, M)])
    bl = np.concatenate([arrays[nm].reshape(-1).astype(bool)
                         for nm, _ in in_layout_bool(T, D, Z, C, G, E, P, K, M)])
    return np.concatenate([i64, pack_bits(bl)])


def unpack_outputs1(buf, T, D, Z, C, G, E, P, n_max) -> dict:
    """Host: the single fetched buffer -> dict of arrays."""
    li, lb = out_layout(T, D, Z, C, G, E, P, n_max)
    n_i64 = layout_sizes(li)
    n_bits = layout_sizes(lb)
    bool_flat = unpack_bits(np.ascontiguousarray(buf[n_i64:]), n_bits)
    vals = split(buf[:n_i64], li)
    vals.update(split(bool_flat, lb))
    return vals


def unpack_inputs1(buf, T, D, Z, C, G, E, P, K=0, M=0) -> dict:
    """Inverse of pack_inputs1 (the sidecar server's mesh path unpacks
    the wire buffer back into arrays to shard them over its local mesh)."""
    li = in_layout_i64(T, D, Z, C, G, E, P, K, M)
    lb = in_layout_bool(T, D, Z, C, G, E, P, K, M)
    n_i64 = layout_sizes(li)
    bool_flat = unpack_bits(np.ascontiguousarray(buf[n_i64:]),
                            layout_sizes(lb))
    vals = split(np.asarray(buf[:n_i64]), li)
    vals.update(split(bool_flat, lb))
    return vals


def pack_outputs1(arrays: dict, T, D, Z, C, G, E, P, n_max) -> np.ndarray:
    """Inverse of unpack_outputs1 (the server's mesh path re-packs the
    carry into the single wire buffer the client expects)."""
    li, lb = out_layout(T, D, Z, C, G, E, P, n_max)
    i64 = np.concatenate([
        np.asarray(arrays[nm]).reshape(-1).astype(np.int64)
        for nm, _ in li])
    bl = np.concatenate([np.asarray(arrays[nm]).reshape(-1).astype(bool)
                         for nm, _ in lb])
    return np.concatenate([i64, pack_bits(bl)])
