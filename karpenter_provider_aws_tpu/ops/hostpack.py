"""Host-side (numpy-only, jax-free) packing for the single-buffer solve.

The layout lists here are the single source of truth for BOTH sides of
the device boundary: the host packs kernel inputs into ONE int64 buffer
(bools bitpacked little-endian via the native codec) and unpacks the ONE
int64 output buffer; ops/ffd_jax.py walks the same layouts on device.
Living apart from ffd_jax keeps the control-plane side of the sidecar
(sidecar/client.py) free of any jax import — dispatch rides the wire.
"""

from __future__ import annotations

import numpy as np

from ..native import deltawalk as _dw
from ..native import pack_bits, unpack_bits


#: statics order on the sidecar wire — shared by client and server. The
#: minValues keys append AFTER n_max so a version-skewed old server still
#: reads its 8 keys correctly (its buffer-size check then rejects K>0
#: requests loudly instead of misparsing n_max); the fusion factor F
#: appends after M under the same discipline (an old server reads 11
#: keys and rejects the 12-key request loudly, never misparses); the
#: priority-tier count Q appends last, version-gated the same way
#: (Q=0 = priority axis absent, zero extra bytes — an old 12-key
#: server rejects a Q>0 request loudly, never misparses)
STATIC_KEYS = ("T", "D", "Z", "C", "G", "E", "P", "n_max", "K", "V", "M",
               "F", "Q")

#: default fused-scan block width (groups batched per scan step when the
#: encoder's run detection proves them pairwise pool/existing-disjoint) —
#: the ONE source for the solver knob (solver/tpu.py dev_fuse) and the
#: kernel signature default. 4 cuts the scan trip count 4x on run-heavy
#: snapshots while keeping the step body (both cond branches trace F
#: group fills) within the compile-time envelope of the base step.
DEV_FUSE = 4

#: default exact-slot budget per pruned-kernel step — the ONE source for
#: the kernel signature default (ops/ffd_jax.py), the local solver knob
#: (solver/tpu.py dev_pruned_slots) and the sidecar client's wire
#: fallback (sidecar/client.py). The compat-aware bound pass counts only
#: slots the exact kernel could fill, and BASELINE config 7 (50k pods,
#: ~10k signatures, ~5 pods/signature) clears its deepest fill at S=48;
#: 64 leaves margin without moving the O(S*T*D) step-cost class.
DEV_PRUNED_SLOTS = 64


def in_layout_i64(T, D, Z, C, G, E, P, K=0, M=0, F=1, Q=0):
    """(name, shape) of every int64 input, in buffer order. K/M are the
    minValues key/pair counts (0 = feature absent, zero extra bytes);
    Q is the priority-tier count gating the per-group priority vector
    under the same zero-when-absent discipline."""
    lay = [("A", (T, D)), ("R", (G, D)), ("n", (G,)),
           ("daemon", (G, P, D)), ("pool_limit", (P, D)),
           ("pool_used0", (P, D)), ("ex_alloc", (E, D)),
           ("ex_used0", (E, D)), ("mv_floor", (P, K)),
           ("mv_pairs_t", (K, M)), ("mv_pairs_v", (K, M))]
    if Q:
        # resolved per-group priority: data for per-tier reporting and
        # the preemption search — the base solve's decisions never read
        # it (canonical order already encodes priority)
        lay.append(("prio", (G,)))
    return lay


def in_layout_bool(T, D, Z, C, G, E, P, K=0, M=0, F=1, Q=0):
    base = [("avail_zc", (T, Z * C)), ("F", (G, T)), ("agz", (G, Z)),
            ("agc", (G, C)), ("admit", (G, P)),
            ("pool_types", (P, T)), ("pool_agz", (P, Z)),
            ("pool_agc", (P, C)), ("ex_compat", (G, E))]
    if F > 1:
        # same_run_as_prev flags (models/encoding.py independent_runs):
        # data, not statics — only the block width F keys the compile
        base.append(("fuse", (G,)))
    return base


def out_layout(T, D, Z, C, G, E, P, n_max):
    """((i64 name, shape)…), ((i32 name, shape)…), ((bool name, shape)…)
    of the packed outputs. takes rides the int32 section: a single
    slot's take is bounded by the pod count (< 2^31 by construction),
    so two lanes pack per int64 wire word and the dominant [G, N]
    output tensor halves on the d2h leg."""
    N = E + n_max
    i64 = [("leftover", (G,)), ("used", (N, D)),
           ("pool", (N,)), ("num_nodes", (1,)), ("pool_used", (P, D))]
    i32 = [("takes", (G, N))]
    bl = [("types", (N, T)), ("zones", (N, Z)), ("ct", (N, C)),
          ("alive", (N,))]
    return i64, i32, bl


def split(buf, layout) -> dict:
    """Walk a flat buffer by a (name, shape) layout list. Works on both
    numpy and jax arrays; the ONLY buffer walker — host pack and device
    unpack share it so the layouts can never drift apart."""
    vals = {}
    off = 0
    for nm, shp in layout:
        sz = 1
        for s in shp:
            sz *= s
        vals[nm] = buf[off:off + sz].reshape(shp)
        off += sz
    return vals


def layout_sizes(layout) -> int:
    total = 0
    for _, shp in layout:
        sz = 1
        for s in shp:
            sz *= s
        total += sz
    return total


def nwords(nbits: int) -> int:
    return (nbits + 63) // 64


def nwords32(nvals: int) -> int:
    """int64 wire words needed for ``nvals`` int32 lanes (two per word)."""
    return (nvals + 1) // 2


def pack_i32_words(vals: np.ndarray) -> np.ndarray:
    """Host: flat int32-valued array -> int64 wire words, two lanes per
    word, little-lane-first — mirrors the device's bitcast packing
    (ops/ffd_jax.py _i32_to_words) so no layout assumption crosses."""
    v = np.asarray(vals).reshape(-1).astype(np.int64)
    if v.size % 2:
        v = np.concatenate([v, np.zeros(1, np.int64)])
    u = (v & np.int64(0xFFFFFFFF)).view(np.uint64)
    return (u[0::2] | (u[1::2] << np.uint64(32))).view(np.int64)


def unpack_i32_words(words: np.ndarray, nvals: int) -> np.ndarray:
    """Host: int64 wire words -> int64 array of the sign-extended int32
    lanes (callers keep doing int64 math on the result)."""
    u = np.ascontiguousarray(words).view(np.uint64)
    lo = (u & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    hi = (u >> np.uint64(32)).astype(np.uint32)
    out = np.empty(u.size * 2, dtype=np.int32)
    out[0::2] = lo.view(np.int32)
    out[1::2] = hi.view(np.int32)
    return out[:nvals].astype(np.int64)


def pad_to(a: np.ndarray, shape, fill=0) -> np.ndarray:
    """Grow ``a`` to ``shape`` by appending ``fill`` along every axis
    (never shrinks). The tenancy layer's bucketed-padding path
    (tenancy/bucketing.py) builds its inert pad rows with this so the
    pad geometry lives next to the layouts it must agree with."""
    a = np.asarray(a)
    if tuple(a.shape) == tuple(shape):
        return a
    out = np.full(shape, fill, dtype=a.dtype)
    out[tuple(slice(0, s) for s in a.shape)] = a
    return out


def pack_inputs1(arrays: dict, T, D, Z, C, G, E, P, K=0, M=0,
                 F=1, Q=0) -> np.ndarray:
    """Host: all inputs -> ONE int64 buffer [i64 fields | bitpacked bools]."""
    return pack_inputs1_state(arrays, T, D, Z, C, G, E, P, K, M, F, Q)[0]


def pack_inputs1_state(arrays: dict, T, D, Z, C, G, E, P, K=0, M=0,
                       F=1, Q=0):
    """``pack_inputs1`` that also returns the pre-bitpack bool plane, so
    a caller can keep ``(buf, bool_flat)`` RESIDENT between solves and
    patch dirty sections in place (``patch_inputs1``) instead of
    re-packing the whole arena. The buffer is byte-identical to
    ``pack_inputs1``'s (which delegates here)."""
    empty = np.zeros(0, dtype=np.int64)
    i64 = np.concatenate([
        np.asarray(arrays.get(nm, empty)).reshape(-1).astype(np.int64)
        for nm, _ in in_layout_i64(T, D, Z, C, G, E, P, K, M, F, Q)])
    bl = np.concatenate([arrays[nm].reshape(-1).astype(bool)
                         for nm, _ in in_layout_bool(T, D, Z, C, G, E, P,
                                                     K, M, F, Q)])
    packer = _dw.pack_bits if _dw.enabled() else pack_bits
    return np.concatenate([i64, packer(bl)]), bl


def patch_inputs1(buf: np.ndarray, bool_flat: np.ndarray, arrays: dict,
                  dirty_i64, dirty_bool, T, D, Z, C, G, E, P, K=0, M=0,
                  F=1, Q=0):
    """Patch dirty fields of a RESIDENT packed arena in place.

    ``(buf, bool_flat)`` must be the pair ``pack_inputs1_state``
    returned for the SAME statics; ``arrays`` carries the new field
    values (only the dirty names are read). i64 fields overwrite their
    buffer words directly. Bool fields update the resident bool plane,
    then re-bitpack only the words covering the field's bit range —
    sections are not word-aligned, so the repack rounds out to the
    enclosing words and re-reads the neighbours from the plane, which
    is exactly why the plane must stay resident. The result is
    byte-identical to a fresh pack of the same arrays by construction;
    tests/test_delta_encoding.py fuzzes that equality over random dirty
    subsets.

    Returns the list of ``(start, stop)`` int64-word sections of ``buf``
    that were overwritten (bool sections reported word-rounded, exactly
    as repacked), so callers shipping the arena over a wire or onto a
    device can move only the touched bytes. Existing callers that
    ignore the return value are unaffected."""
    use_native = _dw.enabled()
    if use_native:
        _dw.record_engaged("patch")
    else:
        _dw.record_fallback(_dw.fallback_reason())
    sections = []
    lay64 = in_layout_i64(T, D, Z, C, G, E, P, K, M, F, Q)
    want64 = set(dirty_i64)
    off = 0
    for nm, shp in lay64:
        sz = 1
        for s in shp:
            sz *= s
        if nm in want64 and sz:
            fresh = np.asarray(arrays[nm]).reshape(-1).astype(np.int64)
            hit = np.nonzero(buf[off:off + sz] != fresh)[0]
            if hit.size:
                # narrow to the changed word RUN (one span per field
                # keeps the section count bounded): the delta wire then
                # ships only moved words, and the server-side dirty
                # frontier (frontier_from_sections) resolves to the
                # first moved GROUP instead of the field start — whole-
                # field sections would pin every frontier at 0
                w0, w1 = int(hit[0]), int(hit[-1]) + 1
                buf[off + w0:off + w1] = fresh[w0:w1]
                sections.append((off + w0, off + w1))
        off += sz
    layb = in_layout_bool(T, D, Z, C, G, E, P, K, M, F, Q)
    nbits = layout_sizes(layb)
    wantb = set(dirty_bool)
    boff = 0
    for nm, shp in layb:
        sz = 1
        for s in shp:
            sz *= s
        if nm in wantb and sz:
            fresh = np.asarray(arrays[nm]).reshape(-1)
            span = _dw.patch_bits(buf[off:], bool_flat, fresh, boff) \
                if use_native else None
            if span is not None:
                # native: fresh bits landed in the plane and the
                # covering words were repacked straight from it
                w0, nw = span
                sections.append((off + w0, off + w0 + nw))
            else:
                bool_flat[boff:boff + sz] = fresh.astype(bool)
                w0 = boff >> 6
                end = min(((boff + sz + 63) >> 6) << 6, nbits)
                words = pack_bits(np.ascontiguousarray(
                    bool_flat[w0 << 6:end]))
                buf[off + w0:off + w0 + words.size] = words
                sections.append((off + w0, off + w0 + words.size))
        boff += sz
    return sections


#: arena fields whose leading axis is the canonical GROUP axis — the
#: only fields a dirty section can touch while still permitting a
#: suffix-only re-solve past its group index. Everything else (catalog,
#: pool vectors, existing-node tables) feeds the scan's INITIAL carry
#: or every step, so touching it forces frontier 0 (full solve).
GROUP_MAJOR_FIELDS = frozenset(
    ("R", "n", "daemon", "prio", "F", "agz", "agc", "admit", "ex_compat",
     "fuse"))


def frontier_from_sections(sections, T, D, Z, C, G, E, P, K=0, M=0,
                           F=1, Q=0) -> int:
    """Minimum canonical group index the patched ``(start, stop)``
    int64-word sections of a resident arena can influence — the
    server-side dirty frontier of the SolvePatch wire (the client-side
    twin is models/delta.py ``SnapshotDelta.dirty_frontier``, computed
    semantically; this one is computed purely from the wire layout so
    the delta wire and the incremental solve compose without a new
    RPC). Returns G for an empty section list (clean resend) and 0 as
    soon as any section overlaps a non-group-major field. Bool sections
    arrive word-rounded from ``patch_inputs1``; rounding can only widen
    a section, hence only LOWER the result — conservative, never
    stale."""
    lay64 = in_layout_i64(T, D, Z, C, G, E, P, K, M, F, Q)
    layb = in_layout_bool(T, D, Z, C, G, E, P, K, M, F, Q)
    n_i64 = layout_sizes(lay64)
    # every field as (start_bit, stop_bit, per-group stride in bits, or
    # None for non-group fields) in one combined bit space: i64 word w
    # spans bits [w*64, w*64+64)
    fields = []
    off = 0
    for nm, shp in lay64:
        sz = 1
        for s in shp:
            sz *= s
        stride = (sz // G) * 64 if nm in GROUP_MAJOR_FIELDS and G else None
        fields.append((off * 64, (off + sz) * 64, stride))
        off += sz
    boff = n_i64 * 64
    for nm, shp in layb:
        sz = 1
        for s in shp:
            sz *= s
        stride = sz // G if nm in GROUP_MAJOR_FIELDS and G else None
        fields.append((boff, boff + sz, stride))
        boff += sz
    frontier = G
    for s0, s1 in sections:
        b0, b1 = s0 * 64, s1 * 64
        for f0, f1, stride in fields:
            lo, hi = max(b0, f0), min(b1, f1)
            if lo >= hi:
                continue
            if stride is None or stride == 0:
                return 0
            frontier = min(frontier, (lo - f0) // stride)
            if frontier == 0:
                return 0
    return frontier


def tier_leftovers(leftover: np.ndarray, prio) -> dict:
    """Per-priority-tier unschedulable pod counts from the solve's [G]
    leftover output and the encoding's per-group priority vector (None =
    priority axis disabled -> single tier 0). Host-side reporting: the
    kernels never read priority (canonical order encodes it), so this is
    THE per-tier view both the device and CPU paths share."""
    left = np.asarray(leftover).reshape(-1)
    if prio is None:
        return {0: int(left.sum())}
    pr = np.asarray(prio).reshape(-1)[:left.size]
    out: dict = {}
    for tier in np.unique(pr):
        out[int(tier)] = int(left[: pr.size][pr == tier].sum())
    return out


def unpack_outputs1(buf, T, D, Z, C, G, E, P, n_max) -> dict:
    """Host: the single fetched buffer -> dict of arrays."""
    li, l32, lb = out_layout(T, D, Z, C, G, E, P, n_max)
    n_i64 = layout_sizes(li)
    n_32 = layout_sizes(l32)
    w32 = nwords32(n_32)
    n_bits = layout_sizes(lb)
    i32_flat = unpack_i32_words(buf[n_i64:n_i64 + w32], n_32)
    bool_flat = unpack_bits(np.ascontiguousarray(buf[n_i64 + w32:]), n_bits)
    vals = split(buf[:n_i64], li)
    vals.update(split(i32_flat, l32))
    vals.update(split(bool_flat, lb))
    return vals


def unpack_inputs1(buf, T, D, Z, C, G, E, P, K=0, M=0, F=1, Q=0) -> dict:
    """Inverse of pack_inputs1 (the sidecar server's mesh path unpacks
    the wire buffer back into arrays to shard them over its local mesh)."""
    li = in_layout_i64(T, D, Z, C, G, E, P, K, M, F, Q)
    lb = in_layout_bool(T, D, Z, C, G, E, P, K, M, F, Q)
    n_i64 = layout_sizes(li)
    bool_flat = unpack_bits(np.ascontiguousarray(buf[n_i64:]),
                            layout_sizes(lb))
    vals = split(np.asarray(buf[:n_i64]), li)
    vals.update(split(bool_flat, lb))
    return vals


def pack_outputs1(arrays: dict, T, D, Z, C, G, E, P, n_max) -> np.ndarray:
    """Inverse of unpack_outputs1 (the server's mesh path re-packs the
    carry into the single wire buffer the client expects)."""
    li, l32, lb = out_layout(T, D, Z, C, G, E, P, n_max)
    i64 = np.concatenate([
        np.asarray(arrays[nm]).reshape(-1).astype(np.int64)
        for nm, _ in li])
    i32 = np.concatenate([
        np.asarray(arrays[nm]).reshape(-1) for nm, _ in l32])
    bl = np.concatenate([np.asarray(arrays[nm]).reshape(-1).astype(bool)
                         for nm, _ in lb])
    return np.concatenate([i64, pack_i32_words(i32), pack_bits(bl)])


#: frame header ceiling — a SolveBatch frame larger than this is a
#: protocol violation, not a workload (consolidation's pre-screen and
#: the preference relaxer cap out far below; the bound keeps a hostile
#: header from sizing server allocations)
BATCH_MAX_ITEMS = 64


def pack_batch_frame(bufs, statics: dict) -> np.ndarray:
    """B packed solve buffers sharing ONE statics bucket -> one int64
    frame: [B | offsets[0..B] (cumulative words, offs[0]=0, offs[B]=
    payload size) | statics vector (STATIC_KEYS order) | payload].
    The offsets are redundant with the statics (every item of a shape
    class has the same width) — they exist so the receiving side can
    validate the frame BEFORE trusting the statics to size anything."""
    B = len(bufs)
    if not 1 <= B <= BATCH_MAX_ITEMS:
        raise ValueError(f"batch size {B} outside [1, {BATCH_MAX_ITEMS}]")
    flat = [np.asarray(b).reshape(-1).astype(np.int64) for b in bufs]
    offs = np.zeros(B + 1, dtype=np.int64)
    np.cumsum([b.size for b in flat], out=offs[1:])
    svec = np.array([int(statics.get(k, 0)) for k in STATIC_KEYS],
                    dtype=np.int64)
    return np.concatenate([np.array([B], dtype=np.int64), offs, svec]
                          + flat)


#: patch-frame section ceiling — patch_inputs1 emits at most one section
#: per arena field (~21 at the full layout), and a prime ships exactly
#: one; anything larger is a protocol violation, not a workload (the
#: bound keeps a hostile header from sizing server-side loops)
PATCH_MAX_SECTIONS = 64

#: words before the section table in a patch frame:
#: [token | epoch0 | epoch1 | base_version | new_version | S] + statics
PATCH_HEADER_WORDS = 6 + len(STATIC_KEYS)


def pack_patch_frame(sections, payloads, statics: dict, *, token: int,
                     epoch, base_version: int,
                     new_version: int) -> np.ndarray:
    """Dirty arena sections -> one int64 SolvePatch frame:
    ``[token | epoch0 | epoch1 | base_version | new_version | S
    | statics (STATIC_KEYS order) | sections (start, stop) x S
    | payload words]``.

    ``token`` names the client arena instance (so two clients of one
    tenant never alias a resident arena), ``epoch`` is the solver's
    ``arena_epoch()`` pair, and ``base_version`` is the version the
    server's resident copy must currently hold (-1 = prime: exactly one
    full-coverage section establishes or overwrites residency).
    ``payloads`` carries one int64 array per section, section order.
    An EMPTY section list is the clean resend: the server re-solves its
    resident arena as-is — zero payload words on the wire."""
    S = len(sections)
    if S > PATCH_MAX_SECTIONS:
        raise ValueError(f"patch sections {S} > {PATCH_MAX_SECTIONS}")
    if S != len(payloads):
        raise ValueError(f"{S} sections but {len(payloads)} payloads")
    hdr = np.array([int(token), int(epoch[0]), int(epoch[1]),
                    int(base_version), int(new_version), S],
                   dtype=np.int64)
    svec = np.array([int(statics.get(k, 0)) for k in STATIC_KEYS],
                    dtype=np.int64)
    sec = np.array([w for se in sections for w in se],
                   dtype=np.int64).reshape(-1)
    flat = [np.asarray(p).reshape(-1).astype(np.int64) for p in payloads]
    for (s0, s1), p in zip(sections, flat):
        if p.size != s1 - s0:
            raise ValueError(f"payload size {p.size} != section "
                             f"[{s0}, {s1})")
    return np.concatenate([hdr, svec, sec] + flat)


def pack_patch_frame_from(buf, sections, statics: dict, *, token: int,
                          epoch, base_version: int,
                          new_version: int) -> np.ndarray:
    """``pack_patch_frame`` fed straight from the RESIDENT pack buffer:
    the payload for section ``(s0, s1)`` is ``buf[s0:s1]``, gathered
    into ONE preallocated frame (native ``frame_gather`` when the
    deltawalk library serves, numpy slice-assign otherwise — byte-
    identical either way, and to ``pack_patch_frame`` fed copies of the
    same slices). This removes the per-tick payload-copy +
    ``np.concatenate`` chain from the wire hot path: the resident arena
    is touched exactly once, at its dirty words."""
    S = len(sections)
    if S > PATCH_MAX_SECTIONS:
        raise ValueError(f"patch sections {S} > {PATCH_MAX_SECTIONS}")
    buf = np.asarray(buf).reshape(-1)
    hdr = np.empty(PATCH_HEADER_WORDS, dtype=np.int64)
    hdr[0] = int(token)
    hdr[1], hdr[2] = int(epoch[0]), int(epoch[1])
    hdr[3], hdr[4], hdr[5] = int(base_version), int(new_version), S
    for i, k in enumerate(STATIC_KEYS):
        hdr[6 + i] = int(statics.get(k, 0))
    total = PATCH_HEADER_WORDS + 2 * S
    for s0, s1 in sections:
        if not 0 <= s0 <= s1 <= buf.size:
            raise ValueError(f"section [{s0}, {s1}) outside resident "
                             f"buffer [0, {buf.size})")
        total += s1 - s0
    frame = np.empty(total, dtype=np.int64)
    if _dw.enabled() and _dw.frame_gather(frame, hdr, sections, buf):
        _dw.record_engaged("frame")
        return frame
    if not _dw.enabled():
        _dw.record_fallback(_dw.fallback_reason())
    frame[:PATCH_HEADER_WORDS] = hdr
    off = PATCH_HEADER_WORDS
    for s0, s1 in sections:
        frame[off], frame[off + 1] = s0, s1
        off += 2
    for s0, s1 in sections:
        frame[off:off + s1 - s0] = buf[s0:s1]
        off += s1 - s0
    return frame


def unpack_patch_frame(frame) -> tuple:
    """Inverse of pack_patch_frame -> (header dict, statics vector,
    [(start, stop)], [payload arrays]). Raises ValueError on ANY
    malformation (truncated header, section count out of bounds,
    sections not strictly increasing and disjoint, payload size
    mismatch) so the server rejects BEFORE statics-derived sizing and a
    chaos-torn frame can never alias a valid patch."""
    frame = np.asarray(frame).reshape(-1)
    if frame.dtype != np.int64:
        raise ValueError(f"patch frame dtype {frame.dtype} != int64")
    if frame.size < PATCH_HEADER_WORDS:
        raise ValueError(f"patch frame truncated: {frame.size} < header "
                         f"{PATCH_HEADER_WORDS}")
    hdr = dict(token=int(frame[0]), epoch=(int(frame[1]), int(frame[2])),
               base_version=int(frame[3]), new_version=int(frame[4]))
    S = int(frame[5])
    if not 0 <= S <= PATCH_MAX_SECTIONS:
        raise ValueError(f"patch sections {S} outside "
                         f"[0, {PATCH_MAX_SECTIONS}]")
    svec = frame[6:PATCH_HEADER_WORDS]
    body = frame[PATCH_HEADER_WORDS:]
    if body.size < 2 * S:
        raise ValueError(f"patch frame truncated: {body.size} words "
                         f"< {2 * S} section words")
    sections = []
    prev_stop = 0
    for i in range(S):
        s0, s1 = int(body[2 * i]), int(body[2 * i + 1])
        if s0 < prev_stop or s1 <= s0:
            raise ValueError("patch sections not strictly increasing "
                             "and disjoint")
        sections.append((s0, s1))
        prev_stop = s1
    payload = body[2 * S:]
    want = sum(s1 - s0 for s0, s1 in sections)
    if payload.size != want:
        raise ValueError(f"patch payload size {payload.size} != "
                         f"declared {want}")
    payloads = []
    off = 0
    for s0, s1 in sections:
        payloads.append(payload[off:off + (s1 - s0)])
        off += s1 - s0
    return hdr, svec, sections, payloads


def unpack_batch_frame(frame) -> tuple:
    """Inverse of pack_batch_frame -> (statics dict, [item buffers]).
    Raises ValueError on ANY malformation (truncated header, offsets
    not monotone from zero, payload size mismatch) so the server can
    reject before statics-derived sizing, and the client's resilience
    layer can classify a truncated reply as retryable-malformed."""
    frame = np.asarray(frame).reshape(-1)
    if frame.dtype != np.int64:
        raise ValueError(f"batch frame dtype {frame.dtype} != int64")
    if frame.size < 1:
        raise ValueError("batch frame empty")
    B = int(frame[0])
    if not 1 <= B <= BATCH_MAX_ITEMS:
        raise ValueError(f"batch size {B} outside [1, {BATCH_MAX_ITEMS}]")
    hdr = 1 + (B + 1) + len(STATIC_KEYS)
    if frame.size < hdr:
        raise ValueError(f"batch frame truncated: {frame.size} < header "
                         f"{hdr}")
    offs = frame[1:1 + B + 1]
    if int(offs[0]) != 0 or np.any(np.diff(offs) <= 0):
        raise ValueError("batch frame offsets not strictly increasing "
                         "from zero")
    payload = frame[hdr:]
    if int(offs[B]) != payload.size:
        raise ValueError(f"batch frame payload size {payload.size} != "
                         f"declared {int(offs[B])}")
    svec = frame[1 + B + 1:hdr]
    statics = {k: int(svec[i]) for i, k in enumerate(STATIC_KEYS)}
    bufs = [payload[int(offs[i]):int(offs[i + 1])] for i in range(B)]
    return statics, bufs
