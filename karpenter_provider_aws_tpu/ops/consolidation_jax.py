"""Batched consolidation kernel: deletion feasibility for many candidates
in ONE device call.

The disruption controller's hot inner loop asks, per candidate node c:
"do c's pods first-fit onto the remaining nodes?" (designs/
consolidation.md "Node Deletion": a simulated scheduling run against the
existing cluster). Sequentially that is O(candidates) solver calls; here
the candidate axis is just a batch dimension — one ``lax.scan`` over the
candidate's pod groups, ``vmap``-ed over candidates.

Transfer discipline (the Go↔sidecar serialization concern of SURVEY §7
"hard parts" #4, applied to host↔device): candidates share the cluster, so
the node axis is sent ONCE — shared ``ex_alloc/ex_used/compat_tab`` tables
— and each candidate carries only index vectors: which unique pod-group
signatures it moves (``gid``), how many pods (``n``), and which node rows
are dead for it (``alive``). Per-candidate payload is O(G + E) bytes, not
O(E·D) tensors; a 256-candidate × 300-node batch ships ~200KB instead of
~17MB.

Semantics per group: headroom per node = min_d floor((alloc - used)/R),
prefix-sum greedy fill in name-sorted node order — bit-identical to the
CPU oracle's first-fit over existing nodes (solver/cpu.py:243-258).
Feasible ⇔ every group's leftover is 0. All int64 (jax_enable_x64):
decisions match the oracle exactly
(tests/test_consolidation_equivalence.py).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

jax.config.update("jax_enable_x64", True)

from .ffd_jax import KernelInputs, _solve  # noqa: E402 (after x64 flag)

BIG = jnp.int64(1) << 60


@jax.jit
def deletions_feasible_kernel(ex_alloc: jax.Array,    # [E, D] int64 shared
                              ex_used0: jax.Array,    # [E, D] int64 shared
                              compat_tab: jax.Array,  # [Sc, E] bool per
                              #                         constraint profile
                              R_tab: jax.Array,       # [S, D] int64 per sig
                              gid: jax.Array,         # [B, G] int32 -> S
                              cid: jax.Array,         # [B, G] int32 -> Sc
                              n: jax.Array,           # [B, G] int64
                              alive: jax.Array,       # [B, E] bool
                              ) -> jax.Array:         # [B] bool
    def one_candidate(gids, cids, nb, alv):
        def step(used, xs):
            gi, ci, ng = xs
            Rg = R_tab[gi]                                   # [D]
            cg = compat_tab[ci] & alv                        # [E]
            Rsafe = jnp.where(Rg > 0, Rg, 1)
            q = (ex_alloc - used) // Rsafe[None, :]          # [E, D]
            q = jnp.where((Rg > 0)[None, :], q, BIG)
            k = jnp.clip(q.min(axis=-1), 0, BIG)             # [E]
            k = jnp.where(cg, k, 0)
            cum = jnp.cumsum(k) - k
            take = jnp.clip(ng - cum, 0, k)
            used = used + take[:, None] * Rg[None, :]
            return used, ng - take.sum()

        _, leftover = jax.lax.scan(step, ex_used0, (gids, cids, nb))
        return (leftover == 0).all()

    return jax.vmap(one_candidate)(gid, cid, n, alive)


@jax.jit
def replacements_prescreen_kernel(
        ex_alloc: jax.Array,    # [E, D] int64 shared node table
        ex_used0: jax.Array,    # [E, D] int64 shared
        compat_tab: jax.Array,  # [Sc, E] bool profile x node
        R_tab: jax.Array,       # [S, D] int64 per signature
        type_alloc: jax.Array,  # [T, D] int64 allocatable per catalog type
        type_price: jax.Array,  # [T] int64 cheapest available price (BIG
        #                         when the type has no available offering)
        tcompat: jax.Array,     # [Sc, T] bool profile x type (no req
        #                         conflict + an availability-compat offering)
        padmit: jax.Array,      # [P, Sc] bool pool admits profile
        #                         (requirements compatible, taints tolerated)
        pool_types: jax.Array,  # [P, T] bool type is in the pool's catalog
        gid: jax.Array,         # [B, G] int32 -> S
        cid: jax.Array,         # [B, G] int32 -> Sc
        n: jax.Array,           # [B, G] int64 pod count (0 => padded row)
        alive: jax.Array,       # [B, E] bool surviving nodes
        price_cap: jax.Array,   # [B] int64 strict upper price bound
) -> jax.Array:                 # [B] bool: False => replacement IMPOSSIBLE
    """Exact-NO / maybe-YES pre-screen for consolidation's replacement
    search: "do this batch's pods fit the remaining nodes plus at most ONE
    new node from the price-capped catalog?"

    The absorption half (scan over pod groups, greedy prefix fill in
    name-sorted node order) is bit-identical to the oracle's first-fit over
    existing nodes — leftovers are exact. The new-node half is a
    *relaxation* (a necessary condition for the oracle to succeed): one
    admitted type must hold the aggregate leftover. It ignores daemonset
    overhead, pool limits, minValues floors and cross-group requirement
    union narrowing, each of which can only shrink oracle feasibility —
    so a False here is proof the sequential simulate would fail
    (designs/consolidation.md:7-15 "Node Replacement"), while a True still
    gets the authoritative simulate. No false negatives => decisions are
    identical to the oracle; positives only cost one confirming solve.
    """
    def one_candidate(gids, cids, nb, alv, cap):
        def step(used, xs):
            gi, ci, ng = xs
            Rg = R_tab[gi]
            cg = compat_tab[ci] & alv
            Rsafe = jnp.where(Rg > 0, Rg, 1)
            q = (ex_alloc - used) // Rsafe[None, :]
            q = jnp.where((Rg > 0)[None, :], q, BIG)
            k = jnp.clip(q.min(axis=-1), 0, BIG)
            k = jnp.where(cg, k, 0)
            cum = jnp.cumsum(k) - k
            take = jnp.clip(ng - cum, 0, k)
            used = used + take[:, None] * Rg[None, :]
            return used, ng - take.sum()

        _, leftover = jax.lax.scan(step, ex_used0, (gids, cids, nb))
        active = leftover > 0                                    # [G]
        agg = (leftover[:, None] * R_tab[gids]).sum(axis=0)      # [D]
        # a type must be individually compatible with EVERY leftover group
        g_ok = (tcompat[cids] | ~active[:, None]).all(axis=0)    # [T]
        # ... and live in some pool that admits every leftover group
        p_ok = (padmit[:, cids].T | ~active[:, None]).all(axis=0)  # [P]
        from_pools = (p_ok[:, None] & pool_types).any(axis=0)    # [T]
        fits = (agg[None, :] <= type_alloc).all(axis=-1)         # [T]
        priced = type_price < cap                                # [T]
        ok = (g_ok & from_pools & fits & priced).any()
        return ok | ~active.any()

    return jax.vmap(one_candidate)(gid, cid, n, alive, price_cap)


@jax.jit
def deletions_feasible_dense(ex_alloc: jax.Array,   # [B, E, D] int64
                             ex_used0: jax.Array,   # [B, E, D] int64
                             ex_compat: jax.Array,  # [B, G, E] bool
                             R: jax.Array,          # [B, G, D] int64
                             n: jax.Array,          # [B, G] int64
                             ) -> jax.Array:        # [B] bool
    """General fallback: fully per-candidate tensors (used when candidates
    do not share a common node table — e.g. ad-hoc snapshots in tests)."""
    def one_candidate(alloc, used0, compat, Rb, nb):
        def step(used, xs):
            Rg, ng, cg = xs
            Rsafe = jnp.where(Rg > 0, Rg, 1)
            q = (alloc - used) // Rsafe[None, :]
            q = jnp.where((Rg > 0)[None, :], q, BIG)
            k = jnp.clip(q.min(axis=-1), 0, BIG)
            k = jnp.where(cg, k, 0)
            cum = jnp.cumsum(k) - k
            take = jnp.clip(ng - cum, 0, k)
            used = used + take[:, None] * Rg[None, :]
            return used, ng - take.sum()

        _, leftover = jax.lax.scan(step, used0, (Rb, nb, compat))
        return (leftover == 0).all()

    return jax.vmap(one_candidate)(ex_alloc, ex_used0, ex_compat, R, n)


#: subset_solve_kernel summary columns, one row per candidate subset
SUBSET_OUT_COLS = ("leftover", "num_nodes", "flex", "min_price", "savings")


@partial(jax.jit, static_argnames=("n_max", "E", "P"))
def subset_solve_kernel(
        # ---- shared union arena (one copy for the whole batch) --------
        A: jax.Array,            # [T, D] int64 catalog allocatable
        avail_zc: jax.Array,     # [T, Z*C] bool offering availability
        tprice: jax.Array,       # [T] int64 cheapest available price
        #                          (BIG when the type has no offering)
        R_tab: jax.Array,        # [G, D] int64 per union group row
        n_tab: jax.Array,        # [G] int64 (unused by lanes; keeps the
        #                          table set = KernelInputs group fields)
        F_tab: jax.Array,        # [G, T] bool
        agz_tab: jax.Array,      # [G, Z] bool
        agc_tab: jax.Array,      # [G, C] bool
        admit_tab: jax.Array,    # [G, P] bool
        daemon_tab: jax.Array,   # [G, P, D] int64
        excompat_tab: jax.Array,  # [G, E] bool
        pool_types: jax.Array,   # [P, T] bool
        pool_agz: jax.Array,     # [P, Z] bool
        pool_agc: jax.Array,     # [P, C] bool
        pool_limit: jax.Array,   # [P, D] int64
        pool_used0: jax.Array,   # [P, D] int64
        ex_alloc: jax.Array,     # [E, D] int64
        ex_used0: jax.Array,     # [E, D] int64
        # ---- per-candidate-subset lanes -------------------------------
        gid: jax.Array,          # [B, Gq] int32 -> union group rows
        n: jax.Array,            # [B, Gq] int64 pod count (0 = padding)
        dead: jax.Array,         # [B, E] bool: node is in the subset
        keep: jax.Array,         # [B, T] bool: type under the price cap
        removed_price: jax.Array,  # [B] int64 price of the deleted subset
        *, n_max: int, E: int, P: int) -> jax.Array:  # [B, 5] int64
    """Whole-fleet replacement search: one FFD re-solve of "cluster minus
    subset" per lane, vmapped over the subset axis.

    Every lane is a GATHERED, MASKED view of one shared union arena — the
    per-lane payload is O(Gq + E + T) index/mask words, never O(E*D)
    tensors, so a 1000-node round ships one node table, not a thousand.
    Masking is exactly removal for the scan (the exactness argument in
    docs/solver-design.md "Device-native consolidation"):

    - a dead existing node has ``ex_compat`` False everywhere, so its
      headroom row is forced to 0 and the greedy prefix fill skips it —
      identical to the row being absent;
    - a type over the price cap has its ``avail_zc`` row and F columns
      cleared, so it is never a fill candidate and never minted —
      identical to the price-filtered catalog the host oracle solves;
    - union-arena group rows / dims / pools a lane doesn't reference are
      inert (n=0 rows are no-op scan steps, extra dims carry R=0 and
      daemon=0, a fully type-masked pool can never open a node).

    Per-lane output is a 5-word summary (SUBSET_OUT_COLS): total leftover
    pods, new nodes opened, the minted node's surviving type flexibility
    and cheapest price, and the spot-aware cost delta
    ``removed_price - min_price`` (when exactly one node was minted) —
    the on-device objective the controller argmin/selects on without a
    host round trip per candidate."""
    # module-level import (not in-function): importing ffd_jax while this
    # kernel is being traced would create its module constants as tracers
    del n_tab  # lanes carry their own counts

    def lane(gids, nb, dd, kp, rp):
        inp = KernelInputs(
            A=A,
            avail_zc=avail_zc & kp[:, None],
            R=R_tab[gids],
            n=nb,
            F=F_tab[gids] & kp[None, :],
            agz=agz_tab[gids],
            agc=agc_tab[gids],
            admit=admit_tab[gids],
            daemon=daemon_tab[gids],
            pool_types=pool_types,
            pool_agz=pool_agz,
            pool_agc=pool_agc,
            pool_limit=pool_limit,
            pool_used0=pool_used0,
            ex_alloc=jnp.where(dd[:, None], 0, ex_alloc),
            ex_used0=jnp.where(dd[:, None], 0, ex_used0),
            ex_compat=excompat_tab[gids] & ~dd[None, :],
        )
        _takes, leftover, final = _solve(inp, n_max, E, P)
        nn = final.num_nodes.astype(jnp.int64)
        # evidence for the winning lane: the FIRST minted slot's narrowed
        # type mask — its surviving flexibility (spot floor evidence) and
        # cheapest price (the replacement's cost)
        t0 = final.types[E] & kp
        minted = nn > 0
        flex = jnp.where(minted, t0.sum(), 0).astype(jnp.int64)
        min_price = jnp.where(minted, jnp.where(t0, tprice, BIG).min(), 0)
        savings = rp - jnp.where(nn == 1, min_price, 0)
        return jnp.stack([leftover.sum(), nn, flex, min_price, savings])

    return jax.vmap(lane)(gid, n, dead, keep, removed_price)
