"""Batched consolidation kernel: deletion feasibility for many candidates
in ONE device call.

The disruption controller's hot inner loop asks, per candidate node c:
"do c's pods first-fit onto the remaining nodes?" (designs/
consolidation.md "Node Deletion": a simulated scheduling run against the
existing cluster). Sequentially that is O(candidates) solver calls; here
the candidate axis is just a batch dimension — one ``lax.scan`` over the
candidate's pod groups, ``vmap``-ed over candidates.

Transfer discipline (the Go↔sidecar serialization concern of SURVEY §7
"hard parts" #4, applied to host↔device): candidates share the cluster, so
the node axis is sent ONCE — shared ``ex_alloc/ex_used/compat_tab`` tables
— and each candidate carries only index vectors: which unique pod-group
signatures it moves (``gid``), how many pods (``n``), and which node rows
are dead for it (``alive``). Per-candidate payload is O(G + E) bytes, not
O(E·D) tensors; a 256-candidate × 300-node batch ships ~200KB instead of
~17MB.

Semantics per group: headroom per node = min_d floor((alloc - used)/R),
prefix-sum greedy fill in name-sorted node order — bit-identical to the
CPU oracle's first-fit over existing nodes (solver/cpu.py:243-258).
Feasible ⇔ every group's leftover is 0. All int64 (jax_enable_x64):
decisions match the oracle exactly
(tests/test_consolidation_equivalence.py).
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

jax.config.update("jax_enable_x64", True)

BIG = jnp.int64(1) << 60


@jax.jit
def deletions_feasible_kernel(ex_alloc: jax.Array,    # [E, D] int64 shared
                              ex_used0: jax.Array,    # [E, D] int64 shared
                              compat_tab: jax.Array,  # [Sc, E] bool per
                              #                         constraint profile
                              R_tab: jax.Array,       # [S, D] int64 per sig
                              gid: jax.Array,         # [B, G] int32 -> S
                              cid: jax.Array,         # [B, G] int32 -> Sc
                              n: jax.Array,           # [B, G] int64
                              alive: jax.Array,       # [B, E] bool
                              ) -> jax.Array:         # [B] bool
    def one_candidate(gids, cids, nb, alv):
        def step(used, xs):
            gi, ci, ng = xs
            Rg = R_tab[gi]                                   # [D]
            cg = compat_tab[ci] & alv                        # [E]
            Rsafe = jnp.where(Rg > 0, Rg, 1)
            q = (ex_alloc - used) // Rsafe[None, :]          # [E, D]
            q = jnp.where((Rg > 0)[None, :], q, BIG)
            k = jnp.clip(q.min(axis=-1), 0, BIG)             # [E]
            k = jnp.where(cg, k, 0)
            cum = jnp.cumsum(k) - k
            take = jnp.clip(ng - cum, 0, k)
            used = used + take[:, None] * Rg[None, :]
            return used, ng - take.sum()

        _, leftover = jax.lax.scan(step, ex_used0, (gids, cids, nb))
        return (leftover == 0).all()

    return jax.vmap(one_candidate)(gid, cid, n, alive)


@jax.jit
def deletions_feasible_dense(ex_alloc: jax.Array,   # [B, E, D] int64
                             ex_used0: jax.Array,   # [B, E, D] int64
                             ex_compat: jax.Array,  # [B, G, E] bool
                             R: jax.Array,          # [B, G, D] int64
                             n: jax.Array,          # [B, G] int64
                             ) -> jax.Array:        # [B] bool
    """General fallback: fully per-candidate tensors (used when candidates
    do not share a common node table — e.g. ad-hoc snapshots in tests)."""
    def one_candidate(alloc, used0, compat, Rb, nb):
        def step(used, xs):
            Rg, ng, cg = xs
            Rsafe = jnp.where(Rg > 0, Rg, 1)
            q = (alloc - used) // Rsafe[None, :]
            q = jnp.where((Rg > 0)[None, :], q, BIG)
            k = jnp.clip(q.min(axis=-1), 0, BIG)
            k = jnp.where(cg, k, 0)
            cum = jnp.cumsum(k) - k
            take = jnp.clip(ng - cum, 0, k)
            used = used + take[:, None] * Rg[None, :]
            return used, ng - take.sum()

        _, leftover = jax.lax.scan(step, used0, (Rb, nb, compat))
        return (leftover == 0).all()

    return jax.vmap(one_candidate)(ex_alloc, ex_used0, ex_compat, R, n)
