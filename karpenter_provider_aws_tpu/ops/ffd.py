"""Vectorized FFD packing kernels.

Two engines over the same math, decision-identical by construction:

- :func:`fill_group_closed_form` — the host (numpy) engine: one call per
  pod group in canonical order, mutating :class:`NodeState`.
- :func:`ops.ffd_jax.solve_scan` — the pure-device engine: one ``lax.scan`` over
  pod groups; the carry is the open-node state (candidate-type masks,
  zone/capacity-type masks, int64 request vectors, pool budgets); each step
  does the vectorized headroom + prefix-sum greedy fill + closed-form
  new-node creation. Compiled once per (G, N, T, Z, C, D, P) shape class.

The group fill math (identical in both engines)
-----------------------------------------------
For group g with per-pod request vector R and n pods:

1. slot admission: alive ∧ (existing-node compat OR pool-level admission of
   the group by the slot's pool)
2. candidate types per open slot: node_types ∧ F[g] ∧ "has an available
   offering inside the slot's merged (zone × capacity-type) allow-masks"
3. headroom k[slot] = max over candidate types of
   min_d floor((A[t,d] − used[slot,d]) / R[d])   (dims with R[d]=0 ignored),
   capped by the slot's pool limit budget
4. greedy FFD prefix fill: take[slot] = clip(n − cumsum_excl(k), 0, k)
5. leftovers open new nodes pool-by-pool (weight order): capacity per new
   node = max over admitted types of floor((A − daemon)/R); the final type
   mask of a node holding m pods is {t : headroom_t ≥ m} — exactly the
   narrowing the per-pod oracle produces.

Equivalence to the per-pod CPU oracle holds because the canonical pod order
keeps groups contiguous (solver/cpu.py::pod_sort_key) and all the above
counters are the closed forms of the oracle's per-pod loop.

The device engine's FUSED scan (ops/ffd_jax.py ``_solve_fused``) changes
none of this math: it only reorders the evaluation of fill phases across
groups the encoder proves pairwise disjoint on both contention axes
(admitted pools and compatible existing nodes — models/encoding.py
``independent_runs``), which therefore commute. This host twin stays the
per-group reference the fused kernel is fuzz-checked against.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..models.encoding import SnapshotEncoding

BIG = np.int64(1) << 60


@dataclass
class NodeState:
    """Mutable open-node state for the numpy engine. Slots [0, E) are
    existing cluster nodes; slots [E, N) are (potential) new nodes."""
    E: int
    N: int
    T: int
    D: int
    Z: int
    C: int
    used: np.ndarray          # [N, D] int64
    types: np.ndarray         # [N, T] bool (all-False rows for existing/free)
    zones: np.ndarray         # [N, Z] bool
    ct: np.ndarray            # [N, C] bool
    pool: np.ndarray          # [N] int32, -1 free, -2 existing
    alive: np.ndarray         # [N] bool
    num_nodes: int = 0        # new nodes created (slots E..E+num_nodes)
    ex_alloc: Optional[np.ndarray] = None   # [E, D]
    ex_compat: Optional[np.ndarray] = None  # [G, E] bool
    #: pods-per-slot per group: filled by the engines
    takes: List[np.ndarray] = field(default_factory=list)
    leftover: List[int] = field(default_factory=list)
    #: per-slot count of pods of the currently-processed scheduling group
    #: (topology bookkeeping, host engine only)
    pool_used: Optional[np.ndarray] = None  # [P, D]
    #: R-signature -> [N] bool "proven zero headroom for this request
    #: vector". Slot usage only grows within a solve, so fullness under an
    #: identical R transfers across pod groups — later groups skip the
    #: exact per-slot headroom recompute (topo._Pour lazy ensure).
    full_for: Dict[bytes, np.ndarray] = field(default_factory=dict)
    #: [N, D] per-slot capacity UPPER BOUND: max allocatable over the
    #: slot's candidate types at the last tightening. Safe to be stale
    #: HIGH (type masks only ever narrow, usage only grows), so mutation
    #: sites may skip updating it — the high-cardinality fast path
    #: (_fill_group_fast) just probes a few extra slots. BIG = unknown.
    cap_hint: Optional[np.ndarray] = None

    @staticmethod
    def create(enc: SnapshotEncoding, n_max: int,
               ex_alloc: np.ndarray, ex_used: np.ndarray,
               ex_compat: np.ndarray) -> "NodeState":
        E = ex_alloc.shape[0]
        T, D = enc.A.shape
        Z, C = len(enc.zones), enc.avail.shape[2]
        N = E + n_max
        st = NodeState(
            E=E, N=N, T=T, D=D, Z=Z, C=C,
            used=np.zeros((N, D), dtype=np.int64),
            types=np.zeros((N, T), dtype=bool),
            zones=np.zeros((N, Z), dtype=bool),
            ct=np.zeros((N, C), dtype=bool),
            pool=np.full(N, -1, dtype=np.int32),
            alive=np.zeros(N, dtype=bool),
            ex_alloc=ex_alloc, ex_compat=ex_compat,
            pool_used=np.stack([p.in_use_vec for p in enc.pools])
            if enc.pools else np.zeros((0, D), dtype=np.int64),
        )
        st.used[:E] = ex_used
        st.pool[:E] = -2
        st.alive[:E] = True
        st.cap_hint = np.full((N, D), BIG, dtype=np.int64)
        st.cap_hint[:E] = ex_alloc
        return st


def snapshot_state(st: NodeState) -> dict:
    """Checkpoint of everything a group fill mutates — the host twin of
    the device kernel's carry bank (solver/incremental.py). ``ex_alloc``
    / ``ex_compat`` are read-only inputs and deliberately not captured:
    any tick on which they move invalidates every checkpoint (dirty
    frontier 0) before a restore could alias them. ``full_for`` and
    ``cap_hint`` ARE captured — both are monotone caches whose state at
    group *i* depends on the fill history, and a resumed suffix must
    probe exactly what the from-scratch solve would have."""
    return dict(
        used=st.used.copy(), types=st.types.copy(),
        zones=st.zones.copy(), ct=st.ct.copy(), pool=st.pool.copy(),
        alive=st.alive.copy(), num_nodes=st.num_nodes,
        pool_used=st.pool_used.copy(),
        full_for={k: v.copy() for k, v in st.full_for.items()},
        cap_hint=None if st.cap_hint is None else st.cap_hint.copy())


def restore_state(st: NodeState, snap: dict) -> None:
    """Rewind ``st`` to a ``snapshot_state`` checkpoint, leaving the
    checkpoint pristine for future restores."""
    st.used[:] = snap["used"]
    st.types[:] = snap["types"]
    st.zones[:] = snap["zones"]
    st.ct[:] = snap["ct"]
    st.pool[:] = snap["pool"]
    st.alive[:] = snap["alive"]
    st.num_nodes = snap["num_nodes"]
    st.pool_used[:] = snap["pool_used"]
    st.full_for = {k: v.copy() for k, v in snap["full_for"].items()}
    if snap["cap_hint"] is None:
        st.cap_hint = None
    else:
        if st.cap_hint is None:
            st.cap_hint = snap["cap_hint"].copy()
        else:
            st.cap_hint[:] = snap["cap_hint"]


def _headroom(A_eff: np.ndarray, used: np.ndarray, R: np.ndarray) -> np.ndarray:
    """min_d floor((A_eff - used)/R) over dims with R>0; shapes broadcast.
    Result clipped at 0."""
    sel = R > 0
    if not sel.any():
        return np.full(np.broadcast_shapes(A_eff.shape[:-1], used.shape[:-1]),
                       BIG, dtype=np.int64)
    diff = A_eff[..., sel] - used[..., sel]
    q = np.floor_divide(diff, R[sel])
    return np.clip(q.min(axis=-1), 0, BIG)


def _mv_value_headroom(enc: SnapshotEncoding, cand: np.ndarray,
                       hr: np.ndarray) -> np.ndarray:
    """[..., K, V]: 1 + max headroom over candidate types carrying each
    minValues (key, value); 0 when no candidate type carries the value.
    Segment-max over the encoding's (type, value-id) pairs."""
    K, M = enc.mv_pairs_t.shape
    V = enc.mv_V
    hr1 = np.where(cand, hr + 1, 0)
    lead = hr1.shape[:-1]
    flat = hr1.reshape(-1, hr1.shape[-1])
    B = flat.shape[0]
    out = np.zeros((B, K, V + 1), dtype=np.int64)  # col V = pad dump
    rows = np.arange(B)[:, None]
    for k in range(K):
        contrib = flat[:, enc.mv_pairs_t[k]]           # [B, M]
        np.maximum.at(out[:, k, :], (rows, enc.mv_pairs_v[k][None, :]),
                      contrib)
    return out[:, :, :V].reshape(lead + (K, V))


def min_values_cap(enc: SnapshotEncoding, pi: int, cand: np.ndarray,
                   hr: np.ndarray) -> np.ndarray:
    """Max pods a node may take while its surviving candidate-type mask
    ``{t in cand : hr_t >= m}`` keeps every minValues floor of pool ``pi``
    (the closed form of core nodeclaim.Add's SatisfiesMinValues check):
    for floor f on a key, the cap is the f-th largest per-value max
    headroom. cand/hr: [..., T]; returns [...] int64 (BIG = no floors)."""
    lead = np.asarray(hr).shape[:-1]
    if enc.mv_floor is None or not enc.mv_floor[pi].any():
        return np.full(lead, BIG, dtype=np.int64)
    floors = enc.mv_floor[pi]
    h1 = _mv_value_headroom(enc, cand, hr)         # [..., K, V]
    S = -np.sort(-h1, axis=-1)
    cap = np.full(lead, BIG, dtype=np.int64)
    for k in range(enc.mv_K):
        f = int(floors[k])
        if f <= 0:
            continue
        if f > enc.mv_V:
            capk = np.full(lead, -1, dtype=np.int64)
        else:
            capk = S[..., k, f - 1] - 1
        cap = np.minimum(cap, capk)
    return np.maximum(cap, 0)


def _pool_budget(enc: SnapshotEncoding, pool_used: np.ndarray,
                 pi: int, R: np.ndarray) -> int:
    """Max additional pods of per-pod vector R pool pi's limits allow."""
    lim = enc.pools[pi].limit_vec
    if lim is None:
        return int(BIG)
    budget = int(BIG)
    for d in range(len(R)):
        if lim[d] >= 0 and R[d] > 0:
            budget = min(budget, max(0, (lim[d] - pool_used[pi, d])) // R[d])
    return budget


def slot_candidates(st: NodeState, enc: SnapshotEncoding, g: int,
                    agz: np.ndarray) -> np.ndarray:
    """[N, T] candidate types per open slot for group g (steps 1-2).
    Computed on the alive prefix only — slots beyond E+num_nodes have
    all-False type rows, and a solve with many groups would otherwise pay
    O(G * N * T) for dead slots."""
    n_act = st.E + st.num_nodes
    cand = np.zeros((st.N, enc.A.shape[0]), dtype=bool)
    if n_act == 0:
        return cand
    act = slice(0, n_act)
    c = st.types[act] & enc.F[g][None, :]
    zc = (st.zones[act] & agz[None, :])[:, :, None] \
        & (st.ct[act] & enc.agc[g][None, :])[:, None, :]     # [act, Z, C]
    off = np.tensordot(zc.reshape(n_act, -1),
                       enc.avail.reshape(enc.avail.shape[0], -1).T, axes=1) > 0
    cand[act] = c & off
    return cand


def slot_headroom(st: NodeState, enc: SnapshotEncoding, g: int,
                  cand: np.ndarray):
    """([N] max pods each slot can still absorb, per-type headroom info for
    the open rows) — step 3, before budgets. The second element is
    ``(open_mask[N], hr[open, T])`` (or None), reused by the minValues cap
    so the O(rows*T*D) headroom matrix is computed once."""
    R = enc.R[g]
    k = np.zeros(st.N, dtype=np.int64)
    hr_info = None
    # open slots: max over candidate types
    open_rows = cand.any(axis=1)
    if open_rows.any():
        hr = _headroom(enc.A[None, :, :], st.used[open_rows][:, None, :], R)
        k[open_rows] = np.where(cand[open_rows], hr, 0).max(axis=1)
        hr_info = (open_rows, hr)
    # existing slots: concrete allocatable + compat
    E = st.E
    if E:
        ex_ok = st.alive[:E] & st.ex_compat[g]
        if ex_ok.any():
            he = _headroom(st.ex_alloc[ex_ok], st.used[:E][ex_ok], R)
            k[:E][ex_ok] = he
    return k, hr_info


def admission(st: NodeState, enc: SnapshotEncoding, g: int) -> np.ndarray:
    """[N] bool — slot-level admission of group g (step 1)."""
    adm = st.alive.copy()
    E = st.E
    if E:
        adm[:E] &= st.ex_compat[g]
    open_sel = st.pool >= 0
    adm[open_sel] &= enc.admit[g][st.pool[open_sel]]
    return adm


def greedy_fill(k: np.ndarray, n: int) -> Tuple[np.ndarray, int]:
    """FFD prefix fill (step 4)."""
    cum = np.cumsum(k) - k
    take = np.clip(n - cum, 0, k)
    return take.astype(np.int64), int(n - take.sum())


def _off_any(enc: SnapshotEncoding, zmask: np.ndarray,
             cmask: np.ndarray) -> np.ndarray:
    """[T] has-an-available-offering under the (zone, ct) masks; cached on
    the encoding by mask bytes (slots share few distinct patterns)."""
    cache = getattr(enc, "_off_any_cache", None)
    if cache is None:
        cache = enc._off_any_cache = {}
    key = zmask.tobytes() + cmask.tobytes()
    off = cache.get(key)
    if off is None:
        off = cache[key] = (enc.avail & zmask[None, :, None]
                            & cmask[None, None, :]).any(axis=(1, 2))
    return off


def _fill_group_fast(st: NodeState, enc: SnapshotEncoding, g: int
                     ) -> Tuple[np.ndarray, int]:
    """The high-cardinality (G-axis) fast path of the closed form:
    identical decisions, O(probed slots) instead of O(N x T) per group.

    The full [N, T] candidate/headroom pass recomputes near-identical
    tensors for every group; at ~10k distinct pod signatures that O(G x
    N x T) dominates the solve (BASELINE config 7). FFD only ever
    consumes per-slot headroom in slot order until the group is placed,
    so this walk (a) prunes slots whose conservative capacity bound
    (cap_hint, stale-high-safe) cannot fit even one pod — provably k=0 —
    and (b) computes the exact [T] candidate/headroom row only for the
    few surviving probe slots, committing in the same slot order the
    prefix fill uses. Guards in fill_group_closed_form keep every
    override/minValues/pool-limit shape on the exact full pass."""
    n_rem = int(enc.n[g])
    R = enc.R[g]
    sel = R > 0
    Rsel = R[sel]
    take = np.zeros(st.N, dtype=np.int64)
    agz_g = enc.agz[g]
    agc_g = enc.agc[g]
    n_act = st.E + st.num_nodes
    if n_act and n_rem:
        adm = st.alive[:n_act].copy()
        if st.E:
            adm[:st.E] &= st.ex_compat[g]
        open_sel = st.pool[:n_act] >= 0
        adm[open_sel] &= enc.admit[g][st.pool[:n_act][open_sel]]
        if sel.any():
            room = (st.cap_hint[:n_act][:, sel]
                    - st.used[:n_act][:, sel]) >= Rsel[None, :]
            adm &= room.all(axis=1)
        for slot in np.nonzero(adm)[0]:
            slot = int(slot)
            if slot < st.E:
                k = int(_headroom(st.ex_alloc[slot], st.used[slot], R))
                crow = None
            else:
                crow = st.types[slot] & enc.F[g]
                if not crow.any():
                    continue
                crow = crow & _off_any(enc, st.zones[slot] & agz_g,
                                       st.ct[slot] & agc_g)
                if not crow.any():
                    continue
                hr = _headroom(enc.A, st.used[slot][None, :], R)
                k = int(np.where(crow, hr, 0).max())
            if k <= 0:
                continue
            m = min(k, n_rem)
            take[slot] = m
            n_rem -= m
            st.used[slot] += m * R
            if crow is not None:  # open slot: narrow + tighten the bound
                fit = (st.used[slot][None, :] <= enc.A).all(axis=1)
                st.types[slot] = crow & fit
                st.zones[slot] &= agz_g
                st.ct[slot] &= agc_g
                st.cap_hint[slot] = np.where(
                    st.types[slot][:, None], enc.A, 0).max(axis=0)
                pi = int(st.pool[slot])
                st.pool_used[pi] += m * R
            if n_rem == 0:
                return take, 0
    return _open_new_nodes(st, enc, g, n_rem, R, agz_g, agc_g, take)


def _open_new_nodes(st: NodeState, enc: SnapshotEncoding, g: int,
                    n_rem: int, R: np.ndarray, agz_g: np.ndarray,
                    agc_g: np.ndarray, take: np.ndarray
                    ) -> Tuple[np.ndarray, int]:
    """Step 5 — open new nodes pool-by-pool (weight order). The single
    Python implementation shared by the fast walk and the full closed
    form (the C twin in native/fastfill.cpp is the third copy and is
    fuzz-pinned to this one). Candidate masks are cached per
    (constraint-bytes, pool) on the encoding."""
    if not enc.pools:
        return take, n_rem
    cache = getattr(enc, "_cand_new_cache", None)
    if cache is None:
        cache = enc._cand_new_cache = {}
    for pe in enc.pools:
        if n_rem == 0:
            break
        pi = pe.index
        if not enc.admit[g, pi]:
            continue
        daemon = enc.daemon[g, pi]
        key = (enc.F[g].tobytes() + agz_g.tobytes() + agc_g.tobytes(), pi)
        ent = cache.get(key)
        if ent is None:
            agz_p = agz_g & pe.agz
            agc_p = agc_g & pe.agc
            if not agz_p.any() or not agc_p.any():
                cand_new = None
            else:
                cand_new = enc.F[g] & pe.type_rows \
                    & _off_any(enc, agz_p, agc_p)
                if not cand_new.any():
                    cand_new = None
            ent = cache[key] = (cand_new,
                                agz_p if cand_new is not None else None,
                                agc_p if cand_new is not None else None)
        cand_new, agz_p, agc_p = ent
        if cand_new is None:
            continue
        hr = _headroom(enc.A, daemon[None, :], R)
        hr = np.where(cand_new, hr, 0)
        cap = int(hr.max())
        if enc.mv_floor is not None and enc.mv_floor[pi].any():
            cap = min(cap, int(min_values_cap(enc, pi, cand_new, hr)))
        if cap < 1:
            continue
        budget = _pool_budget(enc, st.pool_used, pi, R)
        can_place = min(n_rem, budget)
        if can_place < 1:
            continue
        while can_place > 0 and st.num_nodes < st.N - st.E:
            slot = st.E + st.num_nodes
            m = min(cap, can_place)
            st.num_nodes += 1
            st.alive[slot] = True
            st.pool[slot] = pi
            st.used[slot] = daemon + m * R
            st.types[slot] = cand_new & (hr >= m)
            st.zones[slot] = agz_p
            st.ct[slot] = agc_p
            if st.cap_hint is not None:
                st.cap_hint[slot] = np.where(
                    st.types[slot][:, None], enc.A, 0).max(axis=0)
            take[slot] = m
            st.pool_used[pi] += m * R
            can_place -= m
            n_rem -= m
    return take, n_rem


def fill_group_closed_form(st: NodeState, enc: SnapshotEncoding, g: int,
                           n_override: Optional[int] = None,
                           agz_override: Optional[np.ndarray] = None,
                           slot_cap: Optional[np.ndarray] = None,
                           forbid_slots: Optional[np.ndarray] = None,
                           ) -> Tuple[np.ndarray, int]:
    """Steps 1-5 for one topology-free (sub)group. Mutates ``st``; returns
    (take[N], leftover). Overrides support the topology pre-pass: zone-
    restricted subgroups, per-slot pod caps (hostname spread), forbidden
    slots (hostname anti-affinity)."""
    if (n_override is None and agz_override is None and slot_cap is None
            and forbid_slots is None and enc.mv_floor is None
            and st.cap_hint is not None
            and all(pe.limit_vec is None for pe in enc.pools)):
        return _fill_group_fast(st, enc, g)
    n_rem = int(enc.n[g]) if n_override is None else n_override
    R = enc.R[g]
    agz_g = enc.agz[g] if agz_override is None else agz_override

    # ---- fill open + existing slots -----------------------------------
    cand = slot_candidates(st, enc, g, agz_g)
    adm = admission(st, enc, g)
    cand &= adm[:, None]
    k, hr_info = slot_headroom(st, enc, g, cand)
    k = np.where(adm, k, 0)
    # minValues floors cap per-slot takes BEFORE the budget prefix caps
    # (same order as the device kernel — min-composition order matters
    # because the budget caps are prefix sums over earlier slots' k)
    if enc.mv_floor is not None and hr_info is not None:
        open_mask, hr_open = hr_info
        pos = np.cumsum(open_mask) - 1  # slot index -> row in hr_open
        for pi in range(len(enc.pools)):
            if not enc.mv_floor[pi].any():
                continue
            rows = np.where((st.pool == pi) & open_mask & (k > 0))[0]
            if rows.size == 0:
                continue
            k[rows] = np.minimum(
                k[rows], min_values_cap(enc, pi, cand[rows],
                                        hr_open[pos[rows]]))
    # pool limit budgets cap fills pool-by-pool (node order preserved)
    for pi, pe in enumerate(enc.pools):
        if pe.limit_vec is None:
            continue
        rows = st.pool == pi
        if not rows.any():
            continue
        budget = _pool_budget(enc, st.pool_used, pi, R)
        kp = k[rows]
        cum = np.cumsum(kp) - kp
        k[rows] = np.clip(np.minimum(kp, budget - cum), 0, None)
    if slot_cap is not None:
        k = np.minimum(k, slot_cap)
    if forbid_slots is not None:
        k = np.where(forbid_slots, 0, k)
    take, n_rem = greedy_fill(k, n_rem)

    # commit fills
    filled = take > 0
    if filled.any():
        st.used[filled] += take[filled, None] * R[None, :]
        rows = np.where(filled & (st.pool >= 0))[0]
        for i in rows:
            # narrow: requirement intersection (cand) + refit vs new aggregate
            fit = (st.used[i][None, :] <= enc.A).all(axis=1)
            st.types[i] = cand[i] & fit
            st.zones[i] &= agz_g
            st.ct[i] &= enc.agc[g]
            pi = int(st.pool[i])
            st.pool_used[pi] += int(take[i]) * R
    if n_rem == 0 or not enc.pools:
        return take, n_rem
    return _open_new_nodes(st, enc, g, n_rem, R, agz_g, enc.agc[g], take)
