from . import ffd

__all__ = ["ffd"]
