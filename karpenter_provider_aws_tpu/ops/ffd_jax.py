"""The jitted FFD group-scan kernel.

One ``lax.scan`` over pod groups; the carry is the whole open-node state as
dense device arrays. Every step runs the group-fill math of ops/ffd.py
(identical closed forms) fully vectorized:

- headroom tensor  [N, T] = min_d floor((A - used) / R)  (masked dims → BIG)
- prefix-sum greedy fill across node slots
- closed-form new-node creation per pool (vectorized slot writes — no
  data-dependent Python control flow; the pool loop is static)

Shapes (N, T, Z, C, D, P, E) are static per snapshot class, so the kernel
compiles once and is reused across solve rounds while the catalog seqnum is
stable — the same cache-warmness discipline the reference applies to its
instance-type cache (instancetype.go:119-130).

Exactness: all quantities are int64 (``jax_enable_x64``); comparisons and
floor-divisions are bit-identical to the numpy engine, so decisions match
the CPU oracle exactly.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

jax.config.update("jax_enable_x64", True)

# Persistent XLA compilation cache: a tunneled-TPU healthy window is
# rare and short (BASELINE.md "device-engine truth"), and first compiles
# cost 20-40s each. Caching compiled executables on disk means compiles
# done in ONE healthy window carry across processes — so a ~5-minute
# window is enough for the device-evidence capture to serve fully timed
# rounds on every bench shape bucket. Shared by every kernel module
# (topo/mesh import this one).
import os as _os  # noqa: E402

_CACHE_DIR = _os.environ.get(
    "KARPENTER_JAX_CACHE",
    _os.path.join(_os.path.dirname(_os.path.dirname(_os.path.dirname(
        _os.path.abspath(__file__)))), ".jax_cache"))
try:
    jax.config.update("jax_compilation_cache_dir", _CACHE_DIR)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
except Exception:  # older jax without the knobs: in-memory cache only
    pass

BIG = jnp.int64(1) << 60


def _cumsum(x: jax.Array) -> jax.Array:
    """Exclusive-free prefix sum via associative_scan. Bit-identical to
    jnp.cumsum for integers, but lowers to log-depth slices instead of a
    reduce-window — the reduce-window lowering of emulated int64 blows the
    TPU scoped-vmem budget in this kernel's fusion context."""
    return jax.lax.associative_scan(jnp.add, x)


class KernelInputs(NamedTuple):
    """Static-shape device arrays for one solve."""
    # catalog
    A: jax.Array          # [T, D] int64 allocatable
    avail_zc: jax.Array   # [T, Z*C] bool (flattened offerings availability)
    # groups (scanned)
    R: jax.Array          # [G, D] int64
    n: jax.Array          # [G] int64
    F: jax.Array          # [G, T] bool
    agz: jax.Array        # [G, Z] bool
    agc: jax.Array        # [G, C] bool
    admit: jax.Array      # [G, P] bool
    daemon: jax.Array     # [G, P, D] int64
    # pools
    pool_types: jax.Array  # [P, T] bool
    pool_agz: jax.Array    # [P, Z] bool
    pool_agc: jax.Array    # [P, C] bool
    pool_limit: jax.Array  # [P, D] int64 (-1 = unlimited)
    pool_used0: jax.Array  # [P, D] int64
    # existing nodes
    ex_alloc: jax.Array    # [E, D] int64
    ex_used0: jax.Array    # [E, D] int64
    ex_compat: jax.Array   # [G, E] bool
    # minValues floors (None when no pool carries a floor). Membership is
    # (type, value-id) pairs per key driving a segment-max; pair type
    # indices are GLOBAL and localized per shard inside the kernel.
    mv_floor: "jax.Array | None" = None    # [P, K] int64 (0 = no floor)
    mv_pairs_t: "jax.Array | None" = None  # [K, M] int64
    mv_pairs_v: "jax.Array | None" = None  # [K, M] int64 (pad = V)


class Carry(NamedTuple):
    used: jax.Array       # [N, D]
    types: jax.Array      # [N, T]
    zones: jax.Array      # [N, Z]
    ct: jax.Array         # [N, C]
    pool: jax.Array       # [N] int32 (-1 free, -2 existing)
    alive: jax.Array      # [N] bool
    num_nodes: jax.Array  # scalar int32
    pool_used: jax.Array  # [P, D]


def _headroom_matrix(A: jax.Array, used: jax.Array, R: jax.Array) -> jax.Array:
    """[N, T] per-type pod headroom per slot."""
    Rsafe = jnp.where(R > 0, R, 1)
    q = (A[None, :, :] - used[:, None, :]) // Rsafe[None, None, :]   # [N,T,D]
    q = jnp.where((R > 0)[None, None, :], q, BIG)
    return jnp.clip(q.min(axis=-1), 0, BIG)                          # [N,T]


def _mv_h1(hr1: jax.Array, pairs_t: jax.Array, pairs_v: jax.Array,
           V: int, T: int, axis: "str | None") -> jax.Array:
    """[..., K, V] per-value max of ``hr1`` (= headroom+1 over candidates,
    0 = not a candidate) via segment-max over membership pairs. Pair type
    indices are global; each shard contributes only its local types — the
    caller pmax-reduces across shards."""
    off = jax.lax.axis_index(axis) * T if axis is not None else 0
    K, _M = pairs_t.shape
    cols = []
    for k in range(K):
        tloc = pairs_t[k] - off
        valid = (tloc >= 0) & (tloc < T)
        gathered = jnp.where(valid,
                             hr1[..., jnp.clip(tloc, 0, T - 1)], 0)  # [..,M]
        seg = jax.ops.segment_max(
            jnp.moveaxis(gathered, -1, 0), pairs_v[k],
            num_segments=V + 1)[:V]                                  # [V,..]
        cols.append(jnp.clip(jnp.moveaxis(seg, 0, -1), 0, None))     # [..,V]
    return jnp.stack(cols, axis=-2)                                  # [..,K,V]


def _mv_cap(h1: jax.Array, f: jax.Array, V: int) -> jax.Array:
    """[...] max take m keeping, per key, at least f distinct values with
    per-value max headroom >= m: the f-th largest of the per-value maxima.
    h1: [..., K, V] (headroom+1); f: [..., K] floors (0 = none)."""
    if V == 0:
        capk = jnp.where(f <= 0, BIG, -1)
    else:
        S = -jnp.sort(-h1, axis=-1)                                  # desc
        idx = jnp.clip(f - 1, 0, V - 1)
        val = jnp.take_along_axis(S, idx[..., None], axis=-1)[..., 0]
        capk = jnp.where(f <= 0, BIG, jnp.where(f > V, -1, val - 1))
    return jnp.maximum(capk.min(axis=-1), 0)


def _headroom_vec(A_eff: jax.Array, base: jax.Array, R: jax.Array) -> jax.Array:
    """[rows] headroom of concrete capacity rows (existing nodes / new-node
    capacity): min_d floor((A_eff - base)/R)."""
    Rsafe = jnp.where(R > 0, R, 1)
    q = (A_eff - base) // Rsafe[None, :]
    q = jnp.where((R > 0)[None, :], q, BIG)
    return jnp.clip(q.min(axis=-1), 0, BIG)


@partial(jax.jit, static_argnames=("n_max", "E", "P", "V"))
def solve_scan(inp: KernelInputs, n_max: int, E: int, P: int, V: int = 0
               ) -> Tuple[jax.Array, jax.Array, Carry]:
    """Returns (takes[G, N], leftover[G], final carry)."""
    return _solve(inp, n_max, E, P, V=V)


def _solve(inp: KernelInputs, n_max: int, E: int, P: int,
           axis: "str | None" = None, V: int = 0
           ) -> Tuple[jax.Array, jax.Array, Carry]:
    """The scan. With ``axis`` set, the TYPE dimension of every input is a
    per-device shard under shard_map over that mesh axis: candidate masks
    and headrooms are computed on local type shards and the two cross-type
    max-reductions ride pmax over ICI; the (tiny) node-state carry stays
    replicated. This is the tensor-parallel split of the solver — the type
    axis is embarrassingly wide (full EC2 catalog) while the carry is a
    few KB. See parallel/mesh.py for the mesh wrapper."""
    T, D = inp.A.shape
    Z = inp.agz.shape[1]
    C = inp.agc.shape[1]
    N = E + n_max

    carry0 = Carry(
        used=jnp.zeros((N, D), jnp.int64).at[:E].set(inp.ex_used0),
        types=jnp.zeros((N, T), bool),
        zones=jnp.zeros((N, Z), bool),
        ct=jnp.zeros((N, C), bool),
        pool=jnp.full((N,), -1, jnp.int32).at[:E].set(-2),
        alive=jnp.zeros((N,), bool).at[:E].set(True),
        num_nodes=jnp.int32(0),
        pool_used=inp.pool_used0,
    )

    slot_idx = jnp.arange(N)

    def step(carry: Carry, xs):
        return plain_group_step(inp, carry, xs, axis=axis, P=P, E=E, N=N,
                                V=V, slot_idx=slot_idx)

    xs = (inp.R, inp.n, inp.F, inp.agz, inp.agc, inp.admit, inp.daemon,
          inp.ex_compat)
    final, (takes, leftover) = jax.lax.scan(step, carry0, xs)
    return takes, leftover, final


def plain_group_step(inp: KernelInputs, carry: Carry, xs, *, axis, P, E, N,
                     V, slot_idx):
    """One scan step of the closed-form (topology-free) group fill —
    factored out so the topology kernel (ops/topo_jax.py) runs the same
    math for its non-topology groups, sharing this single implementation
    with the plain kernel."""
    R, n, F, agz, agc, admit, daemon, ex_compat = xs
    T, D = inp.A.shape
    Z = inp.agz.shape[1]
    C = inp.agc.shape[1]
    n_rem = n

    # ---- candidate types per open slot (steps 1-2) ----------------
    zc = ((carry.zones & agz[None, :])[:, :, None]
          & (carry.ct & agc[None, :])[:, None, :]).reshape(N, Z * C)
    off_ok = (zc.astype(jnp.int32) @ inp.avail_zc.T.astype(jnp.int32)) > 0
    pool_clipped = jnp.clip(carry.pool, 0, P - 1)
    adm_open = jnp.where(carry.pool >= 0, admit[pool_clipped], False)
    cand = carry.types & F[None, :] & off_ok & adm_open[:, None]

    # ---- headroom (step 3) ---------------------------------------
    hr_nt = _headroom_matrix(inp.A, carry.used, R)
    k = jnp.where(cand, hr_nt, 0).max(axis=1)
    if axis is not None:
        k = jax.lax.pmax(k, axis)   # max over type shards
    if E:
        ex_ok = carry.alive[:E] & ex_compat
        k_ex = jnp.where(ex_ok, _headroom_vec(inp.ex_alloc, carry.used[:E], R), 0)
        k = k.at[:E].set(k_ex)
    # minValues floors cap per-slot takes BEFORE the budget prefix
    # caps (ops/ffd.py applies the same order)
    if inp.mv_floor is not None:
        hr1 = jnp.where(cand, hr_nt + 1, 0)
        h1 = _mv_h1(hr1, inp.mv_pairs_t, inp.mv_pairs_v, V, T, axis)
        if axis is not None:
            h1 = jax.lax.pmax(h1, axis)
        f = jnp.where((carry.pool >= 0)[:, None],
                      inp.mv_floor[pool_clipped], 0)        # [N, K]
        k = jnp.minimum(k, jnp.where(carry.pool >= 0,
                                     _mv_cap(h1, f, V), BIG))
    # pool limit budgets: cap per-pool prefix fills
    pool_used = carry.pool_used
    for pi in range(P):
        has_limit = (inp.pool_limit[pi] >= 0).any()
        budget = _pool_budget_jax(inp.pool_limit[pi], pool_used[pi], R)
        rows = carry.pool == pi
        kp = jnp.where(rows, k, 0)
        cum = _cumsum(kp) - kp
        capped = jnp.clip(jnp.minimum(kp, budget - cum), 0, None)
        k = jnp.where(rows & has_limit, capped, k)

    # ---- greedy prefix fill (step 4) ------------------------------
    cum = _cumsum(k) - k
    take = jnp.clip(n_rem - cum, 0, k)
    n_rem = n_rem - take.sum()

    used = carry.used + take[:, None] * R[None, :]
    filled_open = (take > 0) & (carry.pool >= 0)
    fit_all = (used[:, None, :] <= inp.A[None, :, :]).all(axis=-1)
    types = jnp.where(filled_open[:, None], cand & fit_all, carry.types)
    zones = jnp.where(filled_open[:, None], carry.zones & agz[None, :], carry.zones)
    ct = jnp.where(filled_open[:, None], carry.ct & agc[None, :], carry.ct)
    take_by_pool = jax.ops.segment_sum(
        take, pool_clipped * (carry.pool >= 0) + (carry.pool < 0) * P,
        num_segments=P + 1)[:P]
    pool_used = pool_used + take_by_pool[:, None] * R[None, :]

    # ---- new nodes pool-by-pool (step 5) --------------------------
    pool_arr = carry.pool
    alive = carry.alive
    num_nodes = carry.num_nodes
    for pi in range(P):
        agz_p = agz & inp.pool_agz[pi]
        agc_p = agc & inp.pool_agc[pi]
        zc_p = (agz_p[:, None] & agc_p[None, :]).reshape(Z * C)
        off_p = (inp.avail_zc & zc_p[None, :]).any(axis=1)
        cand_new = F & inp.pool_types[pi] & off_p
        hr = _headroom_vec(inp.A, daemon[pi][None, :], R)
        hr = jnp.where(cand_new, hr, 0)
        cap = hr.max()
        if axis is not None:
            cap = jax.lax.pmax(cap, axis)
        if inp.mv_floor is not None:
            h1n = _mv_h1(jnp.where(cand_new, hr + 1, 0),
                         inp.mv_pairs_t, inp.mv_pairs_v, V, T, axis)
            if axis is not None:
                h1n = jax.lax.pmax(h1n, axis)
            cap = jnp.minimum(cap, _mv_cap(h1n, inp.mv_floor[pi], V))
        budget = _pool_budget_jax(inp.pool_limit[pi], pool_used[pi], R)
        can_place = jnp.where(
            admit[pi] & (cap >= 1), jnp.minimum(n_rem, budget), 0)
        # q new nodes: full nodes of `cap` + one partial
        q = jnp.where(can_place > 0, -(-can_place // jnp.maximum(cap, 1)), 0)
        free_slots = N - E - num_nodes
        q = jnp.minimum(q, free_slots)
        placed = jnp.minimum(can_place, q * cap)
        start = E + num_nodes
        is_new = (slot_idx >= start) & (slot_idx < start + q)
        # pods per new slot: cap, except the last gets the remainder
        offset = slot_idx - start
        m_slot = jnp.where(
            is_new,
            jnp.where(offset == q - 1, placed - cap * (q - 1), cap), 0)
        take = take + m_slot
        used = used + m_slot[:, None] * R[None, :] \
            + is_new[:, None] * daemon[pi][None, :]
        hr_fit = (hr[None, :] >= m_slot[:, None]) & cand_new[None, :]
        types = jnp.where(is_new[:, None], hr_fit, types)
        zones = jnp.where(is_new[:, None], agz_p[None, :], zones)
        ct = jnp.where(is_new[:, None], agc_p[None, :], ct)
        pool_arr = jnp.where(is_new, pi, pool_arr)
        alive = alive | is_new
        num_nodes = num_nodes + q.astype(jnp.int32)
        pool_used = pool_used.at[pi].add(placed * R)
        n_rem = n_rem - placed

    new_carry = Carry(used=used, types=types, zones=zones, ct=ct,
                      pool=pool_arr, alive=alive, num_nodes=num_nodes,
                      pool_used=pool_used)
    return new_carry, (take, n_rem)


def _pool_budget_jax(limit: jax.Array, used: jax.Array, R: jax.Array) -> jax.Array:
    """Max additional pods the pool's limits allow (BIG if unlimited)."""
    active = (limit >= 0) & (R > 0)
    Rsafe = jnp.where(R > 0, R, 1)
    per_dim = jnp.where(active, jnp.clip(limit - used, 0, None) // Rsafe, BIG)
    return per_dim.min()


# ---------------------------------------------------------------------------
# Packed I/O path: the TPU sits behind a network tunnel, so PER-TRANSFER
# round-trip latency dominates end-to-end solve time (measured ~5ms h2d and
# far worse d2h per array vs ~30KB of actual payload). All 17 inputs ride
# ONE int64 buffer (bool tensors bitpacked into words — see the
# single-buffer section below), and all outputs ride ONE int64 buffer
# back. The layout lists below are the single source of truth for both
# sides; ``_split`` is the only buffer walker.
# ---------------------------------------------------------------------------

from .hostpack import (in_layout_bool as _in_layout_bool,  # noqa: E402
                       in_layout_i64 as _in_layout_i64,
                       layout_sizes as _layout_sizes,
                       nwords as _nwords, out_layout, pack_inputs1,
                       split as _split, unpack_outputs1)


def _unpack_inputs(buf_i64: jax.Array, buf_bool: jax.Array,
                   T, D, Z, C, G, E, P, K=0, M=0) -> KernelInputs:
    vals = _split(buf_i64, _in_layout_i64(T, D, Z, C, G, E, P, K, M))
    vals.update(_split(buf_bool, _in_layout_bool(T, D, Z, C, G, E, P, K, M)))
    if K == 0:
        for nm in ("mv_floor", "mv_pairs_t", "mv_pairs_v"):
            vals.pop(nm, None)
    return KernelInputs(**vals)


# ---------------------------------------------------------------------------
# Single-buffer path. Each device round trip costs ~30-65ms of tunnel
# latency regardless of payload, and enqueues pipeline without acks — so
# the optimal shape is ONE int64 h2d buffer (bools bitpacked into words),
# an async dispatch, and ONE synchronous d2h fetch that rides the same
# wait as the execution. Bit packing is little-endian on both sides
# (host: native codec / np.packbits(bitorder='little'); device:
# arithmetic shifts), so no memory-layout assumptions cross the wire.
# The host half lives in ops/hostpack.py (numpy-only, jax-free) so the
# sidecar's control-plane side never imports jax.
# ---------------------------------------------------------------------------

def _bits_to_words(bits: jax.Array) -> jax.Array:
    """Device: flat bool [n*64] -> int64 words via arithmetic packing."""
    w = bits.reshape(-1, 64).astype(jnp.uint64)
    weights = jnp.left_shift(jnp.uint64(1), jnp.arange(64, dtype=jnp.uint64))
    packed = (w * weights[None, :]).sum(axis=1, dtype=jnp.uint64)
    return jax.lax.bitcast_convert_type(packed, jnp.int64)


def _words_to_bits(words: jax.Array, nbits: int) -> jax.Array:
    """Device: int64 words -> flat bool [nbits]."""
    w = jax.lax.bitcast_convert_type(words, jnp.uint64)
    shifts = jnp.arange(64, dtype=jnp.uint64)
    bits = jnp.right_shift(w[:, None], shifts[None, :]) & jnp.uint64(1)
    return bits.reshape(-1)[:nbits].astype(bool)


@partial(jax.jit, static_argnames=("T", "D", "Z", "C", "G", "E", "P",
                                   "K", "V", "M", "n_max"))
def solve_scan_packed1(buf: jax.Array, *, T: int, D: int, Z: int, C: int,
                       G: int, E: int, P: int, n_max: int,
                       K: int = 0, V: int = 0, M: int = 0) -> jax.Array:
    """One buffer in, one buffer out — a solve is a single round trip."""
    n_i64 = _layout_sizes(_in_layout_i64(T, D, Z, C, G, E, P, K, M))
    n_bits = _layout_sizes(_in_layout_bool(T, D, Z, C, G, E, P, K, M))
    bool_flat = _words_to_bits(buf[n_i64:n_i64 + _nwords(n_bits)], n_bits)
    inp = _unpack_inputs(buf[:n_i64], bool_flat, T, D, Z, C, G, E, P, K, M)
    takes, leftover, carry = _solve(inp, n_max, E, P, V=V)
    out_i64 = jnp.concatenate([
        takes.reshape(-1), leftover.reshape(-1),
        carry.used.reshape(-1), carry.pool.astype(jnp.int64),
        carry.num_nodes.reshape(1).astype(jnp.int64),
        carry.pool_used.reshape(-1)])
    out_bool = jnp.concatenate([
        carry.types.reshape(-1), carry.zones.reshape(-1),
        carry.ct.reshape(-1), carry.alive])
    nb = out_bool.shape[0]
    pad = _nwords(nb) * 64 - nb
    out_words = _bits_to_words(jnp.concatenate(
        [out_bool, jnp.zeros(pad, bool)]))
    return jnp.concatenate([out_i64, out_words])
