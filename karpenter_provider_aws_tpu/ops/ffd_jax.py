"""The jitted FFD group-scan kernel.

One ``lax.scan`` over pod groups; the carry is the whole open-node state as
dense device arrays. Every step runs the group-fill math of ops/ffd.py
(identical closed forms) fully vectorized:

- headroom tensor  [N, T] = min_d floor((A - used) / R)  (masked dims → BIG)
- prefix-sum greedy fill across node slots
- closed-form new-node creation per pool (vectorized slot writes — no
  data-dependent Python control flow; the pool loop is static)

Shapes (N, T, Z, C, D, P, E) are static per snapshot class, so the kernel
compiles once and is reused across solve rounds while the catalog seqnum is
stable — the same cache-warmness discipline the reference applies to its
instance-type cache (instancetype.go:119-130).

Exactness: resource quantities and headrooms are int64
(``jax_enable_x64`` — BIG sentinels and byte-scale quantities overflow
int32); comparisons and floor-divisions are bit-identical to the numpy
engine, so decisions match the CPU oracle exactly. Bookkeeping outputs
whose range is bounded by the POD COUNT (the per-slot ``takes``) are
carried int32 on the wire — two lanes per int64 word — halving the
dominant [G, N] d2h tensor without touching any decision-bearing
comparison.

Inert padding: the kernel guarantees that enlarging any static axis
with neutral elements cannot change a decision, which is what lets the
host packer pad G/E/n_max and the sidecar's bucketing layer
(tenancy/bucketing.py) pad every bucketable axis to a shared shape
class. The guarantee is structural, not incidental — every read path
has a masking guard the neutral element hits:

- a group with ``n=0`` and all-False masks scans through without
  taking a slot or opening a node (the fill prefix-sum is 0 and the
  new-node count is 0);
- a type with ``A=0``/all-False availability never survives the
  candidate mask, because eligibility ANDs F, avail_zc, agz, agc and
  pool_types before any headroom compare;
- a zero-allocatable existing row's headroom is floor(0/R) = 0 with
  ``ex_compat=False`` masking it besides — a dead row is never chosen;
- an all-zero ``R`` column contributes ``BIG`` (masked) to every
  min-over-dims headroom, so new resource dims with no demand never
  constrain a fit; pool budgets treat ``limit=-1`` as unlimited in
  those columns.

Any new read path added to the kernel must preserve these guards —
tests/test_tenancy.py fuzzes bucket-padded solves against solo solves
for byte-identical outputs, and will catch a violation.

Fused-group scan (``_solve_fused``): the encoder's run detection
(models/encoding.py independent_runs) marks maximal runs of groups whose
admit rows — and, when existing nodes are present, ex_compat rows — are
pairwise disjoint. Disjoint groups cannot contend for any slot, any
existing node, or any pool budget, so their fill phases (steps 1-4)
commute: the kernel scans BLOCKS of F groups and, when a block lies
inside one run, computes all F fill phases from the block-start carry in
one vmapped pass and merges the disjoint deltas. New-node creation
(step 5) stays sequential within the block either way — slot indices
are ordinal in ``num_nodes``. Blocks that straddle runs unroll the F
plain steps sequentially inside the block, so the scan trip count drops
F-fold unconditionally; the vectorized branch additionally collapses
the per-group latency chain on run-heavy snapshots.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_enable_x64", True)

# Persistent XLA compilation cache: a tunneled-TPU healthy window is
# rare and short (BASELINE.md "device-engine truth"), and first compiles
# cost 20-40s each. Caching compiled executables on disk means compiles
# done in ONE healthy window carry across processes — so a ~5-minute
# window is enough for the device-evidence capture to serve fully timed
# rounds on every bench shape bucket. Shared by every kernel module
# (topo/mesh import this one).
import os as _os  # noqa: E402

_CACHE_DIR = _os.environ.get(
    "KARPENTER_JAX_CACHE",
    _os.path.join(_os.path.dirname(_os.path.dirname(_os.path.dirname(
        _os.path.abspath(__file__)))), ".jax_cache"))
try:
    # a DEFAULT, not a mandate: the sidecar server configures an
    # explicit (possibly shared) cache dir at startup via
    # tenancy/compilecache.py, and this module imports lazily at first
    # solve — after that configuration, which must win
    if jax.config.jax_compilation_cache_dir is None:
        jax.config.update("jax_compilation_cache_dir", _CACHE_DIR)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.5)
except Exception:  # older jax without the knobs: in-memory cache only
    pass

# a host scalar, NOT jnp: a module-level jnp computation initializes
# the jax backend at import time, which forecloses everything that must
# run first in a worker process — jax.distributed.initialize (the
# multi-process mesh, parallel/distmesh.py), platform pins, device-count
# flags. np.int64 binds into traced code with the identical int64 value.
BIG = np.int64(1) << 60


def _axis_max(x: jax.Array, axis: "str | None", sum_only: bool) -> jax.Array:
    """Cross-shard max over the mesh axis (identity when ``axis`` is
    None, i.e. the single-device kernel). Native ``pmax`` by default;
    with ``sum_only`` the exact max is an ``all_gather`` + local max —
    the tunneled axon AOT backend cannot lower a Max all-reduce (int64
    pmax fails there with "Supported lowering only of Sum all reduce",
    and int64 is non-negotiable in this kernel: BIG sentinels and
    byte-scale resource quantities overflow int32) but AllGather is a
    different HLO and lowers fine. Exact integer math either way, so
    decisions are unchanged; bandwidth is S× on KB-scale buffers,
    latency-dominated either way."""
    if axis is None:
        return x
    if not sum_only:
        return jax.lax.pmax(x, axis)
    return jax.lax.all_gather(x, axis).max(axis=0)


def _cumsum(x: jax.Array) -> jax.Array:
    """Exclusive-free prefix sum via associative_scan. Bit-identical to
    jnp.cumsum for integers, but lowers to log-depth slices instead of a
    reduce-window — the reduce-window lowering of emulated int64 blows the
    TPU scoped-vmem budget in this kernel's fusion context."""
    return jax.lax.associative_scan(jnp.add, x)


class KernelInputs(NamedTuple):
    """Static-shape device arrays for one solve."""
    # catalog
    A: jax.Array          # [T, D] int64 allocatable
    avail_zc: jax.Array   # [T, Z*C] bool (flattened offerings availability)
    # groups (scanned)
    R: jax.Array          # [G, D] int64
    n: jax.Array          # [G] int64
    F: jax.Array          # [G, T] bool
    agz: jax.Array        # [G, Z] bool
    agc: jax.Array        # [G, C] bool
    admit: jax.Array      # [G, P] bool
    daemon: jax.Array     # [G, P, D] int64
    # pools
    pool_types: jax.Array  # [P, T] bool
    pool_agz: jax.Array    # [P, Z] bool
    pool_agc: jax.Array    # [P, C] bool
    pool_limit: jax.Array  # [P, D] int64 (-1 = unlimited)
    pool_used0: jax.Array  # [P, D] int64
    # existing nodes
    ex_alloc: jax.Array    # [E, D] int64
    ex_used0: jax.Array    # [E, D] int64
    ex_compat: jax.Array   # [G, E] bool
    # minValues floors (None when no pool carries a floor). Membership is
    # (type, value-id) pairs per key driving a segment-max; pair type
    # indices are GLOBAL and localized per shard inside the kernel.
    mv_floor: "jax.Array | None" = None    # [P, K] int64 (0 = no floor)
    mv_pairs_t: "jax.Array | None" = None  # [K, M] int64
    mv_pairs_v: "jax.Array | None" = None  # [K, M] int64 (pad = V)


class Carry(NamedTuple):
    used: jax.Array       # [N, D]
    types: jax.Array      # [N, T]
    zones: jax.Array      # [N, Z]
    ct: jax.Array         # [N, C]
    pool: jax.Array       # [N] int32 (-1 free, -2 existing)
    alive: jax.Array      # [N] bool
    num_nodes: jax.Array  # scalar int32
    pool_used: jax.Array  # [P, D]


def _headroom_matrix(A: jax.Array, used: jax.Array, R: jax.Array) -> jax.Array:
    """[N, T] per-type pod headroom per slot."""
    Rsafe = jnp.where(R > 0, R, 1)
    q = (A[None, :, :] - used[:, None, :]) // Rsafe[None, None, :]   # [N,T,D]
    q = jnp.where((R > 0)[None, None, :], q, BIG)
    return jnp.clip(q.min(axis=-1), 0, BIG)                          # [N,T]


def _mv_h1(hr1: jax.Array, pairs_t: jax.Array, pairs_v: jax.Array,
           V: int, T: int, axis: "str | None") -> jax.Array:
    """[..., K, V] per-value max of ``hr1`` (= headroom+1 over candidates,
    0 = not a candidate) via segment-max over membership pairs. Pair type
    indices are global; each shard contributes only its local types — the
    caller pmax-reduces across shards."""
    off = jax.lax.axis_index(axis) * T if axis is not None else 0
    K, _M = pairs_t.shape
    cols = []
    for k in range(K):
        tloc = pairs_t[k] - off
        valid = (tloc >= 0) & (tloc < T)
        gathered = jnp.where(valid,
                             hr1[..., jnp.clip(tloc, 0, T - 1)], 0)  # [..,M]
        seg = jax.ops.segment_max(
            jnp.moveaxis(gathered, -1, 0), pairs_v[k],
            num_segments=V + 1)[:V]                                  # [V,..]
        cols.append(jnp.clip(jnp.moveaxis(seg, 0, -1), 0, None))     # [..,V]
    return jnp.stack(cols, axis=-2)                                  # [..,K,V]


def _mv_cap(h1: jax.Array, f: jax.Array, V: int) -> jax.Array:
    """[...] max take m keeping, per key, at least f distinct values with
    per-value max headroom >= m: the f-th largest of the per-value maxima.
    h1: [..., K, V] (headroom+1); f: [..., K] floors (0 = none)."""
    if V == 0:
        capk = jnp.where(f <= 0, BIG, -1)
    else:
        S = -jnp.sort(-h1, axis=-1)                                  # desc
        idx = jnp.clip(f - 1, 0, V - 1)
        val = jnp.take_along_axis(S, idx[..., None], axis=-1)[..., 0]
        capk = jnp.where(f <= 0, BIG, jnp.where(f > V, -1, val - 1))
    return jnp.maximum(capk.min(axis=-1), 0)


def _headroom_vec(A_eff: jax.Array, base: jax.Array, R: jax.Array) -> jax.Array:
    """[rows] headroom of concrete capacity rows (existing nodes / new-node
    capacity): min_d floor((A_eff - base)/R)."""
    Rsafe = jnp.where(R > 0, R, 1)
    q = (A_eff - base) // Rsafe[None, :]
    q = jnp.where((R > 0)[None, :], q, BIG)
    return jnp.clip(q.min(axis=-1), 0, BIG)


@partial(jax.jit, static_argnames=("n_max", "E", "P", "V"))
def solve_scan(inp: KernelInputs, n_max: int, E: int, P: int, V: int = 0
               ) -> Tuple[jax.Array, jax.Array, Carry]:
    """Returns (takes[G, N], leftover[G], final carry)."""
    return _solve(inp, n_max, E, P, V=V)


def _solve(inp: KernelInputs, n_max: int, E: int, P: int,
           axis: "str | None" = None, V: int = 0,
           sum_only: bool = False
           ) -> Tuple[jax.Array, jax.Array, Carry]:
    """The scan. With ``axis`` set, the TYPE dimension of every input is a
    per-device shard under shard_map over that mesh axis: candidate masks
    and headrooms are computed on local type shards and the two cross-type
    max-reductions ride pmax over ICI; the (tiny) node-state carry stays
    replicated. This is the tensor-parallel split of the solver — the type
    axis is embarrassingly wide (full EC2 catalog) while the carry is a
    few KB. See parallel/mesh.py for the mesh wrapper."""
    T, D = inp.A.shape
    Z = inp.agz.shape[1]
    C = inp.agc.shape[1]
    N = E + n_max

    carry0 = Carry(
        used=jnp.zeros((N, D), jnp.int64).at[:E].set(inp.ex_used0),
        types=jnp.zeros((N, T), bool),
        zones=jnp.zeros((N, Z), bool),
        ct=jnp.zeros((N, C), bool),
        pool=jnp.full((N,), -1, jnp.int32).at[:E].set(-2),
        alive=jnp.zeros((N,), bool).at[:E].set(True),
        num_nodes=jnp.int32(0),
        pool_used=inp.pool_used0,
    )

    slot_idx = jnp.arange(N)

    def step(carry: Carry, xs):
        new_carry, (take, n_rem) = plain_group_step(
            inp, carry, xs, axis=axis, P=P, E=E, N=N,
            V=V, slot_idx=slot_idx, sum_only=sum_only)
        # takes ride the wire int32 (bounded by the pod count); the
        # carry and leftover stay int64
        return new_carry, (take.astype(jnp.int32), n_rem)

    xs = (inp.R, inp.n, inp.F, inp.agz, inp.agc, inp.admit, inp.daemon,
          inp.ex_compat)
    final, (takes, leftover) = jax.lax.scan(step, carry0, xs)
    return takes, leftover, final


def _fill_phase(inp: KernelInputs, carry: Carry, R, n, F, agz, agc, admit,
                ex_compat, *, axis, P, E, N, V, sum_only,
                pool_clipped=None):
    """Steps 1-4 of one group fill, WITHOUT mutating the carry: returns
    (take [N], n_rem, cand [N, T]). Factored out of plain_group_step so
    the fused kernel can vmap it over a run of pairwise pool/existing-
    disjoint groups from the same block-start carry — disjointness makes
    every quantity read here (slot masks, existing headrooms, pool
    budgets) identical to what the sequential execution would read.

    ``pool_clipped`` is the precomputed ``clip(carry.pool, 0, P-1)``
    when the caller already needs it for its own pool accounting (the
    plain step does) — one clip per step instead of two."""
    T, D = inp.A.shape
    Z = inp.agz.shape[1]
    C = inp.agc.shape[1]
    n_rem = n

    # ---- candidate types per open slot (steps 1-2) ----------------
    zc = ((carry.zones & agz[None, :])[:, :, None]
          & (carry.ct & agc[None, :])[:, None, :]).reshape(N, Z * C)
    off_ok = (zc.astype(jnp.int32) @ inp.avail_zc.T.astype(jnp.int32)) > 0
    if pool_clipped is None:
        pool_clipped = jnp.clip(carry.pool, 0, P - 1)
    adm_open = jnp.where(carry.pool >= 0, admit[pool_clipped], False)
    cand = carry.types & F[None, :] & off_ok & adm_open[:, None]

    # ---- headroom (step 3) ---------------------------------------
    hr_nt = _headroom_matrix(inp.A, carry.used, R)
    k = jnp.where(cand, hr_nt, 0).max(axis=1)
    k = _axis_max(k, axis, sum_only)   # max over type shards
    if E:
        ex_ok = carry.alive[:E] & ex_compat
        k_ex = jnp.where(ex_ok, _headroom_vec(inp.ex_alloc, carry.used[:E], R), 0)
        k = k.at[:E].set(k_ex)
    # minValues floors cap per-slot takes BEFORE the budget prefix
    # caps (ops/ffd.py applies the same order)
    if inp.mv_floor is not None:
        hr1 = jnp.where(cand, hr_nt + 1, 0)
        h1 = _mv_h1(hr1, inp.mv_pairs_t, inp.mv_pairs_v, V, T, axis)
        h1 = _axis_max(h1, axis, sum_only)
        f = jnp.where((carry.pool >= 0)[:, None],
                      inp.mv_floor[pool_clipped], 0)        # [N, K]
        k = jnp.minimum(k, jnp.where(carry.pool >= 0,
                                     _mv_cap(h1, f, V), BIG))
    # pool limit budgets: cap per-pool prefix fills
    pool_used = carry.pool_used
    for pi in range(P):
        has_limit = (inp.pool_limit[pi] >= 0).any()
        budget = _pool_budget_jax(inp.pool_limit[pi], pool_used[pi], R)
        rows = carry.pool == pi
        kp = jnp.where(rows, k, 0)
        cum = _cumsum(kp) - kp
        capped = jnp.clip(jnp.minimum(kp, budget - cum), 0, None)
        k = jnp.where(rows & has_limit, capped, k)

    # ---- greedy prefix fill (step 4) ------------------------------
    cum = _cumsum(k) - k
    take = jnp.clip(n_rem - cum, 0, k)
    n_rem = n_rem - take.sum()
    return take, n_rem, cand


def plain_group_step(inp: KernelInputs, carry: Carry, xs, *, axis, P, E, N,
                     V, slot_idx, sum_only=False):
    """One scan step of the closed-form (topology-free) group fill —
    factored out so the topology kernel (ops/topo_jax.py) runs the same
    math for its non-topology groups, sharing this single implementation
    with the plain kernel."""
    R, n, F, agz, agc, admit, daemon, ex_compat = xs
    pool_clipped = jnp.clip(carry.pool, 0, P - 1)
    take, n_rem, cand = _fill_phase(
        inp, carry, R, n, F, agz, agc, admit, ex_compat,
        axis=axis, P=P, E=E, N=N, V=V, sum_only=sum_only,
        pool_clipped=pool_clipped)

    # ---- narrowing + pool accounting for the filled slots ---------
    used = carry.used + take[:, None] * R[None, :]
    filled_open = (take > 0) & (carry.pool >= 0)
    fit_all = (used[:, None, :] <= inp.A[None, :, :]).all(axis=-1)
    types = jnp.where(filled_open[:, None], cand & fit_all, carry.types)
    zones = jnp.where(filled_open[:, None], carry.zones & agz[None, :], carry.zones)
    ct = jnp.where(filled_open[:, None], carry.ct & agc[None, :], carry.ct)
    take_by_pool = jax.ops.segment_sum(
        take, pool_clipped * (carry.pool >= 0) + (carry.pool < 0) * P,
        num_segments=P + 1)[:P]
    pool_used = carry.pool_used + take_by_pool[:, None] * R[None, :]

    (take, used, types, zones, ct, pool_arr, alive, num_nodes, pool_used,
     n_rem) = _new_nodes_phase(
        inp, take, used, types, zones, ct, carry.pool, carry.alive,
        carry.num_nodes, pool_used, n_rem, R, F, agz, agc, admit, daemon,
        axis=axis, P=P, E=E, N=N, V=V, slot_idx=slot_idx,
        sum_only=sum_only)

    new_carry = Carry(used=used, types=types, zones=zones, ct=ct,
                      pool=pool_arr, alive=alive, num_nodes=num_nodes,
                      pool_used=pool_used)
    return new_carry, (take, n_rem)


def _new_nodes_phase(inp: KernelInputs, take, used, types, zones, ct,
                     pool_arr, alive, num_nodes, pool_used, n_rem,
                     R, F, agz, agc, admit, daemon, *, axis, P, E, N, V,
                     slot_idx, sum_only):
    """Step 5 of one group fill: open new nodes pool-by-pool. Operates on
    explicit state arrays (not the Carry) so the fused kernel can run it
    sequentially per group AFTER merging a whole run's fill phases —
    new-node slot indices are ordinal in ``num_nodes`` and must be
    allocated in group order regardless of how the fills were batched."""
    T, D = inp.A.shape
    Z = inp.agz.shape[1]
    C = inp.agc.shape[1]
    for pi in range(P):
        agz_p = agz & inp.pool_agz[pi]
        agc_p = agc & inp.pool_agc[pi]
        zc_p = (agz_p[:, None] & agc_p[None, :]).reshape(Z * C)
        off_p = (inp.avail_zc & zc_p[None, :]).any(axis=1)
        cand_new = F & inp.pool_types[pi] & off_p
        hr = _headroom_vec(inp.A, daemon[pi][None, :], R)
        hr = jnp.where(cand_new, hr, 0)
        cap = _axis_max(hr.max(), axis, sum_only)
        if inp.mv_floor is not None:
            h1n = _mv_h1(jnp.where(cand_new, hr + 1, 0),
                         inp.mv_pairs_t, inp.mv_pairs_v, V, T, axis)
            h1n = _axis_max(h1n, axis, sum_only)
            cap = jnp.minimum(cap, _mv_cap(h1n, inp.mv_floor[pi], V))
        budget = _pool_budget_jax(inp.pool_limit[pi], pool_used[pi], R)
        can_place = jnp.where(
            admit[pi] & (cap >= 1), jnp.minimum(n_rem, budget), 0)
        # q new nodes: full nodes of `cap` + one partial
        q = jnp.where(can_place > 0, -(-can_place // jnp.maximum(cap, 1)), 0)
        free_slots = N - E - num_nodes
        q = jnp.minimum(q, free_slots)
        placed = jnp.minimum(can_place, q * cap)
        start = E + num_nodes
        is_new = (slot_idx >= start) & (slot_idx < start + q)
        # pods per new slot: cap, except the last gets the remainder
        offset = slot_idx - start
        m_slot = jnp.where(
            is_new,
            jnp.where(offset == q - 1, placed - cap * (q - 1), cap), 0)
        take = take + m_slot
        used = used + m_slot[:, None] * R[None, :] \
            + is_new[:, None] * daemon[pi][None, :]
        hr_fit = (hr[None, :] >= m_slot[:, None]) & cand_new[None, :]
        types = jnp.where(is_new[:, None], hr_fit, types)
        zones = jnp.where(is_new[:, None], agz_p[None, :], zones)
        ct = jnp.where(is_new[:, None], agc_p[None, :], ct)
        pool_arr = jnp.where(is_new, pi, pool_arr)
        alive = alive | is_new
        num_nodes = num_nodes + q.astype(jnp.int32)
        pool_used = pool_used.at[pi].add(placed * R)
        n_rem = n_rem - placed

    return (take, used, types, zones, ct, pool_arr, alive, num_nodes,
            pool_used, n_rem)


# ---------------------------------------------------------------------------
# 2-D sharded scan (pods/slot axis x type axis).
#
# The 1-D mesh (``_solve`` with ``axis=``) shards only the type dimension;
# every device still materialises the full [N, ...] node state, which caps
# one giant solve at ~50k pods of slot state per chip. The dp variant below
# additionally shards the SLOT axis (slots grow with pods: N = E + n_max)
# across a second mesh axis. Each device owns a contiguous run of Nl slots
# identified by GLOBAL slot ids ``axis_index(dp) * Nl + arange(Nl)``; the
# python-static ``[:E]`` updates of the replicated kernel become ``slots < E``
# masks, and the two order-dependent reductions become distributed forms:
#
#   * exclusive prefix sums (pool budgets, greedy fill) = local exclusive
#     cumsum + the all_gathered totals of earlier shards — exact because the
#     global slot order IS the shard-major order of the ids above;
#   * totals (pods placed, per-pool take accounting) = psum over dp.
#
# Everything else is elementwise per slot (or per [slot, type] cell) and
# needs no communication. Scalars entering the new-node phase (cap, budget,
# q, placed, num_nodes) are replicated across both axes, so the existing
# ``_new_nodes_phase`` is reused VERBATIM with the global slot ids — the dp
# kernel cannot drift from the replicated one in that phase. Slot padding
# (to a multiple of the dp axis) is inert by the same argument as the type
# padding: padded slots carry types=False/pool=-1 so they never win a fill,
# and ``free_slots`` uses the TRUE N so new nodes never land there.
# minValues floors are NOT supported here (callers gate K == 0 and fall
# back to the 1-D type mesh — the floors' segment-max rides type shards and
# is already exact there).
# ---------------------------------------------------------------------------


def _dp_prefix(x: jax.Array, axis: "str | None") -> jax.Array:
    """Distributed EXCLUSIVE prefix sum of a dp-sharded [Nl] vector in
    global slot order: local exclusive cumsum plus the summed totals of
    the earlier shards (one small all_gather)."""
    local = _cumsum(x) - x
    if axis is None:
        return local
    tots = jax.lax.all_gather(x.sum(), axis)             # [ndp]
    idx = jax.lax.axis_index(axis)
    before = jnp.where(jnp.arange(tots.shape[0]) < idx, tots, 0).sum()
    return local + before


def _dp_sum(x: jax.Array, axis: "str | None") -> jax.Array:
    """Global sum of a dp-sharded quantity (Sum all-reduce lowers on every
    backend, including the sum-only interconnects _needs_sum_only guards)."""
    return x if axis is None else jax.lax.psum(x, axis)


def _fill_phase_dp(inp: KernelInputs, carry: Carry, R, n, F, agz, agc, admit,
                   ex_compat, *, dp_axis, tp_axis, P, E, slots, sum_only):
    """``_fill_phase`` on a dp slot shard: same steps 1-4, with the [:E]
    existing-node block replaced by ``slots < E`` masking against the
    slot-padded existing tables and the two prefix/total reductions in
    their distributed forms. Returns (take [Nl], n_rem, cand [Nl, Tl])."""
    Nl = slots.shape[0]
    Z = inp.agz.shape[1]
    C = inp.agc.shape[1]
    n_rem = n

    # ---- candidate types per open slot (steps 1-2) ----------------
    zc = ((carry.zones & agz[None, :])[:, :, None]
          & (carry.ct & agc[None, :])[:, None, :]).reshape(Nl, Z * C)
    off_ok = (zc.astype(jnp.int32) @ inp.avail_zc.T.astype(jnp.int32)) > 0
    pool_clipped = jnp.clip(carry.pool, 0, P - 1)
    adm_open = jnp.where(carry.pool >= 0, admit[pool_clipped], False)
    cand = carry.types & F[None, :] & off_ok & adm_open[:, None]

    # ---- headroom (step 3) ---------------------------------------
    hr_nt = _headroom_matrix(inp.A, carry.used, R)
    k = jnp.where(cand, hr_nt, 0).max(axis=1)
    k = _axis_max(k, tp_axis, sum_only)   # max over type shards
    is_ex = slots < E
    ex_ok = is_ex & carry.alive & ex_compat
    k_ex = jnp.where(ex_ok, _headroom_vec(inp.ex_alloc, carry.used, R), 0)
    k = jnp.where(is_ex, k_ex, k)

    # pool limit budgets: cap per-pool prefix fills
    pool_used = carry.pool_used
    for pi in range(P):
        has_limit = (inp.pool_limit[pi] >= 0).any()
        budget = _pool_budget_jax(inp.pool_limit[pi], pool_used[pi], R)
        rows = carry.pool == pi
        kp = jnp.where(rows, k, 0)
        cum = _dp_prefix(kp, dp_axis)
        capped = jnp.clip(jnp.minimum(kp, budget - cum), 0, None)
        k = jnp.where(rows & has_limit, capped, k)

    # ---- greedy prefix fill (step 4) ------------------------------
    cum = _dp_prefix(k, dp_axis)
    take = jnp.clip(n_rem - cum, 0, k)
    n_rem = n_rem - _dp_sum(take.sum(), dp_axis)
    return take, n_rem, cand


def dp_group_step(inp: KernelInputs, carry: Carry, xs, *, dp_axis, tp_axis,
                  P, E, N, slots, sum_only=False):
    """One scan step of the 2-D sharded fill: dp fill phase, elementwise
    narrowing, psum'd pool accounting, then the shared new-nodes phase."""
    R, n, F, agz, agc, admit, daemon, ex_compat = xs
    take, n_rem, cand = _fill_phase_dp(
        inp, carry, R, n, F, agz, agc, admit, ex_compat,
        dp_axis=dp_axis, tp_axis=tp_axis, P=P, E=E, slots=slots,
        sum_only=sum_only)

    # ---- narrowing + pool accounting for the filled slots ---------
    used = carry.used + take[:, None] * R[None, :]
    filled_open = (take > 0) & (carry.pool >= 0)
    fit_all = (used[:, None, :] <= inp.A[None, :, :]).all(axis=-1)
    types = jnp.where(filled_open[:, None], cand & fit_all, carry.types)
    zones = jnp.where(filled_open[:, None], carry.zones & agz[None, :], carry.zones)
    ct = jnp.where(filled_open[:, None], carry.ct & agc[None, :], carry.ct)
    pool_clipped = jnp.clip(carry.pool, 0, P - 1)
    take_by_pool = jax.ops.segment_sum(
        take, pool_clipped * (carry.pool >= 0) + (carry.pool < 0) * P,
        num_segments=P + 1)[:P]
    take_by_pool = _dp_sum(take_by_pool, dp_axis)
    pool_used = carry.pool_used + take_by_pool[:, None] * R[None, :]

    (take, used, types, zones, ct, pool_arr, alive, num_nodes, pool_used,
     n_rem) = _new_nodes_phase(
        inp, take, used, types, zones, ct, carry.pool, carry.alive,
        carry.num_nodes, pool_used, n_rem, R, F, agz, agc, admit, daemon,
        axis=tp_axis, P=P, E=E, N=N, V=0, slot_idx=slots,
        sum_only=sum_only)

    new_carry = Carry(used=used, types=types, zones=zones, ct=ct,
                      pool=pool_arr, alive=alive, num_nodes=num_nodes,
                      pool_used=pool_used)
    return new_carry, (take, n_rem)


def _solve_dp(inp: KernelInputs, n_max: int, E: int, P: int,
              dp_axis: "str | None", tp_axis: "str | None",
              sum_only: bool = False
              ) -> Tuple[jax.Array, jax.Array, Carry]:
    """The 2-D sharded scan body, run under shard_map over a ("dp","tp")
    mesh: every input field is the LOCAL shard (types split over tp, slot
    tables split over dp, the rest replicated). The caller (parallel/
    mesh.py) pads the slot axis of ex_alloc/ex_used0/ex_compat to the full
    padded slot range Np = ceil((E + n_max)/ndp)*ndp with inert zeros.
    Requires inp.mv_floor is None (K == 0); see the section comment."""
    Tl, D = inp.A.shape
    Z = inp.agz.shape[1]
    C = inp.agc.shape[1]
    Nl = inp.ex_used0.shape[0]
    N = E + n_max   # TRUE slot count; slots in [N, Nl*ndp) are inert pad

    idx = jax.lax.axis_index(dp_axis) if dp_axis is not None else 0
    slots = idx * Nl + jnp.arange(Nl)
    is_ex = slots < E

    carry0 = Carry(
        used=jnp.where(is_ex[:, None], inp.ex_used0, jnp.int64(0)),
        types=jnp.zeros((Nl, Tl), bool),
        zones=jnp.zeros((Nl, Z), bool),
        ct=jnp.zeros((Nl, C), bool),
        pool=jnp.where(is_ex, -2, -1).astype(jnp.int32),
        alive=is_ex,
        num_nodes=jnp.int32(0),
        pool_used=inp.pool_used0,
    )

    def step(carry: Carry, xs):
        new_carry, (take, n_rem) = dp_group_step(
            inp, carry, xs, dp_axis=dp_axis, tp_axis=tp_axis, P=P, E=E,
            N=N, slots=slots, sum_only=sum_only)
        return new_carry, (take.astype(jnp.int32), n_rem)

    xs = (inp.R, inp.n, inp.F, inp.agz, inp.agc, inp.admit, inp.daemon,
          inp.ex_compat)
    final, (takes, leftover) = jax.lax.scan(step, carry0, xs)
    return takes, leftover, final


def _solve_fused(inp: KernelInputs, n_max: int, E: int, P: int, Fu: int,
                 fuse: jax.Array, V: int = 0
                 ) -> Tuple[jax.Array, jax.Array, Carry]:
    """The F-wide block scan: same decisions as ``_solve``, G/Fu trips.

    ``fuse`` [G] bool is the encoder/solver's ``same_run_as_prev`` flag
    (models/encoding.py independent_runs ANDed with the solver's
    existing-node walk): True at g proves group g's admit AND ex_compat
    rows are disjoint from every row of the run containing g-1. A block
    of Fu consecutive groups whose last Fu-1 flags are all True lies
    inside ONE run, so its groups are pairwise disjoint and the block
    takes the vectorized branch:

    - all Fu fill phases run from the BLOCK-START carry via vmap. Exact,
      because a group's fill reads only state its run-mates never write:
      open slots belong to admitted pools (disjoint), existing rows to
      compatible nodes (disjoint), pool budgets to admitted pools
      (disjoint), and a run-mate's step-5 slots belong to ITS pools —
      never admitted by this group;
    - the disjoint fill deltas merge by sum (used, pool_used) and
      masked select (types/zones/ct — at most one group fills a slot);
    - step 5 unrolls sequentially over the block either way: new-node
      slots are ordinal in num_nodes and later groups' budgets read
      earlier groups' placements.

    A block that straddles runs takes the sequential branch — Fu plain
    steps unrolled inside one trip — so the scan's trip count (the
    per-step dispatch/latency floor the roofline in
    docs/solver-design.md measures) drops Fu-fold unconditionally.
    The caller guarantees G % Fu == 0 (pow2 bucketing) and gates off
    minValues floors and the mesh axis."""
    T, D = inp.A.shape
    Z = inp.agz.shape[1]
    C = inp.agc.shape[1]
    N = E + n_max
    G = inp.R.shape[0]
    B = G // Fu

    carry0 = Carry(
        used=jnp.zeros((N, D), jnp.int64).at[:E].set(inp.ex_used0),
        types=jnp.zeros((N, T), bool),
        zones=jnp.zeros((N, Z), bool),
        ct=jnp.zeros((N, C), bool),
        pool=jnp.full((N,), -1, jnp.int32).at[:E].set(-2),
        alive=jnp.zeros((N,), bool).at[:E].set(True),
        num_nodes=jnp.int32(0),
        pool_used=inp.pool_used0,
    )
    slot_idx = jnp.arange(N)

    xs = (inp.R, inp.n, inp.F, inp.agz, inp.agc, inp.admit, inp.daemon,
          inp.ex_compat)
    xs_b = tuple(x.reshape((B, Fu) + x.shape[1:]) for x in xs)
    blk_indep = fuse.reshape(B, Fu)[:, 1:].all(axis=1)

    def seq_block(args):
        carry, xs_blk = args
        takes, lefts = [], []
        for i in range(Fu):
            xs_i = tuple(x[i] for x in xs_blk)
            carry, (tk, lf) = plain_group_step(
                inp, carry, xs_i, axis=None, P=P, E=E, N=N, V=V,
                slot_idx=slot_idx)
            takes.append(tk)
            lefts.append(lf)
        return carry, (jnp.stack(takes), jnp.stack(lefts))

    def vec_block(args):
        carry, xs_blk = args
        R, n, F, agz, agc, admit, daemon, ex_compat = xs_blk
        # one clip for the whole block: the vmapped fills and the pool
        # accounting below all read the same block-start carry
        pool_clipped = jnp.clip(carry.pool, 0, P - 1)

        def fill(R_, n_, F_, agz_, agc_, admit_, exc_):
            return _fill_phase(inp, carry, R_, n_, F_, agz_, agc_,
                               admit_, exc_, axis=None, P=P, E=E, N=N,
                               V=V, sum_only=False,
                               pool_clipped=pool_clipped)

        take_f, n_rem_f, cand_f = jax.vmap(fill)(
            R, n, F, agz, agc, admit, ex_compat)

        # merge the pairwise-disjoint fill deltas
        used = carry.used + (take_f[:, :, None] * R[:, None, :]).sum(axis=0)
        filled_f = (take_f > 0) & (carry.pool >= 0)[None, :]
        any_filled = filled_f.any(axis=0)
        fit_all = (used[:, None, :] <= inp.A[None, :, :]).all(axis=-1)
        # at most one group fills a slot, so OR selects ITS cand row;
        # fit_all from the merged `used` is exact for that slot (the
        # other groups contributed zero there)
        cand_sel = (filled_f[:, :, None] & cand_f).any(axis=0)
        types = jnp.where(any_filled[:, None], cand_sel & fit_all,
                          carry.types)
        agz_keep = jnp.where(filled_f[:, :, None], agz[:, None, :],
                             True).all(axis=0)
        zones = jnp.where(any_filled[:, None], carry.zones & agz_keep,
                          carry.zones)
        agc_keep = jnp.where(filled_f[:, :, None], agc[:, None, :],
                             True).all(axis=0)
        ct = jnp.where(any_filled[:, None], carry.ct & agc_keep, carry.ct)
        seg = pool_clipped * (carry.pool >= 0) + (carry.pool < 0) * P

        def pool_delta(take_, R_):
            tbp = jax.ops.segment_sum(take_, seg, num_segments=P + 1)[:P]
            return tbp[:, None] * R_[None, :]

        pool_used = carry.pool_used \
            + jax.vmap(pool_delta)(take_f, R).sum(axis=0)

        # step 5 sequentially per group: ordinal slot allocation
        pool_arr, alive, num_nodes = carry.pool, carry.alive, carry.num_nodes
        takes, lefts = [], []
        for i in range(Fu):
            (tk, used, types, zones, ct, pool_arr, alive, num_nodes,
             pool_used, lf) = _new_nodes_phase(
                inp, take_f[i], used, types, zones, ct, pool_arr, alive,
                num_nodes, pool_used, n_rem_f[i], R[i], F[i], agz[i],
                agc[i], admit[i], daemon[i], axis=None, P=P, E=E, N=N,
                V=V, slot_idx=slot_idx, sum_only=False)
            takes.append(tk)
            lefts.append(lf)
        new_carry = Carry(used=used, types=types, zones=zones, ct=ct,
                          pool=pool_arr, alive=alive, num_nodes=num_nodes,
                          pool_used=pool_used)
        return new_carry, (jnp.stack(takes), jnp.stack(lefts))

    def step(carry, xsb):
        xs_blk, indep = xsb[:-1], xsb[-1]
        carry2, (tk, lf) = jax.lax.cond(indep, vec_block, seq_block,
                                        (carry, xs_blk))
        return carry2, (tk.astype(jnp.int32), lf)

    final, (takes_b, left_b) = jax.lax.scan(step, carry0,
                                            xs_b + (blk_indep,))
    return takes_b.reshape(G, N), left_b.reshape(G), final


# ---------------------------------------------------------------------------
# Checkpointed scan + suffix-only re-solve.
#
# The carry entering group i is a pure function of groups < i (the scan
# order IS the restriction-stable canonical order), so a tick whose dirty
# rows all sit at or past a frontier index f can resume from a saved
# carry at a checkpoint <= f and re-scan only the suffix. The chunked
# kernel below scans G in G/CK chunks of CK plain steps and emits each
# chunk's ENTRY carry as one row of a [G/CK, ...] checkpoint bank — the
# same step math as ``_solve`` applied in the same order, so outputs are
# bit-identical (all decision arithmetic is integer-exact).
#
# The suffix kernel restores a caller-provided checkpoint carry and scans
# only the last SUF*CK groups, re-emitting the suffix's own mini bank so
# the resident bank stays fully fresh after every warm tick. SUF is a
# STATIC rounded up the tenancy/bucketing.py {2^k, 1.5*2^k} ladder by the
# dispatcher, so warm frontiers land on a handful of compiled shape
# classes instead of one per frontier. Exactness of the splice (suffix
# takes over the resident prefix takes) is by construction: the prefix
# groups' inputs are unchanged, so their recorded outputs and the
# checkpoint carry are exactly what a from-scratch solve would recompute.
# ---------------------------------------------------------------------------


def _make_carry0(inp: KernelInputs, N: int, E: int) -> Carry:
    """The scan's initial node state — shared verbatim by the base,
    fused and checkpointed kernels (ex_used0 rides the carry, which is
    why existing-row dirtiness invalidates every checkpoint)."""
    T, D = inp.A.shape
    Z = inp.agz.shape[1]
    C = inp.agc.shape[1]
    return Carry(
        used=jnp.zeros((N, D), jnp.int64).at[:E].set(inp.ex_used0),
        types=jnp.zeros((N, T), bool),
        zones=jnp.zeros((N, Z), bool),
        ct=jnp.zeros((N, C), bool),
        pool=jnp.full((N,), -1, jnp.int32).at[:E].set(-2),
        alive=jnp.zeros((N,), bool).at[:E].set(True),
        num_nodes=jnp.int32(0),
        pool_used=inp.pool_used0,
    )


def _chunked_scan(inp: KernelInputs, carry0: Carry, xs, *, P, E, N, V,
                  CK: int):
    """Scan ``xs`` (any group count divisible by CK) from ``carry0`` in
    chunks of CK plain steps, emitting each chunk's entry carry. Returns
    (takes [Gs, N] int32, leftover [Gs], final carry, bank) where
    ``bank`` is a Carry of [Gs/CK, ...] stacked entry states
    (bank[j] = carry entering group j*CK of this scan)."""
    Gs = xs[0].shape[0]
    NC = Gs // CK
    slot_idx = jnp.arange(N)
    xs_c = tuple(x.reshape((NC, CK) + x.shape[1:]) for x in xs)

    def chunk(carry, xs_blk):
        entry = carry
        takes, lefts = [], []
        for i in range(CK):
            xs_i = tuple(x[i] for x in xs_blk)
            carry, (tk, lf) = plain_group_step(
                inp, carry, xs_i, axis=None, P=P, E=E, N=N, V=V,
                slot_idx=slot_idx)
            takes.append(tk.astype(jnp.int32))
            lefts.append(lf)
        return carry, (jnp.stack(takes), jnp.stack(lefts), entry)

    final, (takes_c, left_c, bank) = jax.lax.scan(chunk, carry0, xs_c)
    return (takes_c.reshape(Gs, N), left_c.reshape(Gs), final, bank)


def _solve_ckpt(inp: KernelInputs, n_max: int, E: int, P: int, V: int,
                CK: int):
    """Full solve that additionally records the checkpoint bank.
    Decisions are bit-identical to ``_solve`` (same steps, same order);
    the caller guarantees G % CK == 0 (both pow2-bucketed)."""
    N = E + n_max
    xs = (inp.R, inp.n, inp.F, inp.agz, inp.agc, inp.admit, inp.daemon,
          inp.ex_compat)
    return _chunked_scan(inp, _make_carry0(inp, N, E), xs,
                         P=P, E=E, N=N, V=V, CK=CK)


def _solve_suffix(inp: KernelInputs, ck: Carry, n_max: int, E: int,
                  P: int, V: int, CK: int, SUF: int, GL: int):
    """Resume from checkpoint carry ``ck`` (the state entering group
    GL - SUF*CK) and scan only the SUF*CK groups up to the live bound
    ``GL`` (the chunk-aligned end of the non-empty groups: every group
    past it has n == 0 and is a carry no-op, so skipping it changes no
    output byte), re-emitting the suffix's own mini checkpoint bank.
    Returns (takes [SUF*CK, N], leftover [SUF*CK], final carry, mini
    bank [SUF, ...])."""
    N = E + n_max
    s0 = GL - SUF * CK
    xs = tuple(x[s0:GL] for x in (inp.R, inp.n, inp.F, inp.agz, inp.agc,
                                  inp.admit, inp.daemon, inp.ex_compat))
    return _chunked_scan(inp, ck, xs, P=P, E=E, N=N, V=V, CK=CK)


def _pool_budget_jax(limit: jax.Array, used: jax.Array, R: jax.Array) -> jax.Array:
    """Max additional pods the pool's limits allow (BIG if unlimited)."""
    active = (limit >= 0) & (R > 0)
    Rsafe = jnp.where(R > 0, R, 1)
    per_dim = jnp.where(active, jnp.clip(limit - used, 0, None) // Rsafe, BIG)
    return per_dim.min()


# ---------------------------------------------------------------------------
# Pruned scan: the device G-axis kernel.
#
# The base step pays O(N*T*D) per group for the full [N, T] candidate/
# headroom pass — at the 10k-signature envelope that is ~2e11 ops per
# solve, which is why high-G solves route to the host engine. This
# variant applies the host fast path's insight (ops/ffd.py
# _fill_group_fast) in data-parallel form:
#
# - the carry keeps a per-slot capacity UPPER BOUND ``cap_hint`` [N, D]
#   (max allocatable over the slot's candidate types at open; stale-high
#   after narrowing — safe, exactly like the host's NodeState.cap_hint),
#   so a cheap O(N*D) bound pass proves most slots full for this group;
# - EXACT candidate masks + headroom are computed only for the FIRST S
#   bound-positive open slots in slot order ([S, T] gather) — FFD fills
#   in slot order, so those are the only slots the oracle could touch
#   unless they all fill;
# - if the group still has pods left after those S slots AND more
#   bound-positive slots existed beyond them, the step sets a BAIL flag:
#   the caller discards the solve and re-runs on the bit-identical host
#   twin (the TopoKernelBail discipline). Decisions are therefore always
#   oracle-identical — the flag marks exactly the inputs where pruning
#   could have mattered.
#
# Per-step cost drops to O(N*D + S*T*D + P*T*D); compile cost stays O(1)
# in G (one scan body). Scope guards (enforced by the caller): no
# minValues floors, single device (the mesh path keeps the base kernel).
# ---------------------------------------------------------------------------


class CarryP(NamedTuple):
    used: jax.Array       # [N, D]
    types: jax.Array      # [N, T]
    zones: jax.Array      # [N, Z]
    ct: jax.Array         # [N, C]
    pool: jax.Array       # [N] int32 (-1 free, -2 existing)
    alive: jax.Array      # [N] bool
    num_nodes: jax.Array  # scalar int32
    pool_used: jax.Array  # [P, D]
    cap_hint: jax.Array   # [N, D] int64 stale-high capacity bound
    bail: jax.Array       # scalar bool — pruning was insufficient


def pruned_group_step(inp: KernelInputs, carry: CarryP, xs, *, P, E, N, S,
                      slot_idx):
    R, n, F, agz, agc, admit, daemon, ex_compat = xs
    T, D = inp.A.shape
    Z = inp.agz.shape[1]
    C = inp.agc.shape[1]
    n_rem = n

    # ---- bound pass over every slot: O(N*D + N*T bool) ------------
    pool_clipped = jnp.clip(carry.pool, 0, P - 1)
    adm_open = jnp.where(carry.pool >= 0, admit[pool_clipped], False)
    Rsafe = jnp.where(R > 0, R, 1)
    qb = (carry.cap_hint - carry.used) // Rsafe[None, :]
    qb = jnp.where((R > 0)[None, :], qb, BIG)
    k_bound = jnp.clip(qb.min(axis=-1), 0, BIG)
    # compatibility pre-screen, EXACT wrt the base kernel: carry.types
    # is the same narrowed mask the base kernel carries (selected slots
    # narrow identically, unselected slots never took), so a slot with
    # no (types ∧ F) overlap — or no zone / capacity-type overlap —
    # has an all-False cand row there and k=0: excluding it from the
    # selection AND from n_pos loses nothing and stops incompatible
    # slots from wasting the S selection (the high-signature-diversity
    # shape of BASELINE config 7, where resource-positive slots
    # usually belong to other signatures' pools/selectors).
    compat = (carry.types & F[None, :]).any(axis=1) \
        & (carry.zones & agz[None, :]).any(axis=1) \
        & (carry.ct & agc[None, :]).any(axis=1)
    open_cand = adm_open & (k_bound > 0) & carry.alive & compat
    if E:
        open_cand = open_cand.at[:E].set(False)
    n_pos = open_cand.sum()

    # ---- first S bound-positive open slots, slot order ------------
    sel_rank = jnp.where(open_cand, slot_idx, N + 1)
    sel = jnp.argsort(sel_rank)[:S]                       # [S] slots
    sel_valid = open_cand[sel]

    # ---- exact candidates + headroom for the selected: O(S*T*D) ---
    types_s = carry.types[sel]
    zc_s = ((carry.zones[sel] & agz[None, :])[:, :, None]
            & (carry.ct[sel] & agc[None, :])[:, None, :]).reshape(S, Z * C)
    off_ok_s = (zc_s.astype(jnp.int32)
                @ inp.avail_zc.T.astype(jnp.int32)) > 0
    cand_s = types_s & F[None, :] & off_ok_s & sel_valid[:, None]
    hr_s = _headroom_matrix(inp.A, carry.used[sel], R)    # [S, T]
    k_exact_s = jnp.where(cand_s, hr_s, 0).max(axis=1)

    k = jnp.zeros(N, jnp.int64).at[sel].set(
        jnp.where(sel_valid, k_exact_s, 0))
    if E:
        ex_ok = carry.alive[:E] & ex_compat
        k_ex = jnp.where(ex_ok,
                         _headroom_vec(inp.ex_alloc, carry.used[:E], R), 0)
        k = k.at[:E].set(k_ex)

    # ---- pool limit budgets (same order as the base kernel) -------
    pool_used = carry.pool_used
    for pi in range(P):
        has_limit = (inp.pool_limit[pi] >= 0).any()
        budget = _pool_budget_jax(inp.pool_limit[pi], pool_used[pi], R)
        rows = carry.pool == pi
        kp = jnp.where(rows, k, 0)
        cum = _cumsum(kp) - kp
        capped = jnp.clip(jnp.minimum(kp, budget - cum), 0, None)
        k = jnp.where(rows & has_limit, capped, k)

    # ---- greedy prefix fill ---------------------------------------
    cum = _cumsum(k) - k
    take = jnp.clip(n_rem - cum, 0, k)
    n_rem = n_rem - take.sum()

    # pruning was insufficient: pods remain AND an unselected bound-
    # positive open slot existed (FFD would have consulted it next)
    bail = carry.bail | ((n_pos > S) & (n_rem > 0))

    used = carry.used + take[:, None] * R[None, :]
    # narrowing — only slots that took pods narrow, and every open
    # taker is in the selection (take > 0 needs k > 0)
    took_s = (take[sel] > 0) & sel_valid
    fit_s = (used[sel][:, None, :] <= inp.A[None, :, :]).all(axis=-1)
    new_types_s = cand_s & fit_s
    types = carry.types.at[sel].set(jnp.where(
        took_s[:, None], new_types_s, carry.types[sel]))
    zones = carry.zones.at[sel].set(jnp.where(
        took_s[:, None], carry.zones[sel] & agz[None, :],
        carry.zones[sel]))
    ct = carry.ct.at[sel].set(jnp.where(
        took_s[:, None], carry.ct[sel] & agc[None, :], carry.ct[sel]))
    # cap_hint stays stale-high for narrowed slots (host discipline)
    take_by_pool = jax.ops.segment_sum(
        take, pool_clipped * (carry.pool >= 0) + (carry.pool < 0) * P,
        num_segments=P + 1)[:P]
    pool_used = pool_used + take_by_pool[:, None] * R[None, :]

    # ---- new nodes pool-by-pool (base math + cap_hint rows) -------
    pool_arr = carry.pool
    alive = carry.alive
    num_nodes = carry.num_nodes
    cap_hint = carry.cap_hint
    for pi in range(P):
        agz_p = agz & inp.pool_agz[pi]
        agc_p = agc & inp.pool_agc[pi]
        zc_p = (agz_p[:, None] & agc_p[None, :]).reshape(Z * C)
        off_p = (inp.avail_zc & zc_p[None, :]).any(axis=1)
        cand_new = F & inp.pool_types[pi] & off_p
        hr = _headroom_vec(inp.A, daemon[pi][None, :], R)
        hr = jnp.where(cand_new, hr, 0)
        cap = hr.max()
        budget = _pool_budget_jax(inp.pool_limit[pi], pool_used[pi], R)
        can_place = jnp.where(
            admit[pi] & (cap >= 1), jnp.minimum(n_rem, budget), 0)
        q = jnp.where(can_place > 0, -(-can_place // jnp.maximum(cap, 1)), 0)
        free_slots = N - E - num_nodes
        q = jnp.minimum(q, free_slots)
        placed = jnp.minimum(can_place, q * cap)
        start = E + num_nodes
        is_new = (slot_idx >= start) & (slot_idx < start + q)
        offset = slot_idx - start
        m_slot = jnp.where(
            is_new,
            jnp.where(offset == q - 1, placed - cap * (q - 1), cap), 0)
        take = take + m_slot
        used = used + m_slot[:, None] * R[None, :] \
            + is_new[:, None] * daemon[pi][None, :]
        hr_fit = (hr[None, :] >= m_slot[:, None]) & cand_new[None, :]
        types = jnp.where(is_new[:, None], hr_fit, types)
        zones = jnp.where(is_new[:, None], agz_p[None, :], zones)
        ct = jnp.where(is_new[:, None], agc_p[None, :], ct)
        # capacity bound for the opened slots: max allocatable over the
        # pool's candidate set (a superset of the kept mask — stale-high
        # safe, and O(T*D) once per pool instead of per slot)
        cap_row = jnp.where(cand_new[:, None], inp.A,
                            jnp.int64(0)).max(axis=0)
        cap_hint = jnp.where(is_new[:, None], cap_row[None, :], cap_hint)
        pool_arr = jnp.where(is_new, pi, pool_arr)
        alive = alive | is_new
        num_nodes = num_nodes + q.astype(jnp.int32)
        pool_used = pool_used.at[pi].add(placed * R)
        n_rem = n_rem - placed

    new_carry = CarryP(used=used, types=types, zones=zones, ct=ct,
                       pool=pool_arr, alive=alive, num_nodes=num_nodes,
                       pool_used=pool_used, cap_hint=cap_hint, bail=bail)
    return new_carry, (take, n_rem)


def _solve_pruned(inp: KernelInputs, n_max: int, E: int, P: int, S: int):
    T, D = inp.A.shape
    Z = inp.agz.shape[1]
    C = inp.agc.shape[1]
    N = E + n_max
    # selection cannot exceed the slot count: argsort(...)[:S] would
    # silently yield N rows and the [S, ...] reshapes would fail at
    # trace time (a small-n_max solver with the 64-slot default).
    # S == N selects everything — exact, bail-free.
    S = min(S, N)
    carry0 = CarryP(
        used=jnp.zeros((N, D), jnp.int64).at[:E].set(inp.ex_used0),
        types=jnp.zeros((N, T), bool),
        zones=jnp.zeros((N, Z), bool),
        ct=jnp.zeros((N, C), bool),
        pool=jnp.full((N,), -1, jnp.int32).at[:E].set(-2),
        alive=jnp.zeros((N,), bool).at[:E].set(True),
        num_nodes=jnp.int32(0),
        pool_used=inp.pool_used0,
        cap_hint=jnp.zeros((N, D), jnp.int64).at[:E].set(inp.ex_alloc),
        bail=jnp.asarray(False),
    )
    slot_idx = jnp.arange(N)

    def step(carry, xs):
        new_carry, (take, n_rem) = pruned_group_step(
            inp, carry, xs, P=P, E=E, N=N, S=S, slot_idx=slot_idx)
        return new_carry, (take.astype(jnp.int32), n_rem)

    xs = (inp.R, inp.n, inp.F, inp.agz, inp.agc, inp.admit, inp.daemon,
          inp.ex_compat)
    final, (takes, leftover) = jax.lax.scan(step, carry0, xs)
    return takes, leftover, final


# ---------------------------------------------------------------------------
# Packed I/O path: the TPU sits behind a network tunnel, so PER-TRANSFER
# round-trip latency dominates end-to-end solve time (measured ~5ms h2d and
# far worse d2h per array vs ~30KB of actual payload). All 17 inputs ride
# ONE int64 buffer (bool tensors bitpacked into words — see the
# single-buffer section below), and all outputs ride ONE int64 buffer
# back. The layout lists below are the single source of truth for both
# sides; ``_split`` is the only buffer walker.
# ---------------------------------------------------------------------------

from .hostpack import (DEV_PRUNED_SLOTS,  # noqa: E402
                       in_layout_bool as _in_layout_bool,
                       in_layout_i64 as _in_layout_i64,
                       layout_sizes as _layout_sizes,
                       nwords as _nwords, out_layout, pack_inputs1,
                       split as _split, unpack_outputs1)


def _unpack_inputs(buf_i64: jax.Array, buf_bool: jax.Array,
                   T, D, Z, C, G, E, P, K=0, M=0, F=1, Q=0):
    """Returns (KernelInputs, fuse-or-None): the same_run_as_prev flags
    ride the bool section only when the fused kernel is engaged (F>1).
    The Q>0 priority vector is dropped here on purpose: the base solve's
    decisions are priority-blind (canonical group order already encodes
    priority), so per-tier reporting reads the [G] leftover output
    against the host's own prio copy (tier_leftovers)."""
    vals = _split(buf_i64, _in_layout_i64(T, D, Z, C, G, E, P, K, M, F, Q))
    vals.update(_split(buf_bool,
                       _in_layout_bool(T, D, Z, C, G, E, P, K, M, F, Q)))
    if K == 0:
        for nm in ("mv_floor", "mv_pairs_t", "mv_pairs_v"):
            vals.pop(nm, None)
    vals.pop("prio", None)
    fuse = vals.pop("fuse", None)
    return KernelInputs(**vals), fuse


# ---------------------------------------------------------------------------
# Single-buffer path. Each device round trip costs ~30-65ms of tunnel
# latency regardless of payload, and enqueues pipeline without acks — so
# the optimal shape is ONE int64 h2d buffer (bools bitpacked into words),
# an async dispatch, and ONE synchronous d2h fetch that rides the same
# wait as the execution. Bit packing is little-endian on both sides
# (host: native codec / np.packbits(bitorder='little'); device:
# arithmetic shifts), so no memory-layout assumptions cross the wire.
# The host half lives in ops/hostpack.py (numpy-only, jax-free) so the
# sidecar's control-plane side never imports jax.
# ---------------------------------------------------------------------------

def _bits_to_words(bits: jax.Array) -> jax.Array:
    """Device: flat bool [n*64] -> int64 words via arithmetic packing."""
    w = bits.reshape(-1, 64).astype(jnp.uint64)
    weights = jnp.left_shift(jnp.uint64(1), jnp.arange(64, dtype=jnp.uint64))
    packed = (w * weights[None, :]).sum(axis=1, dtype=jnp.uint64)
    return jax.lax.bitcast_convert_type(packed, jnp.int64)


def _words_to_bits(words: jax.Array, nbits: int) -> jax.Array:
    """Device: int64 words -> flat bool [nbits]."""
    w = jax.lax.bitcast_convert_type(words, jnp.uint64)
    shifts = jnp.arange(64, dtype=jnp.uint64)
    bits = jnp.right_shift(w[:, None], shifts[None, :]) & jnp.uint64(1)
    return bits.reshape(-1)[:nbits].astype(bool)


def _i32_to_words(x: jax.Array) -> jax.Array:
    """Device: int32-valued array -> int64 wire words, two lanes per
    word, little-lane-first (hostpack.unpack_i32_words is the inverse)."""
    v = x.reshape(-1).astype(jnp.int32)
    if v.shape[0] % 2:
        v = jnp.concatenate([v, jnp.zeros(1, jnp.int32)])
    u = jax.lax.bitcast_convert_type(v, jnp.uint32).astype(jnp.uint64)
    w = u[0::2] | (u[1::2] << jnp.uint64(32))
    return jax.lax.bitcast_convert_type(w, jnp.int64)


def _pack_solve_outputs(takes, leftover, carry) -> jax.Array:
    """[i64 section | int32-packed takes | bitpacked bools] — the device
    half of hostpack.out_layout's three-section contract."""
    out_i64 = jnp.concatenate([
        leftover.reshape(-1).astype(jnp.int64),
        carry.used.reshape(-1), carry.pool.astype(jnp.int64),
        carry.num_nodes.reshape(1).astype(jnp.int64),
        carry.pool_used.reshape(-1)])
    out_t32 = _i32_to_words(takes)
    out_bool = jnp.concatenate([
        carry.types.reshape(-1), carry.zones.reshape(-1),
        carry.ct.reshape(-1), carry.alive])
    nb = out_bool.shape[0]
    pad = _nwords(nb) * 64 - nb
    out_words = _bits_to_words(jnp.concatenate(
        [out_bool, jnp.zeros(pad, bool)]))
    return jnp.concatenate([out_i64, out_t32, out_words])


def _packed1_body(buf: jax.Array, *, T, D, Z, C, G, E, P, n_max,
                  K, V, M, F, Q=0) -> jax.Array:
    n_i64 = _layout_sizes(_in_layout_i64(T, D, Z, C, G, E, P, K, M, F, Q))
    n_bits = _layout_sizes(_in_layout_bool(T, D, Z, C, G, E, P, K, M, F,
                                           Q))
    bool_flat = _words_to_bits(buf[n_i64:n_i64 + _nwords(n_bits)], n_bits)
    inp, fuse = _unpack_inputs(buf[:n_i64], bool_flat, T, D, Z, C, G, E,
                               P, K, M, F, Q)
    if F > 1:
        takes, leftover, carry = _solve_fused(inp, n_max, E, P, F, fuse,
                                              V=V)
    else:
        takes, leftover, carry = _solve(inp, n_max, E, P, V=V)
    return _pack_solve_outputs(takes, leftover, carry)


@partial(jax.jit, static_argnames=("T", "D", "Z", "C", "G", "E", "P",
                                   "K", "V", "M", "n_max", "F", "Q"))
def solve_scan_packed1(buf: jax.Array, *, T: int, D: int, Z: int, C: int,
                       G: int, E: int, P: int, n_max: int,
                       K: int = 0, V: int = 0, M: int = 0,
                       F: int = 1, Q: int = 0) -> jax.Array:
    """One buffer in, one buffer out — a solve is a single round trip.
    F > 1 engages the fused-group block scan (caller-gated: G % F == 0,
    no minValues floors, single device). Q > 0 means the arena carries
    the per-group priority vector (layout only — decisions are
    priority-blind; canonical order encodes priority)."""
    return _packed1_body(buf, T=T, D=D, Z=Z, C=C, G=G, E=E, P=P,
                         n_max=n_max, K=K, V=V, M=M, F=F, Q=Q)


@partial(jax.jit, static_argnames=("T", "D", "Z", "C", "G", "E", "P",
                                   "K", "V", "M", "n_max", "F", "Q"))
def solve_scan_packed1_many(bufs: jax.Array, *, T: int, D: int, Z: int,
                            C: int, G: int, E: int, P: int, n_max: int,
                            K: int = 0, V: int = 0, M: int = 0,
                            F: int = 1, Q: int = 0) -> jax.Array:
    """B solves, ONE dispatch: vmap of the packed body over stacked
    [B, W] buffers sharing one statics bucket. vmap-of-scan batches the
    carry, so B snapshots cost G (or G/F) scan trips TOTAL — the
    multi-solve amortization consolidation's pre-screen and the
    sidecar's queued solves ride (solver/tpu.py solve_batch)."""
    fn = partial(_packed1_body, T=T, D=D, Z=Z, C=C, G=G, E=E, P=P,
                 n_max=n_max, K=K, V=V, M=M, F=F, Q=Q)
    return jax.vmap(fn)(bufs)


def _packed1_pruned_body(buf: jax.Array, *, T, D, Z, C, G, E, P, n_max,
                         S) -> jax.Array:
    n_i64 = _layout_sizes(_in_layout_i64(T, D, Z, C, G, E, P, 0, 0))
    n_bits = _layout_sizes(_in_layout_bool(T, D, Z, C, G, E, P, 0, 0))
    bool_flat = _words_to_bits(buf[n_i64:n_i64 + _nwords(n_bits)], n_bits)
    inp, _ = _unpack_inputs(buf[:n_i64], bool_flat, T, D, Z, C, G, E, P,
                            0, 0)
    takes, leftover, carry = _solve_pruned(inp, n_max, E, P, S)
    return jnp.concatenate([_pack_solve_outputs(takes, leftover, carry),
                            carry.bail.astype(jnp.int64).reshape(1)])


@partial(jax.jit, static_argnames=("T", "D", "Z", "C", "G", "E", "P",
                                   "n_max", "S"))
def solve_scan_packed1_pruned(buf: jax.Array, *, T: int, D: int, Z: int,
                              C: int, G: int, E: int, P: int, n_max: int,
                              S: int = DEV_PRUNED_SLOTS) -> jax.Array:
    """The pruned G-axis kernel behind the same single-buffer wire as
    the base kernel, with ONE extra trailing int64: the bail flag (1 =
    pruning was insufficient; the caller must discard and re-solve on
    the host twin). minValues floors are out of scope (caller-gated)."""
    return _packed1_pruned_body(buf, T=T, D=D, Z=Z, C=C, G=G, E=E, P=P,
                                n_max=n_max, S=S)


@partial(jax.jit, static_argnames=("T", "D", "Z", "C", "G", "E", "P",
                                   "n_max", "S"))
def solve_scan_packed1_pruned_many(bufs: jax.Array, *, T: int, D: int,
                                   Z: int, C: int, G: int, E: int, P: int,
                                   n_max: int,
                                   S: int = DEV_PRUNED_SLOTS) -> jax.Array:
    """B pruned solves, ONE dispatch — the vmapped twin of
    solve_scan_packed1_pruned for the sidecar's coalescing window.
    Each lane carries its OWN trailing bail flag, so a rider whose
    pruning was insufficient degrades alone (its caller re-solves on
    the host twin) without touching its batchmates."""
    fn = partial(_packed1_pruned_body, T=T, D=D, Z=Z, C=C, G=G, E=E, P=P,
                 n_max=n_max, S=S)
    return jax.vmap(fn)(bufs)


def _packed1_ckpt_body(buf: jax.Array, *, T, D, Z, C, G, E, P, n_max,
                       K, V, M, Q=0, CK=4):
    n_i64 = _layout_sizes(_in_layout_i64(T, D, Z, C, G, E, P, K, M, 1, Q))
    n_bits = _layout_sizes(_in_layout_bool(T, D, Z, C, G, E, P, K, M, 1,
                                           Q))
    bool_flat = _words_to_bits(buf[n_i64:n_i64 + _nwords(n_bits)], n_bits)
    inp, _ = _unpack_inputs(buf[:n_i64], bool_flat, T, D, Z, C, G, E, P,
                            K, M, 1, Q)
    takes, leftover, carry, bank = _solve_ckpt(inp, n_max, E, P, V, CK)
    return _pack_solve_outputs(takes, leftover, carry), bank


@partial(jax.jit, static_argnames=("T", "D", "Z", "C", "G", "E", "P",
                                   "K", "V", "M", "n_max", "Q", "CK"))
def solve_scan_packed1_ckpt(buf: jax.Array, *, T: int, D: int, Z: int,
                            C: int, G: int, E: int, P: int, n_max: int,
                            K: int = 0, V: int = 0, M: int = 0,
                            Q: int = 0, CK: int = 4
                            ) -> Tuple[jax.Array, Carry]:
    """The checkpoint-recording full solve behind the single-buffer
    wire: the packed output buffer PLUS a device-resident [G/CK, ...]
    checkpoint bank (carry entering every CK-th group). The bank never
    crosses the wire — the dispatcher keeps it on device and feeds one
    entry back into ``solve_scan_suffix`` on warm ticks. Unfused only
    (caller-gated F == 1): the fused block scan has no per-group carry
    sequence to checkpoint."""
    return _packed1_ckpt_body(buf, T=T, D=D, Z=Z, C=C, G=G, E=E, P=P,
                              n_max=n_max, K=K, V=V, M=M, Q=Q, CK=CK)


def _packed1_suffix_body(buf: jax.Array, bank: Carry, *, T, D, Z, C, G,
                         E, P, n_max, K, V, M, Q=0, CK=4, SUF=1,
                         GL=None):
    n_i64 = _layout_sizes(_in_layout_i64(T, D, Z, C, G, E, P, K, M, 1, Q))
    n_bits = _layout_sizes(_in_layout_bool(T, D, Z, C, G, E, P, K, M, 1,
                                           Q))
    bool_flat = _words_to_bits(buf[n_i64:n_i64 + _nwords(n_bits)], n_bits)
    inp, _ = _unpack_inputs(buf[:n_i64], bool_flat, T, D, Z, C, G, E, P,
                            K, M, 1, Q)
    if GL is None:
        GL = G
    jr = GL // CK - SUF  # static: resume chunk index
    ck = jax.tree_util.tree_map(lambda a: a[jr], bank)
    takes_s, left_s, carry, mini = _solve_suffix(inp, ck, n_max, E, P, V,
                                                 CK, SUF, GL)
    pad_chunks = G // CK - GL // CK
    if pad_chunks:
        # chunks past the live bound are all-empty groups: their entry
        # carries equal the final carry (empty groups are carry
        # no-ops), so the bank splice stays byte-identical to a full
        # re-record without ever scanning them
        mini = jax.tree_util.tree_map(
            lambda m, c: jnp.concatenate(
                [m, jnp.broadcast_to(c[None], (pad_chunks,) + c.shape)]),
            mini, carry)
    new_bank = jax.tree_util.tree_map(lambda f, m: f.at[jr:].set(m),
                                      bank, mini)
    return _pack_solve_outputs(takes_s, left_s, carry), new_bank


@partial(jax.jit, static_argnames=("T", "D", "Z", "C", "G", "E", "P",
                                   "K", "V", "M", "n_max", "Q", "CK",
                                   "SUF", "GL"))
def solve_scan_suffix(buf: jax.Array, bank: Carry, *, T: int, D: int,
                      Z: int, C: int, G: int, E: int, P: int, n_max: int,
                      K: int = 0, V: int = 0, M: int = 0, Q: int = 0,
                      CK: int = 4, SUF: int = 1, GL: int = None
                      ) -> Tuple[jax.Array, Carry]:
    """Suffix-only re-solve: select the checkpoint entering group
    GL - SUF*CK out of the resident [G/CK, ...] ``bank`` and scan only
    the SUF*CK groups below the live bound ``GL`` of the SAME packed
    arena the full solve consumes (GL, chunk-aligned, bounds the
    non-empty groups: everything past it is padding or an emptied
    group, a carry no-op the scan can skip without changing a byte —
    solver/incremental.py ``live_bound``; None means G). The output
    buffer is the standard three-section layout with the group axis
    sized SUF*CK (hostpack.unpack_outputs1 with G=SUF*CK) covering
    groups [GL - SUF*CK, GL); the second output is the UPDATED bank
    (the suffix's own mini checkpoints spliced over the stale tail,
    pad chunks re-stamped with the final carry), ready to be adopted
    as-is for the next tick. Checkpoint select and bank splice both
    live inside the jit: a warm tick costs ONE dispatch, not a flurry
    of per-leaf eager gathers. SUF is bucketed by the dispatcher
    (solver/incremental.py) so warm frontiers never trigger
    recompiles."""
    return _packed1_suffix_body(buf, bank, T=T, D=D, Z=Z, C=C, G=G, E=E,
                                P=P, n_max=n_max, K=K, V=V, M=M, Q=Q,
                                CK=CK, SUF=SUF, GL=GL)
