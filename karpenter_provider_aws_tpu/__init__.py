"""karpenter_provider_aws_tpu — a TPU-native node-provisioning framework.

A ground-up rebuild of the capabilities of karpenter-provider-aws (the AWS
provider plugin) *plus* the sigs.k8s.io/karpenter core engine it plugs into
(provisioning bin-packing, consolidation/disruption, cluster state, node
lifecycle), re-designed TPU-first: the scheduling hot path is a dense
constraint tensor (pods x instance-types x topology-domains) evaluated by
batched jit-compiled JAX/XLA kernels, behind a pluggable ``Solver`` interface
with a CPU reference oracle (decision-identical by construction).

Layout
------
- ``apis``            CRD-shaped user API: NodePool / NodeClaim / EC2NodeClass,
                      the requirements (label-set) algebra, resources, labels.
- ``models``          Tensor encodings of the scheduling problem (the
                      "model" of this framework): constraint-tensor builder.
- ``ops``             JAX kernels: feasibility, vectorized FFD packing,
                      scoring, consolidation replacement search.
- ``parallel``        Mesh/sharding: pods-axis SPMD via shard_map/pjit.
- ``solver``          Solver interface + CPU oracle + TPU solver.
- ``state``           In-memory cluster state cache (core `state.Cluster`).
- ``cloudprovider``   The CloudProvider plugin boundary (Create/Delete/Get/
                      List/GetInstanceTypes/IsDrifted/RepairPolicies).
- ``providers``       Resource services: instancetype catalog, instance
                      launcher, pricing, subnet, securitygroup, amifamily,
                      launchtemplate, instanceprofile, ssm, sqs, version.
- ``controllers``     Reconcilers: provisioning, disruption, GC, tagging,
                      interruption, nodeclass status, catalog/pricing refresh.
- ``batcher``         Generic request micro-batching engine.
- ``cache``           TTL caches + UnavailableOfferings (ICE blacklist).
- ``fake``            In-memory fake cloud + fake kube API for tests.
- ``sidecar``         Solver RPC service (control plane <-> solver boundary).

Reference parity citations use ``file:line`` against /root/reference
(karpenter-provider-aws @ 2025-03-03).
"""

__version__ = "0.1.0"
