"""Fake kubelet + kube-scheduler: turns running cloud instances into Ready
Nodes and binds nominated pods.

The E2E analog of real nodes joining the cluster (the reference tests this
against live EKS; we simulate the join so the control-plane loop closes:
launch -> register -> initialize -> pods bound).
"""

from __future__ import annotations

import time
from typing import Dict

from ..apis import labels as L
from ..apis.objects import Node
from ..fake.ec2 import FakeEC2
from ..fake.kube import FakeKube
from ..state.cluster import ClusterState


from .catalog import table_pod_limit as _table_pod_limit


class FakeKubelet:
    def __init__(self, kube: FakeKube, ec2: FakeEC2, catalog_by_name,
                 state: ClusterState, clock=time.time,
                 vm_overhead_percent: float = 0.075,
                 reserved_enis: int = 0, metrics=None):
        self.kube = kube
        self.ec2 = ec2
        self.catalog = catalog_by_name
        self.state = state
        self.clock = clock
        self.overhead = vm_overhead_percent
        self.reserved_enis = reserved_enis
        self.metrics = metrics
        self._paused = False

    def pause(self) -> None:
        """Stop nodes from joining (the E2E 'node never registers'
        scenario — drives the registration-TTL reap path)."""
        self._paused = True

    def resume(self) -> None:
        self._paused = False

    def tick(self) -> int:
        """Join running instances that have a NodeClaim; bind nominated pods
        on ready nodes. Returns number of nodes joined."""
        if self._paused:
            return 0
        joined = 0
        claims = {c.provider_id: c for c in self.kube.list("NodeClaim")
                  if c.provider_id}
        nodes_by_pid = {n.provider_id: n for n in self.kube.list("Node")}
        for inst in self.ec2.describe_instances():
            if inst.state != "running" or inst.provider_id in nodes_by_pid:
                continue
            claim = claims.get(inst.provider_id)
            if claim is None:
                continue
            node = self._make_node(inst, claim)
            self.kube.create(node)
            joined += 1
            if self.metrics is not None:
                self.metrics.inc(
                    "karpenter_nodes_created_total",
                    labels={"nodepool":
                            node.metadata.labels.get(L.NODEPOOL, "")})
        self._bind_nominated_pods()
        self._reap_terminated(nodes_by_pid)
        self._reap_orphaned_ephemeral_pvcs()
        return joined

    def _reap_orphaned_ephemeral_pvcs(self) -> None:
        """The ownerRef cascade on generic ephemeral PVCs: a pod-owned
        PVC (and its bound dynamic PV — Delete reclaim) is garbage-
        collected once the owning pod is gone. Without this a recreated
        same-named pod with a different volume spec would inherit the
        stale claim and be pinned to the old zone/class."""
        from ..fake.kube import NotFound
        for pvc in list(self.kube.list("PersistentVolumeClaim")):
            for ref in pvc.metadata.owner_refs:
                parts = ref.split("/")
                if len(parts) != 4 or parts[0] != "Pod":
                    continue
                _, ns, name, uid = parts
                owner = self.kube.try_get("Pod", name, namespace=ns)
                # UID match: a recreated same-named pod is NOT the owner
                if owner is None or owner.metadata.uid != uid:
                    if pvc.volume_name:
                        try:
                            self.kube.delete("PersistentVolume",
                                             pvc.volume_name)
                        except NotFound:
                            pass
                    try:
                        self.kube.delete("PersistentVolumeClaim",
                                         pvc.metadata.name,
                                         namespace=pvc.metadata.namespace)
                    except NotFound:
                        pass
                    break

    def _make_node(self, inst, claim) -> Node:
        from ..apis.resources import Resources
        info = self.catalog.get(inst.instance_type)
        labels = dict(claim.metadata.labels)
        labels.update({
            L.INSTANCE_TYPE: inst.instance_type,
            L.ZONE: inst.zone, L.ZONE_ID: inst.zone_id,
            L.CAPACITY_TYPE: inst.capacity_type,
            L.HOSTNAME: claim.name,
        })
        # OS rides the claim's resolved requirements (windows families
        # produce windows nodes); default linux
        labels.setdefault(L.OS, L.OS_LINUX)
        if info is not None:
            from ..apis.resources import ATTACHABLE_VOLUMES
            from .catalog import ebs_attachment_limit
            labels[L.ARCH] = info.arch
            capacity = Resources({
                "cpu": info.vcpus * 1000,
                # real nodes report true memory (discovered-capacity source)
                "memory": int(info.memory_bytes * (1 - self.overhead * 0.9)),
                "pods": _table_pod_limit(info, self.reserved_enis),
                "ephemeral-storage": 20 * 1024**3,
                ATTACHABLE_VOLUMES: ebs_attachment_limit(info),
            })
        else:
            capacity = claim.capacity
        allocatable = claim.allocatable if not claim.allocatable.is_zero() \
            else capacity
        node = Node(name=claim.name, labels=labels, capacity=capacity,
                    allocatable=allocatable,
                    taints=[t for t in claim.taints],
                    provider_id=inst.provider_id)
        # claim annotations propagate to the node (core registration)
        node.metadata.annotations.update(claim.metadata.annotations)
        node.ready = True
        return node

    def _bind_nominated_pods(self) -> None:
        ready = {n.name for n in self.kube.list("Node") if n.ready}
        for pod in self.kube.list("Pod"):
            if pod.node_name:
                continue
            target = self.state.nomination_for(pod.full_name())
            if target and target in ready:
                pod.node_name = target
                pod.phase = "Running"
                self.state.clear_nomination(pod.full_name())
                self._bind_volumes(pod, target)
                self.kube.update(pod)
                if self.metrics is not None:
                    # created -> running wall-clock (metrics.md pods group)
                    self.metrics.observe(
                        "karpenter_pods_startup_duration_seconds",
                        max(0.0, self.clock()
                            - pod.metadata.creation_timestamp))

    def _bind_volumes(self, pod, node_name: str) -> None:
        """Dynamic provisioning: unbound PVCs bind to a fresh PV in the
        pod's zone once the pod lands (WaitForFirstConsumer semantics —
        the storage suite's dynamic-volume specs). Generic ephemeral
        volumes create their pod-owned `<pod>-<volume>` PVC here first
        (the k8s ephemeral-controller analog), then bind the same way."""
        ephemeral = getattr(pod, "ephemeral_volumes", None) or ()
        if not getattr(pod, "volume_claims", None) and not ephemeral:
            return
        from ..apis.objects import PersistentVolume, PersistentVolumeClaim
        node = self.kube.try_get("Node", node_name)
        zone = node.metadata.labels.get(L.ZONE, "") if node else ""
        claim_names = list(pod.volume_claims)
        for vol_name, sc_name in ephemeral:
            cn = f"{pod.metadata.name}-{vol_name}"
            owner_ref = (f"Pod/{pod.metadata.namespace}/"
                         f"{pod.metadata.name}/{pod.metadata.uid}")
            existing = self.kube.try_get(
                "PersistentVolumeClaim", cn,
                namespace=pod.metadata.namespace)
            if existing is None:
                pvc = PersistentVolumeClaim(
                    cn, namespace=pod.metadata.namespace,
                    storage_class=sc_name)
                # pod-owned BY UID: the GC sweep below reaps it with the
                # pod (the k8s ownerRef cascade on generic ephemeral
                # PVCs), and a recreated same-named pod never matches
                pvc.metadata.owner_refs.append(owner_ref)
                self.kube.create(pvc)
            elif owner_ref not in existing.metadata.owner_refs:
                # claim-name collision with a claim this pod does NOT
                # own (e.g. pods 'a'/'b-data' vs 'a-b'/'data'): real
                # k8s's ephemeral controller refuses to adopt — never
                # bind someone else's volume (its owner's deletion
                # would reap the PV out from under us)
                import logging
                logging.getLogger(__name__).warning(
                    "ephemeral volume %s of pod %s collides with a "
                    "claim owned elsewhere; not adopting", cn,
                    pod.full_name())
                continue
            claim_names.append(cn)
        for claim_name in claim_names:
            pvc = self.kube.try_get("PersistentVolumeClaim", claim_name,
                                    namespace=pod.metadata.namespace)
            if pvc is None or pvc.bound:
                continue
            # the zone is part of the PV identity: a recreated same-named
            # PVC landing in another zone must get a fresh volume, never a
            # leftover one pinned elsewhere
            pv = PersistentVolume(
                name=f"pv-{claim_name}-{pod.metadata.namespace}-{zone}",
                zone=zone, storage_class=pvc.storage_class,
                capacity=pvc.requested)
            pv.phase = "Bound"
            if self.kube.try_get("PersistentVolume", pv.name) is None:
                self.kube.create(pv)
            pvc.volume_name = pv.name
            self.kube.update(pvc)

    def _reap_terminated(self, nodes_by_pid: Dict[str, Node]) -> None:
        """Instance terminated out from under a node -> node NotReady."""
        live = {i.provider_id for i in self.ec2.describe_instances()}
        for pid, node in nodes_by_pid.items():
            if pid not in live and node.ready:
                node.ready = False
                self.kube.update(node)
