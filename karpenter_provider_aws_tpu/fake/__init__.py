from .catalog import (DEFAULT_REGION, DEFAULT_ZONES, FAMILIES,
                      InstanceTypeInfo, ZoneInfo, build_catalog,
                      catalog_by_name, spot_price)
from .ec2 import (FakeEC2, FakeImage, FakeInstance, FakeLaunchTemplate,
                  FakeSecurityGroup, FakeSubnet)
from .faultwire import FaultInjector, FaultPlan
from .kube import Conflict, Event, FakeKube, NotFound

__all__ = [
    "DEFAULT_REGION", "DEFAULT_ZONES", "FAMILIES", "InstanceTypeInfo",
    "ZoneInfo", "build_catalog", "catalog_by_name", "spot_price",
    "FakeEC2", "FakeImage", "FakeInstance", "FakeLaunchTemplate",
    "FakeSecurityGroup", "FakeSubnet", "FakeKube", "Event", "Conflict",
    "NotFound", "FaultInjector", "FaultPlan",
]
