"""Parametric EC2-like instance catalog for the fake cloud.

The reference ships generated data tables for the real EC2 catalog
(zz_generated.describe_instance_types.go — 885 LoC of 5 sample types for
tests; zz_generated.vpclimits.go — 13k LoC of ENI limits;
zz_generated.bandwidth.go; zz_generated.pricing_aws*.go). We generate an
equivalent-scale catalog parametrically: families x generations x sizes with
realistic vCPU/memory ratios, GPU/accelerator models, ENI-formula pod limits,
and deterministic on-demand + per-zone spot pricing (fixed-point micro-USD).

Determinism: every number derives from the type/zone names via stable
hashing — two processes always build the identical catalog.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Sequence, Tuple

from ..cloudprovider.types import usd

GIB = 1024**3


@dataclass(frozen=True)
class ZoneInfo:
    name: str      # us-west-2a
    zone_id: str   # usw2-az1
    zone_type: str = "availability-zone"  # | local-zone


DEFAULT_REGION = "us-west-2"
DEFAULT_ZONES = (
    ZoneInfo("us-west-2a", "usw2-az1"),
    ZoneInfo("us-west-2b", "usw2-az2"),
    ZoneInfo("us-west-2c", "usw2-az3"),
    ZoneInfo("us-west-2d", "usw2-az4"),
)


@dataclass(frozen=True)
class InstanceTypeInfo:
    """Raw catalog row (the DescribeInstanceTypes analog)."""
    name: str                      # m6i.2xlarge
    family: str                    # m6i
    category: str                  # m
    generation: int                # 6
    size: str                      # 2xlarge
    arch: str                      # amd64 | arm64
    vcpus: int
    memory_bytes: int
    cpu_manufacturer: str          # intel | amd | aws
    hypervisor: str                # nitro | xen | "" (metal)
    bare_metal: bool
    enis: int
    ipv4_per_eni: int
    network_bandwidth_mbps: int
    ebs_bandwidth_mbps: int
    local_nvme_bytes: int = 0
    gpu_name: str = ""
    gpu_manufacturer: str = ""
    gpu_count: int = 0
    gpu_memory_bytes: int = 0
    accelerator_name: str = ""
    accelerator_manufacturer: str = ""
    accelerator_count: int = 0
    efa_count: int = 0
    encryption_in_transit: bool = True
    od_price: int = 0              # micro-USD/hour

    @property
    def eni_pod_limit(self) -> int:
        """ENI-formula max pods: enis*(ips-1)+2 (vpclimits analog)."""
        return self.enis * (self.ipv4_per_eni - 1) + 2


# (size -> vcpus) ladder
_SIZES: Dict[str, int] = {
    "medium": 1, "large": 2, "xlarge": 4, "2xlarge": 8, "4xlarge": 16,
    "8xlarge": 32, "12xlarge": 48, "16xlarge": 64, "24xlarge": 96,
    "32xlarge": 128, "48xlarge": 192, "metal": 96,
}

# vcpus -> (enis, ipv4 per eni): the base curve of the real vpclimits table
_ENI_LIMITS: Sequence[Tuple[int, int, int]] = (
    (1, 2, 4), (2, 3, 10), (4, 4, 15), (8, 4, 15), (16, 8, 30),
    (32, 8, 30), (48, 15, 50), (64, 15, 50), (96, 15, 50),
    (128, 15, 50), (192, 15, 50),
)

#: per-type irregularities, exactly the kind the generated
#: zz_generated.vpclimits.go table encodes where the formula is wrong
#: for a specific type (burstables, macs, network-heavy giants)
_VPC_LIMIT_OVERRIDES: Dict[str, Tuple[int, int]] = {
    "t1.micro": (2, 2),
    "t2.nano": (2, 2), "t2.micro": (2, 2), "t2.small": (3, 4),
    "t3.nano": (2, 2), "t3.micro": (2, 2), "t3.small": (3, 4),
    "t3a.nano": (2, 2), "t3a.micro": (2, 2), "t3a.small": (2, 4),
    "t4g.nano": (2, 2), "t4g.micro": (2, 2), "t4g.small": (3, 4),
    "mac1.metal": (8, 30), "mac2.metal": (8, 14),
    "mac2-m2.metal": (8, 14), "mac2-m2pro.metal": (8, 14),
    "p5.48xlarge": (64, 50), "p5e.48xlarge": (64, 50),
    "trn1.32xlarge": (40, 50), "trn1n.32xlarge": (80, 50),
    "trn2.48xlarge": (80, 50),
    "u-6tb1.112xlarge": (15, 50), "u-12tb1.112xlarge": (15, 50),
    "hpc6a.48xlarge": (2, 50), "hpc6id.32xlarge": (2, 50),
    "hpc7a.96xlarge": (2, 50), "hpc7g.16xlarge": (1, 50),
}

#: per-type network-bandwidth irregularities (zz_generated.bandwidth.go
#: carries explicit Mbps per type; these are the rows the per-family
#: rate formula cannot produce)
_BANDWIDTH_OVERRIDES: Dict[str, int] = {
    "p4d.24xlarge": 400_000, "p4de.24xlarge": 400_000,
    "p5.48xlarge": 3_200_000, "p5e.48xlarge": 3_200_000,
    "trn1.32xlarge": 800_000, "trn1n.32xlarge": 1_600_000,
    "trn2.48xlarge": 3_200_000,
    "p3dn.24xlarge": 100_000, "dl1.24xlarge": 400_000,
    "hpc6a.48xlarge": 100_000, "hpc6id.32xlarge": 200_000,
    "hpc7a.96xlarge": 300_000, "hpc7g.16xlarge": 200_000,
    "mac1.metal": 25_000, "mac2.metal": 10_000,
    "mac2-m2.metal": 10_000, "mac2-m2pro.metal": 10_000,
    "c5n.18xlarge": 100_000, "c5n.metal": 100_000,
    "c6gn.16xlarge": 100_000, "c7gn.16xlarge": 200_000,
    "m5zn.12xlarge": 100_000, "m5zn.metal": 100_000,
    "x2iezn.12xlarge": 100_000, "x2iezn.metal": 100_000,
}


def _eni(vcpus: int) -> Tuple[int, int]:
    for v, enis, ips in _ENI_LIMITS:
        if vcpus <= v:
            return enis, ips
    return 15, 50


@dataclass(frozen=True)
class FamilySpec:
    family: str
    category: str
    generation: int
    arch: str
    cpu_manufacturer: str
    gib_per_vcpu: int
    sizes: Tuple[str, ...]
    od_price_per_vcpu: float        # USD/hour
    local_nvme_gib_per_vcpu: int = 0
    gpu: Tuple[str, str, int] = ("", "", 0)      # (name, mfr, GiB mem/gpu)
    gpus_by_size: Mapping[str, int] = field(default_factory=dict)
    accel: Tuple[str, str] = ("", "")
    accels_by_size: Mapping[str, int] = field(default_factory=dict)
    efa_sizes: Tuple[str, ...] = ()
    network_gbps_per_vcpu: float = 0.4
    metal_vcpus: int = 0            # metal-only families (mac) set this


_STD = ("large", "xlarge", "2xlarge", "4xlarge", "8xlarge", "12xlarge",
        "16xlarge", "24xlarge", "metal")
_STD_NO_METAL = _STD[:-1]
_BURST = ("nano", "micro", "small", "medium", "large", "xlarge", "2xlarge")


def _f(family, category, gen, arch, mfr, ratio, price, sizes=_STD_NO_METAL, **kw):
    return FamilySpec(family, category, gen, arch, mfr, ratio, tuple(sizes), price, **kw)


FAMILIES: Tuple[FamilySpec, ...] = (
    # compute optimized (2 GiB/vCPU)
    _f("c4", "c", 4, "amd64", "intel", 2, 0.0500, sizes=("large", "xlarge", "2xlarge", "4xlarge", "8xlarge")),
    _f("c5", "c", 5, "amd64", "intel", 2, 0.0425, sizes=_STD),
    _f("c5a", "c", 5, "amd64", "amd", 2, 0.0385),
    _f("c5d", "c", 5, "amd64", "intel", 2, 0.0480, local_nvme_gib_per_vcpu=25, sizes=_STD),
    _f("c6g", "c", 6, "arm64", "aws", 2, 0.0340, sizes=_STD),
    _f("c6gd", "c", 6, "arm64", "aws", 2, 0.0384, local_nvme_gib_per_vcpu=25),
    _f("c6i", "c", 6, "amd64", "intel", 2, 0.0425, sizes=_STD),
    _f("c6a", "c", 6, "amd64", "amd", 2, 0.0383, sizes=_STD),
    _f("c7g", "c", 7, "arm64", "aws", 2, 0.0363, sizes=_STD),
    _f("c7i", "c", 7, "amd64", "intel", 2, 0.0446, sizes=_STD),
    _f("c7a", "c", 7, "amd64", "amd", 2, 0.0513),
    # general purpose (4 GiB/vCPU)
    _f("m4", "m", 4, "amd64", "intel", 4, 0.0575, sizes=("large", "xlarge", "2xlarge", "4xlarge", "16xlarge")),
    _f("m5", "m", 5, "amd64", "intel", 4, 0.0480, sizes=_STD),
    _f("m5a", "m", 5, "amd64", "amd", 4, 0.0430),
    _f("m5d", "m", 5, "amd64", "intel", 4, 0.0565, local_nvme_gib_per_vcpu=37, sizes=_STD),
    _f("m6g", "m", 6, "arm64", "aws", 4, 0.0385, sizes=_STD),
    _f("m6gd", "m", 6, "arm64", "aws", 4, 0.0452, local_nvme_gib_per_vcpu=59),
    _f("m6i", "m", 6, "amd64", "intel", 4, 0.0480, sizes=_STD),
    _f("m6a", "m", 6, "amd64", "amd", 4, 0.0432, sizes=_STD),
    _f("m7g", "m", 7, "arm64", "aws", 4, 0.0408, sizes=_STD),
    _f("m7i", "m", 7, "amd64", "intel", 4, 0.0504, sizes=_STD),
    _f("m7a", "m", 7, "amd64", "amd", 4, 0.0580),
    # memory optimized (8 GiB/vCPU)
    _f("r4", "r", 4, "amd64", "intel", 7, 0.0665, sizes=("large", "xlarge", "2xlarge", "4xlarge", "8xlarge", "16xlarge")),
    _f("r5", "r", 5, "amd64", "intel", 8, 0.0630, sizes=_STD),
    _f("r5a", "r", 5, "amd64", "amd", 8, 0.0565),
    _f("r5d", "r", 5, "amd64", "intel", 8, 0.0720, local_nvme_gib_per_vcpu=75, sizes=_STD),
    _f("r6g", "r", 6, "arm64", "aws", 8, 0.0504, sizes=_STD),
    _f("r6gd", "r", 6, "arm64", "aws", 8, 0.0576, local_nvme_gib_per_vcpu=118),
    _f("r6i", "r", 6, "amd64", "intel", 8, 0.0630, sizes=_STD),
    _f("r6a", "r", 6, "amd64", "amd", 8, 0.0567, sizes=_STD),
    _f("r7g", "r", 7, "arm64", "aws", 8, 0.0536, sizes=_STD),
    _f("r7i", "r", 7, "amd64", "intel", 8, 0.0661, sizes=_STD),
    # high memory (16 GiB/vCPU)
    _f("x2gd", "x", 2, "arm64", "aws", 16, 0.0835, local_nvme_gib_per_vcpu=59,
       sizes=("medium", "large", "xlarge", "2xlarge", "4xlarge", "8xlarge", "12xlarge", "16xlarge", "metal")),
    _f("x2idn", "x", 2, "amd64", "intel", 16, 0.1668, sizes=("16xlarge", "24xlarge", "32xlarge", "metal")),
    # burstable (t) — 2-4 GiB/vCPU
    _f("t2", "t", 2, "amd64", "intel", 4, 0.0464, sizes=_BURST, network_gbps_per_vcpu=0.1),
    _f("t3", "t", 3, "amd64", "intel", 4, 0.0416, sizes=_BURST, network_gbps_per_vcpu=0.1),
    _f("t3a", "t", 3, "amd64", "amd", 4, 0.0376, sizes=_BURST, network_gbps_per_vcpu=0.1),
    _f("t4g", "t", 4, "arm64", "aws", 4, 0.0336, sizes=_BURST, network_gbps_per_vcpu=0.1),
    # storage optimized
    _f("i3", "i", 3, "amd64", "intel", 7, 0.0780, local_nvme_gib_per_vcpu=232,
       sizes=("large", "xlarge", "2xlarge", "4xlarge", "8xlarge", "16xlarge", "metal")),
    _f("i3en", "i", 3, "amd64", "intel", 8, 0.1130, local_nvme_gib_per_vcpu=312,
       sizes=("large", "xlarge", "2xlarge", "4xlarge", "8xlarge", "12xlarge", "24xlarge", "metal")),
    _f("i4i", "i", 4, "amd64", "intel", 8, 0.0858, local_nvme_gib_per_vcpu=234,
       sizes=("large", "xlarge", "2xlarge", "4xlarge", "8xlarge", "16xlarge", "32xlarge", "metal")),
    _f("d3", "d", 3, "amd64", "intel", 8, 0.1248, local_nvme_gib_per_vcpu=0,
       sizes=("xlarge", "2xlarge", "4xlarge", "8xlarge")),
    # GPU — inference
    _f("g4dn", "g", 4, "amd64", "intel", 4, 0.1315, local_nvme_gib_per_vcpu=28,
       sizes=("xlarge", "2xlarge", "4xlarge", "8xlarge", "12xlarge", "16xlarge", "metal"),
       gpu=("t4", "nvidia", 16),
       gpus_by_size={"xlarge": 1, "2xlarge": 1, "4xlarge": 1, "8xlarge": 1,
                     "12xlarge": 4, "16xlarge": 1, "metal": 8}),
    _f("g5", "g", 5, "amd64", "amd", 4, 0.2518, local_nvme_gib_per_vcpu=58,
       sizes=("xlarge", "2xlarge", "4xlarge", "8xlarge", "12xlarge", "16xlarge", "24xlarge", "48xlarge"),
       gpu=("a10g", "nvidia", 24),
       gpus_by_size={"xlarge": 1, "2xlarge": 1, "4xlarge": 1, "8xlarge": 1,
                     "12xlarge": 4, "16xlarge": 1, "24xlarge": 4, "48xlarge": 8}),
    _f("g6", "g", 6, "amd64", "amd", 4, 0.2012, local_nvme_gib_per_vcpu=58,
       sizes=("xlarge", "2xlarge", "4xlarge", "8xlarge", "12xlarge", "16xlarge", "24xlarge", "48xlarge"),
       gpu=("l4", "nvidia", 24),
       gpus_by_size={"xlarge": 1, "2xlarge": 1, "4xlarge": 1, "8xlarge": 1,
                     "12xlarge": 4, "16xlarge": 1, "24xlarge": 4, "48xlarge": 8}),
    # GPU — training
    _f("p3", "p", 3, "amd64", "intel", 7, 0.3825, sizes=("2xlarge", "8xlarge", "16xlarge"),
       gpu=("v100", "nvidia", 16),
       gpus_by_size={"2xlarge": 1, "8xlarge": 4, "16xlarge": 8}),
    _f("p4d", "p", 4, "amd64", "intel", 12, 0.3414, local_nvme_gib_per_vcpu=83,
       sizes=("24xlarge",), gpu=("a100", "nvidia", 40),
       gpus_by_size={"24xlarge": 8}, efa_sizes=("24xlarge",)),
    _f("p5", "p", 5, "amd64", "amd", 10, 0.5120, local_nvme_gib_per_vcpu=158,
       sizes=("48xlarge",), gpu=("h100", "nvidia", 80),
       gpus_by_size={"48xlarge": 8}, efa_sizes=("48xlarge",)),
    # accelerators — inferentia / trainium
    _f("inf1", "inf", 1, "amd64", "intel", 2, 0.0570,
       sizes=("xlarge", "2xlarge", "6xlarge", "24xlarge"),
       accel=("inferentia", "aws"),
       accels_by_size={"xlarge": 1, "2xlarge": 1, "6xlarge": 4, "24xlarge": 16}),
    _f("inf2", "inf", 2, "amd64", "amd", 4, 0.0947,
       sizes=("xlarge", "8xlarge", "24xlarge", "48xlarge"),
       accel=("inferentia2", "aws"),
       accels_by_size={"xlarge": 1, "8xlarge": 1, "24xlarge": 6, "48xlarge": 12}),
    _f("trn1", "trn", 1, "amd64", "intel", 4, 0.4163,
       sizes=("2xlarge", "32xlarge"), accel=("trainium", "aws"),
       accels_by_size={"2xlarge": 1, "32xlarge": 16}, efa_sizes=("32xlarge",)),
    # ---- full-catalog expansion: the ~850-type surface of the real
    # DescribeInstanceTypes sweep (instancetype.go:200-220). Network
    # (-n), local-NVMe (-d), combined (-dn/-id/-in), block-storage (-b),
    # high-clock (-z), flex, HPC, and previous-generation families.
    # compute optimized extras
    _f("c5n", "c", 5, "amd64", "intel", 3, 0.0540,
       sizes=("large", "xlarge", "2xlarge", "4xlarge", "9xlarge", "18xlarge", "metal"),
       network_gbps_per_vcpu=1.4, efa_sizes=("18xlarge", "metal")),
    _f("c5ad", "c", 5, "amd64", "amd", 2, 0.0430, local_nvme_gib_per_vcpu=29),
    _f("c6gn", "c", 6, "arm64", "aws", 2, 0.0432, network_gbps_per_vcpu=1.6,
       efa_sizes=("16xlarge",)),
    _f("c6id", "c", 6, "amd64", "intel", 2, 0.0504, local_nvme_gib_per_vcpu=29, sizes=_STD),
    _f("c6in", "c", 6, "amd64", "intel", 2, 0.0567, network_gbps_per_vcpu=1.6,
       sizes=_STD, efa_sizes=("24xlarge", "metal")),
    _f("c7gd", "c", 7, "arm64", "aws", 2, 0.0435, local_nvme_gib_per_vcpu=29),
    _f("c7gn", "c", 7, "arm64", "aws", 2, 0.0499, network_gbps_per_vcpu=3.1,
       efa_sizes=("16xlarge",)),
    _f("c8g", "c", 8, "arm64", "aws", 2, 0.0399, sizes=_STD + ("48xlarge",)),
    _f("c7i-flex", "c", 7, "amd64", "intel", 2, 0.0424,
       sizes=("large", "xlarge", "2xlarge", "4xlarge", "8xlarge")),
    # general purpose extras
    _f("m5n", "m", 5, "amd64", "intel", 4, 0.0595, network_gbps_per_vcpu=1.4, sizes=_STD),
    _f("m5dn", "m", 5, "amd64", "intel", 4, 0.0680, network_gbps_per_vcpu=1.4,
       local_nvme_gib_per_vcpu=37, sizes=_STD),
    _f("m5ad", "m", 5, "amd64", "amd", 4, 0.0515, local_nvme_gib_per_vcpu=37),
    _f("m5zn", "m", 5, "amd64", "intel", 4, 0.0826, network_gbps_per_vcpu=1.6,
       sizes=("large", "xlarge", "2xlarge", "3xlarge", "6xlarge", "12xlarge", "metal")),
    _f("m6id", "m", 6, "amd64", "intel", 4, 0.0566, local_nvme_gib_per_vcpu=59, sizes=_STD),
    _f("m6idn", "m", 6, "amd64", "intel", 4, 0.0764, local_nvme_gib_per_vcpu=59,
       network_gbps_per_vcpu=1.6, sizes=_STD),
    _f("m6in", "m", 6, "amd64", "intel", 4, 0.0668, network_gbps_per_vcpu=1.6, sizes=_STD),
    _f("m7gd", "m", 7, "arm64", "aws", 4, 0.0481, local_nvme_gib_per_vcpu=59),
    _f("m7i-flex", "m", 7, "amd64", "intel", 4, 0.0479,
       sizes=("large", "xlarge", "2xlarge", "4xlarge", "8xlarge")),
    _f("m8g", "m", 8, "arm64", "aws", 4, 0.0449, sizes=_STD + ("48xlarge",)),
    _f("a1", "a", 1, "arm64", "aws", 2, 0.0255,
       sizes=("medium", "large", "xlarge", "2xlarge", "4xlarge", "metal")),
    # memory optimized extras
    _f("r5b", "r", 5, "amd64", "intel", 8, 0.0744, sizes=_STD),
    _f("r5n", "r", 5, "amd64", "intel", 8, 0.0744, network_gbps_per_vcpu=1.4, sizes=_STD),
    _f("r5dn", "r", 5, "amd64", "intel", 8, 0.0836, network_gbps_per_vcpu=1.4,
       local_nvme_gib_per_vcpu=75, sizes=_STD),
    _f("r5ad", "r", 5, "amd64", "amd", 8, 0.0655, local_nvme_gib_per_vcpu=75),
    _f("r6id", "r", 6, "amd64", "intel", 8, 0.0756, local_nvme_gib_per_vcpu=118, sizes=_STD),
    _f("r6idn", "r", 6, "amd64", "intel", 8, 0.0977, local_nvme_gib_per_vcpu=118,
       network_gbps_per_vcpu=1.6, sizes=_STD),
    _f("r6in", "r", 6, "amd64", "intel", 8, 0.0871, network_gbps_per_vcpu=1.6, sizes=_STD),
    _f("r7gd", "r", 7, "arm64", "aws", 8, 0.0683, local_nvme_gib_per_vcpu=118),
    _f("r7a", "r", 7, "amd64", "amd", 8, 0.0761, sizes=_STD + ("48xlarge",)),
    _f("r7iz", "r", 7, "amd64", "intel", 8, 0.0930,
       sizes=("large", "xlarge", "2xlarge", "4xlarge", "8xlarge", "12xlarge",
              "16xlarge", "32xlarge", "metal")),
    _f("r8g", "r", 8, "arm64", "aws", 8, 0.0590, sizes=_STD + ("48xlarge",)),
    _f("z1d", "z", 1, "amd64", "intel", 8, 0.0930, local_nvme_gib_per_vcpu=75,
       sizes=("large", "xlarge", "2xlarge", "3xlarge", "6xlarge", "12xlarge", "metal")),
    # high memory extras
    _f("x1", "x", 1, "amd64", "intel", 15, 0.1043, sizes=("16xlarge", "32xlarge")),
    _f("x1e", "x", 1, "amd64", "intel", 30, 0.2086,
       sizes=("xlarge", "2xlarge", "4xlarge", "8xlarge", "16xlarge", "32xlarge")),
    _f("x2iedn", "x", 2, "amd64", "intel", 32, 0.3336, local_nvme_gib_per_vcpu=59,
       sizes=("xlarge", "2xlarge", "4xlarge", "8xlarge", "16xlarge", "24xlarge",
              "32xlarge", "metal")),
    _f("x2iezn", "x", 2, "amd64", "intel", 16, 0.2084,
       sizes=("2xlarge", "4xlarge", "6xlarge", "8xlarge", "12xlarge", "metal")),
    _f("x8g", "x", 8, "arm64", "aws", 16, 0.0900, sizes=_STD + ("48xlarge",)),
    # storage optimized extras
    _f("im4gn", "i", 4, "arm64", "aws", 4, 0.0910, local_nvme_gib_per_vcpu=234,
       sizes=("large", "xlarge", "2xlarge", "4xlarge", "8xlarge", "16xlarge")),
    _f("is4gen", "i", 4, "arm64", "aws", 6, 0.1152, local_nvme_gib_per_vcpu=468,
       sizes=("medium", "large", "xlarge", "2xlarge", "4xlarge", "8xlarge")),
    _f("i4g", "i", 4, "arm64", "aws", 8, 0.0772, local_nvme_gib_per_vcpu=234,
       sizes=("large", "xlarge", "2xlarge", "4xlarge", "8xlarge", "16xlarge")),
    _f("i7ie", "i", 7, "amd64", "intel", 8, 0.1376, local_nvme_gib_per_vcpu=312,
       sizes=("large", "xlarge", "2xlarge", "3xlarge", "6xlarge", "12xlarge",
              "18xlarge", "24xlarge", "48xlarge")),
    _f("d3en", "d", 3, "amd64", "intel", 8, 0.1501,
       sizes=("xlarge", "2xlarge", "4xlarge", "6xlarge", "8xlarge", "12xlarge")),
    _f("h1", "h", 1, "amd64", "intel", 4, 0.1170,
       sizes=("2xlarge", "4xlarge", "8xlarge", "16xlarge")),
    # HPC (EFA-first, no metal)
    _f("hpc6a", "hpc", 6, "amd64", "amd", 4, 0.0300, sizes=("48xlarge",),
       network_gbps_per_vcpu=1.0, efa_sizes=("48xlarge",)),
    _f("hpc6id", "hpc", 6, "amd64", "intel", 16, 0.0892, sizes=("32xlarge",),
       local_nvme_gib_per_vcpu=237, network_gbps_per_vcpu=1.5,
       efa_sizes=("32xlarge",)),
    _f("hpc7a", "hpc", 7, "amd64", "amd", 4, 0.0450,
       sizes=("12xlarge", "24xlarge", "48xlarge", "96xlarge"),
       network_gbps_per_vcpu=1.5, efa_sizes=("12xlarge", "24xlarge", "48xlarge", "96xlarge")),
    _f("hpc7g", "hpc", 7, "arm64", "aws", 2, 0.0270,
       sizes=("4xlarge", "8xlarge", "16xlarge"), network_gbps_per_vcpu=3.0,
       efa_sizes=("4xlarge", "8xlarge", "16xlarge")),
    # GPU extras
    _f("g3", "g", 3, "amd64", "intel", 8, 0.2850, sizes=("4xlarge", "8xlarge", "16xlarge"),
       gpu=("m60", "nvidia", 8),
       gpus_by_size={"4xlarge": 1, "8xlarge": 2, "16xlarge": 4}),
    _f("g3s", "g", 3, "amd64", "intel", 8, 0.1875, sizes=("xlarge",),
       gpu=("m60", "nvidia", 8), gpus_by_size={"xlarge": 1}),
    _f("p2", "p", 2, "amd64", "intel", 15, 0.2250, sizes=("xlarge", "8xlarge", "16xlarge"),
       gpu=("k80", "nvidia", 12),
       gpus_by_size={"xlarge": 1, "8xlarge": 8, "16xlarge": 16}),
    _f("g6e", "g", 6, "amd64", "amd", 8, 0.4661, local_nvme_gib_per_vcpu=58,
       sizes=("xlarge", "2xlarge", "4xlarge", "8xlarge", "12xlarge", "16xlarge",
              "24xlarge", "48xlarge"),
       gpu=("l40s", "nvidia", 48),
       gpus_by_size={"xlarge": 1, "2xlarge": 1, "4xlarge": 1, "8xlarge": 1,
                     "12xlarge": 4, "16xlarge": 1, "24xlarge": 4, "48xlarge": 8}),
    _f("gr6", "g", 6, "amd64", "amd", 8, 0.2723, local_nvme_gib_per_vcpu=58,
       sizes=("4xlarge", "8xlarge"), gpu=("l4", "nvidia", 24),
       gpus_by_size={"4xlarge": 1, "8xlarge": 1}),
    _f("p5e", "p", 5, "amd64", "amd", 10, 0.5500, local_nvme_gib_per_vcpu=158,
       sizes=("48xlarge",), gpu=("h200", "nvidia", 141),
       gpus_by_size={"48xlarge": 8}, efa_sizes=("48xlarge",)),
    # video transcoding / FPGA / ML training extras
    _f("vt1", "vt", 1, "amd64", "intel", 4, 0.1083,
       sizes=("3xlarge", "6xlarge", "24xlarge"),
       accel=("u30", "xilinx"),
       accels_by_size={"3xlarge": 1, "6xlarge": 2, "24xlarge": 8}),
    _f("f1", "f", 1, "amd64", "intel", 15, 0.2063,
       sizes=("2xlarge", "4xlarge", "16xlarge"),
       accel=("vu9p", "xilinx"),
       accels_by_size={"2xlarge": 1, "4xlarge": 2, "16xlarge": 8}),
    _f("dl1", "dl", 1, "amd64", "intel", 8, 0.1365,
       sizes=("24xlarge",), accel=("gaudi", "habana"),
       accels_by_size={"24xlarge": 8}, efa_sizes=("24xlarge",)),
    _f("trn1n", "trn", 1, "amd64", "intel", 4, 0.4992,
       sizes=("32xlarge",), accel=("trainium", "aws"),
       accels_by_size={"32xlarge": 16}, efa_sizes=("32xlarge",),
       network_gbps_per_vcpu=12.5),
    _f("trn2", "trn", 2, "amd64", "intel", 4, 0.5100,
       sizes=("48xlarge",), accel=("trainium2", "aws"),
       accels_by_size={"48xlarge": 16}, efa_sizes=("48xlarge",)),
    # arm GPU + large-scale training variants
    _f("g5g", "g", 5, "arm64", "aws", 4, 0.1053,
       sizes=("xlarge", "2xlarge", "4xlarge", "8xlarge", "16xlarge", "metal"),
       gpu=("t4g", "nvidia", 16),
       gpus_by_size={"xlarge": 1, "2xlarge": 1, "4xlarge": 1, "8xlarge": 1,
                     "16xlarge": 2, "metal": 2}),
    _f("g4ad", "g", 4, "amd64", "amd", 4, 0.0946, local_nvme_gib_per_vcpu=37,
       sizes=("xlarge", "2xlarge", "4xlarge", "8xlarge", "16xlarge"),
       gpu=("radeon-pro-v520", "amd", 8),
       gpus_by_size={"xlarge": 1, "2xlarge": 1, "4xlarge": 1, "8xlarge": 2,
                     "16xlarge": 4}),
    _f("p4de", "p", 4, "amd64", "intel", 12, 0.4270, local_nvme_gib_per_vcpu=83,
       sizes=("24xlarge",), gpu=("a100", "nvidia", 80),
       gpus_by_size={"24xlarge": 8}, efa_sizes=("24xlarge",)),
    _f("p3dn", "p", 3, "amd64", "intel", 8, 0.4266, local_nvme_gib_per_vcpu=18,
       sizes=("24xlarge",), gpu=("v100", "nvidia", 32),
       gpus_by_size={"24xlarge": 8}, efa_sizes=("24xlarge",)),
    _f("i8g", "i", 8, "arm64", "aws", 8, 0.0993, local_nvme_gib_per_vcpu=234,
       sizes=("large", "xlarge", "2xlarge", "4xlarge", "8xlarge", "16xlarge")),
    # high-memory u-family (SAP-class, 112xlarge = 448 vCPUs)
    _f("u-3tb1", "u", 1, "amd64", "intel", 14, 0.0488, sizes=("56xlarge",)),
    _f("u-6tb1", "u", 1, "amd64", "intel", 14, 0.0975, sizes=("56xlarge", "112xlarge")),
    _f("u-9tb1", "u", 1, "amd64", "intel", 21, 0.0915, sizes=("112xlarge",)),
    _f("u-12tb1", "u", 1, "amd64", "intel", 27, 0.0813, sizes=("112xlarge",)),
    _f("u7i-6tb", "u", 7, "amd64", "intel", 14, 0.1040, sizes=("112xlarge",)),
    _f("u7i-8tb", "u", 7, "amd64", "intel", 18, 0.1210, sizes=("112xlarge",)),
    _f("u7i-12tb", "u", 7, "amd64", "intel", 27, 0.1626, sizes=("112xlarge",)),
    # mac workstations (dedicated-host bare metal)
    _f("mac1", "mac", 1, "amd64", "intel", 3, 0.0902, sizes=("metal",),
       metal_vcpus=12),
    _f("mac2", "mac", 2, "arm64", "apple", 2, 0.0813, sizes=("metal",),
       metal_vcpus=8),
    _f("mac2-m2", "mac", 2, "arm64", "apple", 3, 0.0820, sizes=("metal",),
       metal_vcpus=8),
    _f("mac2-m2pro", "mac", 2, "arm64", "apple", 3, 0.1103, sizes=("metal",),
       metal_vcpus=12),
    # previous generations (still served by DescribeInstanceTypes)
    _f("c3", "c", 3, "amd64", "intel", 2, 0.0525,
       sizes=("large", "xlarge", "2xlarge", "4xlarge", "8xlarge")),
    _f("m3", "m", 3, "amd64", "intel", 4, 0.0665,
       sizes=("medium", "large", "xlarge", "2xlarge")),
    _f("r3", "r", 3, "amd64", "intel", 8, 0.0832,
       sizes=("large", "xlarge", "2xlarge", "4xlarge", "8xlarge")),
    _f("i2", "i", 2, "amd64", "intel", 8, 0.2133,
       sizes=("xlarge", "2xlarge", "4xlarge", "8xlarge"),
       local_nvme_gib_per_vcpu=200),
    _f("d2", "d", 2, "amd64", "intel", 8, 0.1725,
       sizes=("xlarge", "2xlarge", "4xlarge", "8xlarge")),
    _f("g2", "g", 2, "amd64", "intel", 2, 0.1625, sizes=("2xlarge", "8xlarge"),
       gpu=("k520", "nvidia", 4), gpus_by_size={"2xlarge": 1, "8xlarge": 4}),
    _f("m1", "m", 1, "amd64", "intel", 2, 0.0438,
       sizes=("small", "medium", "large", "xlarge")),
    _f("m2", "m", 2, "amd64", "intel", 9, 0.0613,
       sizes=("xlarge", "2xlarge", "4xlarge")),
    _f("c1", "c", 1, "amd64", "intel", 1, 0.0650, sizes=("medium", "xlarge")),
    _f("t1", "t", 1, "amd64", "intel", 1, 0.0200, sizes=("micro",),
       network_gbps_per_vcpu=0.1),
)

# irregular sizes used by a few families
_SIZES["nano"] = 1
_SIZES["micro"] = 1
_SIZES["small"] = 1
_SIZES["3xlarge"] = 12
_SIZES["6xlarge"] = 24
_SIZES["9xlarge"] = 36
_SIZES["18xlarge"] = 72
_SIZES["96xlarge"] = 384
_SIZES["56xlarge"] = 224
_SIZES["112xlarge"] = 448


def _stable_fraction(seed: str) -> float:
    """Deterministic [0,1) fraction from a string."""
    h = hashlib.md5(seed.encode()).digest()
    return int.from_bytes(h[:8], "big") / 2**64


def build_catalog(families: Sequence[FamilySpec] = FAMILIES) -> List[InstanceTypeInfo]:
    out: List[InstanceTypeInfo] = []
    for f in families:
        for size in f.sizes:
            vcpus = _SIZES[size]
            if size == "metal":
                non_metal = [_SIZES[s] for s in f.sizes if s != "metal"]
                vcpus = max(non_metal) if non_metal else (f.metal_vcpus or 12)
            name = f"{f.family}.{size}"
            enis, ips = _VPC_LIMIT_OVERRIDES.get(name) or _eni(vcpus)
            gpus = f.gpus_by_size.get(size, 0)
            accels = f.accels_by_size.get(size, 0)
            gpu_name, gpu_mfr, gpu_mem_gib = f.gpu
            price = f.od_price_per_vcpu * vcpus \
                + gpus * (0.35 if gpu_name in ("t4", "l4") else 0.9 if gpu_name == "a10g" else 2.3) \
                + accels * 0.16
            out.append(InstanceTypeInfo(
                name=name, family=f.family, category=f.category,
                generation=f.generation, size=size, arch=f.arch,
                vcpus=vcpus, memory_bytes=vcpus * f.gib_per_vcpu * GIB,
                cpu_manufacturer=f.cpu_manufacturer,
                hypervisor="" if size == "metal" else ("nitro" if f.generation >= 5 or f.category in ("g", "p", "inf", "trn", "x", "i") else "xen"),
                bare_metal=size == "metal",
                enis=enis, ipv4_per_eni=ips,
                network_bandwidth_mbps=_BANDWIDTH_OVERRIDES.get(
                    name, int(vcpus * f.network_gbps_per_vcpu * 1000)),
                ebs_bandwidth_mbps=min(80_000, 650 * vcpus),
                local_nvme_bytes=vcpus * f.local_nvme_gib_per_vcpu * GIB,
                gpu_name=gpu_name if gpus else "",
                gpu_manufacturer=gpu_mfr if gpus else "",
                gpu_count=gpus,
                gpu_memory_bytes=gpus * gpu_mem_gib * GIB if gpus else 0,
                accelerator_name=f.accel[0] if accels else "",
                accelerator_manufacturer=f.accel[1] if accels else "",
                accelerator_count=accels,
                efa_count=(2 if f.family == "p5" else 1) if size in f.efa_sizes else 0,
                encryption_in_transit=f.generation >= 5,
                od_price=usd(price),
            ))
    return out


def spot_price(info: InstanceTypeInfo, zone: str) -> int:
    """Deterministic per-zone spot price: 25-45% of on-demand."""
    frac = 0.25 + 0.20 * _stable_fraction(f"{info.name}/{zone}")
    return int(info.od_price * frac)


def catalog_by_name(catalog: Sequence[InstanceTypeInfo]) -> Dict[str, InstanceTypeInfo]:
    return {i.name: i for i in catalog}


# ---------------------------------------------------------------------------
# generated-table views: the zz_generated.vpclimits.go /
# zz_generated.bandwidth.go equivalents — explicit per-type rows built once
# from the parametric specs + the irregular overrides above, deterministic
# across processes
# ---------------------------------------------------------------------------

_DEFAULT_CATALOG: List[InstanceTypeInfo] = build_catalog()

#: type name -> (max ENIs, IPv4 addresses per ENI)
VPC_LIMITS: Dict[str, Tuple[int, int]] = {
    i.name: (i.enis, i.ipv4_per_eni) for i in _DEFAULT_CATALOG}

#: type name -> network bandwidth in Mbps
BANDWIDTH_MBPS: Dict[str, int] = {
    i.name: i.network_bandwidth_mbps for i in _DEFAULT_CATALOG}


def ebs_attachment_limit(info: InstanceTypeInfo) -> int:
    """Per-node EBS CSI attachment slots. ONE definition for both the
    scheduler's view (instancetype capacity) and the joined node's
    reported capacity — if they diverge, the solver packs volumes against
    capacity the node does not report."""
    return 27 if info.hypervisor == "nitro" else 39


def table_pod_limit(info: InstanceTypeInfo, reserved_enis: int = 0) -> int:
    """ENI-formula max pods with the generated table as the authority by
    type name (how the reference consults zz_generated.vpclimits.go) and
    the info fields as the fallback for types outside the table. This is
    the BASE limit; kubelet maxPods/podsPerCore overrides apply on the
    scheduler side only (they shrink the scheduler's view, never the
    node's, so divergence is always in the safe direction).

    ``reserved_enis`` (the --reserved-enis flag, options.go) withholds
    interfaces from the formula: (enis-reserved)*(ips-1)+2
    (types.go ENILimitedPods)."""
    lim = VPC_LIMITS.get(info.name)
    enis, ips = lim if lim else (info.enis, info.ipv4_per_eni)
    return max(0, enis - reserved_enis) * (ips - 1) + 2
