"""Parametric EC2-like instance catalog for the fake cloud.

The reference ships generated data tables for the real EC2 catalog
(zz_generated.describe_instance_types.go — 885 LoC of 5 sample types for
tests; zz_generated.vpclimits.go — 13k LoC of ENI limits;
zz_generated.bandwidth.go; zz_generated.pricing_aws*.go). We generate an
equivalent-scale catalog parametrically: families x generations x sizes with
realistic vCPU/memory ratios, GPU/accelerator models, ENI-formula pod limits,
and deterministic on-demand + per-zone spot pricing (fixed-point micro-USD).

Determinism: every number derives from the type/zone names via stable
hashing — two processes always build the identical catalog.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

from ..cloudprovider.types import MICRO, usd

GIB = 1024**3


@dataclass(frozen=True)
class ZoneInfo:
    name: str      # us-west-2a
    zone_id: str   # usw2-az1
    zone_type: str = "availability-zone"  # | local-zone


DEFAULT_REGION = "us-west-2"
DEFAULT_ZONES = (
    ZoneInfo("us-west-2a", "usw2-az1"),
    ZoneInfo("us-west-2b", "usw2-az2"),
    ZoneInfo("us-west-2c", "usw2-az3"),
    ZoneInfo("us-west-2d", "usw2-az4"),
)


@dataclass(frozen=True)
class InstanceTypeInfo:
    """Raw catalog row (the DescribeInstanceTypes analog)."""
    name: str                      # m6i.2xlarge
    family: str                    # m6i
    category: str                  # m
    generation: int                # 6
    size: str                      # 2xlarge
    arch: str                      # amd64 | arm64
    vcpus: int
    memory_bytes: int
    cpu_manufacturer: str          # intel | amd | aws
    hypervisor: str                # nitro | xen | "" (metal)
    bare_metal: bool
    enis: int
    ipv4_per_eni: int
    network_bandwidth_mbps: int
    ebs_bandwidth_mbps: int
    local_nvme_bytes: int = 0
    gpu_name: str = ""
    gpu_manufacturer: str = ""
    gpu_count: int = 0
    gpu_memory_bytes: int = 0
    accelerator_name: str = ""
    accelerator_manufacturer: str = ""
    accelerator_count: int = 0
    efa_count: int = 0
    encryption_in_transit: bool = True
    od_price: int = 0              # micro-USD/hour

    @property
    def eni_pod_limit(self) -> int:
        """ENI-formula max pods: enis*(ips-1)+2 (vpclimits analog)."""
        return self.enis * (self.ipv4_per_eni - 1) + 2


# (size -> vcpus) ladder
_SIZES: Dict[str, int] = {
    "medium": 1, "large": 2, "xlarge": 4, "2xlarge": 8, "4xlarge": 16,
    "8xlarge": 32, "12xlarge": 48, "16xlarge": 64, "24xlarge": 96,
    "32xlarge": 128, "48xlarge": 192, "metal": 96,
}

# vcpus -> (enis, ipv4 per eni): the shape of the real vpclimits table
_ENI_LIMITS: Sequence[Tuple[int, int, int]] = (
    (1, 2, 4), (2, 3, 10), (4, 4, 15), (8, 4, 15), (16, 8, 30),
    (32, 8, 30), (48, 15, 50), (64, 15, 50), (96, 15, 50),
    (128, 15, 50), (192, 15, 50),
)


def _eni(vcpus: int) -> Tuple[int, int]:
    for v, enis, ips in _ENI_LIMITS:
        if vcpus <= v:
            return enis, ips
    return 15, 50


@dataclass(frozen=True)
class FamilySpec:
    family: str
    category: str
    generation: int
    arch: str
    cpu_manufacturer: str
    gib_per_vcpu: int
    sizes: Tuple[str, ...]
    od_price_per_vcpu: float        # USD/hour
    local_nvme_gib_per_vcpu: int = 0
    gpu: Tuple[str, str, int] = ("", "", 0)      # (name, mfr, GiB mem/gpu)
    gpus_by_size: Mapping[str, int] = field(default_factory=dict)
    accel: Tuple[str, str] = ("", "")
    accels_by_size: Mapping[str, int] = field(default_factory=dict)
    efa_sizes: Tuple[str, ...] = ()
    network_gbps_per_vcpu: float = 0.4


_STD = ("large", "xlarge", "2xlarge", "4xlarge", "8xlarge", "12xlarge",
        "16xlarge", "24xlarge", "metal")
_STD_NO_METAL = _STD[:-1]
_BURST = ("medium", "large", "xlarge", "2xlarge")


def _f(family, category, gen, arch, mfr, ratio, price, sizes=_STD_NO_METAL, **kw):
    return FamilySpec(family, category, gen, arch, mfr, ratio, tuple(sizes), price, **kw)


FAMILIES: Tuple[FamilySpec, ...] = (
    # compute optimized (2 GiB/vCPU)
    _f("c4", "c", 4, "amd64", "intel", 2, 0.0500, sizes=("large", "xlarge", "2xlarge", "4xlarge", "8xlarge")),
    _f("c5", "c", 5, "amd64", "intel", 2, 0.0425, sizes=_STD),
    _f("c5a", "c", 5, "amd64", "amd", 2, 0.0385),
    _f("c5d", "c", 5, "amd64", "intel", 2, 0.0480, local_nvme_gib_per_vcpu=25, sizes=_STD),
    _f("c6g", "c", 6, "arm64", "aws", 2, 0.0340, sizes=_STD),
    _f("c6gd", "c", 6, "arm64", "aws", 2, 0.0384, local_nvme_gib_per_vcpu=25),
    _f("c6i", "c", 6, "amd64", "intel", 2, 0.0425, sizes=_STD),
    _f("c6a", "c", 6, "amd64", "amd", 2, 0.0383, sizes=_STD),
    _f("c7g", "c", 7, "arm64", "aws", 2, 0.0363, sizes=_STD),
    _f("c7i", "c", 7, "amd64", "intel", 2, 0.0446, sizes=_STD),
    _f("c7a", "c", 7, "amd64", "amd", 2, 0.0513),
    # general purpose (4 GiB/vCPU)
    _f("m4", "m", 4, "amd64", "intel", 4, 0.0575, sizes=("large", "xlarge", "2xlarge", "4xlarge", "16xlarge")),
    _f("m5", "m", 5, "amd64", "intel", 4, 0.0480, sizes=_STD),
    _f("m5a", "m", 5, "amd64", "amd", 4, 0.0430),
    _f("m5d", "m", 5, "amd64", "intel", 4, 0.0565, local_nvme_gib_per_vcpu=37, sizes=_STD),
    _f("m6g", "m", 6, "arm64", "aws", 4, 0.0385, sizes=_STD),
    _f("m6gd", "m", 6, "arm64", "aws", 4, 0.0452, local_nvme_gib_per_vcpu=59),
    _f("m6i", "m", 6, "amd64", "intel", 4, 0.0480, sizes=_STD),
    _f("m6a", "m", 6, "amd64", "amd", 4, 0.0432, sizes=_STD),
    _f("m7g", "m", 7, "arm64", "aws", 4, 0.0408, sizes=_STD),
    _f("m7i", "m", 7, "amd64", "intel", 4, 0.0504, sizes=_STD),
    _f("m7a", "m", 7, "amd64", "amd", 4, 0.0580),
    # memory optimized (8 GiB/vCPU)
    _f("r4", "r", 4, "amd64", "intel", 7, 0.0665, sizes=("large", "xlarge", "2xlarge", "4xlarge", "8xlarge", "16xlarge")),
    _f("r5", "r", 5, "amd64", "intel", 8, 0.0630, sizes=_STD),
    _f("r5a", "r", 5, "amd64", "amd", 8, 0.0565),
    _f("r5d", "r", 5, "amd64", "intel", 8, 0.0720, local_nvme_gib_per_vcpu=75, sizes=_STD),
    _f("r6g", "r", 6, "arm64", "aws", 8, 0.0504, sizes=_STD),
    _f("r6gd", "r", 6, "arm64", "aws", 8, 0.0576, local_nvme_gib_per_vcpu=118),
    _f("r6i", "r", 6, "amd64", "intel", 8, 0.0630, sizes=_STD),
    _f("r6a", "r", 6, "amd64", "amd", 8, 0.0567, sizes=_STD),
    _f("r7g", "r", 7, "arm64", "aws", 8, 0.0536, sizes=_STD),
    _f("r7i", "r", 7, "amd64", "intel", 8, 0.0661, sizes=_STD),
    # high memory (16 GiB/vCPU)
    _f("x2gd", "x", 2, "arm64", "aws", 16, 0.0835, local_nvme_gib_per_vcpu=59,
       sizes=("medium", "large", "xlarge", "2xlarge", "4xlarge", "8xlarge", "12xlarge", "16xlarge", "metal")),
    _f("x2idn", "x", 2, "amd64", "intel", 16, 0.1668, sizes=("16xlarge", "24xlarge", "32xlarge", "metal")),
    # burstable (t) — 2-4 GiB/vCPU
    _f("t2", "t", 2, "amd64", "intel", 4, 0.0464, sizes=_BURST, network_gbps_per_vcpu=0.1),
    _f("t3", "t", 3, "amd64", "intel", 4, 0.0416, sizes=_BURST, network_gbps_per_vcpu=0.1),
    _f("t3a", "t", 3, "amd64", "amd", 4, 0.0376, sizes=_BURST, network_gbps_per_vcpu=0.1),
    _f("t4g", "t", 4, "arm64", "aws", 4, 0.0336, sizes=_BURST, network_gbps_per_vcpu=0.1),
    # storage optimized
    _f("i3", "i", 3, "amd64", "intel", 7, 0.0780, local_nvme_gib_per_vcpu=232,
       sizes=("large", "xlarge", "2xlarge", "4xlarge", "8xlarge", "16xlarge", "metal")),
    _f("i3en", "i", 3, "amd64", "intel", 8, 0.1130, local_nvme_gib_per_vcpu=312,
       sizes=("large", "xlarge", "2xlarge", "4xlarge", "8xlarge", "12xlarge", "24xlarge", "metal")),
    _f("i4i", "i", 4, "amd64", "intel", 8, 0.0858, local_nvme_gib_per_vcpu=234,
       sizes=("large", "xlarge", "2xlarge", "4xlarge", "8xlarge", "16xlarge", "32xlarge", "metal")),
    _f("d3", "d", 3, "amd64", "intel", 8, 0.1248, local_nvme_gib_per_vcpu=0,
       sizes=("xlarge", "2xlarge", "4xlarge", "8xlarge")),
    # GPU — inference
    _f("g4dn", "g", 4, "amd64", "intel", 4, 0.1315, local_nvme_gib_per_vcpu=28,
       sizes=("xlarge", "2xlarge", "4xlarge", "8xlarge", "12xlarge", "16xlarge", "metal"),
       gpu=("t4", "nvidia", 16),
       gpus_by_size={"xlarge": 1, "2xlarge": 1, "4xlarge": 1, "8xlarge": 1,
                     "12xlarge": 4, "16xlarge": 1, "metal": 8}),
    _f("g5", "g", 5, "amd64", "amd", 4, 0.2518, local_nvme_gib_per_vcpu=58,
       sizes=("xlarge", "2xlarge", "4xlarge", "8xlarge", "12xlarge", "16xlarge", "24xlarge", "48xlarge"),
       gpu=("a10g", "nvidia", 24),
       gpus_by_size={"xlarge": 1, "2xlarge": 1, "4xlarge": 1, "8xlarge": 1,
                     "12xlarge": 4, "16xlarge": 1, "24xlarge": 4, "48xlarge": 8}),
    _f("g6", "g", 6, "amd64", "amd", 4, 0.2012, local_nvme_gib_per_vcpu=58,
       sizes=("xlarge", "2xlarge", "4xlarge", "8xlarge", "12xlarge", "16xlarge", "24xlarge", "48xlarge"),
       gpu=("l4", "nvidia", 24),
       gpus_by_size={"xlarge": 1, "2xlarge": 1, "4xlarge": 1, "8xlarge": 1,
                     "12xlarge": 4, "16xlarge": 1, "24xlarge": 4, "48xlarge": 8}),
    # GPU — training
    _f("p3", "p", 3, "amd64", "intel", 7, 0.3825, sizes=("2xlarge", "8xlarge", "16xlarge"),
       gpu=("v100", "nvidia", 16),
       gpus_by_size={"2xlarge": 1, "8xlarge": 4, "16xlarge": 8}),
    _f("p4d", "p", 4, "amd64", "intel", 12, 0.3414, local_nvme_gib_per_vcpu=83,
       sizes=("24xlarge",), gpu=("a100", "nvidia", 40),
       gpus_by_size={"24xlarge": 8}, efa_sizes=("24xlarge",)),
    _f("p5", "p", 5, "amd64", "amd", 10, 0.5120, local_nvme_gib_per_vcpu=158,
       sizes=("48xlarge",), gpu=("h100", "nvidia", 80),
       gpus_by_size={"48xlarge": 8}, efa_sizes=("48xlarge",)),
    # accelerators — inferentia / trainium
    _f("inf1", "inf", 1, "amd64", "intel", 2, 0.0570,
       sizes=("xlarge", "2xlarge", "6xlarge", "24xlarge"),
       accel=("inferentia", "aws"),
       accels_by_size={"xlarge": 1, "2xlarge": 1, "6xlarge": 4, "24xlarge": 16}),
    _f("inf2", "inf", 2, "amd64", "amd", 4, 0.0947,
       sizes=("xlarge", "8xlarge", "24xlarge", "48xlarge"),
       accel=("inferentia2", "aws"),
       accels_by_size={"xlarge": 1, "8xlarge": 1, "24xlarge": 6, "48xlarge": 12}),
    _f("trn1", "trn", 1, "amd64", "intel", 4, 0.4163,
       sizes=("2xlarge", "32xlarge"), accel=("trainium", "aws"),
       accels_by_size={"2xlarge": 1, "32xlarge": 16}, efa_sizes=("32xlarge",)),
)

# irregular sizes used by a few families
_SIZES["6xlarge"] = 24
_SIZES["9xlarge"] = 36


def _stable_fraction(seed: str) -> float:
    """Deterministic [0,1) fraction from a string."""
    h = hashlib.md5(seed.encode()).digest()
    return int.from_bytes(h[:8], "big") / 2**64


def build_catalog(families: Sequence[FamilySpec] = FAMILIES) -> List[InstanceTypeInfo]:
    out: List[InstanceTypeInfo] = []
    for f in families:
        for size in f.sizes:
            vcpus = _SIZES[size]
            if size == "metal":
                vcpus = max(_SIZES[s] for s in f.sizes if s != "metal")
            name = f"{f.family}.{size}"
            enis, ips = _eni(vcpus)
            gpus = f.gpus_by_size.get(size, 0)
            accels = f.accels_by_size.get(size, 0)
            gpu_name, gpu_mfr, gpu_mem_gib = f.gpu
            price = f.od_price_per_vcpu * vcpus \
                + gpus * (0.35 if gpu_name in ("t4", "l4") else 0.9 if gpu_name == "a10g" else 2.3) \
                + accels * 0.16
            out.append(InstanceTypeInfo(
                name=name, family=f.family, category=f.category,
                generation=f.generation, size=size, arch=f.arch,
                vcpus=vcpus, memory_bytes=vcpus * f.gib_per_vcpu * GIB,
                cpu_manufacturer=f.cpu_manufacturer,
                hypervisor="" if size == "metal" else ("nitro" if f.generation >= 5 or f.category in ("g", "p", "inf", "trn", "x", "i") else "xen"),
                bare_metal=size == "metal",
                enis=enis, ipv4_per_eni=ips,
                network_bandwidth_mbps=int(vcpus * f.network_gbps_per_vcpu * 1000),
                ebs_bandwidth_mbps=min(80_000, 650 * vcpus),
                local_nvme_bytes=vcpus * f.local_nvme_gib_per_vcpu * GIB,
                gpu_name=gpu_name if gpus else "",
                gpu_manufacturer=gpu_mfr if gpus else "",
                gpu_count=gpus,
                gpu_memory_bytes=gpus * gpu_mem_gib * GIB if gpus else 0,
                accelerator_name=f.accel[0] if accels else "",
                accelerator_manufacturer=f.accel[1] if accels else "",
                accelerator_count=accels,
                efa_count=(2 if f.family == "p5" else 1) if size in f.efa_sizes else 0,
                encryption_in_transit=f.generation >= 5,
                od_price=usd(price),
            ))
    return out


def spot_price(info: InstanceTypeInfo, zone: str) -> int:
    """Deterministic per-zone spot price: 25-45% of on-demand."""
    frac = 0.25 + 0.20 * _stable_fraction(f"{info.name}/{zone}")
    return int(info.od_price * frac)


def catalog_by_name(catalog: Sequence[InstanceTypeInfo]) -> Dict[str, InstanceTypeInfo]:
    return {i.name: i for i in catalog}
