"""Test environment: every provider wired to fakes (pkg/test/environment.go
analog) plus fixture builders for NodePools/Pods."""

from __future__ import annotations

import itertools
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

from ..apis.objects import (EC2NodeClass, NodeClassRef, NodePool,
                            NodePoolTemplate, Pod, Taint, Toleration,
                            TopologySpreadConstraint)
from ..apis.requirements import Requirements
from ..apis.resources import Resources
from ..cache.ttl import UnavailableOfferings
from ..providers.instancetype import InstanceTypeProvider, OfferingsSnapshot
from ..solver.types import NodePoolSpec, SchedulingSnapshot
from .ec2 import FakeEC2
from .kube import FakeKube

_pod_counter = itertools.count()


def reset_pod_counter(start: int = 0) -> None:
    """Restart the global pod-name counter. Seeded runs that compare
    pod names across arms/processes (bench cross-arm identity, the
    endurance simulator's byte-identical traces) call this instead of
    reaching into the private counter."""
    global _pod_counter
    _pod_counter = itertools.count(start)


class Environment:
    """FakeEC2 + FakeKube + instancetype provider, hydrated."""

    def __init__(self, ec2: Optional[FakeEC2] = None, clock=None):
        self.ec2 = ec2 or FakeEC2()
        self.kube = FakeKube()
        self.unavailable_offerings = UnavailableOfferings(clock=clock)
        self.instance_types = InstanceTypeProvider(
            unavailable_offerings=self.unavailable_offerings, clock=clock)
        self.refresh_catalog()

    def refresh_catalog(self) -> None:
        """What the 12h catalog/pricing controllers do (SURVEY §3.3)."""
        self.instance_types.update_instance_types(self.ec2.describe_instance_types())
        type_zones: Dict[str, set] = {}
        for t, z in self.ec2.describe_instance_type_offerings():
            type_zones.setdefault(t, set()).add(z)
        self.instance_types.update_offerings(OfferingsSnapshot(
            zones={z.name: z for z in self.ec2.zones},
            type_zones=type_zones,
            od_prices=self.ec2.on_demand_prices(),
            spot_prices={(t, z): p for t, z, p in self.ec2.describe_spot_price_history()},
        ))

    def nodeclass(self, name: str = "default", **kw) -> EC2NodeClass:
        """A ready EC2NodeClass with resolved status (what the nodeclass
        status controller produces)."""
        nc = EC2NodeClass(name, **kw)
        nc.status_subnets = [
            {"id": s.id, "zone": s.zone, "zoneID": s.zone_id}
            for s in self.ec2.describe_subnets(
                tag_filters={"karpenter.sh/discovery": "cluster"})]
        nc.status_security_groups = [
            {"id": g.id, "name": g.name}
            for g in self.ec2.describe_security_groups(
                tag_filters={"karpenter.sh/discovery": "cluster"})]
        family = nc.ami_family
        nc.status_amis = [
            {"id": i.id, "name": i.name, "arch": i.arch}
            for i in self.ec2.describe_images()
            if family == "custom" or i.ssm_alias.startswith(family + "@")]
        nc.status_instance_profile = f"{name}-profile"
        nc.set_condition("Ready", "True")
        return nc

    def nodepool(self, name: str = "default",
                 nodeclass: Optional[EC2NodeClass] = None,
                 requirements: Sequence[Mapping] = (),
                 taints: Sequence[Taint] = (),
                 limits: Optional[Mapping] = None,
                 weight: int = 0,
                 labels: Optional[Dict[str, str]] = None) -> Tuple[NodePool, EC2NodeClass]:
        nc = nodeclass or self.nodeclass(name + "-class")
        np = NodePool(
            name,
            template=NodePoolTemplate(
                node_class_ref=NodeClassRef(nc.metadata.name),
                requirements=Requirements.from_terms(list(requirements)),
                labels=dict(labels or {}),
                taints=list(taints),
            ),
            limits=Resources.parse(limits) if limits else None,
            weight=weight)
        return np, nc

    def pool_spec(self, np: NodePool, nc: EC2NodeClass) -> NodePoolSpec:
        return NodePoolSpec(nodepool=np,
                            instance_types=self.instance_types.list(nc))

    def snapshot(self, pods: Sequence[Pod],
                 pools: Sequence[Tuple[NodePool, EC2NodeClass]],
                 existing_nodes=(), daemon_overheads=()) -> SchedulingSnapshot:
        return SchedulingSnapshot(
            pods=pods,
            nodepools=[self.pool_spec(np, nc) for np, nc in pools],
            existing_nodes=list(existing_nodes),
            daemon_overheads=list(daemon_overheads),
            zones={z.name: z.zone_id for z in self.ec2.zones},
        )

    def reset(self) -> None:
        self.ec2.reset()
        self.kube.reset()


def make_pods(count: int, cpu: str = "100m", memory: str = "128Mi",
              prefix: str = "pod", group: str = "",
              node_selector: Optional[Mapping[str, str]] = None,
              tolerations: Sequence[Toleration] = (),
              topology_spread: Sequence[TopologySpreadConstraint] = (),
              pod_affinity=(), affinity_terms: Sequence[Mapping] = (),
              **extra_resources) -> List[Pod]:
    """Fixture builder: ``count`` identical pods."""
    spec = {"cpu": cpu, "memory": memory}
    spec.update(extra_resources)
    out = []
    for _ in range(count):
        i = next(_pod_counter)
        out.append(Pod(
            name=f"{prefix}-{i:06d}",
            requests=Resources.parse(spec),
            node_selector=node_selector,
            required_affinity_terms=list(affinity_terms),
            tolerations=list(tolerations),
            topology_spread=list(topology_spread),
            pod_affinity=list(pod_affinity),
            scheduling_group=group or prefix,
        ))
    return out
