"""In-memory fake EC2-like cloud.

Mirrors the reference's fake AWS layer (pkg/fake/ec2api.go:40-112): an
in-memory instance/launch-template store, CreateFleet that actually
"launches" fake instances, ``insufficient_capacity_pools`` to simulate ICE
per (instanceType, zone, capacityType), ``next_error`` single-shot error
injection, output overrides, and call capture — plus subnet/SG/AMI stores
with tag-filter queries, spot price history, and instance-type offerings.

Thread-safe: every public method takes the store lock (the control plane's
batchers call from worker tasks).
"""

from __future__ import annotations

import itertools
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence, Set, Tuple

from .catalog import (
    DEFAULT_ZONES,
    InstanceTypeInfo,
    ZoneInfo,
    build_catalog,
    catalog_by_name,
    spot_price)

#: instance families offered in local zones — local zones carry a small,
#: older-generation slice of the catalog (the public local-zone feature
#: matrix; the reference models this with a dedicated local-zone test zone,
#: fake/ec2api.go:499)
LOCAL_ZONE_FAMILIES = frozenset(
    {"t3", "c5", "c5d", "m5", "m5d", "r5", "r5d", "g4dn", "i3en"})

_id_counter = itertools.count(1)


def _new_id(prefix: str) -> str:
    return f"{prefix}-{next(_id_counter):017x}"


@dataclass
class FakeSubnet:
    id: str
    zone: str
    zone_id: str
    available_ips: int = 8000
    tags: Dict[str, str] = field(default_factory=dict)
    zone_type: str = "availability-zone"


@dataclass
class FakeSecurityGroup:
    id: str
    name: str
    tags: Dict[str, str] = field(default_factory=dict)


@dataclass
class FakeImage:
    id: str
    name: str
    arch: str                      # amd64 | arm64
    creation_date: float
    deprecated: bool = False
    tags: Dict[str, str] = field(default_factory=dict)
    ssm_alias: str = ""            # e.g. "al2023@latest/amd64"
    #: "self" (account-owned), "amazon" (EKS public), or an account id —
    #: name-based discovery defaults to self+amazon (ami.go:112-116)
    owner: str = "amazon"


@dataclass
class FakeLaunchTemplate:
    id: str
    name: str
    image_id: str
    security_group_ids: List[str]
    user_data: str
    tags: Dict[str, str] = field(default_factory=dict)
    metadata_options: Optional[dict] = None
    block_device_mappings: Optional[list] = None
    network_interfaces: Optional[list] = None
    instance_profile: str = ""


@dataclass
class FakeInstance:
    id: str
    instance_type: str
    zone: str
    zone_id: str
    capacity_type: str             # spot | on-demand
    image_id: str
    launch_template_name: str
    subnet_id: str
    state: str = "running"         # pending|running|shutting-down|terminated
    launch_time: float = 0.0
    tags: Dict[str, str] = field(default_factory=dict)
    provider_id: str = ""
    security_group_ids: List[str] = field(default_factory=list)
    #: assigned when the launch template's interfaces request an IPv6
    #: address (Ipv6AddressCount, launchtemplate.go:289,302)
    ipv6_address: str = ""

    def __post_init__(self):
        if not self.provider_id:
            self.provider_id = f"aws:///{self.zone}/{self.id}"


class CallLog:
    """MockedFunction analog (fake/ec2api.go:48-68): capture calls, inject
    errors, count successes.

    Thread-safe: batcher worker threads and the chaos harness hit the same
    log concurrently, so the read-then-clear in ``maybe_raise`` runs under
    a lock (two racing callers must never both consume — or both miss —
    the same one-shot error).

    ``error`` accepts three forms:

    - an exception INSTANCE: raised once, then cleared (the classic
      single-shot contract);
    - a sequence/iterator of ``Exception | None``: consumed one entry per
      call — ``None`` entries mean "this call succeeds", exhaustion means
      no further faults (the chaos harness schedules storms this way);
    - a callable returning ``Exception | None`` per call (an exception
      CLASS is a callable too: setting ``error = ConnectionError`` makes
      every call fail until cleared).
    """

    def __init__(self):
        self._mu = threading.Lock()
        self.calls: List[Any] = []
        self.error: Any = None
        self.output_override: Optional[Any] = None

    def record(self, inp: Any) -> None:
        with self._mu:
            self.calls.append(inp)

    def maybe_raise(self) -> None:
        with self._mu:
            src = self.error
            if src is None:
                return
            if isinstance(src, BaseException):
                self.error = None
                err: Optional[BaseException] = src
            elif callable(src):
                err = src()
            else:
                it = src if hasattr(src, "__next__") else iter(src)
                self.error = it
                err = next(it, None)
        if err is not None:
            raise err

    @property
    def called_times(self) -> int:
        with self._mu:
            return len(self.calls)

    def reset(self) -> None:
        with self._mu:
            self.calls.clear()
            self.error = None
            self.output_override = None


class DryRunOperation(Exception):
    """The EC2 'DryRunOperation' marker: request WOULD have succeeded.
    The connectivity preflight treats exactly this error as healthy
    (operator.go:222-225)."""


class FakeEC2:
    """The fake cloud. All state mutations lock ``self._mu``."""

    def __init__(self,
                 zones: Sequence[ZoneInfo] = DEFAULT_ZONES,
                 catalog: Optional[Sequence[InstanceTypeInfo]] = None,
                 region: str = "us-west-2",
                 now: Callable[[], float] = time.time):
        self._mu = threading.RLock()
        self.region = region
        self.zones = list(zones)
        self.catalog: List[InstanceTypeInfo] = list(catalog if catalog is not None else build_catalog())
        self.by_name = catalog_by_name(self.catalog)
        self.now = now

        self.instances: Dict[str, FakeInstance] = {}
        self.launch_templates: Dict[str, FakeLaunchTemplate] = {}
        self.subnets: Dict[str, FakeSubnet] = {}
        self.security_groups: Dict[str, FakeSecurityGroup] = {}
        self.images: Dict[str, FakeImage] = {}
        self.ssm_parameters: Dict[str, str] = {}

        # Behavior injection (fake/ec2api.go:40-44,66)
        #: {(instance_type, zone, capacity_type)} that raise ICE on launch
        self.insufficient_capacity_pools: Set[Tuple[str, str, str]] = set()
        #: offerings removed from DescribeInstanceTypeOfferings
        self.removed_offerings: Set[Tuple[str, str]] = set()

        self.create_fleet_log = CallLog()
        self.describe_instances_log = CallLog()
        self.terminate_instances_log = CallLog()
        self.create_launch_template_log = CallLog()
        self.create_tags_log = CallLog()
        self.describe_instance_types_log = CallLog()
        self.ssm_get_parameter_log = CallLog()
        #: EKS DescribeCluster version (the version controller's source)
        self.eks_cluster_version = "1.31"
        #: cluster service CIDR (resolveClusterCIDR source)
        self.eks_cluster_cidr = "10.100.0.0/16"
        #: service IPv6 CIDR; set for IPv6 clusters — resolveClusterCIDR
        #: prefers it when present (launchtemplate.go:448-450)
        self.eks_service_ipv6_cidr: Optional[str] = None

        # boot-preflight failure injection (operator.go:111-115,218-227
        # analogs): a DOWN link errors immediately; a WEDGED link stalls
        # the call — the two failure modes the preflight must fail fast on
        self.link_down = False
        self.link_stall_s = 0.0

        self._seed_default_network()
        self._seed_default_images()

    # -- boot preflight seams ---------------------------------------------
    def _link_gate(self) -> None:
        if self.link_stall_s > 0:
            time.sleep(self.link_stall_s)
        if self.link_down:
            raise ConnectionError("cloud API unreachable")

    def imds_region(self) -> str:
        """IMDS region discovery (operator.go:111-115): the instance
        metadata endpoint names the region the control plane runs in."""
        self._link_gate()
        return self.region

    def dry_run_describe_instance_types(self) -> None:
        """EC2 connectivity preflight (operator.go:218-227): a dry-run
        DescribeInstanceTypes. A healthy, authenticated link answers
        with the DryRunOperation marker error — anything else (silence,
        auth failure, transport error) is a dead cloud seam."""
        self._link_gate()
        raise DryRunOperation()

    # -- seeding -----------------------------------------------------------
    def _seed_default_network(self) -> None:
        for i, z in enumerate(self.zones):
            sn = FakeSubnet(id=f"subnet-{z.zone_id}", zone=z.name, zone_id=z.zone_id,
                            available_ips=8000 - i,  # deterministic tie-break
                            tags={"karpenter.sh/discovery": "cluster", "Name": f"private-{z.name}"},
                            zone_type=z.zone_type)
            self.subnets[sn.id] = sn
        sg = FakeSecurityGroup(id="sg-nodes", name="karpenter-nodes",
                               tags={"karpenter.sh/discovery": "cluster"})
        self.security_groups[sg.id] = sg

    def _seed_default_images(self) -> None:
        t = 1_700_000_000.0
        for fam in ("al2023", "al2", "bottlerocket"):
            for arch in ("amd64", "arm64"):
                img = FakeImage(id=_new_id("ami"), name=f"{fam}-{arch}-v2024",
                                arch=arch, creation_date=t,
                                ssm_alias=f"{fam}@latest/{arch}")
                self.images[img.id] = img
                self.ssm_parameters[_ssm_path(fam, arch)] = img.id
            t += 1000
        for fam in ("windows2019", "windows2022"):  # amd64 only
            img = FakeImage(id=_new_id("ami"), name=f"{fam}-amd64-v2024",
                            arch="amd64", creation_date=t,
                            ssm_alias=f"{fam}@latest/amd64")
            self.images[img.id] = img
            self.ssm_parameters[_ssm_path(fam, "amd64")] = img.id
            t += 1000

    # -- catalog APIs ------------------------------------------------------
    def describe_instance_types(self) -> List[InstanceTypeInfo]:
        self._link_gate()
        with self._mu:
            self.describe_instance_types_log.record(None)
            self.describe_instance_types_log.maybe_raise()
            if self.describe_instance_types_log.output_override is not None:
                return list(self.describe_instance_types_log.output_override)
            return list(self.catalog)

    def describe_instance_type_offerings(self) -> List[Tuple[str, str]]:
        """(instance_type, zone) pairs. Deterministically: newest-generation
        families are absent from the last availability zone (mirrors
        real-world partial zonal rollout), local zones carry only the
        restricted LOCAL_ZONE_FAMILIES slice, plus any injected removals."""
        self._link_gate()
        with self._mu:
            out = []
            last_az = next(
                (z.name for z in reversed(self.zones)
                 if z.zone_type == "availability-zone"), "")
            for info in self.catalog:
                for z in self.zones:
                    if z.zone_type == "local-zone":
                        if info.family not in LOCAL_ZONE_FAMILIES:
                            continue
                    elif z.name == last_az and info.generation >= 7:
                        continue
                    if (info.name, z.name) in self.removed_offerings:
                        continue
                    out.append((info.name, z.name))
            return out

    def describe_spot_price_history(self) -> List[Tuple[str, str, int]]:
        """(instance_type, zone, micro_usd) triples. Local zones publish no
        spot history (local zones are on-demand only)."""
        self._link_gate()
        with self._mu:
            return [(i.name, z.name, spot_price(i, z.name))
                    for i in self.catalog for z in self.zones
                    if z.zone_type != "local-zone"]

    def enable_local_zone(self, name: str = "us-west-2-lax-1a",
                          zone_id: str = "usw2-lax1-az1",
                          subnet_tags: Optional[Mapping[str, str]] = None,
                          ) -> Tuple[ZoneInfo, FakeSubnet]:
        """Register a local zone plus one subnet in it (the fake's
        test-zone-1a-local analog, ec2api.go:496-499). Its offerings are
        the restricted LOCAL_ZONE_FAMILIES slice, on-demand only; callers
        opt workloads in by constraining the NodePool to the zone
        (test/suites/localzone/suite_test.go)."""
        with self._mu:
            z = ZoneInfo(name, zone_id, zone_type="local-zone")
            self.zones.append(z)
            sn = FakeSubnet(
                id=f"subnet-{zone_id}", zone=name, zone_id=zone_id,
                available_ips=4000,
                tags=dict(subnet_tags) if subnet_tags is not None
                else {"karpenter.sh/discovery": "cluster",
                      "Name": f"local-{name}"},
                zone_type="local-zone")
            self.subnets[sn.id] = sn
            return z, sn

    def on_demand_prices(self) -> Dict[str, int]:
        self._link_gate()
        with self._mu:
            return {i.name: i.od_price for i in self.catalog}

    # -- network discovery -------------------------------------------------
    def describe_subnets(self, tag_filters: Mapping[str, str] = (),
                         ids: Sequence[str] = ()) -> List[FakeSubnet]:
        self._link_gate()
        with self._mu:
            return [s for s in self.subnets.values()
                    if _match(s.tags, tag_filters, s.id, ids)]

    def describe_security_groups(self, tag_filters: Mapping[str, str] = (),
                                 ids: Sequence[str] = (),
                                 names: Sequence[str] = ()) -> List[FakeSecurityGroup]:
        self._link_gate()
        with self._mu:
            out = []
            for g in self.security_groups.values():
                if names and g.name not in names:
                    continue
                if _match(g.tags, tag_filters, g.id, ids):
                    out.append(g)
            return out

    def describe_images(self, tag_filters: Mapping[str, str] = (),
                        ids: Sequence[str] = (),
                        names: Sequence[str] = (),
                        owners: Sequence[str] = ()) -> List[FakeImage]:
        self._link_gate()
        with self._mu:
            out = []
            for img in self.images.values():
                if names and img.name not in names:
                    continue
                if owners and img.owner not in owners:
                    continue
                if _match(img.tags, tag_filters, img.id, ids):
                    out.append(img)
            return out

    def eks_describe_cluster_version(self) -> str:
        """EKS DescribeCluster's cluster version (version.go source)."""
        self._link_gate()
        with self._mu:
            return self.eks_cluster_version

    def ssm_get_parameter(self, path: str) -> str:
        self._link_gate()
        self.ssm_get_parameter_log.record(path)
        with self._mu:
            if path not in self.ssm_parameters:
                raise KeyError(f"ParameterNotFound: {path}")
            return self.ssm_parameters[path]

    # -- launch templates --------------------------------------------------
    def create_launch_template(self, lt: FakeLaunchTemplate) -> FakeLaunchTemplate:
        self._link_gate()
        with self._mu:
            self.create_launch_template_log.record(lt)
            self.create_launch_template_log.maybe_raise()
            if not lt.id:
                lt.id = _new_id("lt")
            self.launch_templates[lt.name] = lt
            return lt

    def describe_launch_templates(self, names: Sequence[str] = ()) -> List[FakeLaunchTemplate]:
        self._link_gate()
        with self._mu:
            if not names:
                return list(self.launch_templates.values())
            return [self.launch_templates[n] for n in names if n in self.launch_templates]

    def delete_launch_templates(self, names: Sequence[str]) -> None:
        self._link_gate()
        with self._mu:
            for n in names:
                self.launch_templates.pop(n, None)

    # -- the launcher ------------------------------------------------------
    def create_fleet(self,
                     launch_template_configs: Sequence[Mapping[str, Any]],
                     target_capacity: int,
                     capacity_type: str,
                     tags: Optional[Mapping[str, str]] = None,
                     ) -> Tuple[List[FakeInstance], List[dict]]:
        """Instant-fleet semantics: each config is {"launch_template_name",
        "overrides": [{"instance_type","zone","subnet_id","image_id","priority"?}]}.

        Returns (instances, errors): ICE pools produce per-override errors and
        the fleet falls through to the next-cheapest override, exactly like
        CreateFleet's price-capacity-optimized behavior the launcher relies on
        (instance.go:227-245, 357-363).
        """
        self._link_gate()
        with self._mu:
            req = {"configs": launch_template_configs,
                   "target_capacity": target_capacity,
                   "capacity_type": capacity_type}
            self.create_fleet_log.record(req)
            self.create_fleet_log.maybe_raise()

            overrides: List[dict] = []
            for cfg in launch_template_configs:
                lt_name = cfg["launch_template_name"]
                for o in cfg.get("overrides", []):
                    overrides.append({**o, "launch_template_name": lt_name})
            # price-capacity-optimized: ascending priority (we set priority =
            # price rank on the client side, matching the reference's use of
            # lowest-price/price-capacity-optimized allocation)
            overrides.sort(key=lambda o: (o.get("priority", 0), o["instance_type"], o["zone"]))

            instances: List[FakeInstance] = []
            errors: List[dict] = []
            remaining = target_capacity
            for o in overrides:
                if remaining <= 0:
                    break
                pool = (o["instance_type"], o["zone"], capacity_type)
                if pool in self.insufficient_capacity_pools:
                    errors.append({
                        "code": "InsufficientInstanceCapacity",
                        "instance_type": o["instance_type"],
                        "zone": o["zone"],
                        "capacity_type": capacity_type,
                    })
                    continue
                lt = self.launch_templates.get(o["launch_template_name"])
                if lt is None:
                    # the reference surfaces this as a fleet error the
                    # launcher retries once after re-ensuring templates
                    # (instance.go:111-115)
                    errors.append({
                        "code": "InvalidLaunchTemplateName.NotFoundException",
                        "instance_type": o["instance_type"],
                        "zone": o["zone"],
                        "capacity_type": capacity_type,
                    })
                    continue
                image_id = o.get("image_id") or lt.image_id
                zone_id = next((z.zone_id for z in self.zones if z.name == o["zone"]), "")
                wants_ipv6 = any(
                    ni.get("ipv6_address_count")
                    for ni in getattr(lt, "network_interfaces", ()) or ())
                while remaining > 0:
                    inst = FakeInstance(
                        id=_new_id("i"), instance_type=o["instance_type"],
                        zone=o["zone"], zone_id=zone_id,
                        capacity_type=capacity_type, image_id=image_id,
                        launch_template_name=o["launch_template_name"],
                        subnet_id=o.get("subnet_id", ""),
                        launch_time=self.now(),
                        tags={**dict(lt.tags), **dict(tags or {})},
                        security_group_ids=list(lt.security_group_ids))
                    if wants_ipv6:
                        inst.ipv6_address = \
                            "2600:1f13::" + inst.id.removeprefix("i-")
                    self.instances[inst.id] = inst
                    instances.append(inst)
                    remaining -= 1
                break  # one pool satisfies the whole batch (instant fleet)
            return instances, errors

    # -- instance lifecycle ------------------------------------------------
    def describe_instances(self, ids: Sequence[str] = (),
                           tag_filters: Mapping[str, str] = (),
                           states: Sequence[str] = ("pending", "running",
                                                    "shutting-down", "stopped")
                           ) -> List[FakeInstance]:
        self._link_gate()
        with self._mu:
            self.describe_instances_log.record({"ids": list(ids), "filters": dict(tag_filters)})
            self.describe_instances_log.maybe_raise()
            out = []
            for inst in self.instances.values():
                if ids and inst.id not in ids:
                    continue
                if inst.state not in states:
                    continue
                if tag_filters and not _match(inst.tags, tag_filters, inst.id, ()):
                    continue
                out.append(inst)
            return out

    def terminate_instances(self, ids: Sequence[str]) -> List[str]:
        self._link_gate()
        with self._mu:
            self.terminate_instances_log.record(list(ids))
            self.terminate_instances_log.maybe_raise()
            done = []
            for iid in ids:
                inst = self.instances.get(iid)
                if inst and inst.state != "terminated":
                    inst.state = "terminated"
                    done.append(iid)
            return done

    def create_tags(self, ids: Sequence[str], tags: Mapping[str, str]) -> None:
        self._link_gate()
        with self._mu:
            self.create_tags_log.record({"ids": list(ids), "tags": dict(tags)})
            self.create_tags_log.maybe_raise()
            for iid in ids:
                inst = self.instances.get(iid)
                if inst is None:
                    raise KeyError(f"InvalidInstanceID.NotFound: {iid}")
                inst.tags.update(tags)

    # -- test hygiene ------------------------------------------------------
    def reset(self) -> None:
        """Between-spec reset (fake/ec2api.go:84-110)."""
        with self._mu:
            self.instances.clear()
            self.launch_templates.clear()
            self.insufficient_capacity_pools.clear()
            self.removed_offerings.clear()
            for log in (self.create_fleet_log, self.describe_instances_log,
                        self.terminate_instances_log, self.create_launch_template_log,
                        self.create_tags_log, self.describe_instance_types_log,
                        self.ssm_get_parameter_log):
                log.reset()


def _ssm_path(family: str, arch: str) -> str:
    return f"/aws/service/{family}/{arch}/latest/image_id"


def _match(tags: Mapping[str, str], tag_filters: Mapping[str, str],
           obj_id: str, ids: Sequence[str]) -> bool:
    if ids:
        return obj_id in ids
    if not tag_filters:
        return True
    for k, v in dict(tag_filters).items():
        if v == "*":
            if k not in tags:
                return False
        elif tags.get(k) != v:
            return False
    return True
