"""In-memory IAM: the instance-profile API surface the instanceprofile
provider consumes (the mocking boundary, like fake/ec2.py is for EC2 —
reference seam: pkg/aws/sdk.go IAMAPI, 6 methods).

Profiles hold at most ONE role (the IAM invariant the reference's
provider leans on — instanceprofile.go:94-96) and a tag map. NotFound is
a typed error so provider code can ignore-or-propagate exactly like the
reference's awserrors.IsNotFound handling.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Mapping

from .ec2 import CallLog


class ProfileNotFoundError(Exception):
    """GetInstanceProfile / DeleteInstanceProfile on an absent name."""


@dataclass
class FakeInstanceProfile:
    name: str
    roles: List[str] = field(default_factory=list)  # 0 or 1 entries
    tags: Dict[str, str] = field(default_factory=dict)


class FakeIAM:
    def __init__(self):
        self._mu = threading.RLock()
        self._profiles: Dict[str, FakeInstanceProfile] = {}
        self.create_profile_calls = CallLog()
        self.delete_profile_calls = CallLog()
        self.add_role_calls = CallLog()
        self.remove_role_calls = CallLog()

    def get_instance_profile(self, name: str) -> FakeInstanceProfile:
        with self._mu:
            p = self._profiles.get(name)
            if p is None:
                raise ProfileNotFoundError(name)
            return FakeInstanceProfile(name=p.name, roles=list(p.roles),
                                       tags=dict(p.tags))

    def create_instance_profile(self, name: str,
                                tags: Mapping[str, str] = ()) -> None:
        self.create_profile_calls.record(name)
        self.create_profile_calls.maybe_raise()
        with self._mu:
            if name in self._profiles:
                raise ValueError(f"instance profile {name} already exists")
            self._profiles[name] = FakeInstanceProfile(
                name=name, tags=dict(tags or {}))

    def add_role_to_instance_profile(self, name: str, role: str) -> None:
        self.add_role_calls.record((name, role))
        self.add_role_calls.maybe_raise()
        with self._mu:
            p = self._profiles.get(name)
            if p is None:
                raise ProfileNotFoundError(name)
            if p.roles:
                raise ValueError(
                    f"instance profile {name} already has a role")
            p.roles.append(role)

    def remove_role_from_instance_profile(self, name: str,
                                          role: str) -> None:
        self.remove_role_calls.record((name, role))
        self.remove_role_calls.maybe_raise()
        with self._mu:
            p = self._profiles.get(name)
            if p is None:
                raise ProfileNotFoundError(name)
            if role in p.roles:
                p.roles.remove(role)

    def delete_instance_profile(self, name: str) -> None:
        self.delete_profile_calls.record(name)
        self.delete_profile_calls.maybe_raise()
        with self._mu:
            if name not in self._profiles:
                raise ProfileNotFoundError(name)
            p = self._profiles[name]
            if p.roles:
                raise ValueError(
                    f"instance profile {name} still has a role attached")
            del self._profiles[name]

    # test helpers ---------------------------------------------------------
    def profile_names(self) -> List[str]:
        with self._mu:
            return sorted(self._profiles)

    def reset(self) -> None:
        with self._mu:
            self._profiles.clear()
        for c in (self.create_profile_calls, self.delete_profile_calls,
                  self.add_role_calls, self.remove_role_calls):
            c.reset()
