"""Deterministic fault injection for the cloud seam.

The cloud-side sibling of :mod:`faultwire`: where that module tears the
solver's gRPC channel, this one tears the EC2/SQS seam underneath the
operator. :class:`CloudFaultInjector` wraps a :class:`FakeEC2`'s public
API methods (and, optionally, the SQS provider's ``send``) with wrappers
that consult a seeded :class:`CloudFaultPlan` before each real call.
Everything above the wrapped methods — the :class:`ResilientCloud`
retry/classification proxy, the batchers, the eventual-consistency grace
in the controllers, the interruption dedupe — runs UNCHANGED, which is
the point: chaos tests exercise the exact production resilience path
with the exact production error shapes (``AWSError`` throttle codes,
``ConnectionError`` link failures), not mocks of it.

Injected fault kinds (per call, mutually exclusive):

- ``throttle`` — the API sheds the request (``RequestLimitExceeded``,
                 the retry policy's throttle class; storms of these are
                 what the adaptive rate limiter exists for)
- ``down``     — the request never reaches the endpoint
                 (``ConnectionError`` — a DOWN link flap)
- ``wedge``    — the request stalls briefly then succeeds (a bounded
                 WEDGED link flap; the *unbounded* wedge is the boot
                 preflight suite's job, not a convergence test's)
- ``lag``      — create_fleet succeeded but the new instances are
                 invisible to describe_instances for ``lag_s`` seconds
                 (EC2's documented eventual consistency; without the
                 creation-grace window GC would reap the materializing
                 node)
- ``partial``  — create_fleet under-delivers: the tail instance of the
                 batch never launched (the caller sees an ICE-shaped
                 deficit and reprovisions)
- ``dup``      — an SQS send is delivered twice (at-least-once
                 redelivery; the interruption dedupe must collapse it)

Determinism: faults are drawn from ``random.Random(seed)`` in call
order. The operator's batchers and GC pool are threaded, so the call
ORDER — and therefore the injector log — is not reproducible across
runs; the convergence contract is instead on the terminal state: every
seeded run must settle to the fault-free run's cluster fingerprint with
zero orphaned instances and zero lost interruptions
(``hack/chaoscloud.sh`` sweeps seeds against exactly that bar).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..providers.awsretry import AWSError

#: fault kinds an injector can draw (order matters: it is the cumulative
#: probability order used by CloudFaultPlan.next)
CLOUD_FAULT_KINDS = ("throttle", "down", "wedge", "lag", "partial", "dup")

#: FakeEC2 methods the injector wraps — every operation the providers
#: reach through the ResilientCloud proxy's guarded set that the fake
#: actually serves during steady-state operation
EC2_FAULT_OPS = (
    "create_fleet",
    "describe_instances",
    "terminate_instances",
    "create_tags",
    "create_launch_template",
    "describe_launch_templates",
    "describe_subnets",
    "describe_security_groups",
    "describe_images",
    "describe_instance_types",
    "ssm_get_parameter",
)


class CloudFaultPlan:
    """Seeded per-call fault schedule for the cloud seam.

    Each cloud call draws one uniform sample; the p_* probabilities
    partition [0,1) in CLOUD_FAULT_KINDS order, remainder = clean call.
    Kinds that do not apply to the operation at hand (``lag``/``partial``
    outside create_fleet, ``dup`` outside sqs.send, throttle/down/wedge
    ON sqs.send) resolve to a clean call — the draw is still consumed so
    the schedule stays a pure function of the seed and call order.

    Two bounds keep an adversarial schedule from (correctly but
    unhelpfully) violating the convergence bar:

    - ``max_consecutive`` bounds runs of *delivery* failures
      (throttle/down) below the retry policy's attempt budget, so a
      retried call always eventually lands;
    - ``max_faults`` caps the total number of injected faults, after
      which the plan goes permanently clean — the chaos storm is finite,
      so the settle loop's terminal state is the fault-free one.
    """

    def __init__(self, seed: int,
                 p_throttle: float = 0.12,
                 p_down: float = 0.08,
                 p_wedge: float = 0.08,
                 p_lag: float = 0.10,
                 p_partial: float = 0.06,
                 p_dup: float = 0.25,
                 wedge_ms: float = 25.0,
                 lag_s: float = 0.75,
                 max_consecutive: int = 2,
                 max_faults: int = 40):
        import random
        self.seed = seed
        self._rng = random.Random(seed)
        self._p = (p_throttle, p_down, p_wedge, p_lag, p_partial, p_dup)
        assert sum(self._p) <= 1.0
        self.wedge_ms = wedge_ms
        self.lag_s = lag_s
        self.max_consecutive = max_consecutive
        self.max_faults = max_faults
        self._consecutive = 0
        self._faults = 0

    def next(self, call_index: int, op: str) -> Optional[str]:
        """Draw the fault (or None) for this cloud call. `call_index`
        and `op` ride into the injector's event log; the draw itself is
        purely sequential so the schedule is a function of the seed."""
        u = self._rng.random()
        if self._faults >= self.max_faults:
            return None
        acc = 0.0
        kind = None
        for k, p in zip(CLOUD_FAULT_KINDS, self._p):
            acc += p
            if u < acc:
                kind = k
                break
        # remap kinds that do not apply to this operation to clean
        if op == "sqs.send":
            if kind != "dup":
                kind = None
        else:
            if kind == "dup":
                kind = None
            if kind in ("lag", "partial") and op != "create_fleet":
                kind = None
        if kind in ("throttle", "down"):
            if self._consecutive >= self.max_consecutive:
                kind = None  # forced clean call: bound the failure run
            else:
                self._consecutive += 1
        else:
            self._consecutive = 0
        if kind is not None:
            self._faults += 1
        return kind


class CloudFaultInjector:
    """Wraps a FakeEC2's API methods (and SQS send) with the plan's faults.

    Usage::

        op = Operator(...)
        inj = CloudFaultInjector(op.ec2, sqs=op.sqs,
                                 plan=CloudFaultPlan(seed=7)).install()
        ... drive the cluster; inj.log holds (call_index, op, fault) ...
        inj.uninstall()

    Install AFTER the operator is built: the wrappers then sit between
    the operator's instrumentation layer and the ResilientCloud proxy's
    per-call ``getattr`` (proxy -> injector -> instrumentation -> fake),
    so every injected fault travels the full production retry path.

    Faults that fail delivery (throttle/down) are raised BEFORE the real
    call — the fake's state never mutates on a failed request, so a
    "failure" can never strand a half-created instance the controllers
    cannot see. Orphans, if the grace/GC logic regressed, come from the
    ``lag`` fault instead: the instance exists but describe hides it.
    """

    def __init__(self, ec2, sqs=None, plan: Optional[CloudFaultPlan] = None,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep):
        self.ec2 = ec2
        self.sqs = sqs
        self.plan = plan if plan is not None else CloudFaultPlan(seed=0)
        self._clock = clock
        self._sleep = sleep
        self._mu = threading.Lock()
        self._calls = 0
        #: (call_index, op, fault-or-"ok") per cloud call, in call order
        self.log: List[Tuple[int, str, str]] = []
        self._orig: Dict[str, Callable] = {}
        self._orig_send: Optional[Callable] = None
        #: instance id -> monotonic deadline before which describe_instances
        #: pretends the instance does not exist (eventual consistency)
        self._lagged: Dict[str, float] = {}
        #: instances a ``partial`` fault erased from a fleet result
        self.dropped_instances: List[str] = []
        #: SQS messages the ``dup`` fault re-delivered
        self.dup_sends = 0

    # ------------------------------------------------------------------
    def _draw(self, op: str) -> Optional[str]:
        with self._mu:
            idx = self._calls
            self._calls += 1
            fault = self.plan.next(idx, op)
            self.log.append((idx, op, fault or "ok"))
            return fault

    def fault_counts(self) -> Dict[str, int]:
        """Injected-fault histogram (diagnostics for sweep failures)."""
        out: Dict[str, int] = {}
        with self._mu:
            for _idx, _op, fault in self.log:
                out[fault] = out.get(fault, 0) + 1
        return out

    # ------------------------------------------------------------------
    def _wrap_ec2(self, op: str, real: Callable) -> Callable:
        def call(*args, **kwargs):
            fault = self._draw(op)
            if fault == "throttle":
                raise AWSError("RequestLimitExceeded",
                               "injected: request rate exceeded", status=503)
            if fault == "down":
                raise ConnectionError("injected: cloud endpoint unreachable")
            if fault == "wedge":
                self._sleep(self.plan.wedge_ms / 1e3)
            out = real(*args, **kwargs)
            if op == "create_fleet":
                instances, errors = out
                if fault == "partial" and instances:
                    # the fleet under-delivered: the tail instance never
                    # launched anywhere — erase it from the store too so
                    # the caller's deficit is the only trace
                    lost = instances.pop()
                    self.ec2.instances.pop(lost.id, None)
                    self.dropped_instances.append(lost.id)
                if fault == "lag" and instances:
                    deadline = self._clock() + self.plan.lag_s
                    with self._mu:
                        for inst in instances:
                            self._lagged[inst.id] = deadline
                return instances, errors
            if op == "describe_instances":
                return self._filter_lagged(out)
            return out
        return call

    def _filter_lagged(self, instances):
        now = self._clock()
        with self._mu:
            for iid in [i for i, t in self._lagged.items() if t <= now]:
                del self._lagged[iid]
            if not self._lagged:
                return instances
            hidden = set(self._lagged)
        return [i for i in instances if i.id not in hidden]

    def _wrap_sqs_send(self, real: Callable) -> Callable:
        def send(message):
            fault = self._draw("sqs.send")
            real(message)
            if fault == "dup":
                # at-least-once redelivery: the same logical event lands
                # twice (fresh receipt — real SQS redeliveries do too);
                # the interruption dedupe must collapse it
                import copy
                with self._mu:
                    self.dup_sends += 1
                real(copy.copy(message))
        return send

    # ------------------------------------------------------------------
    def install(self) -> "CloudFaultInjector":
        assert not self._orig, "already installed"
        for op in EC2_FAULT_OPS:
            real = getattr(self.ec2, op)
            self._orig[op] = real
            setattr(self.ec2, op, self._wrap_ec2(op, real))
        if self.sqs is not None:
            self._orig_send = self.sqs.send
            self.sqs.send = self._wrap_sqs_send(self._orig_send)
        return self

    def uninstall(self) -> None:
        for op, real in self._orig.items():
            setattr(self.ec2, op, real)
        self._orig = {}
        if self._orig_send is not None:
            self.sqs.send = self._orig_send
            self._orig_send = None
        with self._mu:
            self._lagged.clear()

    def __enter__(self) -> "CloudFaultInjector":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()
