"""Deterministic fault injection for the solver wire.

:class:`FaultInjector` wraps a live ``SolverClient`` at the channel
callable level — the five raw unary callables (``_solve``,
``_solve_pruned``, ``_solve_topo``, ``_solve_batch``, ``_info``) are
replaced with
wrappers that consult a seeded :class:`FaultPlan` before (and after)
each real wire call. Everything above the callables — the resilience
policy, retries, breaker, arena decode — runs UNCHANGED, which is the
point: chaos tests exercise the exact production path with the exact
production error types (real ``grpc.RpcError`` subclasses carrying
``code()``), not mocks of it.

Injected fault kinds (per call, mutually exclusive):

- ``unavailable``     — the RPC never reaches the server (UNAVAILABLE)
- ``deadline``        — the call times out (DEADLINE_EXCEEDED)
- ``latency``         — the call succeeds after an added delay
- ``truncate``        — the server solved; the response arena arrives
                        torn (the codec checksum catches it client-side)
- ``drop``            — the server solved; the reply is lost mid-call
                        (UNAVAILABLE *after* server work — the
                        retry-a-duplicate case, safe because solves are
                        pure)
- ``stale``           — SolvePatch only: the server pretends its
                        resident arena moved (FAILED_PRECONDITION,
                        "stale arena version") — the client must serve
                        the tick with ONE full Solve and re-prime. On
                        every other RPC the draw is a clean call, so
                        adding ``p_stale`` never perturbs a full-frame
                        schedule.

Determinism: faults are drawn from ``random.Random(seed)`` in call
order. Keep every wire call on ONE thread (backend='jax' with the
liveness verdict pre-resolved) and the same seed replays the same fault
schedule — ``hack/chaoswire.sh`` fails CI on any divergence.

:class:`TenantHammer` is the multi-tenant counterpart: instead of
faulting the wire between one client and the server, it plays a HOSTILE
TENANT against a live server — poison frames (unparseable arenas),
deadline storms (1ms client deadlines), and quota-exhaustion bursts,
all billed to one ``x-solver-tenant`` label. The isolation contract
(tests/test_faultwire.py, ``hack/chaostenant.sh``): a quiet tenant
sharing the server keeps byte-identical decisions and a bounded p99
while the hammer runs.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional, Tuple

import numpy as np

#: fault kinds an injector can draw (order matters: it is the cumulative
#: probability order used by FaultPlan.next — "stale" is appended LAST
#: with a 0.0 default so existing seeds' draw schedules are unchanged)
FAULT_KINDS = ("unavailable", "deadline", "latency", "truncate", "drop",
               "stale")


def _injected_error(code, details: str):
    """A real grpc.RpcError (the concrete class grpc itself raises would
    need a live call object; RpcError + code()/details() is the contract
    every handler in this repo reads)."""
    import grpc

    class _Err(grpc.RpcError):
        def __init__(self):
            super().__init__(details)
            self._code = code
            self._details = details

        def code(self):
            return self._code

        def details(self):
            return self._details

    return _Err()


class FaultPlan:
    """Seeded per-call fault schedule.

    Each wire call draws one uniform sample; the p_* probabilities
    partition [0,1) in FAULT_KINDS order, remainder = clean call.
    ``max_consecutive`` bounds runs of *delivery* failures (unavailable /
    deadline / truncate / drop) so a finite retry budget always
    eventually lands — the acceptance bar is "every solve completes",
    which an adversarial infinite-failure schedule would (correctly,
    but unhelpfully) violate through the host twin instead of the wire.
    """

    def __init__(self, seed: int, p_unavailable: float = 0.15,
                 p_deadline: float = 0.1, p_latency: float = 0.1,
                 p_truncate: float = 0.1, p_drop: float = 0.1,
                 p_stale: float = 0.0,
                 latency_ms: float = 20.0, max_consecutive: int = 2):
        import random
        self.seed = seed
        self._rng = random.Random(seed)
        self._p = (p_unavailable, p_deadline, p_latency, p_truncate,
                   p_drop, p_stale)
        assert sum(self._p) <= 1.0
        self.latency_ms = latency_ms
        self.max_consecutive = max_consecutive
        self._consecutive = 0

    def next(self, call_index: int, rpc: str) -> Optional[str]:
        """Draw the fault (or None) for this wire call. `call_index` and
        `rpc` ride into the injector's event log; the draw itself is
        purely sequential so the schedule is a function of the seed."""
        u = self._rng.random()
        acc = 0.0
        kind = None
        for k, p in zip(FAULT_KINDS, self._p):
            acc += p
            if u < acc:
                kind = k
                break
        if kind == "stale" and rpc != "SolvePatch":
            # only the delta wire has a residency precondition to
            # violate — anywhere else the draw is a clean call
            kind = None
        if kind in ("unavailable", "deadline", "truncate", "drop"):
            if self._consecutive >= self.max_consecutive:
                kind = None  # forced clean call: bound the failure run
            else:
                self._consecutive += 1
        if kind in (None, "latency", "stale"):
            # stale is rejection-class: the peer answered, definitively
            # — it doesn't extend a delivery-failure run
            self._consecutive = 0
        return kind


class FaultInjector:
    """Wraps a SolverClient's channel callables with the plan's faults.

    Usage::

        client = SolverClient(server.address, policy=seeded_policy)
        inj = FaultInjector(client, FaultPlan(seed=7)).install()
        ... run solves; inj.log holds (call_index, rpc, fault) ...
        inj.uninstall()

    The event log is the determinism fingerprint: two runs with equal
    seeds (and single-threaded wire traffic) must produce equal logs.
    """

    _WRAPPED = (("_solve", "Solve"), ("_solve_pruned", "SolvePruned"),
                ("_solve_topo", "SolveTopo"),
                ("_solve_batch", "SolveBatch"),
                ("_solve_patch", "SolvePatch"), ("_info", "Info"))

    def __init__(self, client, plan: FaultPlan,
                 sleep: Callable[[float], None] = time.sleep):
        self.client = client
        self.plan = plan
        self._sleep = sleep
        self._mu = threading.Lock()
        self._calls = 0
        #: (call_index, rpc, fault-or-"ok") per wire call, in call order
        self.log: List[Tuple[int, str, str]] = []
        self._orig = {}

    def _wrap(self, rpc: str, real):
        def call(request, timeout=None, metadata=None):
            import grpc
            with self._mu:
                idx = self._calls
                self._calls += 1
                fault = self.plan.next(idx, rpc)
                self.log.append((idx, rpc, fault or "ok"))
            if fault == "unavailable":
                raise _injected_error(grpc.StatusCode.UNAVAILABLE,
                                      "injected: connection refused")
            if fault == "deadline":
                raise _injected_error(grpc.StatusCode.DEADLINE_EXCEEDED,
                                      "injected: deadline exceeded")
            if fault == "stale":
                # the request never reaches the real handler: the server
                # "lost" this client's residency (restart, eviction,
                # version race) — the client must full-frame this tick
                raise _injected_error(
                    grpc.StatusCode.FAILED_PRECONDITION,
                    "injected: stale arena version")
            if fault == "latency":
                self._sleep(self.plan.latency_ms / 1e3)
                return real(request, timeout=timeout, metadata=metadata)
            resp = real(request, timeout=timeout, metadata=metadata)
            if fault == "truncate":
                # the server did the work; the reply arrives torn — the
                # arena checksum fails client-side and the policy
                # retries (a malformed response is availability-class)
                return resp[:max(1, len(resp) // 2)]
            if fault == "drop":
                # the server did the work; the reply is lost. The retry
                # duplicates a solve — safe by construction (pure).
                raise _injected_error(grpc.StatusCode.UNAVAILABLE,
                                      "injected: connection reset mid-call")
            return resp
        return call

    def install(self) -> "FaultInjector":
        assert not self._orig, "already installed"
        for attr, rpc in self._WRAPPED:
            real = getattr(self.client, attr)
            self._orig[attr] = real
            setattr(self.client, attr, self._wrap(rpc, real))
        return self

    def uninstall(self) -> None:
        for attr, real in self._orig.items():
            setattr(self.client, attr, real)
        self._orig = {}

    def __enter__(self) -> "FaultInjector":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()


# ---------------------------------------------------------------------------
# fleet chaos


#: Info capability flag -> the handler attribute a "legacy build"
#: would not have (downgrade_server wires both sides of the lie)
_CAP_HANDLERS = {"patch": "solve_patch", "batch": "solve_batch",
                 "subsets": "solve_subsets", "pruned": "solve_pruned"}


def downgrade_server(server, drop=("patch",)):
    """Roll a live in-process :class:`SolverServer` to a build without
    the ``drop`` capabilities — BOTH halves of the lie: its Info stops
    advertising the flags, and the corresponding RPCs answer
    UNIMPLEMENTED like a binary that never linked them (a client that
    ships a gated frame anyway gets the real legacy-peer experience,
    which is exactly what the no-SolvePatch-after-failover regression
    asserts). Returns a zero-argument restore function."""
    import grpc

    from ..native import arena_pack, arena_unpack
    handler = server._handler
    saved = {"info": handler.info}
    orig_info = handler.info

    def legacy_info(request, context):
        d = arena_unpack(orig_info(request, context))
        for flag in drop:
            d.pop(flag, None)
        return arena_pack(d)

    handler.info = legacy_info
    for flag in drop:
        attr = _CAP_HANDLERS.get(flag)
        if attr is None or not hasattr(handler, attr):
            continue
        saved[attr] = getattr(handler, attr)

        def unimplemented(request, context, _rpc=attr):
            context.abort(grpc.StatusCode.UNIMPLEMENTED,
                          f"{_rpc}: unimplemented in this build")

        setattr(handler, attr, unimplemented)

    def restore():
        for attr, real in saved.items():
            setattr(handler, attr, real)

    return restore


def corrupt_server(server):
    """Make a live in-process :class:`SolverServer` return WELL-FORMED
    but WRONG decisions: Solve replies still parse cleanly (same arena
    framing, same shapes/dtypes, checksum recomputed over the lie) but
    the decision rows are perturbed. This is the failure class only a
    canary fingerprint catches — transport is healthy, Info answers
    truthfully, breakers never trip — and what the fleet quarantine
    gate (fleet/membership.py probe) must catch. Returns a
    zero-argument restore function."""
    from ..native import arena_pack, arena_unpack
    handler = server._handler
    real = handler.solve

    def lying(request, context):
        d = arena_unpack(real(request, context))
        out = np.array(d["out"])
        if out.size:
            flat = out.reshape(-1)
            flat[0] = flat[0] + 1  # plausible, parseable, wrong
        d["out"] = out
        return arena_pack(d)

    handler.solve = lying

    def restore():
        handler.solve = real

    return restore


#: membership actions a FleetChaosPlan can draw per tick, in cumulative-
#: probability order (the order is ABI for seeded schedules — append
#: only). "kill" stops the bound owner mid-patch-stream; "flap" removes
#: a replica from membership and re-adds it a few ticks later; "roll"
#: downgrades a replica to a legacy build (no `patch`), "unroll"
#: restores it.
FLEET_ACTIONS = ("kill", "revive", "flap", "roll")


class FleetChaosPlan:
    """Seeded per-tick fleet-membership schedule.

    Pure schedule, no side effects: :meth:`next` draws the action for
    one tick; the TEST applies it (stopping servers, flapping the
    membership, rolling builds) so every mutation is visible in the
    test body. ``min_gap`` forces quiet ticks between disruptions —
    the p99 bound in the acceptance criteria is per-tick, and a
    schedule allowed to kill every tick would measure only the
    degradation path, not recovery."""

    def __init__(self, seed: int, p_kill: float = 0.10,
                 p_revive: float = 0.35, p_flap: float = 0.10,
                 p_roll: float = 0.08, min_gap: int = 2):
        import random
        self.seed = seed
        self._rng = random.Random(seed)
        self._p = (p_kill, p_revive, p_flap, p_roll)
        self.min_gap = min_gap
        self._since = min_gap  # first tick may act
        self.log: List[Tuple[int, str]] = []

    def next(self, tick: int) -> Optional[str]:
        u = self._rng.random()
        acc = 0.0
        kind = None
        for k, p in zip(FLEET_ACTIONS, self._p):
            acc += p
            if u < acc:
                kind = k
                break
        if kind is not None and self._since < self.min_gap:
            kind = None  # cool-down: let the fleet re-prime first
        self._since = 0 if kind is not None else self._since + 1
        self.log.append((tick, kind or "none"))
        return kind


#: attack kinds a TenantHammer cycles through (seeded draw order)
ATTACK_KINDS = ("poison", "deadline", "burst")


class TenantHammer:
    """An adversarial tenant against a live sidecar server.

    Three attack shapes, drawn seeded per iteration:

    - ``poison``   — an unparseable request arena (server answers
                     INVALID_ARGUMENT; the request still spends the
                     tenant's admission token)
    - ``deadline`` — a 1ms client deadline (the call dies client-side
                     mid-flight; the server's handler still runs)
    - ``burst``    — 5 back-to-back poison frames, the quota-exhaustion
                     case: past the token-bucket burst the server sheds
                     with RESOURCE_EXHAUSTED + a retry-after hint

    Every call carries ``x-solver-tenant: <tenant>`` so the server's
    admission layer bills the whole storm to this tenant. ``outcomes``
    counts the grpc status codes observed (the test asserts the storm
    really drew INVALID_ARGUMENT / DEADLINE_EXCEEDED /
    RESOURCE_EXHAUSTED). Run inline with :meth:`run` or as a background
    thread via :meth:`start` / :meth:`stop`.
    """

    def __init__(self, address: str, tenant: str = "hammer",
                 seed: int = 0):
        import random
        self.address = address
        self.tenant = tenant
        self._rng = random.Random(seed)
        self._mu = threading.Lock()
        self.outcomes: dict = {}
        self.attacks: List[str] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._channel = None

    def _count(self, code: str) -> None:
        with self._mu:
            self.outcomes[code] = self.outcomes.get(code, 0) + 1

    def _fire(self, timeout: float) -> None:
        import grpc
        try:
            self._solve(b"\x00poison-frame", timeout=timeout,
                        metadata=(("x-solver-tenant", self.tenant),))
            self._count("OK")
        except grpc.RpcError as e:
            code = e.code() if hasattr(e, "code") else None
            self._count(code.name if code is not None else "UNKNOWN")

    def run(self, n_attacks: int = 30) -> dict:
        """Fire `n_attacks` seeded attacks (or until stop() in thread
        mode); returns the outcome counts."""
        import grpc
        if self._channel is None:
            self._channel = grpc.insecure_channel(self.address)
            self._solve = self._channel.unary_unary(
                "/karpenter.solver.v1.Solver/Solve")
        for _ in range(n_attacks):
            if self._stop.is_set():
                break
            kind = self._rng.choice(ATTACK_KINDS)
            self.attacks.append(kind)
            if kind == "poison":
                self._fire(timeout=5.0)
            elif kind == "deadline":
                self._fire(timeout=0.001)
            else:  # burst: overrun the token bucket
                for _ in range(5):
                    self._fire(timeout=5.0)
        return dict(self.outcomes)

    def start(self, n_attacks: int = 10 ** 6) -> "TenantHammer":
        self._thread = threading.Thread(
            target=self.run, args=(n_attacks,), daemon=True)
        self._thread.start()
        return self

    def stop(self) -> dict:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30)
        if self._channel is not None:
            self._channel.close()
            self._channel = None
        return dict(self.outcomes)
