"""In-memory Kubernetes-like API with watches.

The envtest analog (SURVEY §4): stores KubeObjects per kind, supports
list/get/create/update/delete with resource-version bumps, finalizer-gated
deletion, and queue-based watch streams consumed by the controllers.
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from ..apis.objects import KubeObject


@dataclass(frozen=True)
class Event:
    type: str          # ADDED | MODIFIED | DELETED
    obj: KubeObject


class Conflict(Exception):
    """Optimistic-concurrency conflict (stale resourceVersion)."""


class NotFound(KeyError):
    pass


class FakeKube:
    def __init__(self, now: Callable[[], float] = time.time):
        self._mu = threading.RLock()
        self._store: Dict[Tuple[str, str, str], KubeObject] = {}
        self._watchers: List[Tuple[Optional[str], "queue.Queue[Event]"]] = []
        self._rv = 0
        self.now = now

    # -- CRUD --------------------------------------------------------------
    def create(self, obj: KubeObject) -> KubeObject:
        # admission: the CEL-rule analog runs where the kube-apiserver
        # would run it (apis/validation.py)
        from ..apis.validation import validate
        validate(obj)
        with self._mu:
            key = obj.key()
            if key in self._store:
                raise ValueError(f"AlreadyExists: {key}")
            self._rv += 1
            obj.metadata.resource_version = self._rv
            if not obj.metadata.creation_timestamp:
                obj.metadata.creation_timestamp = self.now()
            self._store[key] = obj
            self._notify(Event("ADDED", obj))
            return obj

    def get(self, kind: str, name: str, namespace: str = "") -> KubeObject:
        with self._mu:
            key = (kind, namespace, name)
            if key not in self._store:
                raise NotFound(f"{kind}/{name}")
            return self._store[key]

    def try_get(self, kind: str, name: str, namespace: str = "") -> Optional[KubeObject]:
        with self._mu:
            return self._store.get((kind, namespace, name))

    def list(self, kind: str, namespace: Optional[str] = None,
             label_selector: Optional[Dict[str, str]] = None) -> List[KubeObject]:
        with self._mu:
            out = []
            for (k, ns, _), obj in self._store.items():
                if k != kind:
                    continue
                if namespace is not None and ns != namespace:
                    continue
                if label_selector and any(
                        obj.metadata.labels.get(lk) != lv
                        for lk, lv in label_selector.items()):
                    continue
                out.append(obj)
            return sorted(out, key=lambda o: (o.metadata.namespace, o.metadata.name))

    def update(self, obj: KubeObject, expect_version: Optional[int] = None) -> KubeObject:
        from ..apis.validation import validate, validate_update
        with self._mu:
            key = obj.key()
            cur = self._store.get(key)
            if cur is None:
                raise NotFound(f"{key}")
            if cur is not obj:
                # a distinct old object allows immutability checks too
                validate_update(cur, obj)
            else:
                # in-place mutation + update(obj is cur) is the common test
                # pattern; admission rules still apply
                validate(obj)
            if expect_version is not None and cur.metadata.resource_version != expect_version:
                raise Conflict(f"{key}: rv {cur.metadata.resource_version} != {expect_version}")
            self._rv += 1
            obj.metadata.resource_version = self._rv
            self._store[key] = obj
            self._notify(Event("MODIFIED", obj))
            return obj

    def delete(self, kind: str, name: str, namespace: str = "") -> None:
        """Finalizer-aware: with finalizers present, only stamps
        deletionTimestamp; the object disappears when finalizers clear."""
        with self._mu:
            key = (kind, namespace, name)
            obj = self._store.get(key)
            if obj is None:
                raise NotFound(f"{kind}/{name}")
            if obj.metadata.finalizers:
                if obj.metadata.deletion_timestamp is None:
                    obj.metadata.deletion_timestamp = self.now()
                    self._rv += 1
                    obj.metadata.resource_version = self._rv
                    self._notify(Event("MODIFIED", obj))
                return
            del self._store[key]
            self._notify(Event("DELETED", obj))

    def remove_finalizer(self, obj: KubeObject, finalizer: str) -> None:
        with self._mu:
            if finalizer in obj.metadata.finalizers:
                obj.metadata.finalizers.remove(finalizer)
            if obj.metadata.deletion_timestamp is not None and not obj.metadata.finalizers:
                key = obj.key()
                if key in self._store:
                    del self._store[key]
                    self._notify(Event("DELETED", obj))
            else:
                self.update(obj)

    # -- watch -------------------------------------------------------------
    def watch(self, kind: Optional[str] = None) -> "queue.Queue[Event]":
        q: "queue.Queue[Event]" = queue.Queue()
        with self._mu:
            self._watchers.append((kind, q))
            # replay existing state as ADDED (informer initial-list semantics)
            for (k, _, _), obj in sorted(self._store.items()):
                if kind is None or k == kind:
                    q.put(Event("ADDED", obj))
        return q

    def _notify(self, ev: Event) -> None:
        for kind, q in self._watchers:
            if kind is None or ev.obj.kind == kind:
                q.put(ev)

    def reset(self) -> None:
        with self._mu:
            self._store.clear()
            self._watchers.clear()
            self._rv = 0
