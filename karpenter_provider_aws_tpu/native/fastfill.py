"""ctypes binding for the native whole-solve FFD fill
(native/fastfill.cpp) — the C twin of ops/ffd.py::_fill_group_fast run
over every group in one call.

Used by the solver only when the snapshot fits the fast-path guards (no
topology, no minValues floors, no pool limits); decision identity with
the numpy engine is fuzz-enforced by tests/test_solver_equivalence.py.
Falls back silently (``available() -> False``) when the library can't be
built — the numpy fast path serves instead, slower but identical.
"""

from __future__ import annotations

import ctypes
from typing import Optional, Tuple

import numpy as np

from ._build import build_and_load

_I64P = ctypes.POINTER(ctypes.c_int64)
_U8P = ctypes.POINTER(ctypes.c_uint8)
_I32P = ctypes.POINTER(ctypes.c_int32)


def _load() -> "ctypes.CDLL | None":
    lib = build_and_load("libkarpfastfill.so", "fastfill.cpp")
    if lib is None:
        return None
    lib.karp_fast_fill.restype = ctypes.c_int64
    lib.karp_fast_fill.argtypes = (
        [ctypes.c_int64] * 9
        + [_I64P, _U8P,                       # A, avail
           _I64P, _I64P, _U8P, _U8P, _U8P, _U8P, _U8P, _I64P,  # group rows
           _U8P, _U8P, _U8P,                  # pool rows
           _I64P, _U8P,                       # existing
           _I64P, _U8P, _U8P, _U8P, _I32P, _U8P, _I64P, _I64P,  # state
           _I64P, _I64P, _I64P, ctypes.c_int64, _I64P,  # placement triples
           _I64P])                            # leftover
    return lib


_LIB = _load()


def available() -> bool:
    return _LIB is not None


def _i64(a: np.ndarray) -> _I64P:
    return a.ctypes.data_as(_I64P)


def _u8(a: np.ndarray) -> _U8P:
    return a.ctypes.data_as(_U8P)


def fill_all(st, enc) -> Optional[Tuple[Tuple[np.ndarray, np.ndarray,
                                              np.ndarray], np.ndarray]]:
    """Run every group's closed-form fill natively, mutating ``st`` in
    place exactly as the per-group numpy path would. Returns
    ((g, slot, count) placement triples in walk order, leftover[G]), or
    None when the library is absent or the triple buffer overflowed (the
    caller must then re-solve on FRESH state — ``st`` has been mutated).
    Caller enforces the fast-path guards."""
    if _LIB is None:
        return None
    G = len(enc.groups)
    P = len(enc.pools)
    T, D = enc.A.shape
    Z, C = len(enc.zones), enc.avail.shape[2]
    # each triple is one (group, slot) fill; a group rarely touches more
    # than a couple of slots, so G+N-proportional capacity is generous.
    # Overflow is signalled, never silent (out_n == -1).
    cap = 8 * G + 8 * st.N + 4096
    out_g = np.empty(cap, dtype=np.int64)
    out_slot = np.empty(cap, dtype=np.int64)
    out_cnt = np.empty(cap, dtype=np.int64)
    out_n = np.zeros(1, dtype=np.int64)
    leftover = np.zeros(G, dtype=np.int64)
    pool_types = np.ascontiguousarray(
        np.stack([p.type_rows for p in enc.pools])
        if P else np.zeros((0, T), bool))
    pool_agz = np.ascontiguousarray(
        np.stack([p.agz for p in enc.pools])
        if P else np.zeros((0, Z), bool))
    pool_agc = np.ascontiguousarray(
        np.stack([p.agc for p in enc.pools])
        if P else np.zeros((0, C), bool))
    ex_alloc = st.ex_alloc if st.E else np.zeros((0, D), np.int64)
    ex_compat = st.ex_compat if st.E else np.zeros((G, 0), bool)
    F_full = enc.F_full
    if F_full is None:
        # frontier eligibility per group; normally precomputed row-wise
        # by the encoder's signature bank
        F_full = enc.F_full = np.ascontiguousarray(
            enc.F.all(axis=1), dtype=np.uint8)
    num_nodes = _LIB.karp_fast_fill(
        G, st.N, T, D, Z, C, st.E, P, st.num_nodes,
        _i64(enc.A), _u8(enc.avail),
        _i64(enc.R), _i64(enc.n), _u8(enc.F), _u8(F_full),
        _u8(enc.agz), _u8(enc.agc),
        _u8(enc.admit), _i64(enc.daemon),
        _u8(pool_types), _u8(pool_agz), _u8(pool_agc),
        _i64(np.ascontiguousarray(ex_alloc)),
        _u8(np.ascontiguousarray(ex_compat)),
        _i64(st.used), _u8(st.types), _u8(st.zones), _u8(st.ct),
        st.pool.ctypes.data_as(_I32P), _u8(st.alive),
        _i64(st.cap_hint), _i64(st.pool_used),
        _i64(out_g), _i64(out_slot), _i64(out_cnt), cap, _i64(out_n),
        _i64(leftover))
    st.num_nodes = int(num_nodes)
    n = int(out_n[0])
    if n < 0:
        return None  # overflow: placements incomplete, state is dirty
    return (out_g[:n], out_slot[:n], out_cnt[:n]), leftover
