from .codec import (arena_pack, arena_unpack, native_available,  # noqa: F401
                    pack_bits, unpack_bits)
from . import deltawalk  # noqa: F401
