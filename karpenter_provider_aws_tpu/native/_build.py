"""Shared build-and-dlopen for the native libraries (codec, fastfill).

One-shot silent build on first import when a compiler is available
(atomic: compile to a pid-suffixed temp, rename into place — a
concurrent importer either sees the old state and falls back, or the
complete library, never a truncated file). Honors $CXX like
native/Makefile."""

from __future__ import annotations

import ctypes
import os
import subprocess

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
NATIVE_DIR = os.path.join(REPO_ROOT, "native")


def _stale(so_path: str, cpp: str) -> bool:
    """A .so older than its source must be rebuilt: loading a library
    compiled against a previous signature is an ABI mismatch ctypes
    cannot detect (silent memory corruption, not an error). A .so with
    NO adjacent source (source-pruned deployment artifact) is trusted
    as-is — staleness is indeterminate and refusing to load it would be
    a silent perf cliff."""
    if not os.path.exists(cpp):
        return False
    try:
        return os.path.getmtime(so_path) < os.path.getmtime(cpp)
    except OSError:
        return True


def _ensure_built(so_path: str, src: str, compile_cmd) -> bool:
    """The shared atomic build step: compile to a pid-suffixed temp and
    rename into place (a concurrent builder either sees the old state
    and falls back, or the complete library — never a truncated file).
    ``compile_cmd(tmp)`` returns the argv. True iff so_path is usable."""
    if os.path.exists(so_path) and not _stale(so_path, src):
        return True
    if not os.path.exists(src):
        return False
    tmp = so_path + f".tmp.{os.getpid()}"
    try:
        subprocess.run(compile_cmd(tmp), check=True, capture_output=True,
                       timeout=60)
        os.replace(tmp, so_path)
        return True
    except Exception:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def build_and_load(so_name: str, cpp_name: str) -> "ctypes.CDLL | None":
    so_path = os.path.join(NATIVE_DIR, so_name)
    cpp = os.path.join(NATIVE_DIR, cpp_name)
    if not _ensure_built(so_path, cpp, lambda tmp: [
            os.environ.get("CXX", "g++"), "-O3", "-fPIC", "-std=c++17",
            "-shared", "-o", tmp, cpp]):
        return None
    try:
        return ctypes.CDLL(so_path)
    except OSError:
        return None


def build_ext_and_import(module_name: str, c_name: str):
    """Build and import a CPython extension module from native/ (same
    one-shot/atomic/staleness discipline as the ctypes libraries).
    Returns the module or None — callers keep a pure-python fallback.

    Unlike the ctypes libraries (pure C ABI), a CPython extension is
    ABI-specific — the .so carries the interpreter's EXT_SUFFIX tag so a
    Python upgrade rebuilds instead of importing an extension compiled
    against different object layouts (silent corruption, not an error)."""
    import importlib.util
    import sysconfig
    suffix = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    so_path = os.path.join(NATIVE_DIR, module_name + suffix)
    src = os.path.join(NATIVE_DIR, c_name)
    inc = sysconfig.get_paths()["include"]
    if not _ensure_built(so_path, src, lambda tmp: [
            os.environ.get("CC", os.environ.get("CXX", "gcc")),
            "-O2", "-fPIC", "-shared", "-I", inc, "-o", tmp, src]):
        return None
    try:
        spec = importlib.util.spec_from_file_location(module_name, so_path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod
    except Exception:
        return None
