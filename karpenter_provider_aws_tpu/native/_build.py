"""Shared build-and-dlopen for the native libraries (codec, fastfill).

One-shot silent build on first import when a compiler is available
(atomic: compile to a pid-suffixed temp, rename into place — a
concurrent importer either sees the old state and falls back, or the
complete library, never a truncated file). Honors $CXX like
native/Makefile."""

from __future__ import annotations

import ctypes
import os
import subprocess

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
NATIVE_DIR = os.path.join(REPO_ROOT, "native")


def _stale(so_path: str, cpp: str) -> bool:
    """A .so older than its source must be rebuilt: loading a library
    compiled against a previous signature is an ABI mismatch ctypes
    cannot detect (silent memory corruption, not an error). A .so with
    NO adjacent source (source-pruned deployment artifact) is trusted
    as-is — staleness is indeterminate and refusing to load it would be
    a silent perf cliff."""
    if not os.path.exists(cpp):
        return False
    try:
        return os.path.getmtime(so_path) < os.path.getmtime(cpp)
    except OSError:
        return True


def build_and_load(so_name: str, cpp_name: str) -> "ctypes.CDLL | None":
    so_path = os.path.join(NATIVE_DIR, so_name)
    cpp = os.path.join(NATIVE_DIR, cpp_name)
    if not os.path.exists(so_path) or _stale(so_path, cpp):
        if not os.path.exists(cpp):
            return None
        tmp = so_path + f".tmp.{os.getpid()}"
        try:
            subprocess.run(
                [os.environ.get("CXX", "g++"), "-O3", "-fPIC",
                 "-std=c++17", "-shared", "-o", tmp, cpp],
                check=True, capture_output=True, timeout=60)
            os.replace(tmp, so_path)
        except Exception:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return None
    try:
        return ctypes.CDLL(so_path)
    except OSError:
        return None
