"""ctypes binding for the native warm-tick hot path
(native/deltawalk.cpp): SIMD diff-and-patch over resident encoding
arrays, word-aligned bool-bitfield patching for the packed arena, and
zero-copy SolvePatch frame assembly.

Three-tier fallback ladder, every rung byte-exact to the next:

- AVX2 lanes when the HOST cpu reports them (runtime dispatch inside
  the library — the binary stays runnable on any x86-64),
- scalar C when it doesn't,
- the pure-numpy twins in models/delta.py / ops/hostpack.py when the
  library is absent or the runtime flag disables it.

Runtime flag: ``KARPENTER_NATIVE_DELTAWALK=0`` forces the numpy twins
(the byte-exact oracles the fuzz suite diffs against); tests can also
pin either way with ``force()``. Callers consult ``enabled()`` per
operation and report the outcome through ``record_engaged`` /
``record_fallback`` so the
``karpenter_solver_native_{engaged,fallback}_total`` metric families
(docs/metrics.md) always name which tier actually served — a "native"
deployment silently running pure Python is a perf cliff, not an error,
and the metrics are how it surfaces.

Build with ``make -C native`` (the wrapper also attempts one silent
build on first import when g++ is available)."""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Dict, Optional, Tuple

import numpy as np

from ._build import build_and_load

_I64P = ctypes.POINTER(ctypes.c_int64)
_U8P = ctypes.POINTER(ctypes.c_uint8)

#: exported contract version this wrapper was written against; a .so
#: reporting anything else is refused (stale-library ABI mismatch is
#: silent memory corruption, not an error ctypes could raise)
_ABI = 1


def _load() -> "ctypes.CDLL | None":
    lib = build_and_load("libkarpdeltawalk.so", "deltawalk.cpp")
    if lib is None:
        return None
    try:
        lib.karp_dw_abi.restype = ctypes.c_int64
        if int(lib.karp_dw_abi()) != _ABI:
            return None
    except Exception:
        return None
    lib.karp_dw_level.restype = ctypes.c_int64
    lib.karp_dw_diff_patch_i64.restype = ctypes.c_int64
    lib.karp_dw_diff_patch_i64.argtypes = [_I64P, _I64P, ctypes.c_int64]
    lib.karp_dw_diff_patch_u8.restype = ctypes.c_int64
    lib.karp_dw_diff_patch_u8.argtypes = [_U8P, _U8P, ctypes.c_int64]
    lib.karp_dw_pack_bits.restype = None
    lib.karp_dw_pack_bits.argtypes = [_U8P, ctypes.c_int64, _I64P]
    lib.karp_dw_patch_bits.restype = ctypes.c_int64
    lib.karp_dw_patch_bits.argtypes = [_I64P, _U8P, _U8P,
                                       ctypes.c_int64, ctypes.c_int64,
                                       ctypes.c_int64, _I64P]
    lib.karp_dw_frame_gather.restype = ctypes.c_int64
    lib.karp_dw_frame_gather.argtypes = [_I64P, ctypes.c_int64,
                                         _I64P, ctypes.c_int64,
                                         _I64P, ctypes.c_int64,
                                         _I64P, ctypes.c_int64]
    return lib


_LIB = _load()

#: test hook: force(True/False) pins enabled() regardless of env/lib;
#: force(None) restores the runtime decision
_FORCED: Optional[bool] = None


def available() -> bool:
    return _LIB is not None


def enabled() -> bool:
    """Whether the native path serves this call. Consulted PER
    OPERATION (env lookup is ~100ns) so tests and the bench can flip
    the oracle twin on without re-importing anything."""
    if _FORCED is not None:
        return _FORCED and _LIB is not None
    if _LIB is None:
        return False
    return os.environ.get("KARPENTER_NATIVE_DELTAWALK", "1").lower() \
        not in ("0", "false", "off")


def force(value: Optional[bool]) -> None:
    global _FORCED
    _FORCED = value


def level() -> str:
    """Which rung of the ladder serves: "avx2", "scalar", or ""
    (library absent). Bench reports and docs cite this so a "native"
    number always names its tier."""
    if _LIB is None:
        return ""
    return "avx2" if int(_LIB.karp_dw_level()) == 2 else "scalar"


def fallback_reason() -> str:
    """Why enabled() is False right now (metrics label vocabulary):
    "disabled" (flag/force), "unavailable" (library absent)."""
    if _LIB is None:
        return "unavailable"
    return "disabled"


# ---------------------------------------------------------------------------
# engagement accounting (karpenter_solver_native_* metric families)
# ---------------------------------------------------------------------------

#: module-level tallies — always on, so the bench and the
#: toolchain-absent tests can read engagement without a registry
counters: Dict[Tuple[str, str], int] = {}
_counters_mu = threading.Lock()
#: one optional metrics registry (utils.metrics.Metrics); module-global
#: with last-attach-wins, the same discipline as the compile-cache
#: monitor's process-wide listener (tenancy/compilecache.py)
_metrics = None


def attach_metrics(metrics) -> None:
    """Route engagement counts into a Metrics registry. One registry at
    a time, last attach wins (pass None to detach): the sidecar server
    and the local solver attach theirs at construction."""
    global _metrics
    _metrics = metrics


def record_engaged(component: str) -> None:
    with _counters_mu:
        counters[("engaged", component)] = \
            counters.get(("engaged", component), 0) + 1
        m = _metrics
    if m is not None:
        m.inc("karpenter_solver_native_engaged_total",
              labels={"component": component})


def record_fallback(reason: str) -> None:
    with _counters_mu:
        counters[("fallback", reason)] = \
            counters.get(("fallback", reason), 0) + 1
        m = _metrics
    if m is not None:
        m.inc("karpenter_solver_native_fallback_total",
              labels={"reason": reason})


def counter_snapshot() -> Dict[Tuple[str, str], int]:
    with _counters_mu:
        return dict(counters)


# ---------------------------------------------------------------------------
# operations
# ---------------------------------------------------------------------------

def _writable_i64(a: np.ndarray) -> bool:
    return (a.dtype == np.int64 and a.flags["C_CONTIGUOUS"]
            and a.flags["WRITEABLE"])


def diff_patch_i64(dst: np.ndarray, src: np.ndarray) -> Optional[bool]:
    """Compare ``src`` against ``dst`` and copy it over ``dst`` where
    they differ, ONE pass. Returns True iff anything differed (the
    caller's dirty flag), or None when the pair doesn't qualify for the
    native path (caller must run the numpy twin). ``dst`` is mutated in
    place — it must be a C-contiguous writable int64 array of ``src``'s
    shape."""
    if _LIB is None or not _writable_i64(dst) \
            or dst.shape != src.shape:
        return None
    src = np.ascontiguousarray(src, dtype=np.int64)
    return bool(_LIB.karp_dw_diff_patch_i64(
        dst.ctypes.data_as(_I64P), src.ctypes.data_as(_I64P),
        ctypes.c_int64(dst.size)))


def diff_patch_u8(dst: np.ndarray, src: np.ndarray) -> Optional[bool]:
    """``diff_patch_i64`` for bool/uint8 planes."""
    if _LIB is None or dst.dtype.itemsize != 1 \
            or not dst.flags["C_CONTIGUOUS"] \
            or not dst.flags["WRITEABLE"] or dst.shape != src.shape:
        return None
    src = np.ascontiguousarray(src)
    if src.dtype.itemsize != 1:
        src = np.ascontiguousarray(src, dtype=bool)
    return bool(_LIB.karp_dw_diff_patch_u8(
        dst.ctypes.data_as(_U8P), src.ctypes.data_as(_U8P),
        ctypes.c_int64(dst.size)))


def pack_bits(bits: np.ndarray) -> np.ndarray:
    """0/1 plane -> little-endian u64 words viewed int64 — the AVX2
    movemask formulation of native/codec.cpp's scalar karp_pack_bits
    (byte-identical output). Raises if the library is absent; callers
    gate on enabled()."""
    bits = np.ascontiguousarray(np.asarray(bits).reshape(-1), dtype=bool)
    nw = (bits.size + 63) // 64
    words = np.zeros(nw, dtype=np.int64)
    _LIB.karp_dw_pack_bits(
        bits.view(np.uint8).ctypes.data_as(_U8P),
        ctypes.c_int64(bits.size), words.ctypes.data_as(_I64P))
    return words


def patch_bits(words: np.ndarray, plane: np.ndarray,
               fresh: Optional[np.ndarray],
               bit_off: int) -> Optional[Tuple[int, int]]:
    """The patch_inputs1 bool-section rewrite: copy ``fresh`` into
    ``plane[bit_off:bit_off+len(fresh)]`` and re-bitpack the covering
    words of ``words`` (the bool region of the packed arena) straight
    from the resident plane. Returns the rewritten ``(first_word,
    word_count)`` span, or None when the buffers don't qualify (caller
    runs the numpy twin). ``fresh=None`` means the plane is already
    current — repack only."""
    if _LIB is None or not _writable_i64(words) \
            or plane.dtype != np.bool_ \
            or not plane.flags["C_CONTIGUOUS"] \
            or not plane.flags["WRITEABLE"]:
        return None
    nbits = plane.size - bit_off if fresh is None else int(fresh.size)
    if fresh is not None:
        fresh = np.ascontiguousarray(fresh.reshape(-1), dtype=bool)
    w0 = np.zeros(1, dtype=np.int64)
    n = int(_LIB.karp_dw_patch_bits(
        words.ctypes.data_as(_I64P),
        plane.view(np.uint8).ctypes.data_as(_U8P),
        fresh.view(np.uint8).ctypes.data_as(_U8P)
        if fresh is not None else None,
        ctypes.c_int64(int(bit_off)), ctypes.c_int64(nbits),
        ctypes.c_int64(plane.size), w0.ctypes.data_as(_I64P)))
    if n < 0:
        return None
    return int(w0[0]), n


def frame_gather(dst: np.ndarray, hdr: np.ndarray, sections,
                 base: np.ndarray) -> bool:
    """Assemble a SolvePatch frame into the preallocated ``dst``:
    [hdr | (start,stop) x S | base[s0:s1] words...] in one native pass,
    payload gathered straight from the resident pack buffer. Returns
    False when the buffers don't qualify or a section is out of bounds
    (caller runs the numpy twin / raises)."""
    if _LIB is None or not _writable_i64(dst):
        return False
    base = np.ascontiguousarray(base, dtype=np.int64)
    hdr = np.ascontiguousarray(hdr, dtype=np.int64)
    sec = np.ascontiguousarray(
        np.asarray([w for se in sections for w in se],
                   dtype=np.int64))
    n = int(_LIB.karp_dw_frame_gather(
        dst.ctypes.data_as(_I64P), ctypes.c_int64(dst.size),
        hdr.ctypes.data_as(_I64P), ctypes.c_int64(hdr.size),
        sec.ctypes.data_as(_I64P), ctypes.c_int64(len(sections)),
        base.ctypes.data_as(_I64P), ctypes.c_int64(base.size)))
    return n == dst.size
