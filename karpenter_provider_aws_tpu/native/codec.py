"""ctypes binding for the C++ arena codec (native/codec.cpp), with a pure
Python twin used when the shared library hasn't been built.

The arena is the sidecar wire format: named, 64-byte-aligned array
sections in one contiguous buffer, FNV-1a checksummed. ``arena_unpack``
returns ZERO-COPY numpy views into the source buffer.

Build the native library with ``make -C native`` (the wrapper also
attempts one silent build on first import when g++ is available).
"""

from __future__ import annotations

import ctypes
import os
import struct
from typing import Dict, List, Tuple

import numpy as np

_MAGIC = 0x314E524150524B41
_ALIGN = 64
_DTYPES = {np.dtype(np.int64): 0, np.dtype(np.uint8): 1,
           np.dtype(bool): 1, np.dtype(np.int32): 2,
           np.dtype(np.float64): 3}
_DTYPE_NP = {0: np.dtype(np.int64), 1: np.dtype(np.uint8),
             2: np.dtype(np.int32), 3: np.dtype(np.float64)}

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_SO_PATH = os.path.join(_REPO_ROOT, "native", "libkarpcodec.so")


def _load() -> "ctypes.CDLL | None":
    from ._build import build_and_load
    lib = build_and_load("libkarpcodec.so", "codec.cpp")
    if lib is None:
        return None
    lib.karp_arena_size.restype = ctypes.c_uint64
    lib.karp_arena_pack.restype = ctypes.c_uint64
    lib.karp_arena_parse.restype = ctypes.c_int64
    lib.karp_checksum.restype = ctypes.c_uint64
    return lib


_LIB = _load()


def native_available() -> bool:
    return _LIB is not None


def _align_up(x: int) -> int:
    return (x + _ALIGN - 1) & ~(_ALIGN - 1)


# ---------------------------------------------------------------------------
# pack
# ---------------------------------------------------------------------------

def arena_pack(arrays: Dict[str, np.ndarray]) -> bytes:
    """Named arrays -> one contiguous arena buffer."""
    items: List[Tuple[str, np.ndarray]] = []
    for name, a in arrays.items():
        a = np.ascontiguousarray(a)
        if a.dtype == bool:
            a = a.view(np.uint8)
        if a.dtype not in _DTYPES:
            raise TypeError(f"unsupported dtype {a.dtype} for {name!r}")
        items.append((name, a))
    if _LIB is not None:
        return _arena_pack_native(items)
    return _arena_pack_py(items)


def _arena_pack_native(items) -> bytes:
    n = len(items)
    names = (ctypes.c_char_p * n)(*[nm.encode() for nm, _ in items])
    name_lens = (ctypes.c_uint32 * n)(*[len(nm.encode())
                                        for nm, _ in items])
    dtypes = (ctypes.c_uint32 * n)(*[_DTYPES[a.dtype] for _, a in items])
    ndims = (ctypes.c_uint32 * n)(*[a.ndim for _, a in items])
    shapes_flat: List[int] = []
    for _, a in items:
        shapes_flat.extend(a.shape)
    shapes = (ctypes.c_uint64 * max(1, len(shapes_flat)))(*shapes_flat)
    payloads = (ctypes.c_void_p * n)(
        *[a.ctypes.data_as(ctypes.c_void_p).value for _, a in items])
    size = _LIB.karp_arena_size(name_lens, dtypes, ndims, shapes, n)
    buf = ctypes.create_string_buffer(size)
    written = _LIB.karp_arena_pack(
        ctypes.cast(names, ctypes.POINTER(ctypes.c_char_p)), name_lens,
        dtypes, ndims, shapes,
        ctypes.cast(payloads, ctypes.POINTER(ctypes.c_void_p)),
        n, ctypes.cast(buf, ctypes.POINTER(ctypes.c_uint8)), size)
    if written == 0:
        raise RuntimeError("arena pack overflow")
    return buf.raw[:written]


def _crc(data: bytes) -> int:
    import zlib
    return zlib.crc32(data) & 0xFFFFFFFF


def _arena_pack_py(items) -> bytes:
    head = struct.pack("<QII", _MAGIC, len(items), 0)
    # first pass: header size
    hsz = len(head) - 0
    for nm, a in items:
        nb = nm.encode()
        hsz += 4 + len(nb) + 4 + 4 + 8 * a.ndim + 8 + 8
    hsz = _align_up(hsz)
    parts = [struct.pack("<QII", _MAGIC, len(items), hsz)]
    off = hsz
    payload_spans = []
    for nm, a in items:
        nb = nm.encode()
        off = _align_up(off)
        nbytes = a.nbytes
        parts.append(struct.pack("<I", len(nb)) + nb
                     + struct.pack("<II", _DTYPES[a.dtype], a.ndim)
                     + b"".join(struct.pack("<Q", s) for s in a.shape)
                     + struct.pack("<QQ", off, nbytes))
        payload_spans.append((off, a))
        off += nbytes
    header = b"".join(parts)
    body = bytearray(_align_up(off))
    body[:len(header)] = header
    for o, a in payload_spans:
        body[o:o + a.nbytes] = a.tobytes()
    csum = _crc(bytes(body))
    return bytes(body) + struct.pack("<Q", csum)


# ---------------------------------------------------------------------------
# unpack
# ---------------------------------------------------------------------------

def arena_unpack(buf: bytes) -> Dict[str, np.ndarray]:
    """Arena buffer -> {name: zero-copy numpy view}."""
    if _LIB is not None:
        return _arena_unpack_native(buf)
    return _arena_unpack_py(buf)


_MAX_ARRAYS = 128
_MAX_SHAPE_SLOTS = 512


def _arena_unpack_native(buf: bytes) -> Dict[str, np.ndarray]:
    src = np.frombuffer(buf, dtype=np.uint8)
    names_buf = ctypes.create_string_buffer(_MAX_ARRAYS * 256)
    name_lens = (ctypes.c_uint32 * _MAX_ARRAYS)()
    dtypes = (ctypes.c_uint32 * _MAX_ARRAYS)()
    ndims = (ctypes.c_uint32 * _MAX_ARRAYS)()
    shapes = (ctypes.c_uint64 * _MAX_SHAPE_SLOTS)()
    offsets = (ctypes.c_uint64 * _MAX_ARRAYS)()
    nbytes = (ctypes.c_uint64 * _MAX_ARRAYS)()
    n = _LIB.karp_arena_parse(
        src.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), len(buf),
        names_buf, name_lens, dtypes, ndims, shapes, offsets, nbytes,
        _MAX_ARRAYS, _MAX_SHAPE_SLOTS)
    if n == -1:
        raise ValueError("bad arena magic")
    if n == -2:
        raise ValueError("arena checksum mismatch")
    if n < 0:
        raise ValueError(f"arena parse error {n}")
    out: Dict[str, np.ndarray] = {}
    si = 0
    for i in range(n):
        name = names_buf.raw[i * 256:i * 256 + name_lens[i]].decode()
        shape = tuple(shapes[si:si + ndims[i]])
        si += ndims[i]
        dt = _DTYPE_NP.get(dtypes[i])
        if dt is None:
            raise ValueError(f"arena: unknown dtype {dtypes[i]}")
        try:
            view = np.frombuffer(buf, dtype=dt,
                                 count=(nbytes[i] // dt.itemsize),
                                 offset=offsets[i]).reshape(shape)
        except ValueError as e:
            raise ValueError(f"arena: malformed array {name!r}: {e}") from None
        out[name] = view
    return out


def _arena_unpack_py(buf: bytes) -> Dict[str, np.ndarray]:
    magic, n, _hsz = struct.unpack_from("<QII", buf, 0)
    if magic != _MAGIC:
        raise ValueError("bad arena magic")
    csum = struct.unpack_from("<Q", buf, len(buf) - 8)[0]
    if _crc(buf[:-8]) != csum:
        raise ValueError("arena checksum mismatch")
    r = 16
    out: Dict[str, np.ndarray] = {}
    for _ in range(n):
        nl = struct.unpack_from("<I", buf, r)[0]
        r += 4
        name = buf[r:r + nl].decode()
        r += nl
        dt, nd = struct.unpack_from("<II", buf, r)
        r += 8
        shape = struct.unpack_from(f"<{nd}Q", buf, r) if nd else ()
        r += 8 * nd
        off, nbytes = struct.unpack_from("<QQ", buf, r)
        r += 16
        dtype = _DTYPE_NP.get(dt)
        if dtype is None:
            raise ValueError(f"arena: unknown dtype {dt}")
        try:
            out[name] = np.frombuffer(buf, dtype=dtype,
                                      count=nbytes // dtype.itemsize,
                                      offset=off).reshape(shape)
        except ValueError as e:
            raise ValueError(f"arena: malformed array {name!r}: {e}") from None
    return out


# ---------------------------------------------------------------------------
# bitpack (the single-buffer device path's host side)
# ---------------------------------------------------------------------------

def pack_bits(bits: np.ndarray) -> np.ndarray:
    """flat bool -> little-endian uint64 words viewed as int64."""
    # force bool: the native path reads raw bytes, so a wider input dtype
    # would be reinterpreted instead of cast
    bits = np.ascontiguousarray(np.asarray(bits).reshape(-1), dtype=bool)
    nbits = bits.size
    nw = (nbits + 63) // 64
    if _LIB is not None:
        words = np.zeros(nw, dtype=np.uint64)
        _LIB.karp_pack_bits(
            bits.view(np.uint8).ctypes.data_as(
                ctypes.POINTER(ctypes.c_uint8)),
            ctypes.c_uint64(nbits),
            words.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)))
        return words.view(np.int64)
    padded = np.zeros(nw * 64, dtype=bool)
    padded[:nbits] = bits
    return np.packbits(padded, bitorder="little").view(np.int64)


def unpack_bits(words: np.ndarray, nbits: int) -> np.ndarray:
    words = np.ascontiguousarray(words)
    if _LIB is not None:
        bits = np.zeros(nbits, dtype=np.uint8)
        _LIB.karp_unpack_bits(
            words.view(np.uint64).ctypes.data_as(
                ctypes.POINTER(ctypes.c_uint64)),
            ctypes.c_uint64(nbits),
            bits.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
        return bits.astype(bool)
    return np.unpackbits(words.view(np.uint8),
                         bitorder="little")[:nbits].astype(bool)
