"""CloudProvider metrics decorator.

The core wraps the AWS CloudProvider in a latency/error decorator before
anything else sees it (``metrics.Decorate(awsCloudProvider)``,
cmd/controller/main.go:39): every interface method gets a
``karpenter_cloudprovider_duration_seconds{method}`` histogram and a
``karpenter_cloudprovider_errors_total{method,error_type}`` counter.
"""

from __future__ import annotations

import time

from ..utils.metrics import Metrics

#: the CloudProvider interface methods the decorator times
_METHODS = ("create", "get", "list", "get_instance_types", "delete",
            "is_drifted", "repair_policies")


class MetricsDecorator:
    """Transparent proxy: timed interface methods + passthrough for
    everything else (providers, helpers)."""

    def __init__(self, inner, metrics: Metrics, clock=time.time):
        self._inner = inner
        self._metrics = metrics
        self._clock = clock

    def __getattr__(self, name):
        attr = getattr(self._inner, name)
        if name not in _METHODS:
            return attr

        def timed(*args, **kwargs):
            t0 = self._clock()
            try:
                return attr(*args, **kwargs)
            except Exception as e:
                self._metrics.inc(
                    "karpenter_cloudprovider_errors_total",
                    labels={"method": name,
                            "error_type": type(e).__name__})
                raise
            finally:
                self._metrics.observe(
                    "karpenter_cloudprovider_duration_seconds",
                    self._clock() - t0, labels={"method": name})

        return timed
