from .types import (DEFAULT_REPAIR_POLICIES, MICRO, CloudProviderError,
                    CreateError, InstanceType, InstanceTypes,
                    InsufficientCapacityError, NodeClaimNotFoundError,
                    NodeClassNotReadyError, Offering, Offerings, Overhead,
                    RepairPolicy, usd)

__all__ = [
    "InstanceType", "InstanceTypes", "Offering", "Offerings", "Overhead",
    "CloudProviderError", "InsufficientCapacityError", "NodeClassNotReadyError",
    "CreateError", "NodeClaimNotFoundError", "RepairPolicy",
    "DEFAULT_REPAIR_POLICIES", "MICRO", "usd",
]
