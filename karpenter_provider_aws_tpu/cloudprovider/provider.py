"""The CloudProvider implementation — the plugin boundary.

Mirrors pkg/cloudprovider/cloudprovider.go: Create (:82-120) with NodeClass
resolution + readiness gate + instance-type filtering (:322-333) + label
back-fill from single-valued requirements (:381-400); List/Get (:122-161);
GetInstanceTypes (:164-181); Delete (:183-190); IsDrifted (:196-221 +
drift.go:41-136); RepairPolicies (:252-293); restricted-tag validation +
static tags (getTags, :232-250).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from ..apis import labels as L
from ..apis.objects import EC2NodeClass, NodeClaim, NodePool
from ..apis.requirements import Requirements
from ..fake.kube import FakeKube, NotFound
from ..providers.instance import InstanceProvider, LaunchedInstance
from ..providers.instancetype import InstanceTypeProvider
from .types import (DEFAULT_REPAIR_POLICIES, CloudProviderError,
                    InstanceTypes, InsufficientCapacityError,
                    NodeClaimNotFoundError, NodeClassNotReadyError,
                    RepairPolicy)


class CloudProvider:
    def __init__(self, kube: FakeKube,
                 instance_types: InstanceTypeProvider,
                 instances: InstanceProvider,
                 cluster_name: str = "cluster",
                 clock=time.time, recorder=None):
        self.kube = kube
        self.instance_types = instance_types
        self.instances = instances
        self.cluster_name = cluster_name
        self.clock = clock
        self.recorder = recorder

    # -- Create (cloudprovider.go:82-120) ------------------------------
    def create(self, nodeclaim: NodeClaim) -> NodeClaim:
        nodeclass = self._resolve_nodeclass(nodeclaim)
        if not nodeclass.ready:
            raise NodeClassNotReadyError(
                f"EC2NodeClass {nodeclass.name} is not ready")
        types = self._resolve_instance_types(nodeclaim, nodeclass)
        if not types:
            raise InsufficientCapacityError(
                f"all requested instance types were unavailable during launch "
                f"for {nodeclaim.name}")
        tags = self.get_tags(nodeclass, nodeclaim)
        instance = self.instances.create(nodeclass, nodeclaim, types, tags=tags)
        # stamp the NodeClass static-field hash for drift detection
        # (instanceToNodeClaim annotations, cloudprovider.go:381-446)
        nodeclaim.metadata.annotations[L.EC2NODECLASS_HASH_ANNOTATION] = nodeclass.hash()
        nodeclaim.metadata.annotations[L.EC2NODECLASS_HASH_VERSION_ANNOTATION] = \
            L.EC2NODECLASS_HASH_VERSION
        return self._instance_to_nodeclaim(instance, nodeclaim, types)

    def _resolve_nodeclass(self, nodeclaim: NodeClaim) -> EC2NodeClass:
        try:
            nc = self.kube.get("EC2NodeClass", nodeclaim.node_class_ref.name)
        except NotFound:
            # NodeClass gone => treat as ICE so core retries elsewhere
            # (cloudprovider.go:83-89); surfaced as an event the way
            # cloudprovider/events/events.go publishes it
            if self.recorder is not None:
                from ..utils.events import failed_resolving_nodeclass
                failed_resolving_nodeclass(
                    self.recorder, "NodeClaim", nodeclaim.name,
                    nodeclaim.node_class_ref.name)
            raise InsufficientCapacityError(
                f"EC2NodeClass {nodeclaim.node_class_ref.name} not found")
        return nc  # type: ignore[return-value]

    def _resolve_instance_types(self, nodeclaim: NodeClaim,
                                nodeclass: EC2NodeClass) -> InstanceTypes:
        """compatible ∧ offering-available ∧ resources fit
        (cloudprovider.go:322-333)."""
        reqs = nodeclaim.requirements
        requested = nodeclaim.resources_requested
        out = InstanceTypes()
        for it in self.instance_types.list(nodeclass):
            if it.requirements.conflicts(reqs):
                continue
            if not it.offerings.available().compatible(reqs):
                continue
            if not requested.fits(it.allocatable()):
                continue
            out.append(it)
        return out

    # -- Get / List (cloudprovider.go:122-161) -------------------------
    def get(self, provider_id: str) -> NodeClaim:
        instance = self.instances.get(parse_instance_id(provider_id))
        return self._instance_to_nodeclaim(instance)

    def list(self) -> List[NodeClaim]:
        return [self._instance_to_nodeclaim(i) for i in self.instances.list()]

    # -- GetInstanceTypes (cloudprovider.go:164-181) -------------------
    def get_instance_types(self, nodepool: NodePool) -> InstanceTypes:
        try:
            nodeclass = self.kube.get("EC2NodeClass",
                                      nodepool.template.node_class_ref.name)
        except NotFound:
            # events.go NodePool variant: the pool is skipped, surface why
            if self.recorder is not None:
                from ..utils.events import failed_resolving_nodeclass
                failed_resolving_nodeclass(
                    self.recorder, "NodePool", nodepool.metadata.name,
                    nodepool.template.node_class_ref.name)
            raise
        return self.instance_types.list(nodeclass)  # type: ignore[arg-type]

    # -- Delete (cloudprovider.go:183-190) -----------------------------
    def delete(self, nodeclaim: NodeClaim) -> None:
        self.instances.delete(parse_instance_id(nodeclaim.provider_id))

    # -- IsDrifted (cloudprovider.go:196-221, drift.go:41-136) ---------
    DRIFT_NONE = ""
    DRIFT_AMI = "AMIDrift"
    DRIFT_SUBNET = "SubnetDrift"
    DRIFT_SECURITY_GROUP = "SecurityGroupDrift"
    DRIFT_NODECLASS = "NodeClassDrift"

    def is_drifted(self, nodeclaim: NodeClaim) -> str:
        if not nodeclaim.provider_id:
            return self.DRIFT_NONE
        try:
            nodeclass = self._resolve_nodeclass(nodeclaim)
        except CloudProviderError:
            return self.DRIFT_NONE
        instance = self.instances.get(parse_instance_id(nodeclaim.provider_id))
        # AMI drift: the running image is no longer among resolved AMIs
        amis = {a["id"] for a in nodeclass.status_amis}
        if amis and instance.image_id not in amis:
            return self.DRIFT_AMI
        # Subnet drift: instance subnet no longer selected
        subnet_ids = {s["id"] for s in nodeclass.status_subnets}
        if subnet_ids and instance.subnet_id \
                and instance.subnet_id not in subnet_ids:
            return self.DRIFT_SUBNET
        # Security-group drift: the instance's attached SGs no longer equal
        # the NodeClass's resolved set (drift.go areSecurityGroupsDrifted)
        sg_ids = {g["id"] for g in nodeclass.status_security_groups}
        attached = set(instance.security_group_ids or [])
        if sg_ids and attached and attached != sg_ids:
            return self.DRIFT_SECURITY_GROUP
        # Static-field drift: hash annotation mismatch (versioned)
        ann = nodeclaim.metadata.annotations
        if ann.get(L.EC2NODECLASS_HASH_VERSION_ANNOTATION) == L.EC2NODECLASS_HASH_VERSION \
                and ann.get(L.EC2NODECLASS_HASH_ANNOTATION, nodeclass.hash()) != nodeclass.hash():
            return self.DRIFT_NODECLASS
        return self.DRIFT_NONE

    # -- RepairPolicies (cloudprovider.go:252-293) ---------------------
    def repair_policies(self) -> List[RepairPolicy]:
        return list(DEFAULT_REPAIR_POLICIES)

    # -- tags (cloudprovider.go:232-250) -------------------------------
    def get_tags(self, nodeclass: EC2NodeClass,
                 nodeclaim: NodeClaim) -> Dict[str, str]:
        for key in nodeclass.tags:
            if L.is_restricted_tag(key):
                raise CloudProviderError(f"tag {key!r} is restricted")
        tags = dict(nodeclass.tags)
        tags.update({
            "eks:eks-cluster-name": self.cluster_name,
            f"kubernetes.io/cluster/{self.cluster_name}": "owned",
            L.NODEPOOL: nodeclaim.metadata.labels.get(L.NODEPOOL, ""),
            L.EC2NODECLASS_LABEL: nodeclass.name,
        })
        return tags

    # -- reconstruction (cloudprovider.go:352-446) ---------------------
    def _instance_to_nodeclaim(self, instance: LaunchedInstance,
                               nodeclaim: Optional[NodeClaim] = None,
                               types: Optional[InstanceTypes] = None,
                               ) -> NodeClaim:
        labels = {
            L.INSTANCE_TYPE: instance.instance_type,
            L.ZONE: instance.zone,
            L.ZONE_ID: instance.zone_id,
            L.CAPACITY_TYPE: instance.capacity_type,
        }
        chosen = None
        if types is not None:
            chosen = next((t for t in types
                           if t.name == instance.instance_type), None)
        if chosen is not None:
            # back-fill labels from single-valued requirements (:381-400)
            for k, v in chosen.requirements.single_values().items():
                labels.setdefault(k, v)
        if nodeclaim is None:
            # reconstruct from tags (List/Get path, instance.go:147-163)
            name = instance.tags.get("karpenter.sh/nodeclaim", instance.id)
            from ..apis.objects import NodeClassRef
            nodeclaim = NodeClaim(
                name=name,
                requirements=Requirements([]),
                node_class_ref=NodeClassRef(
                    instance.tags.get(L.EC2NODECLASS_LABEL, "")),
                labels={L.NODEPOOL: instance.tags.get(L.NODEPOOL, "")})
        nodeclaim.metadata.labels.update(labels)
        nodeclaim.provider_id = instance.provider_id
        nodeclaim.image_id = instance.image_id
        if chosen is not None:
            nodeclaim.capacity = chosen.capacity
            nodeclaim.allocatable = chosen.allocatable()
        return nodeclaim


def parse_instance_id(provider_id: str) -> str:
    """``aws:///us-west-2a/i-0123...`` -> ``i-0123...`` (utils.go:36-75)."""
    if not provider_id.startswith("aws:///"):
        raise ValueError(f"invalid provider id {provider_id!r}")
    parts = provider_id.split("/")
    if len(parts) < 5 or not parts[-1]:
        raise ValueError(f"invalid provider id {provider_id!r}")
    return parts[-1]
