"""Core cloud-provider data model: InstanceType, Offering, and the
CloudProvider plugin interface.

Mirrors the core library contract exactly as the reference consumes it
(SURVEY §1/L5): ``cloudprovider.InstanceType{Name, Requirements, Offerings,
Capacity, Overhead}`` constructed at pkg/providers/instancetype/types.go:159-180,
``Allocatable()`` used at pkg/cloudprovider/cloudprovider.go:331,
``Offerings.Compatible(reqs).Available()`` at cloudprovider.go:330,
``InstanceTypes.Truncate(reqs, 60)`` at pkg/providers/instance/instance.go:106.

All prices are fixed-point **micro-USD per hour** (int). No float touches
the scheduling path (decision determinism, see apis/resources.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..apis import labels as L
from ..apis.requirements import IN, Requirement, Requirements
from ..apis.resources import Resources

MICRO = 1_000_000  # 1 USD in price units


def usd(amount: float) -> int:
    """Convert a float dollar amount to fixed-point micro-USD (catalog
    construction only — never called in the scheduling path)."""
    return int(round(amount * MICRO))


@dataclass(frozen=True)
class Offering:
    """One purchasable (capacity-type, zone) combination of an instance type.

    ``requirements`` carries capacity-type + zone + zone-id, exactly like
    types.go:120-157 builds them.
    """
    capacity_type: str          # spot | on-demand | reserved
    zone: str
    zone_id: str
    price: int                  # micro-USD/hour
    available: bool = True

    @property
    def requirements(self) -> Requirements:
        return Requirements([
            Requirement.new(L.CAPACITY_TYPE, IN, [self.capacity_type]),
            Requirement.new(L.ZONE, IN, [self.zone]),
            Requirement.new(L.ZONE_ID, IN, [self.zone_id]),
        ])

    def compatible_with(self, reqs: Requirements) -> bool:
        ct = reqs.get(L.CAPACITY_TYPE)
        if ct is not None and not ct.has(self.capacity_type):
            return False
        z = reqs.get(L.ZONE)
        if z is not None and not z.has(self.zone):
            return False
        zid = reqs.get(L.ZONE_ID)
        if zid is not None and not zid.has(self.zone_id):
            return False
        return True


class Offerings(List[Offering]):
    def available(self) -> "Offerings":
        return Offerings(o for o in self if o.available)

    def compatible(self, reqs: Requirements) -> "Offerings":
        return Offerings(o for o in self if o.compatible_with(reqs))

    def cheapest(self) -> Optional[Offering]:
        if not self:
            return None
        return min(self, key=lambda o: (o.price, o.capacity_type, o.zone))

    def worst_price(self) -> Optional[int]:
        if not self:
            return None
        return max(o.price for o in self)


@dataclass
class Overhead:
    """Allocatable = Capacity - kube_reserved - system_reserved -
    eviction_threshold (types.go:480-565)."""
    kube_reserved: Resources = field(default_factory=Resources)
    system_reserved: Resources = field(default_factory=Resources)
    eviction_threshold: Resources = field(default_factory=Resources)

    def total(self) -> Resources:
        return self.kube_reserved + self.system_reserved + self.eviction_threshold


@dataclass
class InstanceType:
    name: str
    requirements: Requirements
    capacity: Resources
    overhead: Overhead = field(default_factory=Overhead)
    offerings: Offerings = field(default_factory=Offerings)

    def allocatable(self) -> Resources:
        return (self.capacity - self.overhead.total()).clamp_nonnegative()

    def cheapest_price(self, reqs: Optional[Requirements] = None) -> Optional[int]:
        offs = self.offerings.available()
        if reqs is not None:
            offs = offs.compatible(reqs)
        o = offs.cheapest()
        return None if o is None else o.price

    def __repr__(self) -> str:
        return f"InstanceType({self.name})"


class InstanceTypes(List[InstanceType]):
    def compatible(self, reqs: Requirements) -> "InstanceTypes":
        """Types whose requirements are compatible with ``reqs`` AND that
        still have a compatible offering (cloudprovider.go:322-333)."""
        out = InstanceTypes()
        for it in self:
            if it.requirements.conflicts(reqs):
                continue
            if not it.offerings.available().compatible(reqs):
                continue
            out.append(it)
        return out

    def order_by_price(self, reqs: Optional[Requirements] = None) -> "InstanceTypes":
        def key(it: InstanceType) -> Tuple[int, str]:
            p = it.cheapest_price(reqs)
            return (p if p is not None else 1 << 62, it.name)
        return InstanceTypes(sorted(self, key=key))

    def truncate(self, reqs: Requirements, max_items: int = 60) -> "InstanceTypes":
        """Cheapest-first truncation honoring minValues flexibility floors
        (instance.go:55,106; core InstanceTypes.Truncate).

        Two-phase: (1) a cheapest-first *coverage pass* picks types that add
        a still-needed distinct value for some floored key until every floor
        is met; (2) remaining slots fill cheapest-first. The result stays
        price-ordered and within ``max_items``. Raises
        InsufficientCapacityError (a soft launch failure the caller maps to
        ICE retry semantics, like the reference's "validating minValues"
        create error) only when the FULL candidate set cannot satisfy the
        floors within the cap."""
        ordered = self.order_by_price(reqs)
        floors = {r.key: r.min_values for r in reqs
                  if r.min_values is not None}
        if not floors:
            return InstanceTypes(ordered[:max_items])
        seen: Dict[str, set] = {k: set() for k in floors}
        chosen_ids = set()
        for it in ordered:
            if all(len(seen[k]) >= f for k, f in floors.items()):
                break
            adds = False
            for k, f in floors.items():
                if len(seen[k]) >= f:
                    continue
                req = it.requirements.get(k)
                if req is not None and not req.complement \
                        and req.values - seen[k]:
                    adds = True
            if adds:
                chosen_ids.add(id(it))
                for k in floors:
                    req = it.requirements.get(k)
                    if req is not None and not req.complement:
                        seen[k].update(req.values)
        violated = sorted(k for k, f in floors.items() if len(seen[k]) < f)
        if violated or len(chosen_ids) > max_items:
            raise InsufficientCapacityError(
                f"validating minValues: floors unsatisfiable for keys "
                f"{violated or sorted(floors)} within {max_items}-type "
                f"truncation")
        out = InstanceTypes()
        budget = max_items - len(chosen_ids)
        for it in ordered:
            if id(it) in chosen_ids:
                out.append(it)
            elif budget > 0:
                out.append(it)
                budget -= 1
        return out

    @staticmethod
    def _min_values_violations(types: "InstanceTypes", reqs: Requirements) -> List[str]:
        cardinality: Dict[str, set] = {}
        for it in types:
            for r in it.requirements:
                if not r.complement:
                    cardinality.setdefault(r.key, set()).update(r.values)
        return reqs.min_values_violations(
            {k: len(v) for k, v in cardinality.items()})


# ---------------------------------------------------------------------------
# Error taxonomy (cloudprovider.go:89-101, instance.go:129; drives retry)
# ---------------------------------------------------------------------------

class CloudProviderError(Exception):
    pass


class InsufficientCapacityError(CloudProviderError):
    """ICE — no offering could be fulfilled (cloudprovider.go:89,101)."""


class NodeClassNotReadyError(CloudProviderError):
    """NodeClass status not Ready (cloudprovider.go:94)."""


class CreateError(CloudProviderError):
    """Launch failed for a non-capacity reason (cloudprovider.go:98)."""


class NodeClaimNotFoundError(CloudProviderError):
    """Instance backing the NodeClaim is gone (instance.go:129)."""


@dataclass(frozen=True)
class RepairPolicy:
    """Node-condition -> toleration-duration auto-repair table entry
    (cloudprovider.go:252-293)."""
    condition_type: str
    condition_status: str
    toleration_duration: float  # seconds


DEFAULT_REPAIR_POLICIES = (
    RepairPolicy("Ready", "False", 30 * 60),
    RepairPolicy("Ready", "Unknown", 30 * 60),
    RepairPolicy("AcceleratedHardwareReady", "False", 10 * 60),
    RepairPolicy("StorageReady", "False", 30 * 60),
    RepairPolicy("NetworkingReady", "False", 30 * 60),
    RepairPolicy("KernelReady", "False", 30 * 60),
    RepairPolicy("ContainerRuntimeReady", "False", 30 * 60),
)
