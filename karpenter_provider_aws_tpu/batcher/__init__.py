from .core import (Batcher, CreateFleetBatcher, CreateFleetRequest,
                   DescribeInstancesBatcher, TerminateInstancesBatcher,
                   to_hashable)

__all__ = ["Batcher", "CreateFleetBatcher", "CreateFleetRequest",
           "DescribeInstancesBatcher", "TerminateInstancesBatcher",
           "to_hashable"]
