"""Generic request micro-batching engine.

Mirrors pkg/batcher/batcher.go:32-100,131-200: the first request opens a
window; the batch flushes when the window quiesces (``idle_timeout`` with no
new requests), hits ``max_timeout``, or reaches ``max_items``. Requests
hash into buckets (same-shaped requests merge); results fan back to each
caller. Thread-based (the control plane runs reconcilers in threads).

Tuning constants from the reference:
- CreateFleet:        35ms idle / 1s max / 1000 items (createfleet.go:38-40)
- DescribeInstances: 100ms idle / 1s max /  500 items (describeinstances.go:40-42)
- TerminateInstances:100ms idle / 1s max /  500 items (terminateinstances.go:39-41)
"""

from __future__ import annotations

import threading
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import (
    Callable,
    Dict,
    Generic,
    Hashable,
    List,
    Optional,
    Sequence,
    Tuple,
    TypeVar)

from ..sim.clock import as_clock

T = TypeVar("T")  # request
U = TypeVar("U")  # response


@dataclass
class _Bucket(Generic[T, U]):
    requests: List[T] = field(default_factory=list)
    futures: List["Future[U]"] = field(default_factory=list)
    opened: float = 0.0
    last_add: float = 0.0


class Batcher(Generic[T, U]):
    """``exec_fn(requests) -> responses`` is called once per flushed batch;
    it must return one response per request (same order)."""

    #: metric label; concrete batchers override (batcher/metrics.go emits
    #: karpenter_cloudprovider_batcher_* series per batcher)
    name = "generic"

    def __init__(self,
                 exec_fn: Callable[[Sequence[T]], Sequence[U]],
                 idle_timeout: float = 0.100,
                 max_timeout: float = 1.0,
                 max_items: int = 500,
                 hash_fn: Optional[Callable[[T], Hashable]] = None,
                 clock=None,
                 metrics=None):
        self.exec_fn = exec_fn
        self.idle_timeout = idle_timeout
        self.max_timeout = max_timeout
        self.max_items = max_items
        self.hash_fn = hash_fn or (lambda _: 0)
        #: the clock seam (sim/clock.py): reads AND the loop's window
        #: wait go through it, so a VirtualClock can deschedule the
        #: flush timer onto its event queue; a bare callable keeps the
        #: legacy reads-only seam (waits stay real)
        self._clockobj = as_clock(clock)
        self.clock = self._clockobj.monotonic
        self.metrics = metrics
        self._mu = threading.Lock()
        self._buckets: Dict[Hashable, _Bucket[T, U]] = {}
        self._wake = threading.Condition(self._mu)
        self._stopped = False
        #: in-flight batch-exec threads; stop() joins them so a shutdown
        #: never abandons callers blocked in add_sync
        self._exec_threads: List[threading.Thread] = []
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def add(self, request: T) -> "Future[U]":
        """Enqueue a request; the future resolves when its batch executes."""
        fut: "Future[U]" = Future()
        with self._mu:
            if self._stopped:
                raise RuntimeError("batcher stopped")
            key = self.hash_fn(request)
            bucket = self._buckets.get(key)
            now = self.clock()
            if bucket is None:
                bucket = _Bucket(opened=now)
                self._buckets[key] = bucket
            bucket.requests.append(request)
            bucket.futures.append(fut)
            bucket.last_add = now
            if len(bucket.requests) >= self.max_items:
                self._flush_locked(key, bucket)
            self._wake.notify()
        return fut

    def add_sync(self, request: T, timeout: float = 30.0) -> U:
        return self.add(request).result(timeout=timeout)

    def _loop(self) -> None:
        while True:
            with self._mu:
                if self._stopped and not self._buckets:
                    return
                now = self.clock()
                due: List[Tuple[Hashable, _Bucket]] = []
                deadline = None
                for key, b in list(self._buckets.items()):
                    idle_at = b.last_add + self.idle_timeout
                    max_at = b.opened + self.max_timeout
                    fire_at = min(idle_at, max_at)
                    if now >= fire_at or self._stopped:
                        due.append((key, b))
                    elif deadline is None or fire_at < deadline:
                        deadline = fire_at
                for key, b in due:
                    self._flush_locked(key, b)
                if not due:
                    self._clockobj.cond_wait(
                        self._wake, timeout=None if deadline is None
                        else max(0.001, deadline - now))

    def _flush_locked(self, key: Hashable, bucket: _Bucket) -> None:
        self._buckets.pop(key, None)
        requests, futures = bucket.requests, bucket.futures
        if self.metrics is not None:
            self.metrics.observe("karpenter_cloudprovider_batcher_batch_size",
                                 float(len(requests)),
                                 labels={"batcher": self.name})
            self.metrics.observe(
                "karpenter_cloudprovider_batcher_batch_time_seconds",
                max(0.0, self.clock() - bucket.opened),
                labels={"batcher": self.name})
        t = threading.Thread(target=self._execute, args=(requests, futures),
                             daemon=True)
        # caller holds self._mu (both flush paths do)
        self._exec_threads = [x for x in self._exec_threads if x.is_alive()]
        self._exec_threads.append(t)
        t.start()

    def _execute(self, requests: List[T], futures: List["Future[U]"]) -> None:
        try:
            responses = self.exec_fn(requests)
            if len(responses) != len(requests):
                raise RuntimeError(
                    f"batch exec returned {len(responses)} responses for "
                    f"{len(requests)} requests")
        except Exception as e:  # fan the failure to EVERY pending caller:
            # a failing batch must never strand an add_sync on the 30s
            # timeout backstop
            for fut in futures:
                if not fut.done():
                    fut.set_exception(e)
            return
        for fut, resp in zip(futures, responses):
            if not fut.done():  # a cancelled caller must not wedge the rest
                fut.set_result(resp)

    def stop(self) -> None:
        """Stop the loop. Queued buckets are DRAINED (the loop's last pass
        flushes everything once ``_stopped`` is set) and in-flight batch
        execs are joined, so every caller blocked in ``add_sync`` gets its
        result or exception; anything still unresolved after the bounded
        joins (a wedged exec_fn) is failed rather than stranded."""
        with self._mu:
            self._stopped = True
            self._wake.notify()
        self._thread.join(timeout=5)
        with self._mu:
            execs = list(self._exec_threads)
        for t in execs:
            t.join(timeout=5)
        with self._mu:
            leftovers = [b for _k, b in self._buckets.items()]
            self._buckets.clear()
        for b in leftovers:
            for fut in b.futures:
                if not fut.done():
                    fut.set_exception(RuntimeError("batcher stopped"))


# ---------------------------------------------------------------------------
# Concrete batchers over the fake cloud (createfleet.go / describeinstances.go
# / terminateinstances.go shapes)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class CreateFleetRequest:
    launch_template_configs: Tuple    # hashable nested tuples
    capacity_type: str
    #: fleet-level instance tags (nodepool/cluster-scoped, so same-shaped
    #: requests still merge; the per-claim tag comes from the Tagger later)
    tags: Tuple = ()
    #: each caller asks for exactly one instance (the provisioner creates one
    #: NodeClaim per request); the batcher rewrites TotalTargetCapacity=N
    target_capacity: int = 1


class CreateFleetBatcher(Batcher):
    """Merges same-shaped CreateFleet calls, rewrites target capacity to the
    batch size, and hands each caller exactly one instance back
    (createfleet.go:36-100)."""

    name = "create_fleet"

    def __init__(self, ec2, clock=None, metrics=None):
        self.ec2 = ec2
        super().__init__(self._run, idle_timeout=0.035, max_timeout=1.0,
                         max_items=1000, hash_fn=lambda r: r, clock=clock,
                         metrics=metrics)

    def _run(self, requests: Sequence[CreateFleetRequest]):
        req = requests[0]
        configs = _untuple(req.launch_template_configs)
        total = sum(r.target_capacity for r in requests)
        instances, errors = self.ec2.create_fleet(
            configs, target_capacity=total, capacity_type=req.capacity_type,
            tags=_untuple(req.tags) if req.tags else {})
        out = []
        for i, _ in enumerate(requests):
            if i < len(instances):
                out.append((instances[i], errors))
            else:
                out.append((None, errors))  # deficit -> caller sees ICE
        return out


class DescribeInstancesBatcher(Batcher):
    """Merges instance-ID lookups with identical filters
    (describeinstances.go:38-63)."""

    name = "describe_instances"

    def __init__(self, ec2, clock=None, metrics=None):
        self.ec2 = ec2
        super().__init__(self._run, idle_timeout=0.100, max_timeout=1.0,
                         max_items=500, hash_fn=lambda r: 0, clock=clock,
                         metrics=metrics)

    def _run(self, instance_ids: Sequence[str]):
        found = {i.id: i for i in self.ec2.describe_instances(ids=list(instance_ids))}
        return [found.get(iid) for iid in instance_ids]


class TerminateInstancesBatcher(Batcher):
    name = "terminate_instances"

    def __init__(self, ec2, clock=None, metrics=None):
        self.ec2 = ec2
        super().__init__(self._run, idle_timeout=0.100, max_timeout=1.0,
                         max_items=500, hash_fn=lambda r: 0, clock=clock,
                         metrics=metrics)

    def _run(self, instance_ids: Sequence[str]):
        done = set(self.ec2.terminate_instances(list(instance_ids)))
        return [iid in done for iid in instance_ids]


def _untuple(obj):
    """Inverse of the hashable-tuple encoding used for request hashing."""
    if isinstance(obj, tuple) and obj and obj[0] == "__dict__":
        return {k: _untuple(v) for k, v in obj[1]}
    if isinstance(obj, tuple):
        return [_untuple(v) for v in obj]
    return obj


def to_hashable(obj):
    if isinstance(obj, dict):
        return ("__dict__", tuple(sorted((k, to_hashable(v)) for k, v in obj.items())))
    if isinstance(obj, (list, tuple)):
        return tuple(to_hashable(v) for v in obj)
    return obj
