"""The continuous invariant auditor.

Every check COLLECTS violations instead of asserting (bare ``assert``
is stripped under ``python -O`` — the hack/soak.py lesson; soak now
imports these same checks so the two harnesses cannot drift). The
driver runs the cluster checks at every audit tick and the full
catalog at terminus; any surviving :class:`Violation` fails the run.

Invariant catalog (docs/simulator.md):

- **Cluster conservation** — no orphaned cloud instances, no pod bound
  to a missing node, no NodeClaim that never launched, SQS drained.
- **Accounting identities** — per tenant, offered == admitted + shed
  (client-observed offers vs the server's admission counters);
  ``recovered_total{reason}`` never exceeds ``degraded_total{reason}``;
  wire fallback reasons stay within the documented taxonomy.
- **Resource-leak bounds** — threads and fds within a slack of the
  run's own baseline; shape-class/patch-arena tables within capacity;
  fake-cloud object counts bounded (no monotonic leak of launch
  templates or zombie instances).
- **Solve SLO** — per-regime p99 of tenant solve latency under the SLO
  table (docs/simulator.md; generous CPU-CI defaults, post-warmup).
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

__all__ = ["Violation", "check_cluster", "check_accounting",
           "check_slo", "check_priority_slo", "LeakMonitor",
           "DEFAULT_SLO_P99_MS", "CRITICAL_BIND_SLO_P99_S"]

#: per-regime solve p99 SLO in ms (CPU CI bar, post-warmup; the SLO
#: table in docs/simulator.md). Regimes without an entry use "default".
DEFAULT_SLO_P99_MS = {
    "default": 2000.0,
    "tenant_mix": 2000.0,
}

#: critical-tier scheduling SLO in VIRTUAL seconds: p99 of creation-to-
#: bind latency for the priority_surge regime's critical waves. The
#: driver harvests bind times after every reconcile step, so the bound
#: covers real control-plane rounds (launch, register, bind), not audit
#: cadence.
CRITICAL_BIND_SLO_P99_S = 1800.0


@dataclass(frozen=True)
class Violation:
    check: str
    detail: str

    def __str__(self) -> str:
        return f"[{self.check}] {self.detail}"


# -- cluster conservation ---------------------------------------------------

def check_cluster(op, context: str = "") -> List[Violation]:
    """The soak invariants, violation-collecting: run against a settled
    Operator. ``context`` tags each violation with where in the run it
    surfaced (iteration / virtual timestamp)."""
    v: List[Violation] = []
    tag = f" ({context})" if context else ""

    claims = {c.provider_id for c in op.kube.list("NodeClaim")
              if c.provider_id}
    orphans = [i.id for i in op.ec2.instances.values()
               if i.state == "running" and i.provider_id not in claims]
    if orphans:
        v.append(Violation("orphaned-instances",
                           f"running instances with no NodeClaim: "
                           f"{sorted(orphans)}{tag}"))

    nodes = {n.name for n in op.kube.list("Node")}
    stranded = [p.name for p in op.kube.list("Pod")
                if p.node_name and p.node_name not in nodes]
    if stranded:
        v.append(Violation("pod-missing-node",
                           f"pods bound to missing nodes: "
                           f"{sorted(stranded)}{tag}"))

    stuck = [c.name for c in op.kube.list("NodeClaim") if not c.launched]
    if stuck:
        v.append(Violation("claim-never-launched",
                           f"NodeClaims never launched: "
                           f"{sorted(stuck)}{tag}"))

    if len(op.sqs):
        v.append(Violation("queue-not-drained",
                           f"{len(op.sqs)} interruption message(s) left "
                           f"on the queue{tag}"))
    return v


# -- accounting identities --------------------------------------------------

def _sum_counter(metrics, name: str, **match) -> float:
    total = 0.0
    for (n, labels), val in metrics.counters.items():
        if n != name:
            continue
        d = dict(labels)
        if all(d.get(k) == v for k, v in match.items()):
            total += val
    return total


def check_accounting(metrics, offered_by_tenant: Optional[Dict[str, int]]
                     = None, context: str = "") -> List[Violation]:
    """Metric accounting identities over one registry.

    ``offered_by_tenant`` is the CLIENT side of the admission ledger
    (solve attempts the driver actually put on the wire, per tenant);
    the server's admitted+shed must partition it exactly. Passing None
    skips the partition check (no wire traffic ran)."""
    v: List[Violation] = []
    tag = f" ({context})" if context else ""

    if offered_by_tenant:
        for tenant, offered in sorted(offered_by_tenant.items()):
            admitted = _sum_counter(
                metrics, "karpenter_solver_tenant_admitted_total",
                tenant=tenant)
            shed = _sum_counter(
                metrics, "karpenter_solver_tenant_shed_total",
                tenant=tenant)
            if int(admitted + shed) != int(offered):
                v.append(Violation(
                    "admission-partition",
                    f"tenant {tenant}: offered={offered} != "
                    f"admitted={int(admitted)} + shed={int(shed)}{tag}"))

    # recovery never outruns degradation, per reason
    reasons = {dict(labels).get("reason")
               for (n, labels) in metrics.counters
               if n in ("karpenter_solver_distmesh_degraded_total",
                        "karpenter_solver_distmesh_recovered_total")}
    for reason in sorted(r for r in reasons if r):
        deg = _sum_counter(metrics,
                           "karpenter_solver_distmesh_degraded_total",
                           reason=reason)
        rec = _sum_counter(metrics,
                           "karpenter_solver_distmesh_recovered_total",
                           reason=reason)
        if rec > deg:
            v.append(Violation(
                "recovery-exceeds-degrades",
                f"recovered_total{{reason={reason}}}={int(rec)} > "
                f"degraded_total={int(deg)}{tag}"))

    # the wire fallback taxonomy is closed (docs/metrics.md)
    known = {"no_resident", "stale_version", "unimplemented",
             "rejected", "transport"}
    for (n, labels) in metrics.counters:
        if n == "karpenter_solver_wire_fallback_total":
            reason = dict(labels).get("reason")
            if reason not in known:
                v.append(Violation(
                    "unknown-fallback-reason",
                    f"wire fallback reason {reason!r} outside the "
                    f"documented taxonomy{tag}"))
    return v


# -- solve SLO --------------------------------------------------------------

def _p99(samples: Sequence[float]) -> float:
    xs = sorted(samples)
    return xs[min(len(xs) - 1, int(0.99 * len(xs)))]


def check_slo(latencies_by_regime: Dict[str, List[float]],
              slo_p99_ms: Optional[Dict[str, float]] = None,
              context: str = "") -> List[Violation]:
    """Per-regime p99 against the SLO table (latencies in seconds)."""
    slo = dict(DEFAULT_SLO_P99_MS)
    slo.update(slo_p99_ms or {})
    v: List[Violation] = []
    tag = f" ({context})" if context else ""
    for regime, lats in sorted(latencies_by_regime.items()):
        if not lats:
            continue
        p99_ms = _p99(lats) * 1e3
        bound = slo.get(regime, slo["default"])
        if p99_ms > bound:
            v.append(Violation(
                "solve-slo",
                f"regime {regime}: solve p99 {p99_ms:.0f}ms > SLO "
                f"{bound:.0f}ms over {len(lats)} solves{tag}"))
    return v


def check_priority_slo(latencies_s: Sequence[float], unbound: int = 0,
                       bound_s: Optional[float] = None,
                       context: str = "") -> List[Violation]:
    """The critical-tier scheduling SLO (virtual-time latencies from
    pod creation to bind). Two ways to violate: the p99 misses the
    bound, or a critical pod never bound at all — starvation is not a
    latency number."""
    bound = CRITICAL_BIND_SLO_P99_S if bound_s is None else bound_s
    v: List[Violation] = []
    tag = f" ({context})" if context else ""
    if unbound:
        v.append(Violation(
            "critical-pod-unbound",
            f"{unbound} critical pod(s) never bound{tag}"))
    if latencies_s:
        p99 = _p99(list(latencies_s))
        if p99 > bound:
            v.append(Violation(
                "critical-bind-slo",
                f"critical-tier bind p99 {p99:.0f}s > SLO {bound:.0f}s "
                f"over {len(latencies_s)} pods{tag}"))
    return v


# -- resource-leak bounds ---------------------------------------------------

def _fd_count() -> Optional[int]:
    try:
        return len(os.listdir("/proc/self/fd"))
    except OSError:
        return None  # non-procfs platform: the fd bound is skipped


class LeakMonitor:
    """Baseline-relative leak bounds over the whole run.

    Construct BEFORE the run starts (captures the thread/fd baseline),
    then ``check`` at audit ticks and terminus. Slacks absorb the
    legitimate steady-state workers (batcher loops, grpc pollers, the
    solve worker) — what must not happen is unbounded growth."""

    def __init__(self, thread_slack: int = 32, fd_slack: int = 64,
                 max_launch_templates: int = 512,
                 max_instances: int = 2048):
        self.base_threads = threading.active_count()
        self.base_fds = _fd_count()
        self.thread_slack = thread_slack
        self.fd_slack = fd_slack
        self.max_launch_templates = max_launch_templates
        self.max_instances = max_instances

    def check(self, op=None, handler=None,
              context: str = "") -> List[Violation]:
        """``handler`` is the sidecar's _Handler (its shape-class and
        patch-arena tables carry hard capacities to hold)."""
        v: List[Violation] = []
        tag = f" ({context})" if context else ""

        n = threading.active_count()
        if n > self.base_threads + self.thread_slack:
            v.append(Violation(
                "thread-leak",
                f"{n} live threads (baseline {self.base_threads} + "
                f"slack {self.thread_slack}){tag}"))

        fds = _fd_count()
        if fds is not None and self.base_fds is not None \
                and fds > self.base_fds + self.fd_slack:
            v.append(Violation(
                "fd-leak",
                f"{fds} open fds (baseline {self.base_fds} + slack "
                f"{self.fd_slack}){tag}"))

        if op is not None:
            lts = len(op.ec2.launch_templates)
            if lts > self.max_launch_templates:
                v.append(Violation(
                    "launch-template-leak",
                    f"{lts} launch templates (bound "
                    f"{self.max_launch_templates}){tag}"))
            insts = len(op.ec2.instances)
            if insts > self.max_instances:
                v.append(Violation(
                    "instance-object-leak",
                    f"{insts} fake-cloud instance objects (bound "
                    f"{self.max_instances}){tag}"))

        if handler is not None:
            st = handler._shapes_seen
            if len(st) > st.capacity:
                v.append(Violation(
                    "shape-table-overflow",
                    f"shape-class table at {len(st)} > capacity "
                    f"{st.capacity}{tag}"))
            pa = handler._patch_arenas
            if len(pa) > pa.capacity:
                v.append(Violation(
                    "arena-table-overflow",
                    f"patch-arena table at {len(pa)} > capacity "
                    f"{pa.capacity}{tag}"))
        return v
