"""The Clock seam: every timer in the stack, behind one injectable
protocol.

Why a seam and not more bare callables: half the stack already took a
``clock: Callable[[], float]`` (token buckets, TTL caches, breakers),
but *waiting* still went straight to the OS — ``time.sleep`` in retry
backoff, ``Condition.wait(timeout)`` in the batcher loop and the
coalescer top-up window. A 24h scenario could therefore only run in
24h, and timer-interaction bugs (backoff racing TTL expiry racing a
meshgroup regroup) were untestable. The seam adds the two missing
verbs — ``sleep`` and ``cond_wait`` — so a :class:`VirtualClock` can
deschedule a waiter onto its event queue and wake it when simulated
time passes the deadline, in zero wall time.

Three implementations:

- :class:`RealClock` — the default everywhere. ``monotonic``/``time``/
  ``sleep`` delegate to :mod:`time`, ``cond_wait`` to
  ``Condition.wait``: byte-for-byte the pre-seam behavior (tier-1 and
  the RealClock parity tests in tests/test_sim.py pin this).
- :class:`CallableClock` — adapts the legacy bare-callable seam. Reads
  come from the callable; waits stay REAL, exactly what every existing
  hand-driven test clock relied on.
- :class:`VirtualClock` — simulated time. Reads return the simulated
  instant; ``sleep(s)`` parks the calling thread on the clock's waiter
  heap until ``advance()`` moves time past its deadline; ``cond_wait``
  registers a one-shot virtual timeout that ``advance()`` converts
  into a ``notify_all`` on the waiter's own condition (callers already
  loop on their predicate, so a virtual timeout behaves exactly like a
  real ``Condition.wait`` timing out). ``warp_wall`` shifts the wall
  clock relative to the monotonic clock, for testing wall-warp
  behavior (NTP step, suspended VM).

Lock discipline in :class:`VirtualClock`: ``cond_wait`` acquires the
clock lock while HOLDING the caller's condition lock, so ``advance``
must never take a condition lock while holding the clock lock — due
conditions are collected under the clock lock, notified after
releasing it.
"""

from __future__ import annotations

import heapq
import threading
import time
from typing import Callable, List, Optional, Tuple

__all__ = ["Clock", "RealClock", "CallableClock", "VirtualClock",
           "REAL_CLOCK", "as_clock", "monotonic_of"]


class Clock:
    """The protocol (and the real implementation — subclasses override).

    - ``monotonic()`` — suspend-free interval time (``time.monotonic``).
    - ``time()`` — wall time (``time.time``).
    - ``sleep(s)`` — block the calling thread for ``s`` seconds.
    - ``cond_wait(cond, timeout)`` — wait on an externally-owned
      ``threading.Condition`` whose lock the caller holds; returns
      False on timeout (the ``Condition.wait`` contract).
    """

    name = "real"

    def monotonic(self) -> float:
        return time.monotonic()

    def time(self) -> float:
        return time.time()

    def sleep(self, seconds: float) -> None:
        if seconds > 0:
            time.sleep(seconds)

    def cond_wait(self, cond: threading.Condition,
                  timeout: Optional[float] = None) -> bool:
        return cond.wait(timeout)


RealClock = Clock  # the explicit name docs and tests use

#: the shared default — components that receive no clock use this
REAL_CLOCK = Clock()


class CallableClock(Clock):
    """Adapter for the legacy bare-callable clock seam: reads come from
    the callable (a hand-driven test clock), waits stay real — the
    exact semantics every pre-seam caller of ``clock=lambda: t`` got."""

    name = "callable"

    def __init__(self, fn: Callable[[], float]):
        self._fn = fn

    def monotonic(self) -> float:
        return float(self._fn())

    def time(self) -> float:
        return float(self._fn())


class VirtualClock(Clock):
    """Simulated time. Single writer (the driver calling ``advance``),
    any number of reader/waiter threads.

    ``advance_to`` moves time forward in deadline order: each sleeper
    whose deadline is reached is woken AT its deadline — its FIRST
    clock read after waking returns exactly ``deadline``, never a later
    instant, even though the advancer may already have hopped on (the
    wake pins the deadline per-thread; the read consumes the pin). So
    timer boundary behavior is exact — a 30s regroup backoff fires at
    +30s, not +30s plus scheduler jitter — regardless of how the OS
    interleaves the advancer with the woken thread. ``advance_to`` also
    rendezvouses with each woken sleeper (the sleeper acknowledges from
    inside ``sleep`` before returning) so by the time ``advance_to``
    returns every due ``sleep`` call has returned. Registered
    ``cond_wait`` timeouts are one-shot: firing notifies the waiter's
    condition; a waiter that already woke for another reason just
    absorbs a spurious notify (every caller loops on its predicate).
    """

    name = "virtual"

    def __init__(self, start: float = 0.0,
                 epoch: float = 1_700_000_000.0):
        self._mu = threading.Condition(threading.Lock())
        self._now = float(start)
        self._wall_offset = float(epoch)
        #: heap of (deadline, seq, Event, thread-id) — parked ``sleep``
        #: callers
        self._sleepers: List[Tuple[float, int, threading.Event, int]] = []
        #: one-shot (deadline, seq, Condition) virtual timeouts
        self._cond_timeouts: List[Tuple[float, int, threading.Condition]] = []
        self._seq = 0
        #: thread-id -> deadline: a woken sleeper's first read returns
        #: exactly its deadline (consumed by the read)
        self._pins = {}
        #: woken sleepers that have not yet acknowledged from ``sleep``
        self._acks_due = 0

    # -- reads ----------------------------------------------------------
    def monotonic(self) -> float:
        with self._mu:
            pinned = self._pins.pop(threading.get_ident(), None)
            return self._now if pinned is None else pinned

    def time(self) -> float:
        with self._mu:
            pinned = self._pins.pop(threading.get_ident(), None)
            return self._wall_offset + \
                (self._now if pinned is None else pinned)

    def warp_wall(self, delta_s: float) -> None:
        """Shift wall time relative to monotonic time (NTP step /
        suspended-VM simulation). Monotonic readers are unaffected."""
        with self._mu:
            self._wall_offset += float(delta_s)

    # -- waits ----------------------------------------------------------
    def sleep(self, seconds: float) -> None:
        if seconds <= 0:
            return
        ev = threading.Event()
        with self._mu:
            self._seq += 1
            heapq.heappush(self._sleepers,
                           (self._now + seconds, self._seq, ev,
                            threading.get_ident()))
            self._mu.notify_all()  # an advancer waiting in wait_for_waiters
        ev.wait()  # descheduled: woken only by advance()
        with self._mu:
            self._acks_due -= 1
            self._mu.notify_all()  # the advancer's rendezvous

    def cond_wait(self, cond: threading.Condition,
                  timeout: Optional[float] = None) -> bool:
        # Caller holds cond's lock (the Condition.wait contract).
        if timeout is None:
            return cond.wait()
        if timeout <= 0:
            return cond.wait(0)
        with self._mu:
            self._seq += 1
            heapq.heappush(self._cond_timeouts,
                           (self._now + timeout, self._seq, cond))
            deadline = self._now + timeout
            self._mu.notify_all()
        cond.wait()  # a real notify or the virtual timeout wakes us
        with self._mu:
            # the Condition.wait contract: False iff the timeout passed
            return self._now < deadline

    # -- the driver side ------------------------------------------------
    def pending_deadline(self) -> Optional[float]:
        """Earliest registered waiter deadline (sleepers and cond
        timeouts), or None — the driver uses it to run waiters dry."""
        with self._mu:
            cands = []
            if self._sleepers:
                cands.append(self._sleepers[0][0])
            if self._cond_timeouts:
                cands.append(self._cond_timeouts[0][0])
            return min(cands) if cands else None

    def wait_for_waiters(self, n: int = 1, timeout_s: float = 5.0) -> bool:
        """Block (real time) until >= ``n`` waiters are registered —
        the regression tests' rendezvous with a worker thread about to
        be descheduled."""
        deadline = time.monotonic() + timeout_s
        with self._mu:
            while (len(self._sleepers) + len(self._cond_timeouts)) < n:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._mu.wait(left)
            return True

    def advance(self, dt: float) -> None:
        self.advance_to(self.monotonic() + dt)

    def advance_to(self, target: float) -> None:
        """Move simulated time to ``target``, waking every waiter whose
        deadline is reached, in deadline order, AT its deadline."""
        target = float(target)
        while True:
            to_wake: List[threading.Event] = []
            to_notify: List[threading.Condition] = []
            with self._mu:
                if target <= self._now:
                    return
                stop = target
                if self._sleepers and self._sleepers[0][0] < stop:
                    stop = self._sleepers[0][0]
                if self._cond_timeouts and self._cond_timeouts[0][0] < stop:
                    stop = self._cond_timeouts[0][0]
                self._now = max(self._now, stop)
                while self._sleepers and self._sleepers[0][0] <= self._now:
                    deadline, _, ev, tid = heapq.heappop(self._sleepers)
                    self._pins[tid] = deadline
                    self._acks_due += 1
                    to_wake.append(ev)
                while (self._cond_timeouts
                       and self._cond_timeouts[0][0] <= self._now):
                    _, _, cond = heapq.heappop(self._cond_timeouts)
                    to_notify.append(cond)
            for ev in to_wake:
                ev.set()
            for cond in to_notify:
                # never taken while holding the clock lock (docstring)
                with cond:
                    cond.notify_all()
            if to_wake:
                # rendezvous: every woken sleeper acks from inside
                # sleep() before the next hop (bounded, real time)
                ack_by = time.monotonic() + 5.0
                with self._mu:
                    while self._acks_due > 0:
                        left = ack_by - time.monotonic()
                        if left <= 0:
                            break
                        self._mu.wait(left)
            if stop >= target:
                return


def as_clock(clock) -> Clock:
    """Coerce any accepted clock form to a :class:`Clock`:

    - None -> the shared real clock,
    - a Clock -> itself,
    - a bare ``() -> float`` callable -> :class:`CallableClock`
      (legacy test seam: reads virtual, waits real).
    """
    if clock is None:
        return REAL_CLOCK
    if isinstance(clock, Clock):
        return clock
    if callable(clock):
        return CallableClock(clock)
    raise TypeError(f"not a clock: {clock!r}")


def monotonic_of(clock) -> Callable[[], float]:
    """The cheap read-only coercion for components that only ever READ
    time: None -> time.monotonic, Clock -> its bound monotonic, a bare
    callable -> itself (zero wrapping on the legacy seam)."""
    if clock is None:
        return time.monotonic
    if isinstance(clock, Clock):
        return clock.monotonic
    if callable(clock):
        return clock
    raise TypeError(f"not a clock: {clock!r}")
