"""CLI for the endurance simulator: ``python -m
karpenter_provider_aws_tpu.sim --hours 24 --out SIM_r01.json``.

Exit code 0 iff the auditor recorded no violations."""

import argparse
import json
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="karpenter_provider_aws_tpu.sim",
        description="virtual-time endurance replay (docs/simulator.md)")
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--hours", type=float, default=24.0,
                    help="virtual duration (default: one day)")
    ap.add_argument("--regimes", default="",
                    help="comma-separated subset (default: all)")
    ap.add_argument("--scale", type=float, default=1.0)
    ap.add_argument("--no-chaos", action="store_true")
    ap.add_argument("--no-wire", action="store_true",
                    help="skip the loopback sidecar (no grpc)")
    ap.add_argument("--audit-every", type=int, default=40)
    ap.add_argument("--out", default="",
                    help="write the JSON report artifact here")
    args = ap.parse_args(argv)

    from .driver import EnduranceSim
    sim = EnduranceSim(
        seed=args.seed, duration_s=args.hours * 3600.0,
        regimes=[r for r in args.regimes.split(",") if r] or None,
        scale=args.scale, chaos=not args.no_chaos,
        wire=False if args.no_wire else None,
        audit_every=args.audit_every, out=args.out or None)
    report = sim.run()
    print(json.dumps({k: v for k, v in report.items()
                      if k != "events_by_kind"}, indent=1))
    if not report["clean"]:
        print(f"SIM FAILED: {len(report['violations'])} violation(s)",
              file=sys.stderr)
        return 1
    print(f"sim clean: {report['events_total']} events, "
          f"{report['solves']} solves, {report['chaos_windows']} chaos "
          f"windows ({report['chaos_overlaps']} overlapped), "
          f"{report['wall_s']}s wall for "
          f"{report['virtual_duration_s'] / 3600:.1f}h virtual")
    return 0


if __name__ == "__main__":
    sys.exit(main())
