"""Chaos composition onto the trace timeline.

The existing injectors each tear ONE seam for the length of a test:
faultwire wraps a SolverClient, faultcloud wraps the EC2/SQS seam,
TenantHammer storms the admission layer. Production failure is
*overlapping*: a cloud storm lands while the wire is already flaky and
an adversarial tenant is mid-burst. This module schedules those
injectors as WINDOWS on the same virtual timeline the trace runs on,
drawn from the same seed — including deliberately overlapped pairs
(docs/simulator.md's composition grammar).

A window is pure data; the driver engages/disengages the real injector
when virtual time crosses its bounds. Window kinds:

- ``cloud``      — a CloudFaultInjector storm (throttle/down/wedge/
                   lag/partial/dup) on the operator's EC2+SQS seam.
- ``wire``       — a FaultInjector (unavailable/deadline/latency/
                   truncate/drop/stale) on the tenant solve client.
- ``hammer``     — a TenantHammer thread against the live sidecar.
- ``arena_wipe`` — the server's resident patch arenas dropped mid-
                   stream (compile-cache/residency wipe: every tenant's
                   next delta tick must degrade to one full Solve and
                   re-prime).

Every plan parameter is bounded the way the chaos tests bound them
(finite ``max_faults``, ``max_consecutive`` under the client's retry
budget) so a composed schedule stresses recovery without making
convergence impossible.
"""

from __future__ import annotations

import json
import random
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["ChaosWindow", "CHAOS_KINDS", "schedule"]

CHAOS_KINDS: Tuple[str, ...] = ("cloud", "wire", "hammer", "arena_wipe")

_SALT = 0xC405


@dataclass(frozen=True)
class ChaosWindow:
    """One scheduled injector engagement: [t0, t1) on the virtual
    timeline. ``params`` feed the injector's plan constructor; the
    ``overlaps`` flag marks windows the scheduler DELIBERATELY laid on
    top of another (fault-during-recovery coverage — the audit report
    counts them so a run can prove composition actually happened)."""

    t0: float
    t1: float
    kind: str
    params: Dict = field(default_factory=dict)
    overlaps: bool = False

    def encode(self) -> bytes:
        return json.dumps(
            {"t0": round(self.t0, 3), "t1": round(self.t1, 3),
             "kind": self.kind, "params": self.params,
             "overlaps": self.overlaps},
            sort_keys=True, separators=(",", ":")).encode()


def schedule(seed: int, duration_s: float,
             kinds: Optional[Sequence[str]] = None) -> List[ChaosWindow]:
    """The composed chaos schedule for one run: per enabled kind, a few
    seeded windows spread over the day, plus forced OVERLAP pairs — a
    wire window opened inside every cloud window's second half, and an
    arena wipe dropped inside a hammer window when both are enabled.
    Deterministic for equal (seed, duration, kinds)."""
    kinds = list(kinds if kinds is not None else CHAOS_KINDS)
    unknown = set(kinds) - set(CHAOS_KINDS)
    if unknown:
        raise ValueError(f"unknown chaos kinds: {sorted(unknown)}")
    rng = random.Random((seed & 0xFFFFFFFF) ^ _SALT)
    duration_s = float(duration_s)
    out: List[ChaosWindow] = []

    def win(frac_lo, frac_hi, min_s, max_s):
        t0 = rng.uniform(frac_lo, frac_hi) * duration_s
        return t0, min(duration_s, t0 + rng.uniform(min_s, max_s))

    if "cloud" in kinds:
        for _ in range(max(1, int(duration_s // 28800))):
            t0, t1 = win(0.1, 0.8, 300.0, 1200.0)
            out.append(ChaosWindow(t0, t1, "cloud", {
                "seed": rng.randrange(1 << 16),
                "p_throttle": 0.10, "p_down": 0.06, "p_wedge": 0.06,
                "p_lag": 0.08, "p_partial": 0.05, "p_dup": 0.20,
                "max_consecutive": 2, "max_faults": 30}))
            if "wire" in kinds:
                # the forced overlap: the wire goes flaky while the
                # cloud storm is still mid-flight (fault-during-
                # recovery, the regime no single-seam test reaches)
                mid = t0 + (t1 - t0) * 0.5
                out.append(ChaosWindow(
                    mid, min(duration_s, t1 + (t1 - t0) * 0.5), "wire",
                    {"seed": rng.randrange(1 << 16),
                     "p_unavailable": 0.12, "p_deadline": 0.08,
                     "p_latency": 0.10, "p_truncate": 0.08,
                     "p_drop": 0.08, "p_stale": 0.05,
                     "max_consecutive": 2}, overlaps=True))
    if "wire" in kinds:
        for _ in range(max(1, int(duration_s // 43200))):
            t0, t1 = win(0.05, 0.9, 600.0, 1800.0)
            out.append(ChaosWindow(t0, t1, "wire", {
                "seed": rng.randrange(1 << 16),
                "p_unavailable": 0.15, "p_deadline": 0.10,
                "p_latency": 0.10, "p_truncate": 0.10, "p_drop": 0.10,
                "p_stale": 0.05, "max_consecutive": 2}))
    if "hammer" in kinds:
        for i in range(max(1, int(duration_s // 43200))):
            t0, t1 = win(0.2, 0.85, 300.0, 900.0)
            out.append(ChaosWindow(t0, t1, "hammer", {
                "seed": rng.randrange(1 << 16),
                "tenant": f"hammer{i}"}))
            if "arena_wipe" in kinds:
                # wipe the resident arenas mid-hammer: the delta wire
                # re-primes while admission is under adversarial load
                t = t0 + (t1 - t0) * rng.uniform(0.3, 0.7)
                out.append(ChaosWindow(t, t, "arena_wipe", {},
                                       overlaps=True))
    if "arena_wipe" in kinds:
        t = rng.uniform(0.3, 0.9) * duration_s
        out.append(ChaosWindow(t, t, "arena_wipe", {}))
    out.sort(key=lambda w: (w.t0, w.t1, w.kind,
                            json.dumps(w.params, sort_keys=True)))
    return out
