"""Endurance simulator: virtual-time day-long trace replay against the
real stack, with composed chaos and a continuous invariant auditor.

Four pieces (docs/simulator.md):

- :mod:`.clock` — the Clock seam. Every timer in the serving stack
  (batcher windows, TTL caches, resilience backoff, admission buckets,
  coalescer waits, fleet probe aging, meshgroup regroup timers) reads
  time through an injectable :class:`~.clock.Clock`; the default stays
  the real clock (zero behavior change, tier-1 proves it), while
  :class:`~.clock.VirtualClock` lets a simulated day run in minutes.
- :mod:`.traces` — seeded day-long trace generators (diurnal ramp,
  flash crowd, spot-reclaim storm, batch waves, multi-tenant solve
  mix) emitting one totally-ordered, byte-stable event stream.
- :mod:`.chaos` — a chaos scheduler composing the existing injectors
  (faultwire, faultcloud, TenantHammer) onto the trace timeline from
  the same seed, with deliberate overlap windows.
- :mod:`.driver` + :mod:`.audit` — the replay engine driving the real
  Operator under the virtual clock, and the continuously-running
  invariant auditor (shared with hack/soak.py).

This package deliberately imports nothing heavy at import time: the
clock seam is consumed by low-level modules (cache/ttl.py,
batcher/core.py) that must not pull jax or grpc.
"""

from .clock import Clock, RealClock, VirtualClock, as_clock, monotonic_of

__all__ = ["Clock", "RealClock", "VirtualClock", "as_clock",
           "monotonic_of"]
