"""The endurance replay engine.

``EnduranceSim`` runs a seeded day-long trace (sim/traces.py) against
the REAL stack — the full Operator reconcile loop over the fake cloud,
plus live tenant solve traffic through a loopback sidecar — under
composed chaos (sim/chaos.py), with the invariant auditor
(sim/audit.py) running continuously.

Time is split across two clocks, deliberately:

- The **control plane** runs on a :class:`~.clock.VirtualClock`: the
  Operator's grace windows, TTL caches, and the ICE blacklist age on
  the virtual timeline, so a 24h trace of diurnal ramps and reclaim
  storms replays in minutes of wall time.
- The **wire** (sidecar server, resilience backoff, coalescer windows)
  stays on the real clock: solve RPCs are real work on real threads,
  and their latency is the thing the per-regime SLO audits. Descheduling
  the wire onto virtual time would deadlock the single driver thread
  against its own batchers — the clock seam supports it for unit tests
  (tests/test_sim.py), but the replay measures the wire for real.

Determinism: the trace stream is bytes-identical per seed
(traces.encode), pod names are counter-reset so identical across
processes, interruption victims are picked by sorted pool labels (the
faultcloud pattern), and the terminal cluster fingerprint hashes the
capacity multiset — never object ids. Chaos storms are finite by
construction, so the post-chaos settle converges to the fault-free
terminus.
"""

from __future__ import annotations

import hashlib
import json
import queue
import threading
import time
from typing import Dict, List, Optional, Sequence

from . import audit as audit_mod
from . import chaos as chaos_mod
from . import traces as traces_mod
from .clock import VirtualClock

__all__ = ["EnduranceSim", "cluster_fingerprint", "emit_event",
           "emit_violation", "emit_regime"]


# -- metric emitters (test_metrics_parity.py drives these directly) ---------

def emit_event(metrics, event) -> None:
    if metrics is not None:
        metrics.inc("karpenter_sim_events_total",
                    labels={"regime": event.regime, "kind": event.kind})


def emit_violation(metrics, violation) -> None:
    if metrics is not None:
        metrics.inc("karpenter_sim_violations_total",
                    labels={"check": violation.check})


def emit_regime(metrics, regime: str, active: bool) -> None:
    if metrics is not None:
        metrics.set_gauge("karpenter_sim_regime", 1.0 if active else 0.0,
                          labels={"regime": regime})


def cluster_fingerprint(op) -> str:
    """sha256 over the terminal capacity multiset + pod binding counts
    (the faultcloud fingerprint, canonically encoded — no ids, no
    ``hash()``, so it compares across processes)."""
    capacity = sorted(
        (i.instance_type, i.zone, i.capacity_type)
        for i in op.ec2.instances.values() if i.state == "running")
    pods = op.kube.list("Pod")
    doc = {"capacity": capacity, "pods": len(pods),
           "bound": sum(1 for p in pods if p.node_name)}
    return hashlib.sha256(json.dumps(
        doc, sort_keys=True, separators=(",", ":")).encode()).hexdigest()


class _SolveWorker:
    """One background thread draining tenant solve jobs against the
    loopback sidecar — solve traffic runs CONCURRENTLY with the control
    plane, but the wire itself stays single-threaded so seeded fault
    draws land in a reproducible order."""

    def __init__(self, solve_fn, oracle_fn):
        self._solve = solve_fn
        self._oracle = oracle_fn
        self._q: "queue.Queue" = queue.Queue()
        self._mu = threading.Lock()
        self.latencies: Dict[str, List[float]] = {}
        self.mismatches: List[str] = []
        self.errors: List[str] = []
        self.solves = 0
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="sim-solve-worker")
        self._thread.start()

    def submit(self, snap, regime: str, tag: str, timed: bool = True):
        self._q.put((snap, regime, tag, timed))

    def drain(self) -> None:
        self._q.join()

    def stop(self) -> None:
        self._q.put(None)
        self._q.join()
        self._thread.join(timeout=30)

    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                self._q.task_done()
                return
            snap, regime, tag, timed = item
            try:
                t0 = time.perf_counter()
                fp = self._solve(snap)
                dt = time.perf_counter() - t0
                ref = self._oracle(snap)
                with self._mu:
                    self.solves += 1
                    if timed:
                        self.latencies.setdefault(regime, []).append(dt)
                    if fp != ref:
                        self.mismatches.append(tag)
            except Exception as e:  # a solve must NEVER fail (host twin)
                with self._mu:
                    self.errors.append(f"{tag}: {type(e).__name__}: {e}")
            finally:
                self._q.task_done()


class EnduranceSim:
    """One replay run. ``run()`` returns the report dict (also written
    to ``out`` when given — the SIM_r01.json artifact)."""

    def __init__(self, seed: int = 7, duration_s: float = 86400.0,
                 regimes: Optional[Sequence[str]] = None,
                 scale: float = 1.0, chaos: bool = True,
                 chaos_kinds: Optional[Sequence[str]] = None,
                 wire: Optional[bool] = None,
                 audit_every: int = 25,
                 slo_p99_ms: Optional[Dict[str, float]] = None,
                 out: Optional[str] = None):
        self.seed = int(seed)
        self.duration_s = float(duration_s)
        self.regimes = list(regimes if regimes is not None
                            else traces_mod.REGIMES)
        self.scale = float(scale)
        self.chaos = chaos
        self.chaos_kinds = chaos_kinds
        self.wire = wire
        self.audit_every = max(1, int(audit_every))
        self.slo_p99_ms = slo_p99_ms
        self.out = out
        self.violations: List[audit_mod.Violation] = []

    # -- wire availability ------------------------------------------------
    @staticmethod
    def _grpc_available() -> bool:
        try:
            import grpc  # noqa: F401
            return True
        except Exception:
            return False

    # -- event application -------------------------------------------------
    def _apply(self, op, evt) -> None:
        from ..apis import labels as L
        from ..apis.objects import (PriorityClass,
                                    TopologySpreadConstraint)
        from ..fake.environment import make_pods
        from ..providers.sqs import InterruptionMessage
        p = evt.payload
        if evt.kind == "create_pods":
            kw = {}
            if p.get("spread"):
                g = p["prefix"]
                kw = dict(group=g, topology_spread=[
                    TopologySpreadConstraint(max_skew=1,
                                             topology_key=L.ZONE,
                                             group=g)])
            for pod in make_pods(p["count"], cpu=p["cpu"],
                                 memory=p["memory"], prefix=p["prefix"],
                                 **kw):
                if p.get("priority_class"):
                    pod.priority_class_name = p["priority_class"]
                op.kube.create(pod)
                if p.get("critical"):
                    # watch creation-to-bind latency on the virtual
                    # timeline — the critical-tier SLO input
                    self._prio_watch[pod.full_name()] = evt.t
        elif evt.kind == "create_priority_class":
            if op.kube.try_get("PriorityClass", p["name"]) is None:
                op.kube.create(PriorityClass(p["name"],
                                             value=p["value"]))
        elif evt.kind == "delete_pods":
            pods = sorted((x for x in op.kube.list("Pod")
                           if x.name.startswith(p["match"])),
                          key=lambda x: x.name)
            n = int(len(pods) * p["fraction"])
            for pod in pods[:n]:
                op.kube.delete("Pod", pod.name,
                               namespace=pod.metadata.namespace)
        elif evt.kind == "spot_interrupt":
            claims = sorted(
                (c for c in op.kube.list("NodeClaim") if c.provider_id),
                key=lambda c: (c.metadata.labels.get(L.INSTANCE_TYPE, ""),
                               c.metadata.labels.get(L.ZONE, ""),
                               c.metadata.name))
            for c in claims[:p["count"]]:
                op.sqs.send(InterruptionMessage(
                    kind="spot_interruption",
                    instance_id=c.provider_id.split("/")[-1]))
        elif evt.kind == "ice_pool":
            cat = op.ec2.catalog
            t = cat[p["type_idx"] % len(cat)].name
            z = op.ec2.zones[p["zone_idx"] % len(op.ec2.zones)].name
            op.ec2.insufficient_capacity_pools.add(
                (t, z, p["capacity_type"]))
        elif evt.kind == "solve":
            self._apply_solve(evt)
        else:
            raise ValueError(f"unknown trace event kind {evt.kind!r}")

    def _apply_solve(self, evt) -> None:
        """One warm tick for ``evt.payload['tenant']``: swap the churned
        pod groups, snapshot, hand the solve to the worker."""
        from ..fake.environment import make_pods
        tenant = evt.payload["tenant"]
        st = self._tenant_state.get(tenant)
        if st is None:
            pool = self._solve_env.nodepool(f"sim-{tenant}")
            sigs = [dict(cpu=f"{100 + (i * 7) % 400}m",
                         memory=f"{256 + (i * 13) % 700}Mi",
                         group=f"sim{tenant}g{i:03d}") for i in range(10)]
            cur = []
            for gi in range(len(sigs)):
                cur.extend(make_pods(
                    2, cpu=sigs[gi]["cpu"], memory=sigs[gi]["memory"],
                    prefix=sigs[gi]["group"], group=sigs[gi]["group"]))
            st = self._tenant_state[tenant] = {
                "pool": pool, "sigs": sigs, "cur": cur}
            # one untimed warmup solve per tenant: jit compilation of a
            # fresh shape class is a one-off cost, not regime latency
            snap = self._solve_env.snapshot(list(cur), [pool])
            self._worker.submit(snap, evt.regime,
                                f"warmup:{tenant}", timed=False)
        sigs, cur = st["sigs"], st["cur"]
        for gi in evt.payload["churn"]:
            gi = gi % len(sigs)
            if cur:
                cur.pop(0)
            cur.extend(make_pods(
                1, cpu=sigs[gi]["cpu"], memory=sigs[gi]["memory"],
                prefix=sigs[gi]["group"], group=sigs[gi]["group"]))
        snap = self._solve_env.snapshot(list(cur), [st["pool"]])
        self._worker.submit(snap, evt.regime,
                            f"solve:{tenant}:{evt.seq}")

    def _harvest_prio(self, op, now: float) -> None:
        """Record creation-to-bind virtual latency for watched critical
        pods; called after every reconcile step so the sample reflects
        control-plane rounds, not audit cadence."""
        if not self._prio_watch:
            return
        for pod in op.kube.list("Pod"):
            name = pod.full_name()
            if name in self._prio_watch and pod.node_name:
                self._prio_latencies.append(
                    now - self._prio_watch.pop(name))

    # -- chaos -------------------------------------------------------------
    def _engage(self, op, w) -> None:
        if w.kind == "cloud":
            from ..fake.faultcloud import (CloudFaultInjector,
                                           CloudFaultPlan)
            params = dict(w.params)
            inj = CloudFaultInjector(
                op.ec2, sqs=op.sqs,
                plan=CloudFaultPlan(params.pop("seed"), **params))
            inj.install()
            self._active[id(w)] = ("cloud", inj)
        elif w.kind == "wire":
            if self._remote is None:
                return
            from ..fake.faultwire import FaultInjector, FaultPlan
            params = dict(w.params)
            inj = FaultInjector(self._remote.client,
                                FaultPlan(params.pop("seed"), **params))
            # never re-wrap mid-flight: the worker queue is drained by
            # the caller before any window boundary
            inj.install()
            self._active[id(w)] = ("wire", inj)
        elif w.kind == "hammer":
            if self._server is None:
                return
            from ..fake.faultwire import TenantHammer
            h = TenantHammer(self._server.address,
                             tenant=w.params["tenant"],
                             seed=w.params["seed"]).start(n_attacks=200)
            self._active[id(w)] = ("hammer", h)
        elif w.kind == "arena_wipe":
            if self._server is not None:
                self._server._handler._patch_arenas.clear()

    def _disengage(self, key) -> None:
        kind, obj = self._active.pop(key)
        if kind in ("cloud", "wire"):
            obj.uninstall()
        elif kind == "hammer":
            obj.stop()

    def _chaos_tick(self, op, now: float, drain) -> None:
        """Cross every window boundary <= now: engage opens,
        disengage closes. ``drain`` flushes in-flight wire traffic
        before the client's channel callables are (un)wrapped."""
        for w in self._windows:
            key = id(w)
            if key in self._done:
                continue
            if key not in self._active and w.t0 <= now:
                drain()
                self._engage(op, w)
                if w.t0 == w.t1:  # instantaneous (arena_wipe)
                    self._active.pop(key, None)
                    self._done.add(key)
            elif key in self._active and w.t1 <= now:
                drain()
                self._disengage(key)
                self._done.add(key)

    # -- settling ----------------------------------------------------------
    @staticmethod
    def _settle(op, rounds: int = 6) -> bool:
        """Settle under possible chaos: a reconcile aborted by an
        escaped fault is retried (manager panic isolation in
        production). True when the cluster genuinely converged."""
        from ..providers.awsretry import AWSError
        for _ in range(rounds):
            try:
                steps = op.run_until_settled(max_steps=12)
            except (AWSError, ConnectionError, OSError):
                continue
            if steps < 12 and len(op.sqs) == 0 and all(
                    p.node_name for p in op.kube.list("Pod")
                    if p.phase not in ("Succeeded", "Failed")):
                return True
            time.sleep(0.05)  # real wait: let wedge/lag windows expire
        return False

    def _record(self, violations) -> None:
        for v in violations:
            self.violations.append(v)
            emit_violation(self._metrics, v)

    # -- the run -----------------------------------------------------------
    def run(self) -> dict:
        from ..apis.objects import (EC2NodeClass, NodeClassRef, NodePool,
                                    NodePoolTemplate)
        from ..fake.environment import Environment, reset_pod_counter
        from ..operator import Operator

        t_wall = time.perf_counter()
        reset_pod_counter()
        vclock = VirtualClock()
        self.vclock = vclock
        op = Operator(clock=vclock.time)
        self._metrics = op.metrics
        # The cloud batchers read VIRTUAL time but wait REAL time (the
        # CallableClock contract), so their coalescing windows — tuned
        # to amortize real AWS round trips — would each cost the replay
        # 100ms of wall for nothing (virtual time is frozen while the
        # driver blocks in add_sync). Keep the batching semantics, flush
        # almost immediately.
        for b in (op.instances.create_fleet, op.instances.describe,
                  op.instances.terminate_batcher):
            b.idle_timeout = 0.002
            b.max_timeout = 0.01
        op.kube.create(EC2NodeClass("sim-class"))
        op.kube.create(NodePool("sim", template=NodePoolTemplate(
            node_class_ref=NodeClassRef("sim-class"))))

        events = traces_mod.generate(self.seed, self.duration_s,
                                     regimes=self.regimes,
                                     scale=self.scale)
        stream_sha = traces_mod.stream_digest(events)
        self._windows = chaos_mod.schedule(
            self.seed, self.duration_s,
            kinds=self.chaos_kinds) if self.chaos else []
        self._active: dict = {}
        self._done: set = set()

        # wire: loopback sidecar + one RemoteSolver for tenant traffic
        use_wire = self._grpc_available() if self.wire is None \
            else bool(self.wire)
        self._server = self._remote = self._metrics_wire = None
        offered = {}
        if use_wire:
            offered = self._start_wire()
        else:
            from ..solver import CPUSolver
            local = CPUSolver()
            self._worker = _SolveWorker(
                lambda s: local.solve(s).decision_fingerprint(),
                lambda s: local.solve(s).decision_fingerprint())
        self._solve_env = Environment()
        self._tenant_state: dict = {}
        self._prio_watch: Dict[str, float] = {}
        self._prio_latencies: List[float] = []
        leaks = audit_mod.LeakMonitor()

        for r in self.regimes:
            emit_regime(self._metrics, r, True)
        self._metrics.inc("karpenter_sim_violations_total", 0.0,
                          labels={"check": "none"})

        kinds_count: Dict[str, int] = {}
        audits = converged_audits = 0
        try:
            for i, evt in enumerate(events):
                vclock.advance_to(evt.t)
                self._chaos_tick(op, evt.t, drain=self._worker.drain)
                self._apply(op, evt)
                emit_event(self._metrics, evt)
                kinds_count[evt.kind] = kinds_count.get(evt.kind, 0) + 1
                try:
                    op.step()
                except Exception:
                    pass  # an escaped injected fault aborts one round
                self._harvest_prio(op, evt.t)
                if (i + 1) % self.audit_every == 0:
                    audits += 1
                    if self._settle(op, rounds=4):
                        self._harvest_prio(op, evt.t)
                        converged_audits += 1
                        self._record(audit_mod.check_cluster(
                            op, context=f"t={evt.t:.0f}s"))
                    self._record(leaks.check(
                        op, handler=getattr(self._server, "_handler",
                                            None),
                        context=f"t={evt.t:.0f}s"))

            # terminus: all chaos off, drain, settle HARD, full audit
            self._worker.drain()
            for key in list(self._active):
                self._disengage(key)
                self._done.add(key)
            vclock.advance_to(self.duration_s)
            if not self._settle(op, rounds=40):
                self._record([audit_mod.Violation(
                    "no-convergence",
                    "cluster failed to settle after chaos end")])
            else:
                self._record(audit_mod.check_cluster(op,
                                                     context="terminus"))
            self._worker.stop()
            for tag in self._worker.mismatches:
                self._record([audit_mod.Violation(
                    "oracle-divergence",
                    f"solve diverged from the CPU oracle: {tag}")])
            for err in self._worker.errors:
                self._record([audit_mod.Violation("solve-failed", err)])
            self._record(audit_mod.check_accounting(
                self._metrics_wire or self._metrics,
                offered_by_tenant={t: c.count for t, c in offered.items()}
                if offered else None, context="terminus"))
            self._record(audit_mod.check_slo(
                self._worker.latencies, slo_p99_ms=self.slo_p99_ms,
                context="terminus"))
            self._harvest_prio(op, self.duration_s)
            self._record(audit_mod.check_priority_slo(
                self._prio_latencies, unbound=len(self._prio_watch),
                context="terminus"))
            self._record(leaks.check(
                op, handler=getattr(self._server, "_handler", None),
                context="terminus"))
            fingerprint = cluster_fingerprint(op)
        finally:
            for key in list(self._active):
                try:
                    self._disengage(key)
                except Exception:
                    pass
            if self._remote is not None:
                self._remote.client.close()
            if self._server is not None:
                self._server.stop()
            for r in self.regimes:
                emit_regime(self._metrics, r, False)

        report = {
            "seed": self.seed,
            "virtual_duration_s": self.duration_s,
            "wall_s": round(time.perf_counter() - t_wall, 2),
            "regimes": list(self.regimes),
            "events_total": len(events),
            "events_by_kind": dict(sorted(kinds_count.items())),
            "stream_sha256": stream_sha,
            "chaos_windows": len(self._windows),
            "chaos_overlaps": sum(1 for w in self._windows if w.overlaps),
            "wire": use_wire,
            "solves": self._worker.solves,
            "solve_p99_ms": {
                r: round(sorted(ls)[min(len(ls) - 1,
                                        int(0.99 * len(ls)))] * 1e3, 1)
                for r, ls in self._worker.latencies.items() if ls},
            "audits": audits,
            "converged_audits": converged_audits,
            "critical_binds": len(self._prio_latencies),
            "critical_bind_p99_s": round(sorted(
                self._prio_latencies)[min(len(self._prio_latencies) - 1,
                                          int(0.99 * len(
                                              self._prio_latencies)))],
                1) if self._prio_latencies else None,
            "terminal_fingerprint": fingerprint,
            "violations": [str(v) for v in self.violations],
            "clean": not self.violations,
        }
        if self.out:
            with open(self.out, "w") as f:
                json.dump(report, f, indent=1, sort_keys=True)
        return report

    # -- wire plumbing -----------------------------------------------------
    def _start_wire(self) -> dict:
        """Start the loopback sidecar + the tenant RemoteSolver, and
        install the per-tenant OFFER counters underneath any fault
        injector: a call counts as offered exactly when it actually
        reaches the server (admission enter()s once per such RPC), so
        admitted + shed == offered holds to the unit."""
        import random as _random

        from ..sidecar import RemoteSolver, SolverServer
        from ..sidecar.resilience import (CircuitBreaker, ResiliencePolicy,
                                          RetryPolicy)
        from ..solver import CPUSolver
        from ..tenancy.admission import TenantQuota
        from ..utils.metrics import Metrics

        # the wire's own metrics registry: tenant admitted/shed and the
        # wire families accumulate here, audited at terminus
        self._metrics_wire = Metrics()
        self._server = SolverServer(
            metrics=self._metrics_wire,
            default_quota=TenantQuota(rate=200.0, burst=100,
                                      max_inflight=16)).start()

        policy = ResiliencePolicy(
            retry=RetryPolicy(max_attempts=4, backoff_base_s=0.001,
                              backoff_cap_s=0.01,
                              rng=_random.Random(self.seed ^ 0x5EED)),
            breaker=CircuitBreaker(threshold=50, cooldown_s=0.05))
        self._remote = RemoteSolver(self._server.address, n_max=64,
                                    backend="jax", policy=policy,
                                    tenant=traces_mod.TENANTS[0])
        self._remote._router.alive.mark_ok()

        class _Count:
            __slots__ = ("count",)

            def __init__(self):
                self.count = 0

        offered: Dict[str, "_Count"] = {}
        client = self._remote.client
        for attr in ("_solve", "_solve_pruned", "_solve_topo",
                     "_solve_batch", "_solve_subsets", "_solve_patch"):
            real = getattr(client, attr)

            def shim(request, timeout=None, metadata=None, _real=real):
                tenant = "default"
                for k, v in (metadata or ()):
                    if k == "x-solver-tenant":
                        tenant = v
                offered.setdefault(tenant, _Count()).count += 1
                return _real(request, timeout=timeout, metadata=metadata)

            setattr(client, attr, shim)

        oracle = CPUSolver()

        def solve_remote(snap):
            return self._remote.solve(snap).decision_fingerprint()

        def solve_oracle(snap):
            return oracle.solve(snap).decision_fingerprint()

        self._worker = _SolveWorker(solve_remote, solve_oracle)
        return offered
