"""Seeded day-long trace generators.

A trace is one totally-ordered stream of :class:`TraceEvent` — what the
cluster is ASKED to do over a (virtual) day — produced by composing
independent regime generators (docs/simulator.md):

- ``diurnal``      — a sinusoidal arrival ramp: daytime scale-ups,
                     nighttime scale-downs (the classic web-serving day).
- ``flash_crowd``  — a handful of sudden large spikes, mostly drained
                     again after a short hold (launch-event traffic).
- ``spot_storm``   — clustered spot-reclaim storms: bursts of
                     interruption messages plus ICE'd pools (the
                     KubePACS reclaim regime, PAPERS.md).
- ``batch_waves``  — periodic batch-job waves: a large topology-spread
                     group lands, runs for a window, then leaves whole.
- ``tenant_mix``   — multi-tenant solve traffic against the sidecar:
                     warm churn ticks per tenant (the delta-wire regime)
                     interleaved across the day.
- ``priority_surge`` — a low-priority batch flood followed minutes
                     later by a critical-pod wave: the priority-
                     resolution path end to end (PriorityClass objects,
                     per-pod resolution, the prio-aware solve), with the
                     critical tier's creation-to-bind latency audited
                     against its own SLO (sim/audit.py).

Determinism is the contract: every generator draws ONLY from its own
``random.Random(seed ^ salt)``, event payloads are plain JSON values,
and the merged stream is canonically ordered and canonically encoded —
``encode(events)`` is bytes-identical for equal seeds across processes
(PYTHONHASHSEED-independent; pinned by tests/test_sim.py's subprocess
test). Applying an event is the driver's job (sim/driver.py); payloads
therefore carry *instructions* (counts, fractions, indices), never
object references.
"""

from __future__ import annotations

import json
import math
import random
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["TraceEvent", "REGIMES", "generate", "encode",
           "stream_digest"]

#: regime name -> generator salt (xor'd into the seed so regimes draw
#: from independent, stable streams — adding a regime never perturbs
#: the others' schedules)
_SALTS = {
    "diurnal": 0x1D1B,
    "flash_crowd": 0xF1A5,
    "spot_storm": 0x5707,
    "batch_waves": 0xBA7C,
    "tenant_mix": 0x7E4A,
    "priority_surge": 0x9517,
}

REGIMES: Tuple[str, ...] = tuple(_SALTS)

#: solve tenants the tenant_mix regime cycles through
TENANTS = ("team-a", "team-b", "team-c")


@dataclass(frozen=True)
class TraceEvent:
    """One instruction on the trace timeline.

    ``t`` is virtual seconds from trace start; ``seq`` the global order
    tiebreaker assigned at merge; ``kind`` one of ``create_pods`` /
    ``delete_pods`` / ``spot_interrupt`` / ``ice_pool`` / ``solve`` /
    ``create_priority_class``."""

    t: float
    seq: int
    regime: str
    kind: str
    payload: Dict = field(default_factory=dict)

    def encode(self) -> bytes:
        return json.dumps(
            {"t": round(self.t, 3), "seq": self.seq,
             "regime": self.regime, "kind": self.kind,
             "payload": self.payload},
            sort_keys=True, separators=(",", ":")).encode()


def _rng(seed: int, regime: str) -> random.Random:
    return random.Random((seed & 0xFFFFFFFF) ^ _SALTS[regime])


# -- regime generators ------------------------------------------------------
# Each returns [(t, kind, payload)] drawn only from its own rng.

def _diurnal(rng: random.Random, duration_s: float, scale: float):
    out = []
    step = 600.0
    t = step
    while t < duration_s:
        # arrival intensity over the day: trough at t=0, peak mid-day
        phase = (t % 86400.0) / 86400.0
        intensity = 0.5 * (1.0 - math.cos(2 * math.pi * phase))
        n = int(round((2 + 10 * intensity) * scale))
        if intensity >= 0.25 or not out:
            out.append((t, "create_pods", {
                "count": max(1, n), "cpu": rng.choice(["250m", "500m", "1"]),
                "memory": "1Gi", "prefix": f"diurnal{int(t):07d}"}))
        else:
            out.append((t, "delete_pods", {
                "fraction": round(rng.uniform(0.2, 0.5), 2),
                "match": "diurnal"}))
        t += step
    return out


def _flash_crowd(rng: random.Random, duration_s: float, scale: float):
    out = []
    crowds = max(1, int(duration_s // 21600))  # ~one per 6h
    for c in range(crowds):
        t = rng.uniform(0.1, 0.9) * duration_s
        n = int(round(rng.randint(20, 40) * scale))
        hold = rng.uniform(600.0, 1800.0)
        out.append((t, "create_pods", {
            "count": max(2, n), "cpu": "500m", "memory": "1Gi",
            "prefix": f"flash{c:02d}", "spread": True}))
        if t + hold < duration_s:
            out.append((t + hold, "delete_pods", {
                "fraction": 0.9, "match": f"flash{c:02d}"}))
    return out


def _spot_storm(rng: random.Random, duration_s: float, scale: float):
    out = []
    storms = max(1, int(duration_s // 28800))  # ~one per 8h
    for s in range(storms):
        t0 = rng.uniform(0.15, 0.85) * duration_s
        # the storm opens with an ICE'd pool (capacity really is gone),
        # then reclaims land in a tight burst
        out.append((t0, "ice_pool", {
            "type_idx": rng.randrange(64), "zone_idx": rng.randrange(8),
            "capacity_type": "spot"}))
        for k in range(rng.randint(2, 4)):
            out.append((t0 + 30.0 * (k + 1), "spot_interrupt", {
                "count": max(1, int(round(rng.randint(1, 2) * scale)))}))
    return out


def _batch_waves(rng: random.Random, duration_s: float, scale: float):
    out = []
    period = 7200.0
    w = 0
    t = period * rng.uniform(0.5, 1.0)
    while t < duration_s:
        n = int(round(rng.randint(8, 16) * scale))
        dur = rng.uniform(1800.0, 3600.0)
        out.append((t, "create_pods", {
            "count": max(2, n), "cpu": "1", "memory": "2Gi",
            "prefix": f"wave{w:03d}", "spread": True}))
        if t + dur < duration_s:
            out.append((t + dur, "delete_pods", {
                "fraction": 1.0, "match": f"wave{w:03d}"}))
        w += 1
        t += period
    return out


def _tenant_mix(rng: random.Random, duration_s: float, scale: float):
    out = []
    step = 300.0
    t = step * 0.5
    i = 0
    while t < duration_s:
        tenant = TENANTS[i % len(TENANTS)]
        out.append((t, "solve", {
            "tenant": tenant,
            # churn instruction for the per-tenant warm-tick state:
            # which pod-group signatures to swap this tick
            "churn": [rng.randrange(10) for _ in range(2)]}))
        i += 1
        t += step
    return out


def _priority_surge(rng: random.Random, duration_s: float, scale: float):
    out = []
    # the class table lands up front (idempotent on the driver side):
    # the batch tier, and a value for the critical names so resolution
    # ranks them above everything the flood creates
    out.append((1.0, "create_priority_class",
                {"name": "sim-batch", "value": 10}))
    out.append((1.0, "create_priority_class",
                {"name": "system-cluster-critical",
                 "value": 2_000_000_000}))
    surges = max(1, int(duration_s // 28800))  # ~one per 8h
    for s in range(surges):
        t = rng.uniform(0.2, 0.8) * duration_s
        n_low = int(round(rng.randint(18, 30) * scale))
        out.append((t, "create_pods", {
            "count": max(2, n_low), "cpu": "500m", "memory": "1Gi",
            "prefix": f"psurge{s:02d}bulk",
            "priority_class": "sim-batch"}))
        # the critical wave lands while the flood is still provisioning
        n_crit = max(1, int(round(rng.randint(3, 6) * scale)))
        out.append((t + rng.uniform(60.0, 240.0), "create_pods", {
            "count": n_crit, "cpu": "1", "memory": "2Gi",
            "prefix": f"psurge{s:02d}crit",
            "priority_class": "system-cluster-critical",
            "critical": True}))
        t_end = t + rng.uniform(3600.0, 7200.0)
        if t_end < duration_s:
            out.append((t_end, "delete_pods", {
                "fraction": 0.8, "match": f"psurge{s:02d}bulk"}))
    return out


_GENERATORS = {
    "diurnal": _diurnal,
    "flash_crowd": _flash_crowd,
    "spot_storm": _spot_storm,
    "batch_waves": _batch_waves,
    "tenant_mix": _tenant_mix,
    "priority_surge": _priority_surge,
}
assert set(_GENERATORS) == set(_SALTS)


# -- composition ------------------------------------------------------------

def generate(seed: int, duration_s: float,
             regimes: Optional[Sequence[str]] = None,
             scale: float = 1.0) -> List[TraceEvent]:
    """The composed trace: every regime's events merged into one
    totally-ordered stream. Ordering is canonical — (t, regime, kind,
    payload-json) — so ``seq`` is a pure function of the seed and the
    stream is reproducible across processes."""
    regimes = list(regimes if regimes is not None else REGIMES)
    unknown = set(regimes) - set(_GENERATORS)
    if unknown:
        raise ValueError(f"unknown regimes: {sorted(unknown)}")
    raw = []
    for name in sorted(regimes):
        for (t, kind, payload) in _GENERATORS[name](
                _rng(seed, name), float(duration_s), scale):
            raw.append((round(float(t), 3), name, kind, payload))
    raw.sort(key=lambda e: (e[0], e[1], e[2],
                            json.dumps(e[3], sort_keys=True)))
    return [TraceEvent(t=t, seq=i, regime=r, kind=k, payload=p)
            for i, (t, r, k, p) in enumerate(raw)]


def encode(events: Sequence[TraceEvent]) -> bytes:
    """Canonical byte encoding of the stream — the determinism
    fingerprintable artifact (one JSON object per line)."""
    return b"\n".join(e.encode() for e in events) + b"\n"


def stream_digest(events: Sequence[TraceEvent]) -> str:
    """sha256 of the canonical encoding (never ``hash()`` — that is
    PYTHONHASHSEED-dependent and would break the subprocess test)."""
    import hashlib
    return hashlib.sha256(encode(events)).hexdigest()
