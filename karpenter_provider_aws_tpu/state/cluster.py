"""In-memory cluster state cache (the core library's ``state.Cluster``).

Tracks nodes, nodeclaims, and pod bindings/nominations, and produces the
solver's view of existing capacity. Mirrors what main.go:40 constructs and
the provisioner consumes; nomination prevents double-provisioning between
the solve that planned a pod and the kube-scheduler binding it.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Set

from ..apis import labels as L
from ..apis.objects import Node, NodeClaim, Pod
from ..apis.resources import Resources, sum_resources
from ..fake.kube import FakeKube
from ..solver.types import ExistingNode

NOMINATION_TTL = 20.0  # core nomination window


@dataclass
class Nomination:
    node_name: str
    expires: float


class ClusterState:
    def __init__(self, kube: FakeKube, clock=time.time):
        self.kube = kube
        self.clock = clock
        self._mu = threading.Lock()
        self._nominations: Dict[str, Nomination] = {}  # pod full_name -> node

    # -- nominations ---------------------------------------------------
    def nominate(self, pod_full_name: str, node_name: str) -> None:
        with self._mu:
            self._nominations[pod_full_name] = Nomination(
                node_name, self.clock() + NOMINATION_TTL)

    def nomination_for(self, pod_full_name: str) -> Optional[str]:
        with self._mu:
            nom = self._nominations.get(pod_full_name)
            if nom is None:
                return None
            if self.clock() >= nom.expires:
                del self._nominations[pod_full_name]
                return None
            return nom.node_name

    def clear_nomination(self, pod_full_name: str) -> None:
        with self._mu:
            self._nominations.pop(pod_full_name, None)

    def clear_nominations_to(self, node_name: str) -> None:
        """Release every pod nominated toward ``node_name`` — called when
        the target claim dies before joining (failed launch), so its pods
        reappear in pending_pods() immediately instead of after TTL."""
        with self._mu:
            self._nominations = {
                pod: nom for pod, nom in self._nominations.items()
                if nom.node_name != node_name}

    def nomination_targets(self) -> Set[str]:
        """Node/claim names with pods in flight toward them — such nodes are
        off-limits to disruption (core's nominated-node protection)."""
        now = self.clock()
        with self._mu:
            return {n.node_name for n in self._nominations.values()
                    if now < n.expires}

    # -- views ---------------------------------------------------------
    def pending_pods(self) -> List[Pod]:
        """Unscheduled pods with no live nomination."""
        out = []
        for pod in self.kube.list("Pod"):
            if not pod.is_pending_unscheduled():
                continue
            if self.nomination_for(pod.full_name()) is not None:
                continue
            out.append(pod)
        return out

    def bound_pods_by_node(self) -> Dict[str, List[Pod]]:
        out: Dict[str, List[Pod]] = {}
        for pod in self.kube.list("Pod"):
            if pod.phase in ("Succeeded", "Failed"):
                continue  # terminal pods hold no resources
            target = pod.node_name or self.nomination_for(pod.full_name())
            if target:
                out.setdefault(target, []).append(pod)
        return out

    def existing_nodes(self) -> List[ExistingNode]:
        """Solver view: registered nodes + launched-but-unregistered
        NodeClaims, each with committed resources."""
        by_node = self.bound_pods_by_node()
        out: List[ExistingNode] = []
        seen_provider_ids = set()
        # nodes whose claim is deleting are mid-drain: they must not be
        # scheduling targets (core MarkForDeletion semantics) or the
        # solver re-binds just-evicted pods onto the doomed node
        deleting = {c.node_name for c in self.kube.list("NodeClaim")
                    if c.metadata.deletion_timestamp is not None
                    and c.node_name}
        for node in self.kube.list("Node"):
            if not node.ready:
                continue
            if node.name in deleting \
                    or node.metadata.deletion_timestamp is not None:
                continue
            pods = by_node.get(node.name, [])
            out.append(ExistingNode(
                name=node.name,
                labels=dict(node.metadata.labels),
                allocatable=node.allocatable,
                taints=list(node.taints),
                used=sum_resources(p.effective_requests() for p in pods),
                pod_groups=[p.scheduling_group for p in pods
                            if p.scheduling_group],
                nodepool=node.metadata.labels.get(L.NODEPOOL, ""),
                instance_type=node.metadata.labels.get(L.INSTANCE_TYPE, ""),
            ))
            seen_provider_ids.add(node.provider_id)
        for claim in self.kube.list("NodeClaim"):
            if not claim.launched or claim.provider_id in seen_provider_ids:
                continue
            if claim.metadata.deletion_timestamp is not None:
                continue
            pods = by_node.get(claim.name, [])
            out.append(ExistingNode(
                name=claim.name,
                labels=dict(claim.metadata.labels),
                allocatable=claim.allocatable,
                taints=list(claim.taints),
                used=sum_resources(p.effective_requests() for p in pods),
                pod_groups=[p.scheduling_group for p in pods
                            if p.scheduling_group],
                nodepool=claim.nodepool or "",
                instance_type=claim.metadata.labels.get(L.INSTANCE_TYPE, ""),
            ))
        return out

    def nodepool_usage(self) -> Dict[str, Resources]:
        """Aggregate requested capacity per nodepool (limits enforcement)."""
        usage: Dict[str, Resources] = {}
        for claim in self.kube.list("NodeClaim"):
            pool = claim.nodepool
            if not pool or claim.metadata.deletion_timestamp is not None:
                continue
            cap = claim.capacity if not claim.capacity.is_zero() \
                else claim.resources_requested
            usage[pool] = usage.get(pool, Resources()) + cap
        return usage
