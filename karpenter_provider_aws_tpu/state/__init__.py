from .cluster import ClusterState, NOMINATION_TTL

__all__ = ["ClusterState", "NOMINATION_TTL"]
