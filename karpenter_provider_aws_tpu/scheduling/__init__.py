"""Priority-aware preemptive scheduling.

PriorityClass resolution lives in ``apis/objects.py`` (the API surface);
this package owns the preemption *search*: given pending pods the base
solve could not place, find the cheapest set of strictly-lower-priority
victims whose eviction schedules all of them onto EXISTING capacity —
zero new nodes, or no preemption at all (kube-scheduler's preemption
contract, scoped to the capacity the autoscaler already owns).

- ``preempt.py``     — PreemptionPlanner (host oracle twin + device
  routing), PreemptionVerdict, PreemptCommand
- ``preempt_jax.py`` — the batched victim-set kernel (one vmapped lane
  per candidate prefix)
"""

from .preempt import (MAX_LANES, PreemptCommand, PreemptionPlanner,
                      PreemptionVerdict)

__all__ = ["MAX_LANES", "PreemptCommand", "PreemptionPlanner",
           "PreemptionVerdict"]
