"""Preemption planner: cheapest victim set that schedules the blocked
high-priority demand onto existing capacity — or a proof none exists.

The kube-scheduler's preemption loop (pkg/scheduler/framework/preemption)
picks victims per pod, node by node. Scoped to the capacity the
autoscaler already owns, the question batches: candidate victim sets are
PREFIXES of one deterministic ascending (priority, cost, namespace,
name) victim order, and every prefix is evaluated in ONE device call
(scheduling/preempt_jax.py) — the ``subset_solve_kernel`` lane recipe
with usage refunded into the arena instead of nodes masked out of it.
The first feasible prefix is the cheapest: it evicts the fewest,
lowest-priority, smallest pods.

Exactness discipline (the same contract as consolidation's oracle):
``_lanes_numpy`` is the bit-identical numpy twin of the kernel; every
routing fallback — numpy backend, no device engine, a failed dispatch —
lands there, never on different semantics. Verdict-and-command byte
identity across backends is fuzz-enforced (tests/test_preempt.py,
``make fuzz-preempt``).

Hard gates (never victims, never over-promise):

- daemonset pods and ``is_critical`` pods are never victims;
- victims must rank strictly below the LOWEST blocked demand priority;
- PDB allowances are consumed cumulatively in victim order — a pod
  whose eviction would breach a budget is skipped, and everything the
  chosen prefix evicts fits the budgets by construction;
- demand pods with ``preemptionPolicy: Never`` never trigger a search;
- demand pods carrying required topology constraints are excluded (the
  greedy fill cannot honor spread, so a verdict including them could
  evict victims without scheduling the pod).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..apis.objects import Pod, is_critical
from ..models.delta import full_existing_encode
from ..models.encoding import encode_snapshot
from ..solver.types import SchedulingSnapshot

log = logging.getLogger(__name__)

#: candidate-prefix cap per search — one device lane each. Deeper
#: preemption (65+ victims in one round) is out of scope by design; the
#: truncation is logged, never silent, and the next reconcile retries
#: with the survivors.
MAX_LANES = 64

_BIG = np.int64(1) << np.int64(60)


@dataclass(frozen=True)
class PreemptCommand:
    """The canonical applied form of a feasible verdict — what the
    provisioner executes, and the byte string the cross-backend fuzz
    compares. Evictions keep victim order (= eviction order); demand is
    name-sorted (the solve decides placement, not the command)."""
    #: (namespace, name, node_name) per victim, in eviction order
    evictions: Tuple[Tuple[str, str, str], ...]
    #: full names of the demand pods the evictions unblock
    demand: Tuple[str, ...]

    def to_bytes(self) -> bytes:
        return repr((self.evictions, self.demand)).encode("utf-8")


@dataclass
class PreemptionVerdict:
    feasible: bool
    #: chosen victim prefix (empty unless feasible)
    victims: Tuple[Pod, ...] = ()
    #: demand pods the search ran for
    demand: Tuple[Pod, ...] = ()
    #: candidate prefixes evaluated
    lanes: int = 0
    #: per-lane leftover demand pods (device/host parity evidence)
    leftovers: Tuple[int, ...] = ()
    #: "device" | "host" | "none"
    backend: str = "none"
    #: why the search was skipped / fell back (empty when it ran clean)
    reason: str = ""
    command: Optional[PreemptCommand] = None


def victim_sort_key(pod: Pod) -> Tuple:
    """Ascending eviction preference: lowest priority first, then the
    smallest footprint (cheapest disruption), then name — equal-priority
    ties are deterministic by construction."""
    r = pod.effective_requests()
    return (getattr(pod, "priority", 0), r.get("cpu", 0),
            r.get("memory", 0), pod.metadata.namespace, pod.metadata.name)


def _lanes_numpy(ex_alloc: np.ndarray, ex_used0: np.ndarray,
                 ex_compat: np.ndarray, R: np.ndarray, n: np.ndarray,
                 freed: np.ndarray) -> np.ndarray:
    """Numpy twin of ``preempt_solve_kernel`` — bit-identical lane
    semantics (same headroom/prefix-fill arithmetic, same clamps)."""
    B = freed.shape[0]
    out = np.zeros(B, dtype=np.int64)
    for b in range(B):
        used = np.maximum(ex_used0 - freed[b], 0)
        total = np.int64(0)
        for g in range(R.shape[0]):
            Rg, ng, cg = R[g], n[g], ex_compat[g]
            Rsafe = np.where(Rg > 0, Rg, 1)
            q = (ex_alloc - used) // Rsafe[None, :]
            q = np.where((Rg > 0)[None, :], q, _BIG)
            k = np.clip(q.min(axis=-1), 0, _BIG)
            k = np.where(cg, k, 0)
            cum = np.cumsum(k) - k
            take = np.clip(ng - cum, 0, k)
            used = used + take[:, None] * Rg[None, :]
            total += ng - take.sum()
        out[b] = total
    return out


class PreemptionPlanner:
    """One search per provisioning round, consulted AFTER the base solve
    leaves priority-bearing pods unschedulable and BEFORE the controller
    gives up on them. Owns no kube writes — it returns a verdict; the
    provisioner applies it (evict, re-solve, nominate, requeue)."""

    def __init__(self, solver=None, backend: str = "auto", metrics=None):
        assert backend in ("auto", "jax", "numpy")
        if solver is None:
            from ..solver.tpu import TPUSolver
            solver = TPUSolver(backend=backend)
        self.solver = solver
        self.backend = backend
        #: optional metrics registry; the operator injects its own
        self.metrics = metrics
        self.max_lanes = MAX_LANES

    def _inc(self, name: str, value: float = 1.0, **labels) -> None:
        if self.metrics is not None:
            self.metrics.inc(name, value=value, labels=labels or None)

    def _skip(self, reason: str, demand: Tuple[Pod, ...] = ()) \
            -> PreemptionVerdict:
        self._inc("karpenter_solver_preempt_verdicts_total",
                  verdict="skipped")
        return PreemptionVerdict(feasible=False, demand=demand,
                                 reason=reason)

    # ------------------------------------------------------------------
    def plan(self, snapshot: SchedulingSnapshot,
             unschedulable: Sequence[str], state) -> PreemptionVerdict:
        """``unschedulable``: full names the base solve could not place.
        ``state``: the ClusterState (bound pods + PDB universe)."""
        # lazy: controllers/__init__ imports the provisioner, which
        # imports this package — a module-level import would cycle
        from ..controllers.pdb import pdb_state, take_allowance

        blocked = set(unschedulable)
        demand: List[Pod] = []
        for pod in snapshot.pods:
            if pod.full_name() not in blocked:
                continue
            if getattr(pod, "priority", 0) <= 0:
                continue
            if getattr(pod, "preemption_policy", "") == "Never":
                continue
            if pod.topology_spread or pod.pod_affinity:
                log.info("preempt: %s excluded from demand (required "
                         "topology constraints)", pod.full_name())
                continue
            demand.append(pod)
        if not demand:
            return self._skip("no eligible demand")
        demand.sort(key=lambda p: p.full_name())
        floor = min(getattr(p, "priority", 0) for p in demand)

        existing = list(snapshot.existing_nodes)
        npos = {node.name: ei for ei, node in enumerate(existing)}
        if not npos:
            return self._skip("no existing nodes", tuple(demand))

        bound = state.bound_pods_by_node()
        candidates: List[Pod] = []
        for node_name, pods in bound.items():
            if node_name not in npos:
                continue
            for pod in pods:
                if not pod.node_name:
                    continue  # nominated, not bound: nothing to evict
                if pod.owner_kind == "DaemonSet" or is_critical(pod):
                    continue
                if getattr(pod, "priority", 0) >= floor:
                    continue
                candidates.append(pod)
        candidates.sort(key=victim_sort_key)

        # cumulative PDB budgets, consumed in victim order: the chosen
        # prefix can never over-draw a budget
        pdbs = pdb_state(state.kube)
        victims = [p for p in candidates if take_allowance(pdbs, p)]
        if not victims:
            return self._skip("no eligible victims", tuple(demand))
        if len(victims) > self.max_lanes:
            log.info("preempt: victim list truncated to %d lanes "
                     "(%d candidates dropped)", self.max_lanes,
                     len(victims) - self.max_lanes)
            victims = victims[:self.max_lanes]

        # one demand-only encoding shares the base solver's derivation
        # (canonical group order, existing tables) with both twins
        demand_snap = SchedulingSnapshot(
            pods=demand, nodepools=snapshot.nodepools,
            existing_nodes=existing,
            daemon_overheads=snapshot.daemon_overheads,
            zones=snapshot.zones,
            priority_classes=getattr(snapshot, "priority_classes", ()))
        enc = encode_snapshot(demand_snap)
        ex_alloc, ex_used, ex_compat = full_existing_encode(enc, existing)

        dpos = {d: i for i, d in enumerate(enc.dims)}
        B = len(victims)
        freed = np.zeros((B, len(existing), len(enc.dims)), dtype=np.int64)
        refund = np.zeros_like(freed[0])
        for b, pod in enumerate(victims):
            ei = npos[pod.node_name]
            for key, qty in pod.effective_requests().items():
                di = dpos.get(key)
                if di is not None:
                    refund[ei, di] += qty
            freed[b] = refund

        leftovers, backend_used, reason = self._evaluate(
            ex_alloc, ex_used, ex_compat, enc.R, enc.n, freed)

        chosen: Tuple[Pod, ...] = ()
        for b in range(B):
            if leftovers[b] == 0:
                chosen = tuple(victims[:b + 1])
                break
        feasible = bool(chosen)
        self._inc("karpenter_solver_preempt_verdicts_total",
                  verdict="feasible" if feasible else "infeasible")
        command = None
        if feasible:
            self._inc("karpenter_solver_preempt_victims_total",
                      value=float(len(chosen)))
            command = PreemptCommand(
                evictions=tuple((p.metadata.namespace, p.metadata.name,
                                 p.node_name) for p in chosen),
                demand=tuple(p.full_name() for p in demand))
        return PreemptionVerdict(
            feasible=feasible, victims=chosen, demand=tuple(demand),
            lanes=B, leftovers=tuple(int(v) for v in leftovers),
            backend=backend_used, reason=reason, command=command)

    # ------------------------------------------------------------------
    def _evaluate(self, ex_alloc, ex_used, ex_compat, R, n, freed):
        """Route the lane batch: device kernel when the solver carries
        one and its engine answers, else the numpy twin — identical
        verdicts by contract, and every fallback is counted."""
        def host():
            return _lanes_numpy(ex_alloc, ex_used, ex_compat, R, n, freed)

        if self.backend == "numpy":
            return host(), "host", ""
        if not getattr(self.solver, "supports_preempt_kernel", False):
            # CPU solver / remote peer without the capability: the twin
            # IS the engine here, not a degradation — no fallback counter
            return host(), "host", ""
        router = getattr(self.solver, "_router", None)
        if router is not None:
            from ..solver.route import dev_engine_usable
            if not dev_engine_usable(router):
                log.warning("preempt: dev engine unavailable; lanes on "
                            "the host twin")
                self._inc("karpenter_solver_preempt_host_fallback_total",
                          reason="device_unavailable")
                return host(), "host", "device_unavailable"
        try:
            out = self.solver.dispatch_preempt(
                ex_alloc=ex_alloc, ex_used=ex_used, ex_compat=ex_compat,
                R=R, n=n, freed=freed)
        except Exception as e:  # DeviceDispatchFailed or raw XLA error
            log.warning("preempt: device dispatch failed (%s); lanes on "
                        "the host twin", e)
            self._inc("karpenter_solver_preempt_host_fallback_total",
                      reason="dispatch_failed")
            return host(), "host", "dispatch_failed"
        return out, "device", ""
