"""Batched preemption kernel: victim-set feasibility for many candidate
sets in ONE device call.

The preemption planner asks, per candidate victim set v: "with v's usage
refunded to its nodes, do all higher-priority pending pods first-fit onto
the EXISTING nodes — zero new nodes?" Sequentially that is O(candidates)
solver calls; here the candidate axis is just a batch dimension, the
``subset_solve_kernel`` lane recipe (ops/consolidation_jax.py) turned
inside out: consolidation masks nodes OUT of a shared arena, preemption
refunds usage INTO it.

Transfer discipline: candidates share the cluster, so the demand-group
tables (``R/n/ex_compat``) and the node tables (``ex_alloc/ex_used0``)
are sent ONCE; each lane carries only its ``freed`` refund tensor — the
cumulative requests of its victim prefix scattered onto the victims'
node rows. Because candidate sets are PREFIXES of one ascending
(priority, cost) victim order, lane k's refund is lane k-1's plus one
pod: the host builds the stack with a single cumulative sum.

Semantics per demand group: headroom per node = min_d floor((alloc -
used)/R), prefix-sum greedy fill in canonical node order — bit-identical
to the planner's numpy oracle twin (scheduling/preempt.py _lanes_numpy)
and to the CPU solver's first-fit over existing nodes. New nodes are
structurally impossible: the lane never sees a catalog. All int64
(jax_enable_x64): verdicts match the oracle exactly
(tests/test_preempt.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

jax.config.update("jax_enable_x64", True)

BIG = jnp.int64(1) << 60


@jax.jit
def preempt_solve_kernel(ex_alloc: jax.Array,   # [E, D] int64 shared
                         ex_used0: jax.Array,   # [E, D] int64 shared
                         ex_compat: jax.Array,  # [G, E] bool shared
                         R: jax.Array,          # [G, D] int64 demand groups
                         n: jax.Array,          # [G] int64 pod counts
                         freed: jax.Array,      # [B, E, D] int64 refunds
                         ) -> jax.Array:        # [B] int64 leftover pods
    """One greedy existing-node fill of the demand groups per lane,
    vmapped over the victim-set axis. Returns total leftover demand pods
    per lane; 0 ⇔ evicting that lane's victims schedules everything."""
    def lane(fr):
        # refund the victims' usage; clamp guards nodes whose committed
        # usage snapshot lagged the victim's own requests
        used0 = jnp.maximum(ex_used0 - fr, 0)

        def step(used, xs):
            Rg, ng, cg = xs
            Rsafe = jnp.where(Rg > 0, Rg, 1)
            q = (ex_alloc - used) // Rsafe[None, :]          # [E, D]
            q = jnp.where((Rg > 0)[None, :], q, BIG)
            k = jnp.clip(q.min(axis=-1), 0, BIG)             # [E]
            k = jnp.where(cg, k, 0)
            cum = jnp.cumsum(k) - k
            take = jnp.clip(ng - cum, 0, k)
            used = used + take[:, None] * Rg[None, :]
            return used, ng - take.sum()

        _, leftover = jax.lax.scan(step, used0, (R, n, ex_compat))
        return leftover.sum()

    return jax.vmap(lane)(freed)
