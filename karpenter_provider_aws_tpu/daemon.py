"""The operator daemon: a long-running process around the Operator wiring.

The reference's entry point (cmd/controller/main.go:28-74) builds the
operator, wires the cloud provider and cluster state, registers core + AWS
controllers on one manager, and starts it with health/metrics endpoints
served by the core operator. This daemon is that process:

- `Daemon` registers every controller from operator.py on a
  ControllerManager at the reference cadences (catalog/pricing 12h,
  SSM invalidation 30m, version refresh 5m, GC 10s x 20 then 2m,
  interruption long-poll, fast loops for provisioning/lifecycle),
- serves /metrics (Prometheus text) and /healthz on an HTTP port,
- optionally waits on a file lease before taking the controllers live
  (the chart's 2-replica leader election analog),
- shuts down gracefully on SIGTERM/SIGINT.

Run it: ``python -m karpenter_provider_aws_tpu --cluster-name demo``.
The cloud + kube behind it are the in-memory fakes (this framework's
mocking boundary, pkg/fake in the reference); a real deployment would
swap them behind the same provider seams.
"""

from __future__ import annotations

import argparse
import logging
import signal
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from .manager import ControllerManager, FileLease
from .operator import Operator, PreflightError
from .options import Options

log = logging.getLogger(__name__)

#: reference cadences (seconds)
CATALOG_REFRESH = 12 * 3600        # providers/instancetype/controller.go:59
PRICING_REFRESH = 12 * 3600        # providers/pricing/controller.go:43
SSM_INVALIDATION = 30 * 60         # ssm/invalidation/controller.go:55
VERSION_REFRESH = 5 * 60           # providers/version/controller.go:45
GC_INITIAL, GC_INITIAL_COUNT, GC_STEADY = 10.0, 20, 120.0
#                                  # garbagecollection/controller.go:55-62
INTERRUPTION_POLL = 0.5            # continuous long-poll loop
FAST_LOOP = 1.0                    # pod-batch window for provisioning
DISRUPTION_TICK = 10.0             # disruption controller tick
NODECLASS_TICK = 10.0              # status reconciler (watch-driven in ref)
HASH_TICK = 60.0
CAPACITY_TICK = 60.0               # discovered-capacity (node watch in ref)
TAGGER_TICK = 5.0                  # nodeclaim watch in ref


class _MetricsHandler(BaseHTTPRequestHandler):
    def do_GET(self):  # noqa: N802 - stdlib API
        if self.path == "/metrics":
            body = self.server.karpenter_daemon.operator.metrics.render().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
        elif self.path in ("/healthz", "/readyz"):
            ok = self.server.karpenter_daemon.healthy()
            body = b"ok" if ok else b"not ready"
            self.send_response(200 if ok else 503)
            self.send_header("Content-Type", "text/plain")
        else:
            body = b"not found"
            self.send_response(404)
            self.send_header("Content-Type", "text/plain")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args):  # quiet the default stderr spam
        log.debug("http: " + fmt, *args)


class Daemon:
    def __init__(self, operator: Optional[Operator] = None,
                 options: Optional[Options] = None,
                 metrics_port: int = 8080,
                 lease_path: str = "",
                 solver: str = "cpu",
                 sidecar_address: str = "",
                 fleet_endpoints: str = "",
                 simulate_kubelet: bool = True):
        if operator is None:
            sv, ev = self._build_solver(solver, sidecar_address,
                                        fleet_endpoints)
            operator = Operator(options=options, solver=sv,
                               consolidation_evaluator=ev)
        self.operator = operator
        self.manager = ControllerManager(metrics=operator.metrics)
        self.metrics_port = metrics_port
        self.simulate_kubelet = simulate_kubelet
        self.lease: Optional[FileLease] = \
            FileLease(lease_path) if lease_path else None
        if self.lease is not None:
            # leadership loss must PAUSE reconciling, not just flip a
            # flag: two active managers would double-provision
            self.lease.on_lost.append(self._on_lease_lost)
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._http_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._register_controllers()

    @staticmethod
    def _build_solver(name: str, sidecar_address: str = "",
                      fleet_endpoints: str = ""):
        """(solver, consolidation evaluator) for --solver cpu|tpu.

        A sidecar address upgrades the tpu solver to RemoteSolver: the
        packed/topology dispatches ride the chart's companion container
        (gRPC), cost-routed against the in-process host twin; the
        consolidation evaluator stays local (its prescreen kernels are
        latency-sensitive batched calls on host state). A fleet endpoint
        list upgrades it further to FleetSolver: N replicas behind the
        shape-affine ring (fleet/, docs/fleet.md) — the chart sets this
        when sidecar.fleetEndpoints names the headless-Service DNS."""
        if name == "tpu":
            from .solver.consolidation import TPUConsolidationEvaluator
            if fleet_endpoints:
                from .fleet import FleetSolver
                eps = [e.strip() for e in fleet_endpoints.split(",")
                       if e.strip()]
                return FleetSolver(eps), TPUConsolidationEvaluator()
            if sidecar_address:
                from .sidecar.client import RemoteSolver
                return (RemoteSolver(sidecar_address),
                        TPUConsolidationEvaluator())
            from .solver.tpu import TPUSolver
            # auto = per-shape cost routing between the device kernel
            # and the bit-identical host twin (solver/route.py)
            return TPUSolver(backend="auto"), TPUConsolidationEvaluator()
        if sidecar_address or fleet_endpoints:
            import logging
            logging.getLogger(__name__).warning(
                "--solver-sidecar-address/--solver-fleet-endpoints are "
                "ignored with --solver cpu")
        from .solver.cpu import CPUSolver
        return CPUSolver(), None

    # ------------------------------------------------------------------
    def _register_controllers(self) -> None:
        op = self.operator
        reg = self.manager.register
        # fast loops: the provision->launch->join->initialize chain
        reg("provisioner", op.provisioner.reconcile, FAST_LOOP)
        reg("nodeclaim.lifecycle", op.lifecycle.reconcile, FAST_LOOP)
        reg("nodeclaim.termination", op.terminator.reconcile, FAST_LOOP)
        # node auto-repair: condition-toleration table from the
        # cloudprovider (cloudprovider.go:252-293)
        reg("node.repair", op.node_repair.reconcile, FAST_LOOP)
        if self.simulate_kubelet:
            reg("fake.kubelet", op.kubelet.tick, FAST_LOOP)
        # steady state (controllers.go:63-101 cadences)
        reg("nodeclass.status", op.nodeclass_status.reconcile, NODECLASS_TICK)
        reg("nodeclass.hash", op.nodeclass_hash.reconcile, HASH_TICK)
        reg("nodeclaim.tagging", op.tagger.reconcile, TAGGER_TICK)
        reg("nodeclaim.garbagecollection", op.gc.reconcile, GC_STEADY,
            initial_interval=GC_INITIAL, initial_count=GC_INITIAL_COUNT)
        reg("disruption", op.disruption.reconcile, DISRUPTION_TICK)
        reg("providers.instancetype", op.catalog_controller.reconcile,
            CATALOG_REFRESH)
        reg("providers.pricing", op.pricing_controller.reconcile,
            PRICING_REFRESH)
        reg("providers.instancetype.metrics",
            op.catalog_controller.refresh_gauges, 60.0)
        reg("providers.instancetype.capacity",
            op.discovered_capacity.reconcile, CAPACITY_TICK)
        reg("providers.ssm.invalidation", op.ssm_invalidation.reconcile,
            SSM_INVALIDATION)
        reg("providers.version", op.version_controller.reconcile,
            VERSION_REFRESH)
        if op.options.interruption_queue:
            reg("interruption", op.interruption.reconcile, INTERRUPTION_POLL)
        # fleet-ops gauge families (nodes/pods/cluster/conditions)
        reg("telemetry", op.telemetry.reconcile, 30.0)
        # debug transition watchers (test/pkg/debug analog): only when the
        # log level asks for them. Observation is eager (the watcher logs
        # at event time through the kube watch hook) — attaching is all
        # that's needed; keep a reference so it lives with the daemon
        if logging.getLogger().isEnabledFor(logging.DEBUG):
            from .utils.debug import attach
            self._debug_watcher = attach(op.kube)

    # ------------------------------------------------------------------
    def healthy(self) -> bool:
        """Readiness: controllers running AND (when leader-elected) the
        lease still held — a demoted replica reports 503 so traffic and
        dashboards see the standby for what it is."""
        if not self.manager.running:
            return False
        return self.lease is None or self.lease.held

    def _on_lease_lost(self) -> None:
        """Heartbeat observed another holder: stop reconciling NOW (the
        new leader is already acting), flip /readyz to 503 via healthy(),
        and rejoin the standby pool — blocking on re-acquire and resuming
        the manager if leadership ever returns, without a restart."""
        log.warning("leader lease lost; pausing controllers")
        self.manager.stop()
        threading.Thread(target=self._rejoin, daemon=True,
                         name="lease-rejoin").start()

    def _rejoin(self) -> None:
        if self.lease.acquire(stop=self._stop) and not self._stop.is_set():
            log.info("re-acquired leader lease as %s", self.lease.identity)
            self.manager.start()

    def start(self) -> "Daemon":
        """Serve endpoints, wait for the lease (if any), start reconciling."""
        import gc
        gc.collect()
        gc.freeze()  # long-running-server posture: boot state never re-scanned
        self._serve_http()
        if self.lease is not None:
            log.info("waiting for leader lease %s", self.lease.path)
            if not self.lease.acquire(stop=self._stop):
                return self  # stopped while waiting
            log.info("acquired leader lease as %s", self.lease.identity)
        self.manager.start()
        return self

    def run(self) -> None:
        """start() + block until SIGTERM/SIGINT (the __main__ path)."""
        signal.signal(signal.SIGTERM, self._on_signal)
        signal.signal(signal.SIGINT, self._on_signal)
        self.start()
        self._stop.wait()
        self.shutdown()

    def _on_signal(self, signum, frame) -> None:
        log.info("received signal %d, shutting down", signum)
        self._stop.set()

    def shutdown(self) -> None:
        self._stop.set()
        self.manager.stop()
        if self.lease is not None:
            self.lease.release()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None

    # ------------------------------------------------------------------
    def _serve_http(self) -> None:
        if self.metrics_port < 0:
            return
        self._httpd = ThreadingHTTPServer(
            ("127.0.0.1", self.metrics_port), _MetricsHandler)
        self._httpd.karpenter_daemon = self
        self.metrics_port = self._httpd.server_address[1]  # resolve :0
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="metrics-http")
        self._http_thread.start()
        log.info("metrics on http://127.0.0.1:%d/metrics", self.metrics_port)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="karpenter-provider-aws-tpu",
        description="Run the operator daemon against the in-memory cloud.")
    Options.add_flags(parser)
    parser.add_argument("--metrics-port", type=int, default=8080,
                        help="metrics/health port (0 = ephemeral, -1 = off)")
    parser.add_argument("--leader-elect-lease", default="",
                        help="file lease path enabling leader election")
    parser.add_argument("--solver", choices=["cpu", "tpu"], default="cpu",
                        help="provisioning solver backend")
    parser.add_argument("--solver-sidecar-address", default="",
                        help="host:port of the solver sidecar; with "
                             "--solver tpu, device dispatches ride the "
                             "gRPC companion (the chart sets this when "
                             "sidecar.enabled)")
    parser.add_argument("--solver-fleet-endpoints", default="",
                        help="comma-separated solver replica endpoints; "
                             "with --solver tpu, dispatches route per "
                             "(tenant, shape-class) over the replica "
                             "fleet (docs/fleet.md; the chart sets this "
                             "when sidecar.fleetEndpoints is set). "
                             "Takes precedence over "
                             "--solver-sidecar-address")
    parser.add_argument("--log-level", default="INFO")
    import sys as _sys
    if argv is None:
        argv = _sys.argv[1:]
    ns = parser.parse_args(argv)
    logging.basicConfig(
        level=ns.log_level.upper(),
        format="%(asctime)s %(levelname)s %(name)s %(message)s")
    options = Options.parse(argv)
    try:
        daemon = Daemon(options=options, metrics_port=ns.metrics_port,
                        lease_path=ns.leader_elect_lease, solver=ns.solver,
                        sidecar_address=ns.solver_sidecar_address,
                        fleet_endpoints=ns.solver_fleet_endpoints)
    except PreflightError as e:
        # fail-fast boot contract (operator.go:111-115,218-227 analog):
        # a dead/wedged cloud seam must exit with a clear error in
        # seconds, not start controllers that spin against it
        log.error("boot preflight failed: %s", e)
        return 1
    daemon.run()
    return 0
