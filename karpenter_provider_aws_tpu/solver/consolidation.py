"""TPU-backed consolidation evaluator: batch all deletion candidates into
one device call.

Plugs into :class:`controllers.disruption.DisruptionController` in place of
the sequential oracle. The controller hands one deletion-check snapshot per
candidate (pools price-filtered to nothing, existing = cluster minus the
candidate); this evaluator encodes the batch and answers every candidate
with one ``ops.consolidation_jax`` kernel call.

Two encodings:

- **shared-table fast path** (the production shape): all candidates come
  from the same cluster view, differing only by which nodes are masked
  out. Node tensors and per-signature compatibility rows are built ONCE;
  each candidate carries only index vectors. Host encode is O(E + S·E +
  B·G) instead of O(B·E) Python work.
- **dense fallback** for heterogeneous batches (same-named nodes with
  different capacities etc. — never produced by the controller, but the
  evaluator stays correct for any input).

Exactness discipline (same as solver/tpu.py): snapshots whose pods carry
topology spread / pod-affinity constraints fall back to the sequential
oracle; everything else is evaluated with int64 math bit-identical to the
oracle's, so decisions never diverge
(tests/test_consolidation_equivalence.py enforces equality).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..controllers.disruption import ConsolidationEvaluator
from ..models.encoding import canonical_pod_groups
from ..solver.types import ExistingNode
from .cpu import CPUSolver
from .route import Router, routed
from .types import SchedulingSnapshot, Solver


def _pow2(x: int) -> int:
    return max(1, 1 << (x - 1).bit_length())


class TPUConsolidationEvaluator(ConsolidationEvaluator):
    def __init__(self, solver: Optional[Solver] = None,
                 backend: str = "auto"):
        super().__init__(solver or CPUSolver())
        assert backend in ("auto", "jax", "numpy")
        self.backend = backend
        #: optional metrics registry (operator injects, as on TPUSolver)
        self.metrics = None
        self._router = Router(name="consolidation")

    def _routed(self, bucket, host_fn, dev_fn):
        if self.backend == "numpy":
            return host_fn()
        if self.backend == "jax":
            # same wedged-link discipline as TPUSolver's explicit-jax
            # path: nonblocking verdict, host twin while unusable
            from .route import dev_engine_usable
            if dev_engine_usable(self._router):
                return dev_fn()
            import logging
            logging.getLogger(__name__).warning(
                "dev engine unavailable; consolidation batch on the "
                "host twin")
            if self.metrics is not None:
                self.metrics.inc("karpenter_solver_device_fallback_total",
                                 labels={"reason": "device_unavailable"})
            return host_fn()
        self._router.metrics = self.metrics
        return routed(self._router, bucket, host_fn, dev_fn)

    # ------------------------------------------------------------------
    def deletions_feasible(
            self, snapshots: Sequence[SchedulingSnapshot]) -> List[bool]:
        if not snapshots:
            return []
        out: List[Optional[bool]] = [None] * len(snapshots)
        batch_idx: List[int] = []
        for i, snap in enumerate(snapshots):
            if any(p.topology_spread or p.pod_affinity for p in snap.pods):
                # oracle fallback (same discipline as TPUSolver)
                res = self.solver.solve(snap)
                out[i] = not res.new_nodes and not res.unschedulable
            elif not snap.pods:
                out[i] = True
            elif not snap.existing_nodes:
                out[i] = False
            else:
                batch_idx.append(i)
        if batch_idx:
            batch = [snapshots[i] for i in batch_idx]
            flags = self._evaluate_shared(batch)
            if flags is None:
                flags = self._evaluate_dense(batch)
            for i, ok in zip(batch_idx, flags):
                out[i] = bool(ok)
        return out  # type: ignore[return-value]

    # ------------------------------------------------------------------
    # shared-table fast path
    # ------------------------------------------------------------------
    def _evaluate_shared(
            self, snaps: List[SchedulingSnapshot]) -> Optional[np.ndarray]:
        """Encode against one shared node table; None if the batch is not
        table-shaped (same node name, different node object contents)."""
        by_name: Dict[str, ExistingNode] = {}
        for snap in snaps:
            for node in snap.existing_nodes:
                prev = by_name.setdefault(node.name, node)
                if prev is not node:
                    return None  # heterogeneous batch -> dense fallback
        node_names = sorted(by_name)
        npos = {name: i for i, name in enumerate(node_names)}
        E = len(node_names)

        dims_set = {"cpu", "memory", "pods"}
        sig_of: Dict[Tuple, int] = {}
        sig_groups: List[Tuple] = []          # rep pod per full signature
        #: compatibility depends only on (selector, affinity, tolerations)
        #: — the constraint profile — and real batches have FEW of those
        #: even when every candidate's pods carry distinct signatures
        ckey_of: Dict[Tuple, int] = {}
        ckey_groups: List[Tuple] = []         # rep pod per profile
        sig_ckey: List[int] = []              # S -> Sc
        per_snap: List[List[Tuple[int, int]]] = []  # [(sig idx, count)]
        G = 1
        for snap in snaps:
            rows: List[Tuple[int, int]] = []
            for sig, plist in canonical_pod_groups(snap.pods):
                p = plist[0]
                dims_set.update(p.effective_requests().nonzero_keys())
                si = sig_of.get(sig)
                if si is None:
                    si = sig_of[sig] = len(sig_groups)
                    sig_groups.append(p)
                    ck = (sig[0], sig[1], sig[3])
                    ci = ckey_of.get(ck)
                    if ci is None:
                        ci = ckey_of[ck] = len(ckey_groups)
                        ckey_groups.append(p)
                    sig_ckey.append(ci)
                rows.append((si, len(plist)))
            per_snap.append(rows)
            G = max(G, len(rows))
        dims = sorted(dims_set)
        dpos = {d: i for i, d in enumerate(dims)}
        D = len(dims)
        S = len(sig_groups)
        Sc = len(ckey_groups)

        def vec(r) -> np.ndarray:
            v = np.zeros(D, dtype=np.int64)
            for k, q in r.items():
                i = dpos.get(k)
                if i is not None:
                    v[i] = q
            return v

        B = len(snaps)
        Bp, Gp, Ep = _pow2(B), _pow2(G), _pow2(E)
        Sp, Scp, Dp = _pow2(S), _pow2(Sc), max(8, D)

        ex_alloc = np.zeros((Ep, Dp), dtype=np.int64)
        ex_used = np.zeros((Ep, Dp), dtype=np.int64)
        for name, node in by_name.items():
            ei = npos[name]
            ex_alloc[ei, :D] = vec(node.allocatable)
            ex_used[ei, :D] = vec(node.used)

        compat_tab = np.zeros((Scp, Ep), dtype=bool)
        for ci, rep in enumerate(ckey_groups):
            reqs = rep.scheduling_requirements()
            for name, node in by_name.items():
                compat_tab[ci, npos[name]] = (
                    reqs.satisfied_by_labels(node.labels)
                    and all(t.tolerated_by(rep.tolerations)
                            for t in node.taints))
        R_tab = np.zeros((Sp, Dp), dtype=np.int64)
        for si, rep in enumerate(sig_groups):
            R_tab[si, :D] = vec(rep.effective_requests())

        gid = np.zeros((Bp, Gp), dtype=np.int32)
        cid = np.zeros((Bp, Gp), dtype=np.int32)
        n = np.zeros((Bp, Gp), dtype=np.int64)
        alive = np.zeros((Bp, Ep), dtype=bool)
        for bi, snap in enumerate(snaps):
            for gi, (si, cnt) in enumerate(per_snap[bi]):
                gid[bi, gi] = si
                cid[bi, gi] = sig_ckey[si]
                n[bi, gi] = cnt
            for node in snap.existing_nodes:
                alive[bi, npos[node.name]] = True

        def dev_fn():
            import jax.numpy as jnp

            from ..ops.consolidation_jax import deletions_feasible_kernel
            return np.asarray(deletions_feasible_kernel(
                jnp.asarray(ex_alloc), jnp.asarray(ex_used),
                jnp.asarray(compat_tab), jnp.asarray(R_tab),
                jnp.asarray(gid), jnp.asarray(cid), jnp.asarray(n),
                jnp.asarray(alive)))

        return self._routed(
            ("shared", Bp, Gp, Ep, Sp, Scp, Dp),
            lambda: self._numpy_shared(ex_alloc, ex_used, compat_tab,
                                       R_tab, gid, cid, n, alive),
            dev_fn)[:B]

    @staticmethod
    def _numpy_shared(ex_alloc, ex_used, compat_tab, R_tab, gid, cid, n,
                      alive) -> np.ndarray:
        BIG = np.int64(1) << 60
        Bp, Gp = n.shape
        ok = np.ones(Bp, dtype=bool)
        for b in range(Bp):
            used = ex_used.copy()
            for g in range(Gp):
                Rg, ng = R_tab[gid[b, g]], n[b, g]
                cg = compat_tab[cid[b, g]] & alive[b]
                Rsafe = np.where(Rg > 0, Rg, 1)
                q = (ex_alloc - used) // Rsafe[None, :]
                q = np.where((Rg > 0)[None, :], q, BIG)
                k = np.clip(q.min(axis=-1), 0, BIG)
                k = np.where(cg, k, 0)
                cum = np.cumsum(k) - k
                take = np.clip(ng - cum, 0, k)
                used = used + take[:, None] * Rg[None, :]
                if ng - take.sum() > 0:
                    ok[b] = False
        return ok

    # ------------------------------------------------------------------
    # dense fallback (heterogeneous batches)
    # ------------------------------------------------------------------
    def _evaluate_dense(self, snaps: List[SchedulingSnapshot]) -> np.ndarray:
        B = len(snaps)
        dims_set = {"cpu", "memory", "pods"}
        for snap in snaps:
            for p in snap.pods:
                dims_set.update(p.effective_requests().nonzero_keys())
        dims = sorted(dims_set)
        dpos = {d: i for i, d in enumerate(dims)}
        D = len(dims)
        E = max(len(snap.existing_nodes) for snap in snaps)

        def vec(r) -> np.ndarray:
            v = np.zeros(D, dtype=np.int64)
            for k, q in r.items():
                i = dpos.get(k)
                if i is not None:
                    v[i] = q
            return v

        per_snap_groups = []
        G = 1
        for snap in snaps:
            groups = [(plist[0], plist)
                      for _sig, plist in canonical_pod_groups(snap.pods)]
            per_snap_groups.append(groups)
            G = max(G, len(groups))

        Bp, Gp, Ep, Dp = _pow2(B), _pow2(G), _pow2(E), max(8, D)
        ex_alloc = np.zeros((Bp, Ep, Dp), dtype=np.int64)
        ex_used = np.zeros((Bp, Ep, Dp), dtype=np.int64)
        ex_compat = np.zeros((Bp, Gp, Ep), dtype=bool)
        R = np.zeros((Bp, Gp, Dp), dtype=np.int64)
        n = np.zeros((Bp, Gp), dtype=np.int64)

        for bi, snap in enumerate(snaps):
            nodes = sorted(snap.existing_nodes, key=lambda x: x.name)
            for ei, node in enumerate(nodes):
                ex_alloc[bi, ei, :D] = vec(node.allocatable)
                ex_used[bi, ei, :D] = vec(node.used)
            for gi, (rep, pods) in enumerate(per_snap_groups[bi]):
                R[bi, gi, :D] = vec(rep.effective_requests())
                n[bi, gi] = len(pods)
                reqs = rep.scheduling_requirements()
                for ei, node in enumerate(nodes):
                    ex_compat[bi, gi, ei] = (
                        reqs.satisfied_by_labels(node.labels)
                        and all(t.tolerated_by(rep.tolerations)
                                for t in node.taints))

        def dev_fn():
            import jax.numpy as jnp

            from ..ops.consolidation_jax import deletions_feasible_dense
            return np.asarray(deletions_feasible_dense(
                jnp.asarray(ex_alloc), jnp.asarray(ex_used),
                jnp.asarray(ex_compat), jnp.asarray(R), jnp.asarray(n)))

        return self._routed(
            ("dense", Bp, Gp, Ep, Dp),
            lambda: self._numpy_dense(ex_alloc, ex_used, ex_compat, R, n),
            dev_fn)[:B]

    @staticmethod
    def _numpy_dense(ex_alloc, ex_used, ex_compat, R, n) -> np.ndarray:
        BIG = np.int64(1) << 60
        Bp, Gp = n.shape
        ok = np.ones(Bp, dtype=bool)
        for b in range(Bp):
            used = ex_used[b].copy()
            for g in range(Gp):
                Rg, ng = R[b, g], n[b, g]
                Rsafe = np.where(Rg > 0, Rg, 1)
                q = (ex_alloc[b] - used) // Rsafe[None, :]
                q = np.where((Rg > 0)[None, :], q, BIG)
                k = np.clip(q.min(axis=-1), 0, BIG)
                k = np.where(ex_compat[b, g], k, 0)
                cum = np.cumsum(k) - k
                take = np.clip(ng - cum, 0, k)
                used = used + take[:, None] * Rg[None, :]
                if ng - take.sum() > 0:
                    ok[b] = False
        return ok
