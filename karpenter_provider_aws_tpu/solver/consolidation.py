"""TPU-backed consolidation evaluator: batch all deletion candidates into
one device call.

Plugs into :class:`controllers.disruption.DisruptionController` in place of
the sequential oracle. The controller hands one deletion-check snapshot per
candidate (pools price-filtered to nothing, existing = cluster minus the
candidate); this evaluator encodes the batch and answers every candidate
with one ``ops.consolidation_jax`` kernel call.

Two encodings:

- **shared-table fast path** (the production shape): all candidates come
  from the same cluster view, differing only by which nodes are masked
  out. Node tensors and per-signature compatibility rows are built ONCE;
  each candidate carries only index vectors. Host encode is O(E + S·E +
  B·G) instead of O(B·E) Python work.
- **dense fallback** for heterogeneous batches (same-named nodes with
  different capacities etc. — never produced by the controller, but the
  evaluator stays correct for any input).

Exactness discipline (same as solver/tpu.py): snapshots whose pods carry
topology spread / pod-affinity constraints leave the batched kernels and
are served per-candidate by the TENSOR engine's topology path (the exact
pour / device event kernel, solver/tpu.py) — never the sequential
per-pod oracle; everything else is evaluated with int64 math
bit-identical to the oracle's, so decisions never diverge
(tests/test_consolidation_equivalence.py enforces equality).
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..controllers.disruption import ConsolidationEvaluator, SubsetVerdict
from ..models.delta import DeltaEncoder
from ..models.encoding import canonical_pod_groups
from ..solver.types import ExistingNode

from .route import Router, routed
from .types import SchedulingSnapshot, Solver


def _pow2(x: int) -> int:
    return max(1, 1 << (x - 1).bit_length())


class _GroupTables:
    """Shared batch-encoding of pod lists into signature/profile tables —
    the one implementation of constraint-profile identity for both the
    deletion kernel and the replacement pre-screen (a profile = selector,
    affinity terms, tolerations, volume reqs: everything
    scheduling_requirements/taint-compat can see — sig indices 0,1,3,7)."""

    __slots__ = ("sig_groups", "ckeys", "ckey_groups", "sig_ckey",
                 "per_rows", "dims", "dpos", "G")

    def __init__(self, pod_lists):
        dims_set = {"cpu", "memory", "pods"}
        sig_of: Dict[Tuple, int] = {}
        ckey_of: Dict[Tuple, int] = {}
        self.sig_groups: List = []   # representative pod per signature
        self.ckeys: List[Tuple] = []  # profile key per profile index
        self.ckey_groups: List = []  # representative pod per profile
        self.sig_ckey: List[int] = []
        self.per_rows: List[List[Tuple[int, int]]] = []
        self.G = 1
        for pods in pod_lists:
            rows: List[Tuple[int, int]] = []
            for sig, plist in canonical_pod_groups(pods):
                p = plist[0]
                dims_set.update(p.effective_requests().nonzero_keys())
                si = sig_of.get(sig)
                if si is None:
                    si = sig_of[sig] = len(self.sig_groups)
                    self.sig_groups.append(p)
                    ck = (sig[0], sig[1], sig[3], sig[7])
                    ci = ckey_of.get(ck)
                    if ci is None:
                        ci = ckey_of[ck] = len(self.ckey_groups)
                        self.ckeys.append(ck)
                        self.ckey_groups.append(p)
                    self.sig_ckey.append(ci)
                rows.append((si, len(plist)))
            self.per_rows.append(rows)
            self.G = max(self.G, len(rows))
        self.dims = sorted(dims_set)
        self.dpos = {d: i for i, d in enumerate(self.dims)}

    def vec(self, r) -> np.ndarray:
        v = np.zeros(len(self.dims), dtype=np.int64)
        for k, q in r.items():
            i = self.dpos.get(k)
            if i is not None:
                v[i] = q
        return v

    def r_tab(self, Sp: int, Dp: int) -> np.ndarray:
        R = np.zeros((Sp, Dp), dtype=np.int64)
        D = len(self.dims)
        for si, rep in enumerate(self.sig_groups):
            R[si, :D] = self.vec(rep.effective_requests())
        return R

    def node_compat(self, Scp: int, Ep: int, by_name, npos) -> np.ndarray:
        compat = np.zeros((Scp, Ep), dtype=bool)
        for ci, rep in enumerate(self.ckey_groups):
            reqs = rep.scheduling_requirements()
            for name, node in by_name.items():
                compat[ci, npos[name]] = (
                    reqs.satisfied_by_labels(node.labels)
                    and all(t.tolerated_by(rep.tolerations)
                            for t in node.taints))
        return compat


class TPUConsolidationEvaluator(ConsolidationEvaluator):
    def __init__(self, solver: Optional[Solver] = None,
                 backend: str = "auto"):
        assert backend in ("auto", "jax", "numpy")
        if solver is None:
            # topology-bearing candidates leave the batched kernels (the
            # exactness discipline below) but must NOT regress all the
            # way to the sequential per-pod oracle: the tensor engine's
            # topology pour/event kernel (solver/tpu.py) serves them with
            # identical decisions, so mixed clusters keep the batched
            # speedup on the per-candidate solves too
            from .tpu import TPUSolver
            solver = TPUSolver(backend=backend)
        super().__init__(solver)
        self.backend = backend
        #: optional metrics registry (operator injects, as on TPUSolver)
        self._metrics = None
        self._router = Router(name="consolidation")
        #: catalog-derived pre-screen tables, reused while the pools'
        #: resolved InstanceTypes lists are unchanged (instancetype
        #: provider returns the same cached list until a seqnum bump —
        #: instancetype.go:119-130 discipline). A small LRU, not a
        #: single entry: multi-nodepool reconciles interleave distinct
        #: base snapshots and a one-slot cache would rebuild the tables
        #: on every alternation. Values hold strong refs (_refs) to the
        #: nodepools + type lists their key ids point at, so an id can
        #: never be recycled while its entry lives.
        self._base_cache: "OrderedDict[Tuple, dict]" = OrderedDict()
        self._base_cache_cap = 4
        #: last-seen arena-coherence token of the inner solver
        #: (TPUSolver.arena_epoch(): delta epoch + mesh resident
        #: generation; bare DeltaEncoder.epoch for older solvers): a
        #: token move means the structural universe moved (new
        #: catalog/pool/daemon objects, or a from-scratch mesh
        #: re-placement), which is exactly when this identity-keyed
        #: cache must drop its entries coherently with the resident
        #: encoding
        self._base_epoch = None
        #: resident union-arena encoder for the whole-fleet subset
        #: search (subset_solve): one DeltaEncoder so successive rounds
        #: against a stable cluster pay delta patches, not re-encodes
        self._sub_delta = DeltaEncoder()
        #: (enc, version) -> padded device arrays + statics from the last
        #: subset round; reused verbatim while the encoder's version is
        #: unchanged (the version bumps whenever any returned array
        #: differs, models/delta.py)
        self._sub_prep: Optional[Tuple] = None

    @property
    def metrics(self):
        return self._metrics

    @metrics.setter
    def metrics(self, m):
        # forward to the inner solver: its oracle-fallback / slot-growth
        # counters from topology-candidate solves must not go dark (the
        # "fallbacks are never silent" contract, solver/tpu.py)
        self._metrics = m
        if hasattr(self.solver, "metrics"):
            self.solver.metrics = m

    def _routed(self, bucket, host_fn, dev_fn):
        if self.backend == "numpy":
            return host_fn()
        if self.backend == "jax":
            # same wedged-link discipline as TPUSolver's explicit-jax
            # path: nonblocking verdict, host twin while unusable
            from .route import dev_engine_usable
            if dev_engine_usable(self._router):
                return dev_fn()
            import logging
            logging.getLogger(__name__).warning(
                "dev engine unavailable; consolidation batch on the "
                "host twin")
            if self.metrics is not None:
                self.metrics.inc("karpenter_solver_device_fallback_total",
                                 labels={"reason": "device_unavailable"})
            return host_fn()
        self._router.metrics = self.metrics
        return routed(self._router, bucket, host_fn, dev_fn)

    # ------------------------------------------------------------------
    def deletions_feasible(
            self, snapshots: Sequence[SchedulingSnapshot]) -> List[bool]:
        if not snapshots:
            return []
        out: List[Optional[bool]] = [None] * len(snapshots)
        batch_idx: List[int] = []
        for i, snap in enumerate(snapshots):
            if any(p.topology_spread or p.pod_affinity for p in snap.pods):
                # topology path: per-candidate solve on the tensor
                # engine's pour/event kernel (decision-identical)
                res = self.solver.solve(snap)
                out[i] = not res.new_nodes and not res.unschedulable
            elif not snap.pods:
                out[i] = True
            elif not snap.existing_nodes:
                out[i] = False
            else:
                batch_idx.append(i)
        if batch_idx:
            batch = [snapshots[i] for i in batch_idx]
            flags = self._evaluate_shared(batch)
            if flags is None:
                flags = self._evaluate_dense(batch)
            for i, ok in zip(batch_idx, flags):
                out[i] = bool(ok)
        return out  # type: ignore[return-value]

    # ------------------------------------------------------------------
    # replacement pre-screen (batched "± one cheaper node" search)
    # ------------------------------------------------------------------
    def replacements_prescreen(self, base, queries) -> List[bool]:
        """Batched exact-NO/maybe-YES for the replacement search
        (controllers.disruption ReplacementQuery). Queries whose pods carry
        topology/affinity constraints are never pruned (same fallback
        discipline as deletions_feasible); everything else is answered by
        one ops.consolidation_jax.replacements_prescreen_kernel call."""
        if not queries:
            return []
        out: List[Optional[bool]] = [None] * len(queries)
        batch_idx: List[int] = []
        for i, q in enumerate(queries):
            if not q.pods:
                out[i] = True
            elif any(p.topology_spread or p.pod_affinity for p in q.pods):
                out[i] = True  # the authoritative simulate decides
            else:
                batch_idx.append(i)
        if batch_idx:
            flags = self._prescreen_batch(
                base, [queries[i] for i in batch_idx])
            for i, ok in zip(batch_idx, flags):
                out[i] = bool(ok)
        return out  # type: ignore[return-value]

    # ------------------------------------------------------------------
    # whole-fleet device search (subset lanes over one union arena)
    # ------------------------------------------------------------------
    #: slot bucket for subset lanes: every gate the controller consumes
    #: (feasible, n_new in {0, 1}) is decided exactly at two new-node
    #: slots — a re-solve needing more than 2 nodes either strands pods
    #: (leftover > 0) or mints a second node, and both read as "gate
    #: false", which is exactly the oracle's skip
    SUBSET_N_MAX = 2

    def subset_solve(self, base, queries):
        """EXACT per-query verdicts for the whole-fleet replacement
        search (controllers.disruption ConsolidationEvaluator contract):
        encode ONE union arena over the base cluster plus every queried
        pod — riding the resident delta encoder, so a stable cluster
        pays patches, not re-encodes — and answer every lane (deletion
        checks at price_cap=0 and ≤1-cheaper-replacement prefixes alike)
        with ONE subset_solve_kernel dispatch. Each lane is a gathered,
        masked view of the union arena: per-query group rows select the
        pending pods, dead-node masks delete the subset, keep masks
        price-filter the catalog. Any eligibility miss returns None
        (karpenter_solver_consolidation_host_fallback_total says why)
        and the controller runs the sequential oracle unchanged."""
        if not queries:
            return []
        if self.backend == "numpy":
            return self._subset_fallback("numpy_backend")
        if not getattr(self.solver, "supports_subset_kernel", False):
            return self._subset_fallback("no_subset_kernel")
        from .route import dev_engine_usable
        if not dev_engine_usable(self._router):
            return self._subset_fallback("device_unavailable")
        for q in queries:
            for p in q.pods:
                if p.topology_spread or p.pod_affinity:
                    # the pour/event kernels have no subset-lane shape;
                    # the oracle's per-candidate solves still serve
                    # topology candidates from the tensor engine
                    return self._subset_fallback("topology")
        # union pod set: every queried pod once (prefix queries overlap)
        union_pods: List = []
        seen = set()
        for q in queries:
            for p in q.pods:
                if id(p) not in seen:
                    seen.add(id(p))
                    union_pods.append(p)
        if not union_pods:
            return self._subset_fallback("no_pods")
        pod_groups = canonical_pod_groups(union_pods)
        from .preferences import preference_count
        if any(preference_count(plist[0]) for _sig, plist in pod_groups):
            # preference relaxation is an outer host loop (solver/tpu.py
            # discipline): a subset lane cannot iterate it
            return self._subset_fallback("preferences")
        try:
            return self._subset_dispatch(base, queries, union_pods,
                                         pod_groups)
        except Exception:
            import logging
            logging.getLogger(__name__).warning(
                "subset dispatch failed; consolidation round on the "
                "sequential oracle", exc_info=True)
            return self._subset_fallback("dispatch_error")

    def _subset_fallback(self, reason: str):
        if self.metrics is not None:
            self.metrics.inc(
                "karpenter_solver_consolidation_host_fallback_total",
                labels={"reason": reason})
        return None

    def _subset_dispatch(self, base, queries, union_pods, pod_groups):
        solver = self.solver
        existing = sorted(base.existing_nodes, key=lambda n: n.name)
        union = SchedulingSnapshot(
            pods=union_pods, nodepools=base.nodepools,
            existing_nodes=existing,
            daemon_overheads=base.daemon_overheads, zones=base.zones)
        self._sub_delta.metrics = self.metrics
        enc, (ex_alloc, ex_used, ex_compat), _d = \
            self._sub_delta.encode(union, pod_groups, existing)
        if enc.topo_any:
            return self._subset_fallback("topology")
        if not enc.types:
            return self._subset_fallback("no_types")
        if enc.mv_K:
            # minValues floors couple lanes to pool-level argmin state;
            # the oracle's per-prefix simulate handles them exactly
            return self._subset_fallback("minvalues")
        if len(enc.groups) > getattr(solver, "dev_max_groups", 4096):
            return self._subset_fallback("group_cap")

        # padded union arena, reused verbatim while the encoder's
        # version is unchanged (a version bump means some returned
        # array moved — models/delta.py); ndev=2 skips the fuse plan
        # (subset lanes scan unfused, like every vmapped batch)
        ver = self._sub_delta.version
        if (self._sub_prep is not None and self._sub_prep[0] is enc
                and self._sub_prep[1] == ver):
            arrays, stt, tprice = self._sub_prep[2:]
        else:
            arrays, stt = solver._prep_device_inputs(
                enc, ex_alloc, ex_used, ex_compat, 2)
            tprice = np.full(len(enc.types), np.int64(1) << 60,
                             dtype=np.int64)
            for ti, it in enumerate(enc.types):
                p = it.cheapest_price()
                if p is not None:
                    tprice[ti] = p
            self._sub_prep = (enc, ver, arrays, stt, tprice)

        # lane stacks: gid/n gather each query's groups out of the union
        # tables (canonical_group_order is restriction-stable, so the
        # gathered rows ARE the query's own canonical encoding order)
        sig_row = {g.sig: g.index for g in enc.groups}
        npos = {n.name: i for i, n in enumerate(existing)}
        B = len(queries)
        per_rows = [[(sig_row[s], len(plist))
                     for s, plist in canonical_pod_groups(q.pods)]
                    for q in queries]
        Gq = _pow2(max((len(r) for r in per_rows), default=1))
        Bp = _pow2(B)
        E, T = stt["E"], len(enc.types)
        gid = np.zeros((Bp, Gq), dtype=np.int32)
        nq = np.zeros((Bp, Gq), dtype=np.int64)
        dead = np.zeros((Bp, E), dtype=bool)
        keep = np.zeros((Bp, T), dtype=bool)
        rprice = np.zeros(Bp, dtype=np.int64)
        for b, q in enumerate(queries):
            for j, (si, cnt) in enumerate(per_rows[b]):
                gid[b, j] = si
                nq[b, j] = cnt
            for name in q.gone:
                ei = npos.get(name)  # claim names are not node rows
                if ei is not None:
                    dead[b, ei] = True
            # mirror _snapshot's price filter: strictly cheaper, priced
            keep[b] = tprice < q.price_cap
            rprice[b] = q.price_cap
        out = solver.dispatch_subsets(
            arrays, tprice=tprice, gid=gid, n=nq, dead=dead, keep=keep,
            removed_price=rprice, n_max=self.SUBSET_N_MAX,
            E=E, P=stt["P"])
        if out is None:  # remote capability/availability degrade
            return self._subset_fallback("dispatch_degraded")
        if self.metrics is not None:
            self.metrics.inc(
                "karpenter_solver_consolidation_subset_batch_total")
            self.metrics.inc(
                "karpenter_solver_consolidation_device_rounds_total")
        out = np.asarray(out)
        return [SubsetVerdict(feasible=int(r[0]) == 0, n_new=int(r[1]),
                              flex=int(r[2]), min_price=int(r[3]),
                              savings=int(r[4]))
                for r in out[:B]]

    def _base_tables(self, base) -> dict:
        """Catalog-derived tables (unique types, dense allocatable,
        cheapest prices, lazily-filled per-profile compat rows). Cached on
        the identity of the pools' resolved type lists + nodepool hashes;
        the entry holds strong refs so ids cannot be recycled."""
        # NodePool.hash() covers taints but NOT template.requirements
        # (objects.py:322-329), and padmit rows depend on both — fold the
        # requirement tuples in explicitly or a requirements-only edit
        # would keep serving stale pool-admission rows
        # arena coherence: when the inner solver's incremental encoder
        # rebuilt its resident arena for a structural change — OR the
        # mesh engine re-placed its resident sharded arena from scratch
        # (a mesh-patched tick whose key rolled; parallel/mesh.py bumps
        # resident_gen on every full placement) — the same change
        # invalidates these identity-keyed tables. The compound
        # TPUSolver.arena_epoch() token covers both edges; refresh in
        # lockstep so a delta- or mesh-patched base never pre-screens a
        # stale "cluster minus subset" re-solve
        ae = getattr(self.solver, "arena_epoch", None)
        if ae is not None:
            tok = ae()
        else:
            dep = getattr(self.solver, "_delta", None)
            tok = dep.epoch if dep is not None else None
        if tok is not None and tok != self._base_epoch:
            if self._base_epoch is not None:
                self._base_cache.clear()
            self._base_epoch = tok
        key = tuple(
            x for spec in base.nodepools
            for x in (spec.nodepool.hash(),
                      tuple((r.key, r.complement, r.values,
                             r.greater_than, r.less_than)
                            for r in spec.nodepool.scheduling_requirements()),
                      id(spec.instance_types)))
        hit = self._base_cache.get(key)
        if hit is not None:
            self._base_cache.move_to_end(key)
            return hit
        types: List = []
        tpos: Dict[int, int] = {}
        pool_rows: List[List[int]] = []
        for spec in base.nodepools:
            rows = []
            for it in spec.instance_types:
                ti = tpos.get(id(it))
                if ti is None:
                    ti = tpos[id(it)] = len(types)
                    types.append(it)
                rows.append(ti)
            pool_rows.append(rows)
        T = len(types)
        cdims = sorted({k for it in types
                        for k in it.allocatable().nonzero_keys()})
        cpos = {d: j for j, d in enumerate(cdims)}
        alloc = np.zeros((T, len(cdims)), dtype=np.int64)
        price = np.full(T, np.int64(1) << 60, dtype=np.int64)
        for ti, it in enumerate(types):
            for k, q in it.allocatable().items():
                j = cpos.get(k)
                if j is not None:
                    alloc[ti, j] = q
            p = it.cheapest_price()
            if p is not None:
                price[ti] = p
        tab = dict(types=types, pool_rows=pool_rows, cdims=cdims,
                   alloc=alloc, price=price, tcompat={}, padmit={},
                   _refs=[(s.nodepool, s.instance_types)
                          for s in base.nodepools])
        self._base_cache[key] = tab
        while len(self._base_cache) > self._base_cache_cap:
            self._base_cache.popitem(last=False)
        return tab

    def _prescreen_batch(self, base, queries) -> np.ndarray:
        node_names = sorted(n.name for n in base.existing_nodes)
        npos = {name: i for i, name in enumerate(node_names)}
        by_name = {n.name: n for n in base.existing_nodes}
        E = len(node_names)

        tab = self._base_tables(base)
        types, pool_rows = tab["types"], tab["pool_rows"]
        T, P = len(types), len(base.nodepools)

        gt = _GroupTables([q.pods for q in queries])
        D = len(gt.dims)
        S, Sc = len(gt.sig_groups), len(gt.ckey_groups)

        B = len(queries)
        Bp, Gp, Ep = _pow2(B), _pow2(gt.G), _pow2(max(1, E))
        Sp, Scp, Tp, Pp, Dp = (_pow2(S), _pow2(Sc), _pow2(max(1, T)),
                               _pow2(max(1, P)), max(8, D))
        BIG = np.int64(1) << 60

        ex_alloc = np.zeros((Ep, Dp), dtype=np.int64)
        ex_used = np.zeros((Ep, Dp), dtype=np.int64)
        for name, node in by_name.items():
            ei = npos[name]
            ex_alloc[ei, :D] = gt.vec(node.allocatable)
            ex_used[ei, :D] = gt.vec(node.used)

        compat_tab = np.zeros((Scp, Ep), dtype=bool)
        compat_tab[:Sc, :E] = gt.node_compat(Sc, E, by_name, npos)
        tcompat = np.zeros((Scp, Tp), dtype=bool)
        padmit = np.zeros((Pp, Scp), dtype=bool)
        # the per-profile memos live as long as the catalog cache entry
        # (12h between seqnum bumps); churning workloads can mint unbounded
        # distinct profiles — cap like encoding.py's _SIG_CAP intern table
        if len(tab["tcompat"]) > 4096:
            tab["tcompat"].clear()
            tab["padmit"].clear()
        for ci, (ck, rep) in enumerate(zip(gt.ckeys, gt.ckey_groups)):
            reqs = rep.scheduling_requirements()
            trow = tab["tcompat"].get(ck)
            if trow is None:
                trow = np.fromiter(
                    (not it.requirements.conflicts(reqs)
                     and bool(it.offerings.available().compatible(reqs))
                     for it in types), dtype=bool, count=T)
                tab["tcompat"][ck] = trow
            tcompat[ci, :T] = trow
            prow = tab["padmit"].get(ck)
            if prow is None:
                prow = np.fromiter(
                    (not spec.nodepool.scheduling_requirements()
                     .compatible(reqs)
                     and all(t.tolerated_by(rep.tolerations)
                             for t in spec.nodepool.template.taints)
                     for spec in base.nodepools), dtype=bool, count=P)
                tab["padmit"][ck] = prow
            padmit[:P, ci] = prow

        type_alloc = np.zeros((Tp, Dp), dtype=np.int64)
        for i, d in enumerate(gt.dims):
            if d in tab["cdims"]:
                type_alloc[:T, i] = tab["alloc"][:, tab["cdims"].index(d)]
        type_price = np.full(Tp, BIG, dtype=np.int64)
        type_price[:T] = tab["price"]
        pool_types = np.zeros((Pp, Tp), dtype=bool)
        for pi, rows in enumerate(pool_rows):
            pool_types[pi, rows] = True

        R_tab = gt.r_tab(Sp, Dp)

        gid = np.zeros((Bp, Gp), dtype=np.int32)
        cid = np.zeros((Bp, Gp), dtype=np.int32)
        n = np.zeros((Bp, Gp), dtype=np.int64)
        alive = np.zeros((Bp, Ep), dtype=bool)
        price_cap = np.zeros(Bp, dtype=np.int64)
        for bi, q in enumerate(queries):
            for gi, (si, cnt) in enumerate(gt.per_rows[bi]):
                gid[bi, gi] = si
                cid[bi, gi] = gt.sig_ckey[si]
                n[bi, gi] = cnt
            for name, ei in npos.items():
                alive[bi, ei] = name not in q.gone
            price_cap[bi] = q.price_cap

        def dev_fn():
            import jax.numpy as jnp

            from ..ops.consolidation_jax import replacements_prescreen_kernel
            return np.asarray(replacements_prescreen_kernel(
                jnp.asarray(ex_alloc), jnp.asarray(ex_used),
                jnp.asarray(compat_tab), jnp.asarray(R_tab),
                jnp.asarray(type_alloc), jnp.asarray(type_price),
                jnp.asarray(tcompat), jnp.asarray(padmit),
                jnp.asarray(pool_types), jnp.asarray(gid),
                jnp.asarray(cid), jnp.asarray(n), jnp.asarray(alive),
                jnp.asarray(price_cap)))

        return self._routed(
            ("prescreen", Bp, Gp, Ep, Sp, Scp, Tp, Pp, Dp),
            lambda: self._numpy_prescreen(
                ex_alloc, ex_used, compat_tab, R_tab, type_alloc,
                type_price, tcompat, padmit, pool_types, gid, cid, n,
                alive, price_cap),
            dev_fn)[:B]

    @staticmethod
    def _numpy_prescreen(ex_alloc, ex_used, compat_tab, R_tab, type_alloc,
                         type_price, tcompat, padmit, pool_types, gid, cid,
                         n, alive, price_cap) -> np.ndarray:
        BIG = np.int64(1) << 60
        Bp, Gp = n.shape
        out = np.zeros(Bp, dtype=bool)
        for b in range(Bp):
            used = ex_used.copy()
            leftover = np.zeros(Gp, dtype=np.int64)
            for g in range(Gp):
                Rg, ng = R_tab[gid[b, g]], n[b, g]
                cg = compat_tab[cid[b, g]] & alive[b]
                Rsafe = np.where(Rg > 0, Rg, 1)
                q = (ex_alloc - used) // Rsafe[None, :]
                q = np.where((Rg > 0)[None, :], q, BIG)
                k = np.clip(q.min(axis=-1), 0, BIG)
                k = np.where(cg, k, 0)
                cum = np.cumsum(k) - k
                take = np.clip(ng - cum, 0, k)
                used = used + take[:, None] * Rg[None, :]
                leftover[g] = ng - take.sum()
            active = leftover > 0
            if not active.any():
                out[b] = True
                continue
            agg = (leftover[:, None] * R_tab[gid[b]]).sum(axis=0)
            g_ok = (tcompat[cid[b]] | ~active[:, None]).all(axis=0)
            p_ok = (padmit[:, cid[b]] | ~active[None, :]).all(axis=1)
            from_pools = (p_ok[:, None] & pool_types).any(axis=0)
            fits = (agg[None, :] <= type_alloc).all(axis=-1)
            out[b] = bool((g_ok & from_pools & fits
                           & (type_price < price_cap[b])).any())
        return out

    # ------------------------------------------------------------------
    # shared-table fast path
    # ------------------------------------------------------------------
    def _evaluate_shared(
            self, snaps: List[SchedulingSnapshot]) -> Optional[np.ndarray]:
        """Encode against one shared node table; None if the batch is not
        table-shaped (same node name, different node object contents)."""
        by_name: Dict[str, ExistingNode] = {}
        for snap in snaps:
            for node in snap.existing_nodes:
                prev = by_name.setdefault(node.name, node)
                if prev is not node:
                    return None  # heterogeneous batch -> dense fallback
        node_names = sorted(by_name)
        npos = {name: i for i, name in enumerate(node_names)}
        E = len(node_names)

        gt = _GroupTables([snap.pods for snap in snaps])
        D = len(gt.dims)
        S, Sc = len(gt.sig_groups), len(gt.ckey_groups)

        B = len(snaps)
        Bp, Gp, Ep = _pow2(B), _pow2(gt.G), _pow2(E)
        Sp, Scp, Dp = _pow2(S), _pow2(Sc), max(8, D)

        ex_alloc = np.zeros((Ep, Dp), dtype=np.int64)
        ex_used = np.zeros((Ep, Dp), dtype=np.int64)
        for name, node in by_name.items():
            ei = npos[name]
            ex_alloc[ei, :D] = gt.vec(node.allocatable)
            ex_used[ei, :D] = gt.vec(node.used)

        compat_tab = np.zeros((Scp, Ep), dtype=bool)
        compat_tab[:Sc, :E] = gt.node_compat(Sc, E, by_name, npos)
        R_tab = gt.r_tab(Sp, Dp)

        gid = np.zeros((Bp, Gp), dtype=np.int32)
        cid = np.zeros((Bp, Gp), dtype=np.int32)
        n = np.zeros((Bp, Gp), dtype=np.int64)
        alive = np.zeros((Bp, Ep), dtype=bool)
        for bi, snap in enumerate(snaps):
            for gi, (si, cnt) in enumerate(gt.per_rows[bi]):
                gid[bi, gi] = si
                cid[bi, gi] = gt.sig_ckey[si]
                n[bi, gi] = cnt
            for node in snap.existing_nodes:
                alive[bi, npos[node.name]] = True

        def dev_fn():
            import jax.numpy as jnp

            from ..ops.consolidation_jax import deletions_feasible_kernel
            return np.asarray(deletions_feasible_kernel(
                jnp.asarray(ex_alloc), jnp.asarray(ex_used),
                jnp.asarray(compat_tab), jnp.asarray(R_tab),
                jnp.asarray(gid), jnp.asarray(cid), jnp.asarray(n),
                jnp.asarray(alive)))

        return self._routed(
            ("shared", Bp, Gp, Ep, Sp, Scp, Dp),
            lambda: self._numpy_shared(ex_alloc, ex_used, compat_tab,
                                       R_tab, gid, cid, n, alive),
            dev_fn)[:B]

    @staticmethod
    def _numpy_shared(ex_alloc, ex_used, compat_tab, R_tab, gid, cid, n,
                      alive) -> np.ndarray:
        BIG = np.int64(1) << 60
        Bp, Gp = n.shape
        ok = np.ones(Bp, dtype=bool)
        for b in range(Bp):
            used = ex_used.copy()
            for g in range(Gp):
                Rg, ng = R_tab[gid[b, g]], n[b, g]
                cg = compat_tab[cid[b, g]] & alive[b]
                Rsafe = np.where(Rg > 0, Rg, 1)
                q = (ex_alloc - used) // Rsafe[None, :]
                q = np.where((Rg > 0)[None, :], q, BIG)
                k = np.clip(q.min(axis=-1), 0, BIG)
                k = np.where(cg, k, 0)
                cum = np.cumsum(k) - k
                take = np.clip(ng - cum, 0, k)
                used = used + take[:, None] * Rg[None, :]
                if ng - take.sum() > 0:
                    ok[b] = False
        return ok

    # ------------------------------------------------------------------
    # dense fallback (heterogeneous batches)
    # ------------------------------------------------------------------
    def _evaluate_dense(self, snaps: List[SchedulingSnapshot]) -> np.ndarray:
        B = len(snaps)
        dims_set = {"cpu", "memory", "pods"}
        for snap in snaps:
            for p in snap.pods:
                dims_set.update(p.effective_requests().nonzero_keys())
        dims = sorted(dims_set)
        dpos = {d: i for i, d in enumerate(dims)}
        D = len(dims)
        E = max(len(snap.existing_nodes) for snap in snaps)

        def vec(r) -> np.ndarray:
            v = np.zeros(D, dtype=np.int64)
            for k, q in r.items():
                i = dpos.get(k)
                if i is not None:
                    v[i] = q
            return v

        per_snap_groups = []
        G = 1
        for snap in snaps:
            groups = [(plist[0], plist)
                      for _sig, plist in canonical_pod_groups(snap.pods)]
            per_snap_groups.append(groups)
            G = max(G, len(groups))

        Bp, Gp, Ep, Dp = _pow2(B), _pow2(G), _pow2(E), max(8, D)
        ex_alloc = np.zeros((Bp, Ep, Dp), dtype=np.int64)
        ex_used = np.zeros((Bp, Ep, Dp), dtype=np.int64)
        ex_compat = np.zeros((Bp, Gp, Ep), dtype=bool)
        R = np.zeros((Bp, Gp, Dp), dtype=np.int64)
        n = np.zeros((Bp, Gp), dtype=np.int64)

        for bi, snap in enumerate(snaps):
            nodes = sorted(snap.existing_nodes, key=lambda x: x.name)
            for ei, node in enumerate(nodes):
                ex_alloc[bi, ei, :D] = vec(node.allocatable)
                ex_used[bi, ei, :D] = vec(node.used)
            for gi, (rep, pods) in enumerate(per_snap_groups[bi]):
                R[bi, gi, :D] = vec(rep.effective_requests())
                n[bi, gi] = len(pods)
                reqs = rep.scheduling_requirements()
                for ei, node in enumerate(nodes):
                    ex_compat[bi, gi, ei] = (
                        reqs.satisfied_by_labels(node.labels)
                        and all(t.tolerated_by(rep.tolerations)
                                for t in node.taints))

        def dev_fn():
            import jax.numpy as jnp

            from ..ops.consolidation_jax import deletions_feasible_dense
            return np.asarray(deletions_feasible_dense(
                jnp.asarray(ex_alloc), jnp.asarray(ex_used),
                jnp.asarray(ex_compat), jnp.asarray(R), jnp.asarray(n)))

        return self._routed(
            ("dense", Bp, Gp, Ep, Dp),
            lambda: self._numpy_dense(ex_alloc, ex_used, ex_compat, R, n),
            dev_fn)[:B]

    @staticmethod
    def _numpy_dense(ex_alloc, ex_used, ex_compat, R, n) -> np.ndarray:
        BIG = np.int64(1) << 60
        Bp, Gp = n.shape
        ok = np.ones(Bp, dtype=bool)
        for b in range(Bp):
            used = ex_used[b].copy()
            for g in range(Gp):
                Rg, ng = R[b, g], n[b, g]
                Rsafe = np.where(Rg > 0, Rg, 1)
                q = (ex_alloc[b] - used) // Rsafe[None, :]
                q = np.where((Rg > 0)[None, :], q, BIG)
                k = np.clip(q.min(axis=-1), 0, BIG)
                k = np.where(ex_compat[b, g], k, 0)
                cum = np.cumsum(k) - k
                take = np.clip(ng - cum, 0, k)
                used = used + take[:, None] * Rg[None, :]
                if ng - take.sum() > 0:
                    ok[b] = False
        return ok
