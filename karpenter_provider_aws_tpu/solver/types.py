"""Solver contract: snapshot in, decisions out.

This is the pluggable boundary the north star demands (BASELINE.json): the
provisioning controller and the consolidation controller build a
:class:`SchedulingSnapshot` and call ``Solver.solve``; implementations are
``cpu`` (the reference-equivalent FFD oracle) and ``tpu`` (batched jit'd
kernels). Decisions must be identical between the two — the equivalence
harness in tests/test_solver_equivalence.py enforces it.

The solve semantics mirror the core scheduler the reference drives
(designs/bin-packing.md:17-42): sort pending pods by descending size,
first-fit onto open in-flight nodes (whose candidate instance-type sets
narrow as pods land), open a new node from the highest-weight admitting
NodePool otherwise, honoring requirements, taints/tolerations, topology
spread, pod (anti-)affinity, and NodePool resource limits.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Sequence, Tuple

from ..apis.objects import NodePool, Pod, Taint
from ..apis.requirements import Requirements
from ..apis.resources import Resources
from ..cloudprovider.types import InstanceTypes


@dataclass
class ExistingNode:
    """A live node (or in-flight NodeClaim from a previous round) the solver
    may keep packing onto."""
    name: str
    labels: Mapping[str, str]
    allocatable: Resources
    taints: Sequence[Taint] = ()
    #: resources already committed (pods bound + daemonsets)
    used: Resources = field(default_factory=Resources)
    #: scheduling-group identities of pods already on the node (for topology
    #: spread / anti-affinity bookkeeping)
    pod_groups: Sequence[str] = ()
    nodepool: str = ""
    instance_type: str = ""

    def remaining(self) -> Resources:
        return (self.allocatable - self.used).clamp_nonnegative()


@dataclass
class NodePoolSpec:
    """A NodePool plus its resolved instance-type catalog."""
    nodepool: NodePool
    instance_types: InstanceTypes
    #: resources already provisioned under this pool (for limits)
    in_use: Resources = field(default_factory=Resources)


@dataclass
class DaemonOverhead:
    """Aggregate daemonset requests that land on every new node whose
    requirements admit the daemonset's pods."""
    requests: Resources = field(default_factory=Resources)
    requirements: Requirements = field(default_factory=Requirements)


@dataclass
class SchedulingSnapshot:
    pods: Sequence[Pod]
    nodepools: Sequence[NodePoolSpec]
    existing_nodes: Sequence[ExistingNode] = ()
    daemon_overheads: Sequence[DaemonOverhead] = ()
    #: zone -> zone_id for topology bookkeeping
    zones: Mapping[str, str] = field(default_factory=dict)
    #: PriorityClass objects in effect when the snapshot was built; the
    #: pods' .priority attrs are already resolved against this table.
    #: Folded into the delta encoder's structural key (value changes
    #: must force a full re-encode) and read by the preemption planner.
    priority_classes: Sequence = ()


@dataclass
class NewNodeClaim:
    """A node the solver decided to create."""
    nodepool: str
    requirements: Requirements
    pod_names: List[str]
    #: candidate types, cheapest-first; launcher truncates to 60
    instance_type_names: List[str]
    requests: Resources
    taints: Sequence[Taint] = ()


@dataclass
class SolveResult:
    new_nodes: List[NewNodeClaim]
    #: pod name -> existing node name
    existing_assignments: Dict[str, str]
    #: pod name -> human-readable reason
    unschedulable: Dict[str, str]

    def summary(self) -> str:
        return (f"{len(self.new_nodes)} new nodes, "
                f"{len(self.existing_assignments)} pods onto existing, "
                f"{len(self.unschedulable)} unschedulable")

    def decision_fingerprint(self) -> Tuple:
        """A canonical, order-independent encoding of every decision — two
        solvers are 'identical' iff fingerprints match."""
        new = tuple(sorted(
            (n.nodepool, tuple(sorted(n.pod_names)),
             tuple(n.instance_type_names))
            for n in self.new_nodes))
        existing = tuple(sorted(self.existing_assignments.items()))
        unsched = tuple(sorted(self.unschedulable))
        return (new, existing, unsched)


class Solver(abc.ABC):
    name: str = "abstract"
    #: optional metrics registry; the operator injects its own
    metrics = None

    def solve(self, snapshot: SchedulingSnapshot) -> SolveResult:
        """Solve with upstream's preference-relaxation semantics: soft
        constraints (preferred affinity, ScheduleAnyway spread) are
        hardened to required and relaxed per pod only when they block it
        (solver/preferences.py). Engines implement _solve_core."""
        from .preferences import solve_with_preferences
        return solve_with_preferences(self._solve_core, snapshot,
                                      metrics=getattr(self, "metrics", None))

    @abc.abstractmethod
    def _solve_core(self, snapshot: SchedulingSnapshot,
                    pod_groups=None) -> SolveResult:
        """pod_groups: optional canonical [(sig, members)] grouping the
        preference wrapper already computed — engines that encode by
        group reuse it instead of re-walking every pod; the oracle
        ignores it (its independent sort is part of being the oracle)."""
        ...
